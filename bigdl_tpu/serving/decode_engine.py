"""Token-level continuous batching — paged KV-cache decode engine.

ROADMAP item 3: the continuous engine (PR 8) batches *stateless*
predicts; autoregressive generation is stateful — a sequence occupies
its seat for many model steps.  The r05-era answer (``seq2seq.py``'s
one-``lax.scan`` whole-batch decode) holds every seat until the LAST
row finishes: one long request stalls the whole batch, and a request
arriving mid-decode waits for a full batch restart.  This engine runs
generation ONE MODEL STEP AT A TIME over a fixed pool of sequence
slots:

- **Paged KV cache** — decoder self-attention K/V live in a pool of
  fixed-size pages (``page_size`` tokens each); a slot owns an ordered
  page list, so a finished sequence returns its pages mid-flight and a
  queued request reuses them on the next step (vLLM-style paging, at
  the block granularity the TPU memory system likes).
- **Closed compile set** — every jitted program is keyed by a bucketed
  cache length (pages doubling up to the slot cap) and the fixed chunk
  size, all pre-compilable by :meth:`DecodeEngine.warmup` under
  ``expected_compile``; a mixed prompt/generation-length sweep triggers
  ZERO unexpected XLA recompiles (the PR 6 sentinel discipline).
- **In-flight insertion / eviction at step granularity** — admission is
  re-evaluated between steps from a (deadline, seq) heap (the PR 8
  per-tenant deadline ordering); a finished or expired sequence frees
  its slot and pages immediately and the next queued request claims
  them on the following step.  Deadlines are re-checked per token, so
  an expired streaming request never decodes to ``max_new_tokens``.
- **Prefill/decode separation** — prompts chunk through a prefill
  program (``prompt_chunk`` tokens per call, attending over the pages
  written so far) interleaved one chunk per engine iteration with
  decode steps, so a long prompt never stalls the decode batch; the
  decode program only ever runs query-length-1 steps.

Byte-identical parity (the acceptance invariant): the continuous
engine's tokens are byte-identical to :meth:`DecodeEngine.
static_generate` — the one-scan whole-sequence reference — for the
same request set, greedy AND seeded-sample, including requests
inserted mid-flight.  The two paths share ``chunk_forward`` (the layer
math) and ``_select_tokens`` (the sampling rule) verbatim; parity then
rests on three XLA facts the test suite pins: per-row results of a
matmul are independent of the number of co-batched rows (for >= 2
rows — single-row programs take a different gemv path, so every
matmul in both paths keeps >= 2 rows), masked-softmax attention is
bit-stable under padded key lengths (masked lanes contribute exact
zeros), and threefry key streams are counter-based (per-row
``fold_in(request_key, position)`` draws are batch-shape-independent).

Speculative decoding (docs/serving.md §Speculative decoding):
``DecodeConfig.speculative=SpecConfig(k, sparsity)`` swaps the
one-token decode step for a draft+verify iteration — a block-sparse
twin of the SAME checkpoint (weights shared verbatim, only the FFN
block masks differ; BLaST lineage, ops/block_sparse.py) drafts ``k``
tokens against its own float32 KV pages, then ONE target verify
program of query length ``k+1`` scores the whole chunk and the host
accepts the longest agreeing prefix.  Every emitted token is a TARGET
selection, so greedy output is byte-identical to the spec-off engine
and to :meth:`DecodeEngine.static_generate` by construction, and
temperature>0 keeps seeded parity because draft and verify share
``_select_tokens``'s counter-based key streams (the shared-Gumbel
coupling also makes a close draft agree often).  Draft pages live in a
parallel f32 pool indexed by the SAME page table, so cancel/expiry/
migration free draft state together with target state structurally.

Observability: ``serving.decode.*`` gauges/histograms — tokens/s,
time-to-first-token, inter-token latency, slot occupancy, page
utilization, speculation acceptance — all described in
``obs/export.py``'s catalog (docs/serving.md §Autoregressive decode
has the knob table).
"""

import heapq
import itertools
import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.attention import _attn_project, positional_encoding
from bigdl_tpu.nn.module import EMPTY
from bigdl_tpu.obs import flight, trace
from bigdl_tpu.resilience import faults
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.serving.decode")

_NEG_INF = -1e30


class RequestCancelledError(RuntimeError):
    """A request was cancelled before completing — its client went away
    (``reason="client_disconnect"``) or its live slot was migrated to a
    peer worker during a drain (``reason="migrated"``).  Carries the
    request id and reason so the HTTP frontend can pick the right
    framing: a disconnected client gets nothing (it's gone), a migrated
    stream is aborted WITHOUT the chunked terminator so the pool proxy
    detects truncation and fails the stream over."""

    def __init__(self, rid: str, reason: str):
        super().__init__(f"request {rid} cancelled: {reason}")
        self.rid = rid
        self.reason = reason


# ---------------------------------------------------------------------------
# config / request / result
# ---------------------------------------------------------------------------

@dataclass
class SpecConfig:
    """Speculative-decoding knobs (docs/serving.md §Speculative
    decoding).  The draft is ALWAYS the served checkpoint itself with
    block-sparse FFNs — no second model, no distillation; ``sparsity``
    trades draft speed against acceptance rate (0.0 = a dense twin:
    acceptance 1.0, no speedup — the accounting-test configuration)."""

    k: int = 4                 # tokens drafted per engine iteration
    sparsity: float = 0.5      # FFN block sparsity of the draft twin
    sparse_block: Tuple[int, int] = (8, 8)
    # "auto" = the Pallas block-sparse kernel on TPU, masked-dense jnp
    # elsewhere (a grid launch per FFN costs more than the skipped
    # FLOPs at CPU-test sizes); "kernel"/"masked" force a path
    draft_impl: str = "auto"
    # How the target scores the drafted chunk (docs/serving.md
    # §Speculative decoding — "Two verify tracings"):
    #   "scan"  — k+1 single-token steps mirroring the decode step
    #             op-for-op under one lax.scan: ONE dispatch, byte
    #             parity (tokens AND logp) with spec-off output.
    #   "chunk" — one multi-query pass over the chunk (query length
    #             k+1): collapses the per-step op count ~(k+1)x, the
    #             perf configuration.  Token-stream parity holds (the
    #             selections agree); logp is allclose-not-bitwise —
    #             the same contract as spec-off flash decode.  f32 KV
    #             only (int8 RMW is inherently per-position).
    #   "auto"  — "chunk" where the flash kernel runs (TPU), "scan"
    #             elsewhere: byte parity wherever the platform has it.
    verify_impl: str = "auto"
    # Draft attention window: None = the draft attends its full
    # context (exactly like the target); an int W = the draft scan
    # attends only the last W positions through a ring buffer carried
    # across the k+1 steps.  At long contexts this caps the draft's
    # per-step attention traffic at O(W) while the target re-reads the
    # whole cache — the verify is still exact over the full context,
    # so output parity is untouched; only the acceptance rate moves.
    draft_window: Optional[int] = None


@dataclass
class DecodeConfig:
    """Engine geometry.  ``slots * pages_per_slot`` pages exist by
    default; ``page_size * pages_per_slot`` is the per-sequence token
    cap (prompt + generated).  All sizes are static — they define the
    closed set of compiled programs."""

    slots: int = 8
    page_size: int = 16
    pages_per_slot: int = 8
    # total pages in the pool; None = slots * pages_per_slot (admission
    # then never blocks on pages).  Smaller values exercise page-level
    # admission control: a request is only admitted when its WORST-CASE
    # page need is reservable, so a slot can never starve mid-flight.
    num_pages: Optional[int] = None
    # prefill chunk length: prompts run through the prefill program
    # this many tokens at a time, one prefill CALL per engine iteration
    prompt_chunk: int = 16
    # slots co-batched per prefill call (padded to exactly this many
    # rows — one compiled program, and >= 2 rows keeps the bit-parity
    # rule).  Batching amortizes the per-dispatch host cost that would
    # otherwise make admission-heavy traffic prefill-bound
    prefill_batch: int = 4
    max_new_tokens: int = 32          # default per-request cap
    eos_id: int = 1
    base_seed: int = 0
    # False = whole-batch-restart baseline: admission only happens when
    # EVERY slot is free, and each wave decodes the FULL
    # ``max_new_tokens`` horizon before any seat frees — the cost model
    # of the legacy one-``lax.scan`` whole-sequence decode this engine
    # replaces (a fixed-length scan cannot exit early; a finished row
    # holds its seat to the last step).  The A/B arm bench_serving
    # --decode measures the continuous engine against.
    continuous: bool = True
    queue_capacity: int = 4096
    # None = auto (Pallas kernel on TPU, gathered-jnp path elsewhere).
    # The jnp path is the byte-parity reference; the kernel path is the
    # TPU production path (allclose, not bitwise — online softmax).
    use_flash_decode: Optional[bool] = None
    # prefix/KV-cache reuse (docs/serving.md §Decode fleet): completed
    # cold requests DONATE their page-aligned prompt-prefix pages to a
    # per-engine cache (up to this many pages; 0 disables) and later
    # requests sharing the prefix attach to the cached pages instead of
    # re-prefilling them.  Continuous mode only; cached pages are
    # reclaimed (LRU, idle entries only) when admission runs short.
    prefix_cache_pages: int = 0
    # KV page storage dtype (docs/quantization.md §Serving memory
    # hierarchy): "float32" (the byte-parity default) or "int8" —
    # pages store int8 payloads with one abs-max scale per (layer,
    # page) riding the page table.  int8 shrinks page HBM ~4x (so a
    # fixed HBM budget holds ~2x the decode slots once weights are
    # quantized too) at the cost of relaxing byte parity to the
    # token-parity budget (greedy token agreement + bounded logp
    # drift) asserted in tests/test_quant_serving.py.
    kv_dtype: str = "float32"
    # speculative decoding (docs/serving.md §Speculative decoding):
    # a SpecConfig turns every decode iteration into draft(k)+verify —
    # continuous LM engines only.  Greedy output stays byte-identical
    # to speculative=None; the f32 draft page pool roughly doubles the
    # per-page HBM cost (see kv_bytes_per_page).
    speculative: Optional[SpecConfig] = None

    @property
    def cap(self) -> int:
        return self.page_size * self.pages_per_slot

    @property
    def total_pages(self) -> int:
        return self.num_pages if self.num_pages is not None \
            else self.slots * self.pages_per_slot

    def len_buckets(self) -> Tuple[int, ...]:
        """Cache-length buckets in PAGES: doubling from 1 up to the slot
        cap — the closed set every decode/prefill program is keyed by."""
        out = []
        b = 1
        while b < self.pages_per_slot:
            out.append(b)
            b *= 2
        out.append(self.pages_per_slot)
        return tuple(out)

    def bucket_pages(self, tokens: int) -> int:
        """Smallest bucket (in pages) covering ``tokens`` cache slots.
        Floored so the attended width is >= 8 keys: XLA's tiny-reduce
        path for a narrower masked softmax is not bit-stable against
        the wider buckets (measured; docs/serving.md §Autoregressive
        decode), and the parity invariant is non-negotiable."""
        need = max(1, -(-max(tokens, 8) // self.page_size))
        for b in self.len_buckets():
            if b >= need:
                return b
        return self.pages_per_slot


@dataclass
class DecodeRequest:
    """One generation request.  ``tokens`` is the prompt (for seq2seq:
    the SOURCE sequence — the adapter turns it into encoder context and
    a BOS decoder prompt)."""

    tokens: np.ndarray
    max_new_tokens: Optional[int] = None
    temperature: float = 0.0          # 0 = greedy
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    rid: Optional[str] = None
    tenant: str = "default"
    deadline_t: float = math.inf      # absolute; math.inf = never
    on_token: Optional[Callable[[str, int, int], None]] = None
    on_done: Optional[Callable[["DecodeRequest"], None]] = None
    # -- fleet prefill/decode split (docs/serving.md §Decode fleet) ---------
    # export_kv: run as a PREFILL-ONLY request (pair with
    # max_new_tokens=1): on completion the slot's prompt KV pages are
    # copied to host and stashed on ``kv_export`` for
    # fleet.handoff.pack_handoff.  handoff: admit a request whose
    # prefill ran on another worker — the unpacked handoff dict; the
    # engine scatters the transferred pages and continues decoding from
    # the handoff's first token, byte-identical to a local prefill.
    export_kv: bool = False
    handoff: Optional[dict] = None
    # -- engine-internal ----------------------------------------------------
    kv_export: Optional[dict] = None   # filled by the export_kv path
    admit_t: float = 0.0
    seq: int = 0
    prepared: Optional[tuple] = None   # cached adapter.prepare() output
    result: Optional["DecodeResult"] = None
    error: Optional[Exception] = None
    _event: threading.Event = field(default_factory=threading.Event,
                                    repr=False)

    def wait(self, timeout: Optional[float] = None) -> "DecodeResult":
        if not self._event.wait(timeout):
            raise TimeoutError(f"decode request {self.rid} not done")
        if self.error is not None:
            raise self.error
        return self.result


@dataclass
class DecodeResult:
    tokens: np.ndarray        # generated tokens, EOS included if hit
    logp: float               # summed log-prob of the generated tokens
    prompt_len: int
    ttft_s: float             # admission -> first token
    finish_reason: str        # "eos" | "length" | "expired"


class _ActiveSeq:
    """Host-side state of one occupied slot."""

    __slots__ = ("req", "prompt", "ctx", "pages", "reserved",
                 "generated", "logp", "first_logp", "last_logp",
                 "prefill_pos", "shared", "shared_entry",
                 "first_token_t", "last_token_t", "max_new", "done",
                 "frozen")

    def __init__(self, req: DecodeRequest, prompt: np.ndarray, ctx,
                 reserved: int, max_new: int):
        self.req = req
        self.prompt = prompt
        self.ctx = ctx
        self.pages: List[int] = []    # pages this slot OWNS (rows after
        #                               any shared prefix-cache rows)
        self.reserved = reserved      # owned pages reserved, not yet taken
        self.generated: List[int] = []
        self.logp = np.float32(0.0)
        self.first_logp = np.float32(0.0)
        self.last_logp = np.float32(0.0)
        self.prefill_pos = 0          # prompt tokens consumed by prefill
        self.shared: List[int] = []   # prefix-cache pages mapped read-only
        self.shared_entry = None      # the cache entry holding our ref
        self.first_token_t = 0.0
        self.last_token_t = 0.0
        self.max_new = max_new
        self.done = False
        self.frozen = False   # migration export taken; no more decoding

    @property
    def prefilling(self) -> bool:
        return self.prefill_pos < len(self.prompt)


# ---------------------------------------------------------------------------
# shared math: token selection (greedy / temperature / top-k / top-p)
# ---------------------------------------------------------------------------

def _select_tokens(logits, keys, positions, temps, top_ks, top_ps):
    """Per-row next-token selection — THE sampling rule both the
    continuous engine and the static reference trace, so they agree to
    the bit.  ``positions`` is the sequence position each selected token
    will occupy; the draw key is ``fold_in(request_key, position)``, a
    counter-based stream independent of batch shape and engine step
    index (the property that makes mid-flight insertion parity-safe).

    ``temps <= 0`` rows take the greedy argmax; sampling rows apply
    temperature, per-row top-k (threshold at the k-th sorted logit) and
    nucleus top-p (the standard keep-the-crossing-token rule), then an
    explicit per-row Gumbel-max draw (``categorical`` re-derived so the
    bits depend only on the row's key).  Returns ``(token, logp)`` with
    logp from the UNfiltered log-softmax."""
    logits = logits.astype(jnp.float32)
    vocab = logits.shape[-1]
    lp_full = jax.nn.log_softmax(logits, axis=-1)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _sampled(_):
        z = logits / jnp.maximum(temps, 1e-6)[:, None]
        zs = jnp.sort(z, axis=-1)[:, ::-1]
        kth = jnp.take_along_axis(
            zs, jnp.clip(top_ks - 1, 0, vocab - 1)[:, None], axis=-1)
        z = jnp.where((top_ks > 0)[:, None] & (z < kth), -jnp.inf, z)
        zs2 = jnp.sort(z, axis=-1)[:, ::-1]
        ps = jax.nn.softmax(zs2, axis=-1)
        prev_mass = jnp.cumsum(ps, axis=-1) - ps
        keep = prev_mass < top_ps[:, None]
        minz = jnp.min(jnp.where(keep, zs2, jnp.inf), axis=-1,
                       keepdims=True)
        z = jnp.where((top_ps < 1.0)[:, None] & (z < minz), -jnp.inf, z)

        step_keys = jax.vmap(jax.random.fold_in)(keys, positions)
        tiny = jnp.finfo(jnp.float32).tiny
        u = jax.vmap(lambda k: jax.random.uniform(
            k, (vocab,), minval=tiny, maxval=1.0))(step_keys)
        gumbel = -jnp.log(-jnp.log(u))
        return jnp.argmax(z + gumbel, axis=-1).astype(jnp.int32)

    # the sort/threefry machinery above is ~vocab-sized work PER ROW;
    # all-greedy batches (temps <= 0 everywhere) never read its result,
    # so gate it behind a runtime cond — with any sampling row present
    # the exact same ops run, so the bits never change
    sampled_tok = jax.lax.cond(jnp.any(temps > 0.0), _sampled,
                               lambda _: greedy_tok, None)

    tok = jnp.where(temps <= 0.0, greedy_tok, sampled_tok)
    logp = jnp.take_along_axis(lp_full, tok[:, None], axis=-1)[:, 0]
    return tok, logp


def _write_chunk(buf, positions, new, cap):
    """Scatter ``new`` (B, h, C, hd) into ``buf`` (B, h, K, hd) at
    per-row positions ``positions + [0..C)``; out-of-range positions
    (padded chunk tails crossing the cap) are dropped."""
    B, _, C, _ = new.shape
    rows = jnp.arange(B)[:, None]
    cols = positions[:, None] + jnp.arange(C)[None, :]
    cols = jnp.where(cols < cap, cols, buf.shape[2])
    return buf.at[rows, :, cols].set(
        new.transpose(0, 2, 1, 3).astype(buf.dtype), mode="drop")


# ---------------------------------------------------------------------------
# model adapters: the layer math both decode paths share
# ---------------------------------------------------------------------------

class _AdapterBase:
    """Shared transformer step math over an explicit KV buffer.  The
    engine feeds it a page-gathered view; the static reference feeds it
    a contiguous cache — identical values at every unmasked position,
    so the outputs agree bitwise (see the module docstring)."""

    def __init__(self, model, params, layout=None, weight_quant=None):
        """``layout``: serve the checkpoint MODEL-SHARDED — a
        ``parallelism=`` combo string ("tp:8") or a resolved
        :class:`~bigdl_tpu.parallel.ResolvedLayout`; every parameter is
        placed as a ``NamedSharding`` per the model's layout table
        (docs/parallelism.md §Declarative layouts) and the engine's
        jitted programs partition under GSPMD.  The closed compile set
        (cache buckets x prefill/decode programs) is unchanged.

        ``weight_quant="int8"``: store the matmul-family params int8
        with per-out-column scales (docs/quantization.md §Serving
        memory hierarchy) — 4x less HBM at rest, so one chip holds a
        bigger checkpoint.  Every adapter param access happens inside
        the engine's traced programs, so the dequantize compiles into
        each program (fused into the weight reads) and the f32 copy
        never persists between steps.  Accepts an already-quantized
        tree unchanged (the InferenceModel path quantizes once)."""
        self.layout = None
        if layout is not None:
            from bigdl_tpu.parallel.mesh_policy import (ResolvedLayout,
                                                        mesh_and_layout)

            self.layout = (layout if isinstance(layout, ResolvedLayout)
                           else mesh_and_layout(str(layout)))
            params = self.layout.shard_params(model, params)
        if weight_quant not in (None, "int8"):
            raise ValueError(
                f"weight_quant {weight_quant!r}: None | 'int8'")
        self.weight_quant = weight_quant
        if weight_quant == "int8":
            from bigdl_tpu.nn.quantized import quantize_params

            params = quantize_params(params)   # idempotent
        self.model = model
        self._params_stored = params

    @property
    def params(self):
        """The param tree the traced step math consumes.  Under
        ``weight_quant="int8"`` each access rebuilds the f32 view from
        the stored int8 tree — cheap at trace time (ops, not data; XLA
        CSEs repeated accesses within one program)."""
        if self.weight_quant == "int8":
            from bigdl_tpu.nn.quantized import dequantize_params

            return dequantize_params(self._params_stored)
        return self._params_stored

    def _split(self, x):
        b, t, _ = x.shape
        return x.reshape(b, t, self.num_heads, self.head_dim).transpose(
            0, 2, 1, 3)

    def _attend(self, q, kb, vb, valid):
        """Masked single-buffer attention: q (B,h,C,hd) over kb/vb
        (B,h,K,hd); ``valid`` (B,C,K) True = attend.  Mirrors
        ``nn.attention.transformer_decode_cached`` op-for-op."""
        hd = q.shape[-1]
        logits = jnp.einsum(
            "bhqd,bhkd->bhqk", q.astype(jnp.float32), kb,
            preferred_element_type=jnp.float32) / jnp.sqrt(float(hd))
        logits = jnp.where(valid[:, None], logits, _NEG_INF)
        w = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", w, vb,
                          preferred_element_type=jnp.float32)

    def _merge(self, a, x, p):
        B, _, C, _ = a.shape
        a = a.transpose(0, 2, 1, 3).reshape(B, C,
                                            self.num_heads * self.head_dim)
        from bigdl_tpu.tensor.policy import cast_compute

        return (jnp.matmul(a.astype(x.dtype), cast_compute(p["wo"]),
                           preferred_element_type=jnp.float32)
                + p["bo"]).astype(x.dtype)

    def _logits(self, x):
        from bigdl_tpu.tensor.policy import cast_compute

        h, _ = self.model.ln_out.forward(self.params["ln_out"], EMPTY, x)
        emb = cast_compute(self.params["embedding"])
        out = jnp.matmul(cast_compute(h), emb.T,
                         preferred_element_type=jnp.float32)
        return out.astype(jnp.float32)


class LMAdapter(_AdapterBase):
    """Causal LM (``Transformer(mode="lm")``): the prompt prefills the
    self-attention cache; generation continues from its last token."""

    def __init__(self, model, params, cap: int, layout=None,
                 weight_quant=None):
        if model.mode != "lm":
            raise ValueError("LMAdapter needs a Transformer(mode='lm')")
        super().__init__(model, params, layout=layout,
                         weight_quant=weight_quant)
        layer = model.decoder[0].attn
        self.num_heads = layer.num_heads
        self.head_dim = layer.head_dim
        self.num_layers = len(model.decoder)
        self.vocab = model.vocab_size
        self._pe = positional_encoding(cap + 1, model.hidden_size)
        self._scale = jnp.sqrt(float(model.hidden_size))

    def ctx_specs(self) -> Dict[str, Tuple[Tuple[int, ...], Any]]:
        return {}

    def prepare(self, tokens: np.ndarray):
        """LM: the prompt IS the decoder prompt; no cross context."""
        return np.asarray(tokens, np.int32).reshape(-1), {}

    def chunk_forward(self, params, tokens, positions, kbuf, vbuf, ctx,
                      self_attend=None, model=None):
        """One step of C tokens per row: embed at absolute positions,
        write each layer's K/V into the buffer, attend causally over
        the cache, return last-layer logits.  ``kbuf/vbuf``:
        (B, L, h, K, hd) f32.  ``self_attend(i, q, k_new, v_new)``
        overrides the buffer attention (the engine's paged flash
        path, which owns its own cache writes); ``kbuf/vbuf`` may then
        be None.  ``model`` substitutes a same-architecture twin for
        the layer walk (the speculative DRAFT — identical params,
        block-sparse FFNs); attention/layer-norm modules are stateless
        so only the FFN forwards differ."""
        B, C = tokens.shape
        cap = self._pe.shape[0] - 1
        q_pos = positions[:, None] + jnp.arange(C)[None, :]        # (B,C)
        x = (jnp.take(params["embedding"], tokens.astype(jnp.int32),
                      axis=0) * self._scale
             + self._pe[q_pos].astype(jnp.float32))
        if self_attend is None:
            K = kbuf.shape[3]
            valid = jnp.arange(K)[None, None, :] <= q_pos[:, :, None]
        k_news, v_news = [], []
        for i, layer in enumerate((model or self.model).decoder):
            lp = params[f"dec{i}"]
            h1, _ = layer.ln1.forward(lp["ln1"], EMPTY, x)
            sp = lp["attn"]
            q = self._split(_attn_project(sp, h1, "wq", "bq"))
            k_new = self._split(_attn_project(sp, h1, "wk", "bk"))
            v_new = self._split(_attn_project(sp, h1, "wv", "bv"))
            if self_attend is not None:
                a = self_attend(i, q, k_new, v_new)
            else:
                kb = _write_chunk(kbuf[:, i], positions, k_new, cap)
                vb = _write_chunk(vbuf[:, i], positions, v_new, cap)
                kbuf = kbuf.at[:, i].set(kb)
                vbuf = vbuf.at[:, i].set(vb)
                a = self._attend(q, kb, vb, valid)
            x = x + self._merge(a, x, sp)
            h2, _ = layer.ln2.forward(lp["ln2"], EMPTY, x)
            f, _ = layer.ffn.forward(lp["ffn"], EMPTY, h2)
            x = x + f
            k_news.append(k_new)
            v_news.append(v_new)
        return (self._logits(x), kbuf, vbuf,
                jnp.stack(k_news, 1), jnp.stack(v_news, 1))

    def build_draft(self, spec: "SpecConfig"):
        """Construct the weight-shared speculative DRAFT twin
        (docs/serving.md §Speculative decoding): the same LM
        architecture rebuilt with ``ffn_sparsity=spec.sparsity``, whose
        :class:`~bigdl_tpu.ops.block_sparse.BlockSparseLinear` FFNs
        consume the target's params verbatim ({"weight", "bias"} — the
        Linear layout) and whose block masks are derived from the
        SERVED weights by one magnitude-pruning event
        (``derive_draft_masks``).  ``sparsity=0.0`` returns a dense
        twin — bit-identical to the target, acceptance rate 1.0."""
        from bigdl_tpu.nn.attention import Transformer

        m = self.model
        ffn_size = int(m.decoder[0].ffn.l1.out_features)
        sparsity = float(spec.sparsity)
        draft = Transformer(
            m.vocab_size, m.hidden_size, self.num_heads,
            ffn_size=ffn_size, num_layers=self.num_layers, dropout=0.0,
            mode="lm", ffn_sparsity=sparsity,
            sparse_block=tuple(spec.sparse_block))
        if sparsity > 0.0:
            from bigdl_tpu.ops.block_sparse import (derive_draft_masks,
                                                    iter_sparse_modules)

            if spec.draft_impl not in ("auto", "kernel", "masked"):
                raise ValueError(f"SpecConfig.draft_impl "
                                 f"{spec.draft_impl!r}: auto | kernel "
                                 "| masked")
            if spec.draft_impl == "auto":
                from bigdl_tpu.ops.common import on_tpu

                use_kernel = on_tpu()
            else:
                use_kernel = spec.draft_impl == "kernel"
            for _, mod in iter_sparse_modules(draft):
                mod.use_kernel = use_kernel
            # mask derivation reads the DEQUANTIZED weights under
            # weight_quant="int8" — block magnitudes of the f32 view
            derive_draft_masks(draft, self.params, sparsity)
        return draft


class Seq2SeqAdapter(_AdapterBase):
    """Translation transformer: "prefill" is the ENCODER — it turns the
    source sequence into per-layer cross-attention K/V context; the
    decoder prompt is a single BOS and every decode step is query-
    length 1 over the paged self-attention cache plus the fixed cross
    context (masked to the true source length)."""

    def __init__(self, model, params, cap: int, bos_id: int,
                 src_buckets: Sequence[int] = (8, 16, 32, 64),
                 layout=None, weight_quant=None):
        if model.mode != "translation":
            raise ValueError("Seq2SeqAdapter needs a translation-mode "
                             "Transformer")
        super().__init__(model, params, layout=layout,
                         weight_quant=weight_quant)
        layer = model.decoder[0].self_attn
        self.num_heads = layer.num_heads
        self.head_dim = layer.head_dim
        self.num_layers = len(model.decoder)
        self.vocab = model.vocab_size
        self.bos_id = bos_id
        self.src_buckets = tuple(sorted(src_buckets))
        self.src_cap = self.src_buckets[-1]
        self._pe = positional_encoding(cap + 1, model.hidden_size)
        self._scale = jnp.sqrt(float(model.hidden_size))
        self._encode_cache: Dict[int, Any] = {}

    def ctx_specs(self):
        L, h, hd = self.num_layers, self.num_heads, self.head_dim
        return {
            "ck": ((L, h, self.src_cap, hd), jnp.float32),
            "cv": ((L, h, self.src_cap, hd), jnp.float32),
            "src_len": ((), jnp.int32),
        }

    def _encode_fn(self, bucket: int):
        fn = self._encode_cache.get(bucket)
        if fn is None:
            model, params = self.model, self.params

            def encode(src, src_len):
                # key-padding mask keeps padded source positions out of
                # encoder attention, so a bucket-padded encode matches
                # the exact-length encode row-for-row
                mask = (jnp.arange(bucket) < src_len)[None, None, None, :]
                x = model._embed(params, src)
                for i, layer in enumerate(model.encoder):
                    x, _ = layer.forward(params[f"enc{i}"], EMPTY, x,
                                         mask=mask)
                cks, cvs = [], []
                pad = self.src_cap - bucket
                for i in range(len(model.decoder)):
                    cp = params[f"dec{i}"]["cross_attn"]
                    ck = self._split(_attn_project(cp, x, "wk", "bk"))
                    cv = self._split(_attn_project(cp, x, "wv", "bv"))
                    cks.append(jnp.pad(
                        ck, ((0, 0), (0, 0), (0, pad), (0, 0)))[0])
                    cvs.append(jnp.pad(
                        cv, ((0, 0), (0, 0), (0, pad), (0, 0)))[0])
                return jnp.stack(cks), jnp.stack(cvs)

            fn = jax.jit(encode)
            self._encode_cache[bucket] = fn
        return fn

    def prepare(self, tokens: np.ndarray):
        src = np.asarray(tokens, np.int32).reshape(1, -1)
        t = src.shape[1]
        bucket = next((b for b in self.src_buckets if b >= t), None)
        if bucket is None:
            raise ValueError(f"source length {t} exceeds the largest "
                             f"src bucket {self.src_buckets[-1]}")
        if bucket > t:
            src = np.pad(src, ((0, 0), (0, bucket - t)))
        ck, cv = self._encode_fn(bucket)(src, np.int32(t))
        ctx = {"ck": ck, "cv": cv, "src_len": np.int32(t)}
        return np.asarray([self.bos_id], np.int32), ctx

    def warmup_buckets(self, sample_src_lens: Optional[Sequence[int]] = None):
        for b in (sample_src_lens or self.src_buckets):
            b = int(b)
            jax.block_until_ready(self._encode_fn(b)(
                np.zeros((1, b), np.int32), np.int32(b)))

    def chunk_forward(self, params, tokens, positions, kbuf, vbuf, ctx,
                      self_attend=None):
        """Decoder step: causal self-attention over the cache plus
        cross-attention over the per-row encoder context — mirrors
        ``transformer_decode_cached`` op-for-op so the engine path
        stays byte-compatible with the legacy one-scan service."""
        B, C = tokens.shape
        cap = self._pe.shape[0] - 1
        q_pos = positions[:, None] + jnp.arange(C)[None, :]
        x = (jnp.take(params["embedding"], tokens.astype(jnp.int32),
                      axis=0) * self._scale
             + self._pe[q_pos].astype(jnp.float32))
        if self_attend is None:
            K = kbuf.shape[3]
            valid = jnp.arange(K)[None, None, :] <= q_pos[:, :, None]
        src_valid = (jnp.arange(self.src_cap)[None, None, :]
                     < ctx["src_len"].reshape(-1, 1, 1))       # (B,1,Tcap)
        src_valid = jnp.broadcast_to(src_valid, (B, C, self.src_cap))
        k_news, v_news = [], []
        for i, layer in enumerate(self.model.decoder):
            lp = params[f"dec{i}"]
            h1, _ = layer.ln1.forward(lp["ln1"], EMPTY, x)
            sp = lp["self_attn"]
            q = self._split(_attn_project(sp, h1, "wq", "bq"))
            k_new = self._split(_attn_project(sp, h1, "wk", "bk"))
            v_new = self._split(_attn_project(sp, h1, "wv", "bv"))
            if self_attend is not None:
                a = self_attend(i, q, k_new, v_new)
            else:
                kb = _write_chunk(kbuf[:, i], positions, k_new, cap)
                vb = _write_chunk(vbuf[:, i], positions, v_new, cap)
                kbuf = kbuf.at[:, i].set(kb)
                vbuf = vbuf.at[:, i].set(vb)
                a = self._attend(q, kb, vb, valid)
            x = x + self._merge(a, x, sp)
            h2, _ = layer.ln2.forward(lp["ln2"], EMPTY, x)
            cp = lp["cross_attn"]
            qc = self._split(_attn_project(cp, h2, "wq", "bq"))
            a = self._attend(qc, ctx["ck"][:, i], ctx["cv"][:, i],
                             src_valid)
            x = x + self._merge(a, x, cp)
            h3, _ = layer.ln3.forward(lp["ln3"], EMPTY, x)
            f, _ = layer.ffn.forward(lp["ffn"], EMPTY, h3)
            x = x + f
            k_news.append(k_new)
            v_news.append(v_new)
        return (self._logits(x), kbuf, vbuf,
                jnp.stack(k_news, 1), jnp.stack(v_news, 1))


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class DecodeEngine:
    """Fixed slot pool + paged KV cache + step-granular scheduling.

    Thread model: clients call :meth:`submit` (any thread); one engine
    thread owns the slots, pages, and device cache buffers.  Results
    are delivered through ``DecodeRequest.wait()`` / ``on_done``;
    per-token streaming through ``on_token`` (called on the engine
    thread — keep callbacks cheap)."""

    def __init__(self, adapter, config: Optional[DecodeConfig] = None,
                 metrics=None, name: str = "decode"):
        self.adapter = adapter
        self.cfg = config or DecodeConfig()
        if metrics is None:
            from bigdl_tpu.optim.metrics import global_metrics

            metrics = global_metrics()
        self.metrics = metrics
        self.name = name
        cfg = self.cfg
        L, h, hd = adapter.num_layers, adapter.num_heads, adapter.head_dim
        if cfg.slots < 2 or cfg.prefill_batch < 2:
            raise ValueError("DecodeConfig.slots and prefill_batch must "
                             "be >= 2 (single-row programs take a "
                             "different XLA reduction path and break "
                             "decode parity)")
        if cfg.kv_dtype not in ("float32", "int8"):
            raise ValueError(f"DecodeConfig.kv_dtype must be 'float32' "
                             f"or 'int8', got {cfg.kv_dtype!r}")
        # int8 pages (docs/quantization.md §Serving memory hierarchy):
        # pages store int8 payloads; one f32 abs-max scale per (layer,
        # page) rides alongside.  The scale tables exist for the f32
        # engine too (L*P floats — noise next to the pool) so every
        # jitted program has ONE signature; the f32 trace just passes
        # them through untouched.
        self._quant_kv = cfg.kv_dtype == "int8"
        kv_dt = jnp.int8 if self._quant_kv else jnp.float32
        self._kv_k = jnp.zeros((L, cfg.total_pages, h, cfg.page_size, hd),
                               kv_dt)
        self._kv_v = jnp.zeros_like(self._kv_k)
        self._kv_sk = jnp.zeros((L, cfg.total_pages), jnp.float32)
        self._kv_sv = jnp.zeros_like(self._kv_sk)
        # pages popped from the free list whose scales still carry the
        # previous owner's value — zeroed (in fixed-width chunks) before
        # the next program dispatch so a reclaimed page can never
        # dequantize stale payload against a stale scale
        self._fresh_pages: List[int] = []
        self._ctx_bufs = {
            k: jnp.zeros((cfg.slots,) + shape, dtype)
            for k, (shape, dtype) in adapter.ctx_specs().items()}
        # host-side slot boards (numpy; converted per dispatch)
        S = cfg.slots
        self._page_table = np.zeros((S, cfg.pages_per_slot), np.int32)
        self._lengths = np.zeros((S,), np.int32)
        self._last_tokens = np.zeros((S,), np.int32)
        self._active_mask = np.zeros((S,), bool)
        # per-slot request SEEDS — the request key fold happens inside
        # the compiled programs (an eager fold_in per admission costs a
        # device round-trip on the hot loop)
        self._seeds = np.zeros((S,), np.int32)
        self._temps = np.zeros((S,), np.float32)
        self._top_ks = np.zeros((S,), np.int32)
        self._top_ps = np.ones((S,), np.float32)
        self._slots: List[Optional[_ActiveSeq]] = [None] * S
        self._free_pages: List[int] = list(range(cfg.total_pages))
        self._reserved_pages = 0
        # prefix/KV reuse (docs/serving.md §Decode fleet): pages held by
        # the cache leave _free_pages — page accounting stays exact
        self._prefix_cache = None
        if cfg.prefix_cache_pages > 0 and cfg.continuous:
            from bigdl_tpu.serving.fleet.prefix_cache import PrefixCache

            self._prefix_cache = PrefixCache(
                min(cfg.prefix_cache_pages, cfg.total_pages),
                cfg.page_size, page_dtype=cfg.kv_dtype)
        # speculative decoding (docs/serving.md §Speculative decoding):
        # the draft's KV pages live in a parallel ALWAYS-f32 pool
        # indexed by the SAME page table — one allocation/release path,
        # so a cancelled or expired slot structurally cannot leak draft
        # pages (tests/test_spec_decode.py pins the regression)
        self._spec = cfg.speculative
        self._draft_model = None
        self._dr_k = self._dr_v = None
        if self._spec is not None:
            sp = self._spec
            if not cfg.continuous:
                raise ValueError("speculative decoding requires "
                                 "continuous mode")
            if adapter.ctx_specs() or not hasattr(adapter,
                                                  "build_draft"):
                raise ValueError(
                    "speculative decoding supports LM adapters only "
                    "(a seq2seq draft would need its own cross "
                    "context)")
            if not 1 <= int(sp.k) < cfg.cap:
                raise ValueError(f"SpecConfig.k must be in [1, "
                                 f"{cfg.cap}), got {sp.k}")
            if sp.verify_impl not in ("auto", "scan", "chunk"):
                raise ValueError(
                    f"SpecConfig.verify_impl {sp.verify_impl!r}: "
                    "auto | scan | chunk")
            if sp.verify_impl == "chunk" and cfg.kv_dtype != "float32":
                raise ValueError(
                    "SpecConfig.verify_impl='chunk' requires f32 KV "
                    "pages (int8 page RMW is per-position; the scan "
                    "verify handles kv_dtype='int8')")
            if sp.draft_window is not None and int(sp.draft_window) < 1:
                raise ValueError(
                    f"SpecConfig.draft_window must be None or >= 1, "
                    f"got {sp.draft_window}")
            self._draft_model = adapter.build_draft(sp)
            self._dr_k = jnp.zeros(
                (L, cfg.total_pages, h, cfg.page_size, hd), jnp.float32)
            self._dr_v = jnp.zeros_like(self._dr_k)
        self._draft_fns: Dict[int, Callable] = {}
        self._verify_fns: Dict[int, Callable] = {}
        self._draft_prefill_fns: Dict[int, Callable] = {}
        self._accept_window = deque(maxlen=256)  # (t, accepted, adjudicated)
        self._import_fn: Optional[Callable] = None
        self._scale_reset_fn: Optional[Callable] = None
        self._base_key = jax.random.PRNGKey(cfg.base_seed)
        # work queue: (deadline_t, seq, req) — the PR 8 deadline-heap
        # ordering at decode-queue granularity
        self._heap: List[Tuple[float, int, DecodeRequest]] = []
        self._seq = itertools.count(1)
        self._wave_steps = 0     # continuous=False: steps into the wave
        self._wave_horizon = cfg.max_new_tokens
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # jitted program caches — keyed by bucket pages (closed set)
        self._step_fns: Dict[int, Callable] = {}
        self._prefill_fns: Dict[int, Callable] = {}
        self._prefill_scratch: Optional[Dict[str, np.ndarray]] = None
        self._gauge_t = 0.0
        self._last_step_t = 0.0
        self._ctx_write_fn: Optional[Callable] = None
        self._static_prefill_fns: Dict[Tuple[int, int], Callable] = {}
        self._static_scan_fns: Dict[Tuple[int, int], Callable] = {}
        # event ring for scheduling specs ("prefill_chunk"/"decode_step")
        # — also dumped by the flight recorder next to metrics_snapshot
        # (weakref'd: a collected engine's ring is pruned, not pinned)
        self.events: deque = deque(maxlen=512)
        flight.register_dump_source(
            f"decode_engine:{name}:{id(self):x}", self._ring_snapshot)
        self._tokens_window = deque(maxlen=256)   # (t, n) for tokens/s
        # cross-thread cancellation: rid -> reason, swept on the engine
        # thread; _iter_lock serializes one engine iteration against
        # migrate_live_slots so an export+freeze is atomic w.r.t. steps
        self._cancelled: Dict[str, str] = {}
        self._iter_lock = threading.Lock()
        self.stats = {"requests": 0, "completed": 0, "expired": 0,
                      "tokens": 0, "steps": 0, "prefill_chunks": 0,
                      "rejected": 0, "kv_exports": 0, "kv_imports": 0,
                      "cancelled": 0, "spec_drafted": 0,
                      "spec_accepted": 0, "spec_rejected": 0}
        self.metrics.describe(
            "serving.decode.tokens_per_s",
            "generated tokens/s over the recent step window")

    # -- client side --------------------------------------------------------
    def submit(self, req: DecodeRequest) -> DecodeRequest:
        if self._stop.is_set():
            raise RuntimeError("decode engine stopped")
        prompt_preview = np.asarray(req.tokens, np.int32).reshape(-1)
        if len(prompt_preview) == 0:
            # an empty prompt would occupy a slot that can never
            # prefill, decode, or expire — reject at the door
            raise ValueError("empty prompt: a generate request needs at "
                             "least one input token")
        if getattr(self.adapter, "bos_id", None) is None \
                and len(prompt_preview) >= self.cfg.cap:
            raise ValueError(
                f"prompt of {len(prompt_preview)} tokens exceeds the "
                f"cache cap {self.cfg.cap} (page_size * pages_per_slot)")
        if req.handoff is not None or req.export_kv:
            self._validate_fleet_request(req, prompt_preview)
        req.admit_t = time.time()
        req.rid = req.rid or f"{self.name}-{next(self._seq)}"
        with self._cv:
            if len(self._heap) >= self.cfg.queue_capacity:
                self.stats["rejected"] += 1
                raise RuntimeError("decode queue full")
            req.seq = next(self._seq)
            heapq.heappush(self._heap, (req.deadline_t, req.seq, req))
            self._cv.notify_all()
        self._ensure_thread()
        return req

    def generate(self, prompts, **kw) -> List[DecodeResult]:
        """Synchronous helper: submit every prompt, wait for all."""
        reqs = [self.submit(DecodeRequest(tokens=np.asarray(p), **kw))
                for p in prompts]
        return [r.wait(timeout=120.0) for r in reqs]

    def submit_prefilled(self, handoff: dict, **kw) -> DecodeRequest:
        """Admit a request whose chunked prefill ran on ANOTHER worker
        (docs/serving.md §Decode fleet): ``handoff`` is the dict
        ``fleet.handoff.unpack_handoff`` returns — prompt tokens, the
        first generated token + its log-prob, and the exact float32
        page images of the prompt's KV.  Sampling params/seed default
        to the handoff's own (they MUST match the prefill's for the
        parity invariant to mean anything); ``kw`` overrides ride
        through to :class:`DecodeRequest` (max_new_tokens, rid,
        on_token, deadline_t...)."""
        meta = {k: handoff[k]
                for k in ("temperature", "top_k", "top_p", "seed")
                if k in handoff}
        meta.update(kw)
        req = DecodeRequest(
            tokens=np.asarray(handoff["tokens"], np.int32),
            handoff=handoff, **meta)
        return self.submit(req)

    def _validate_fleet_request(self, req: DecodeRequest,
                                prompt: np.ndarray) -> None:
        """Reject a malformed handoff/export at the door — once
        admitted it would fail on the engine thread and take the whole
        in-flight batch down with it."""
        if not self.cfg.continuous:
            raise ValueError("KV handoff/export requires continuous mode")
        if self.adapter.ctx_specs():
            raise ValueError(
                "KV handoff/export supports LM adapters only (a seq2seq "
                "'prefill' is the encoder — there are no prompt KV "
                "pages to transfer)")
        if req.handoff is None:
            return
        h = req.handoff
        cfg, a = self.cfg, self.adapter
        hd_dt = str(h.get("kv_dtype", "float32"))
        if hd_dt != cfg.kv_dtype:
            # mixed-dtype pages must never be imported (an f32 engine
            # has no scale tables; an int8 engine would quantize-import
            # an f32 image and silently break handoff parity) — the
            # pool proxy degrades this slot to re-prefill failover
            raise ValueError(
                f"handoff kv_dtype {hd_dt!r} does not match this "
                f"engine's kv_dtype {cfg.kv_dtype!r}; refusing the "
                "page import (re-prefill instead)")
        n = -(-len(prompt) // cfg.page_size)
        want = (a.num_layers, n, a.num_heads, cfg.page_size, a.head_dim)
        k = np.asarray(h.get("k"))
        v = np.asarray(h.get("v"))
        if k.shape != want or v.shape != want:
            raise ValueError(f"handoff K/V shape {k.shape} does not "
                             f"match engine geometry {want}")
        if self._quant_kv:
            ks = np.asarray(h.get("k_scales"))
            vs = np.asarray(h.get("v_scales"))
            if ks.shape != (a.num_layers, n) \
                    or vs.shape != (a.num_layers, n):
                raise ValueError(
                    f"int8 handoff scale shape {ks.shape} does not "
                    f"match (layers, pages) {(a.num_layers, n)}")
        toks = np.asarray(h.get("tokens"), np.int32).reshape(-1)
        if not np.array_equal(toks, prompt):
            raise ValueError("handoff prompt tokens do not match the "
                             "request's tokens")

    def _ring_snapshot(self) -> dict:
        """The scheduling ring (slot admissions, expiries, prefill
        interleave) as one flight-dump line — a decode postmortem needs
        WHAT the scheduler did, not just the counters."""
        return {"engine": self.name,
                "events": [list(e) for e in list(self.events)],
                "stats": dict(self.stats)}

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._heap)

    def active_slots(self) -> int:
        return int(self._active_mask.sum())

    def kv_bytes_per_page(self) -> int:
        """HBM bytes one page row costs across every layer's K AND V
        pool, in the ACTUAL stored dtype — plus, for int8, the two f32
        scales per (layer, page).  This is the figure the wire/HBM
        ledger and the router's capacity scoring price pages by."""
        a = self.adapter
        elems = (a.num_layers * a.num_heads * self.cfg.page_size
                 * a.head_dim)
        itemsize = 1 if self._quant_kv else 4
        scale_bytes = 2 * a.num_layers * 4 if self._quant_kv else 0
        # speculation: every page id also has a row in the f32 draft
        # K/V pool — the fleet router must price that honestly
        draft_bytes = 2 * elems * 4 if self._spec is not None else 0
        return 2 * elems * itemsize + scale_bytes + draft_bytes

    def decode_pressure(self) -> Dict[str, Any]:
        """Admission-pressure snapshot for the fleet router
        (docs/serving.md §Decode fleet): free slots, reservable pages,
        and the prefill backlog (prefilling slots + queued requests).
        Read from any thread — a torn read across fields only skews a
        heuristic score, never correctness."""
        queued = self.queue_depth()
        slots = list(self._slots)
        out = {
            "total_slots": self.cfg.slots,
            "free_slots": sum(s is None for s in slots),
            "total_pages": self.cfg.total_pages,
            "free_pages": max(
                len(self._free_pages) - self._reserved_pages, 0),
            "queued": queued,
            "prefill_backlog": queued + sum(
                1 for s in slots if s is not None and s.prefilling),
            "active": int(self._active_mask.sum()),
            # proof the physical split is live, not just configured
            "kv_exports": self.stats["kv_exports"],
            "kv_imports": self.stats["kv_imports"],
            # page capacity in BYTES, not just counts: the fleet router
            # must not score an int8 worker's free page and an f32
            # worker's free page as equal capacity (docs/serving.md
            # §Decode fleet)
            "page_dtype": self.cfg.kv_dtype,
            "kv_bytes_per_page": self.kv_bytes_per_page(),
            # draft-page accounting is structural (same page ids), so
            # free_pages above is already honest under speculation —
            # these keys just let the router see the mode and the
            # per-iteration page burst (+k positions per active slot)
            "speculative": self._spec is not None,
            "spec_k": int(self._spec.k) if self._spec is not None else 0,
        }
        if self._prefix_cache is not None:
            out["prefix_cache"] = self._prefix_cache.stats()
        return out

    # -- cancellation / live migration (docs/serving.md §Fleet fault
    # tolerance) ------------------------------------------------------------
    def cancel(self, rid: str, reason: str = "cancelled") -> None:
        """Cancel a queued or in-flight request from any thread.  The
        engine thread sweeps the mark at the next iteration: a queued
        request is dropped from the heap, an active slot frees its
        pages immediately (a disconnected stream must not decode to
        ``max_new_tokens`` on a dead socket).  Unknown rids are a no-op
        — the request may have just finished."""
        with self._cv:
            self._cancelled[rid] = reason
            self._cv.notify_all()

    def _sweep_cancelled(self) -> None:
        with self._cv:
            if not self._cancelled:
                return
            marks = self._cancelled
            self._cancelled = {}
            keep = [(d, q, r) for d, q, r in self._heap
                    if r.rid not in marks]
            dropped = [r for _, _, r in self._heap if r.rid in marks]
            if dropped:
                self._heap = keep
                heapq.heapify(self._heap)
        for req in dropped:
            self.events.append(("cancel_queued", req.rid,
                                marks[req.rid]))
            self._count_cancel(marks[req.rid])
            self._finish_error(
                req, RequestCancelledError(req.rid, marks[req.rid]))
        for s, seq in enumerate(self._slots):
            if seq is not None and seq.req.rid in marks:
                reason = marks[seq.req.rid]
                self.events.append(("cancel", seq.req.rid, s, reason))
                self._count_cancel(reason)
                err = RequestCancelledError(seq.req.rid, reason)
                if seq.generated:
                    err.partial_tokens = np.asarray(
                        seq.generated, np.int32)
                self._finish_error(seq.req, err)
                self._release_slot(s)

    def _count_cancel(self, reason: str) -> None:
        self.stats["cancelled"] += 1
        self.metrics.inc("serving.decode.cancelled")
        if reason == "client_disconnect":
            self.metrics.inc("serving.decode.client_disconnects")

    def migrate_live_slots(self) -> Tuple[List[dict], List[str], List[str]]:
        """Freeze-and-export every migratable live slot (docs/serving.md
        §Fleet fault tolerance): under ``_iter_lock`` — atomically
        w.r.t. engine iterations, so no token is emitted after its
        slot's state left — copy each eligible slot's written KV pages
        plus sampling state into a handoff dict the peer can import via
        ``submit_prefilled``, and deactivate the slot.  The caller
        ships the blobs, THEN evicts the frozen rids with
        :meth:`cancel` (``reason="migrated"``), so the peer has parked
        the state before the victim's stream aborts.

        The export is shaped exactly as a fresh prefill of
        ``prompt + generated[:-1]`` would export: ``lengths[s]`` cache
        positions are written (the pending last token's K/V lands next
        step, so it travels as ``first_token``), and the byte-parity
        invariant (counter-based sampling keys at absolute positions)
        makes the importing engine's continuation byte-identical to the
        no-fault run.

        Returns ``(exports, frozen_rids, leftover_rids)`` — leftover =
        live-but-ineligible (still prefilling, no token yet, or
        seq2seq) plus queued generate requests; the caller evicts those
        too and lets the proxy's re-prefill failover recover them."""
        exports: List[dict] = []
        frozen: List[str] = []
        leftover: List[str] = []
        cfg = self.cfg
        if not cfg.continuous:
            return exports, frozen, leftover
        with self._iter_lock:
            for s, seq in enumerate(self._slots):
                if seq is None or seq.done or seq.frozen:
                    continue
                req = seq.req
                eligible = (not seq.ctx and not seq.prefilling
                            and len(seq.generated) >= 1
                            and not req.export_kv)
                if not eligible:
                    leftover.append(req.rid)
                    continue
                n = -(-int(self._lengths[s]) // cfg.page_size)
                pids = np.zeros((cfg.pages_per_slot,), np.int32)
                pids[:n] = self._page_table[s, :n]
                k = np.asarray(self._kv_k[:, pids])[:, :n]
                v = np.asarray(self._kv_v[:, pids])[:, :n]
                tokens = np.concatenate([
                    np.asarray(seq.prompt, np.int32),
                    np.asarray(seq.generated[:-1], np.int32)])
                export = {
                    "tokens": tokens,
                    "first_token": int(seq.generated[-1]),
                    "first_logp": float(seq.last_logp),
                    "temperature": float(req.temperature),
                    "top_k": int(req.top_k),
                    "top_p": float(req.top_p),
                    "seed": int(req.seed),
                    "request_id": req.rid,
                    "migrated": True,
                    "resume_len": len(seq.generated),
                    "kv_dtype": cfg.kv_dtype,
                    "k": k,
                    "v": v,
                }
                if self._quant_kv:
                    export["k_scales"] = np.asarray(
                        self._kv_sk[:, pids], np.float32)[:, :n]
                    export["v_scales"] = np.asarray(
                        self._kv_sv[:, pids], np.float32)[:, :n]
                exports.append(export)
                seq.frozen = True
                self._active_mask[s] = False
                frozen.append(req.rid)
                self.stats["kv_exports"] += 1
                self.metrics.inc("serving.fleet.kv_exports")
                self.events.append(("kv_export", req.rid, int(n)))
            with self._cv:
                leftover.extend(r.rid for _, _, r in self._heap)
        return exports, frozen, leftover

    # -- lifecycle ----------------------------------------------------------
    def _ensure_thread(self) -> None:
        # under the cv lock: concurrent submits must never race TWO
        # engine threads into existence — both would donate the same
        # device cache buffers and poison every later dispatch
        with self._cv:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name=f"decode-{self.name}")
                self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
        # fail whatever is still queued or in flight — explicit verdicts
        with self._cv:
            queued = [r for _, _, r in self._heap]
            self._heap.clear()
        for req in queued:
            self._finish_error(req, RuntimeError(
                f"decode request {req.rid} dropped: engine stopped"))
        if self._thread is not None and self._thread.is_alive():
            # the engine thread is wedged past the join budget: touching
            # slot/page state from here would race its own release path
            # (a double page free = cross-request KV aliasing).  Leak
            # the in-flight requests instead — strictly safer.
            log.error("decode engine thread did not exit within 10s; "
                      "leaving in-flight slots to it")
            return
        for s, seq in enumerate(self._slots):
            if seq is not None:
                if not seq.done:   # a done (gang-mode) seat already
                    #                delivered its result
                    self._finish_error(seq.req, RuntimeError(
                        f"decode request {seq.req.rid} dropped: engine "
                        "stopped"))
                self._release_slot(s)

    def warmup(self) -> "DecodeEngine":
        """Compile the CLOSED program set before traffic: one decode
        step and one prefill program per cache-length bucket (plus the
        adapter's encode buckets), inside ``expected_compile`` so the
        recompile sentinel stays quiet.  After this, a mixed prompt/
        generation-length sweep runs with zero XLA compiles."""
        from bigdl_tpu.obs.attr import expected_compile

        with expected_compile():
            if hasattr(self.adapter, "warmup_buckets"):
                self.adapter.warmup_buckets()
            # the one eager jax op on the admission path: the
            # per-request key fold.  Same shapes for every seed, so one
            # call here keeps the first real admission compile-free
            np.asarray(jax.random.fold_in(self._base_key, 0))
            for nb in self.cfg.len_buckets():
                self._step_fn(nb)
                self._prefill_fn(nb)
                if self._spec is not None:
                    # the draft/verify/draft-prefill programs join the
                    # SAME closed bucket set — a spec-on mixed sweep
                    # stays at zero unexpected recompiles
                    self._draft_fn(nb)
                    self._verify_fn(nb)
                    self._verify_fn(nb, force_scan=True)
                    self._draft_prefill_fn(nb)
            if self._ctx_bufs:
                # CALL the ctx-write program (jit() alone compiles
                # nothing): the first seq2seq admission must not pay —
                # or flag — a mid-traffic compile
                zeros = {k: jnp.zeros_like(v[0])
                         for k, v in self._ctx_bufs.items()}
                self._ctx_bufs = self._ctx_write()(self._ctx_bufs, 0,
                                                   zeros)
            # trace each program once on zero inputs (compile happens at
            # first CALL, not jit(); results discarded, buffers donated
            # copies so live state is untouched)
            self._warm_run()
            if not self.adapter.ctx_specs():
                # the fleet handoff-import scatter (LM only): one fixed
                # shape — all-dropped page ids make the warm call a
                # no-op on the live cache
                cfg = self.cfg
                a = self.adapter
                z = np.zeros((a.num_layers, cfg.pages_per_slot,
                              a.num_heads, cfg.page_size, a.head_dim),
                             np.int8 if self._quant_kv else np.float32)
                zs = np.zeros((a.num_layers, cfg.pages_per_slot),
                              np.float32)
                (self._kv_k, self._kv_v, self._kv_sk,
                 self._kv_sv) = self._import_write()(
                    self._kv_k, self._kv_v, self._kv_sk, self._kv_sv,
                    np.full((cfg.pages_per_slot,), cfg.total_pages,
                            np.int32), z, z, zs, zs)
                # ...and the export gather (same fixed index width)
                np.asarray(self._kv_k[
                    :, np.zeros((cfg.pages_per_slot,), np.int32)])
                jax.block_until_ready(self._kv_k)
        return self

    def _warm_run(self) -> None:
        cfg = self.cfg
        S = cfg.slots
        kv_k, kv_v = self._kv_k, self._kv_v
        kv_sk, kv_sv = self._kv_sk, self._kv_sv
        dr_k, dr_v = self._dr_k, self._dr_v
        for nb in cfg.len_buckets():
            kv_k, kv_v, kv_sk, kv_sv, _, _ = self._step_fn(nb)(
                kv_k, kv_v, kv_sk, kv_sv, self._ctx_bufs,
                self._page_table, np.zeros((S,), np.int32),
                np.zeros((S,), np.int32),
                np.zeros((S,), bool), np.zeros((S,), np.int32),
                np.zeros((S,), np.float32), np.zeros((S,), np.int32),
                np.ones((S,), np.float32))
            B = cfg.prefill_batch
            kv_k, kv_v, kv_sk, kv_sv, _, _ = self._prefill_fn(nb)(
                kv_k, kv_v, kv_sk, kv_sv, self._ctx_bufs,
                np.zeros((B,), np.int32),
                np.zeros((B, cfg.pages_per_slot), np.int32),
                np.zeros((B, cfg.prompt_chunk), np.int32),
                np.zeros((B,), np.int32), np.zeros((B,), np.int32),
                np.zeros((B,), bool), np.zeros((B,), np.int32),
                np.zeros((B,), np.float32), np.zeros((B,), np.int32),
                np.ones((B,), np.float32))
            if self._spec is not None:
                # all-inactive rows: every write masks out, so the warm
                # calls compile without touching live pool state
                dr_k, dr_v, _ = self._draft_fn(nb)(
                    dr_k, dr_v, self._page_table,
                    np.zeros((S,), np.int32), np.zeros((S,), np.int32),
                    np.zeros((S,), bool), np.zeros((S,), np.int32),
                    np.zeros((S,), np.float32),
                    np.zeros((S,), np.int32), np.ones((S,), np.float32))
                for force_scan in (False, True):
                    kv_k, kv_v, kv_sk, kv_sv, _, _ = self._verify_fn(
                        nb, force_scan)(
                        kv_k, kv_v, kv_sk, kv_sv, self._page_table,
                        np.zeros((S,), np.int32),
                        np.zeros((S, self._spec.k + 1), np.int32),
                        np.zeros((S,), np.int32), np.zeros((S,), bool),
                        np.zeros((S,), np.int32),
                        np.zeros((S,), np.float32),
                        np.zeros((S,), np.int32),
                        np.ones((S,), np.float32))
                dr_k, dr_v = self._draft_prefill_fn(nb)(
                    dr_k, dr_v,
                    np.zeros((B, cfg.pages_per_slot), np.int32),
                    np.zeros((B, cfg.prompt_chunk), np.int32),
                    np.zeros((B,), np.int32), np.zeros((B,), bool))
        if self._quant_kv:
            # the scale-reset program (all page ids dropped — no-op on
            # the live tables)
            kv_sk, kv_sv = self._scale_reset()(
                kv_sk, kv_sv,
                np.full((cfg.pages_per_slot,), cfg.total_pages,
                        np.int32))
            # ...and the fixed-width scale gather the harvest/migration
            # exports run
            np.asarray(kv_sk[:, np.zeros((cfg.pages_per_slot,),
                                         np.int32)])
        jax.block_until_ready(kv_k)
        self._kv_k, self._kv_v = kv_k, kv_v
        self._kv_sk, self._kv_sv = kv_sk, kv_sv
        if self._spec is not None:
            jax.block_until_ready(dr_k)
            self._dr_k, self._dr_v = dr_k, dr_v

    # -- jitted programs ----------------------------------------------------
    def _gather(self, kv, pt):
        """(L, P, h, page, hd)[pages pt (B, nb)] -> (B, L, h, nb*page,
        hd) contiguous per-slot cache view."""
        g = kv[:, pt]                       # (L, B, nb, h, page, hd)
        L, B, nb, h, page, hd = g.shape
        return g.transpose(1, 0, 3, 2, 4, 5).reshape(B, L, h, nb * page,
                                                     hd)

    def _write_chunk_pages(self, pool, new, page_table, lengths,
                           active):
        """Persist a speculative chunk's K/V into an f32 page pool with
        ONE page-granular scatter.  ``new`` is (L, B, h, C, hd) — fresh
        K or V for positions ``lengths..lengths+C-1`` per slot.  A
        cell-granular ``.at[:, pid, :, off]`` scatter costs B*C scatter
        rows (XLA CPU serializes them — it dominated the whole verify
        call); the chunk only ever touches ``ceil(C/page)+1``
        consecutive pages per slot, so gather those, splice the chunk
        in with a vectorized ``where``, and write whole pages back."""
        cfg = self.cfg
        page = cfg.page_size
        L, B, h, C, hd = new.shape
        TP = (C - 1) // page + 2          # straddle: one extra page
        p0 = lengths // page
        tp = p0[:, None] + jnp.arange(TP)[None, :]           # (B, TP)
        pid = jnp.take_along_axis(
            page_table, jnp.clip(tp, 0, cfg.pages_per_slot - 1),
            axis=1)
        # out-of-range slots/pages go to the dump index and drop — and
        # never duplicate an in-range pid, keeping the scatter
        # conflict-free (duplicate rows would race)
        pid = jnp.where(active[:, None] & (tp < cfg.pages_per_slot),
                        pid, cfg.total_pages)
        cell = (tp[:, :, None] * page
                + jnp.arange(page)[None, None, :])       # (B, TP, page)
        c = cell - lengths[:, None, None]   # chunk index of each cell
        inside = (c >= 0) & (c < C) & (cell < cfg.cap)
        cc = jnp.clip(c, 0, C - 1).reshape(1, B, 1, TP * page)
        sel = jnp.take_along_axis(
            new, jnp.broadcast_to(cc[..., None], (L, B, h, TP * page,
                                                  hd)), axis=3)
        sel = sel.reshape(L, B, h, TP, page, hd).transpose(
            0, 1, 3, 2, 4, 5)                # (L, B, TP, h, page, hd)
        old = pool[:, pid]
        mask = inside[None, :, :, None, :, None]
        return pool.at[:, pid].set(jnp.where(mask, sel, old),
                                   mode="drop")

    def _gather_deq(self, kv, sc, pt):
        """:meth:`_gather` for int8 pools: dequantize each gathered page
        against its (layer, page) scale before flattening — a freshly
        allocated page carries scale 0.0, so its stale int8 payload
        dequantizes to exact zeros."""
        g = (kv[:, pt].astype(jnp.float32)
             * sc[:, pt][..., None, None, None])
        L, B, nb, h, page, hd = g.shape
        return g.transpose(1, 0, 3, 2, 4, 5).reshape(B, L, h, nb * page,
                                                     hd)

    def _scale_reset(self):
        if self._scale_reset_fn is None:
            def reset(sk, sv, pids):
                return (sk.at[:, pids].set(0.0, mode="drop"),
                        sv.at[:, pids].set(0.0, mode="drop"))

            self._scale_reset_fn = jax.jit(reset, donate_argnums=(0, 1))
        return self._scale_reset_fn

    def _flush_fresh_scales(self) -> None:
        """Zero the scales of pages just popped off the free list (int8
        only), BEFORE the next program dispatch: a reclaimed page
        otherwise inherits its previous owner's scale and dequantizes
        that owner's stale payload — the stale-scale aliasing hazard
        tests/test_quant_serving.py pins.  Fixed ``pages_per_slot``-wide
        chunks (out-of-range padding drops) keep the compile set
        closed."""
        if not self._fresh_pages:
            return
        fresh, self._fresh_pages = self._fresh_pages, []
        if not self._quant_kv:
            return
        W = self.cfg.pages_per_slot
        fn = self._scale_reset()
        for c0 in range(0, len(fresh), W):
            pids = np.full((W,), self.cfg.total_pages, np.int32)
            chunk = fresh[c0:c0 + W]
            pids[:len(chunk)] = chunk
            self._kv_sk, self._kv_sv = fn(self._kv_sk, self._kv_sv,
                                          pids)

    def _use_flash(self) -> bool:
        if self.cfg.use_flash_decode is not None:
            return bool(self.cfg.use_flash_decode)
        from bigdl_tpu.ops.common import on_tpu

        return on_tpu()

    def _step_fn(self, n_blocks: int):
        fn = self._step_fns.get(n_blocks)
        if fn is not None:
            return fn
        cfg = self.cfg
        adapter = self.adapter
        page = cfg.page_size
        use_flash = self._use_flash()
        quant = self._quant_kv

        base_key = jnp.asarray(np.asarray(self._base_key))

        def step(kv_k, kv_v, kv_sk, kv_sv, ctx_bufs, page_table, lengths,
                 last_tokens, active, seeds, temps, top_ks, top_ps):
            keys = jax.vmap(jax.random.fold_in)(
                jnp.broadcast_to(base_key, (seeds.shape[0], 2)), seeds)
            pt = page_table[:, :n_blocks]
            # write target of this step's K/V: the page holding position
            # ``lengths`` (inactive slots get an out-of-range page id ->
            # the scatter drops their write)
            wid = jnp.where(active,
                            jnp.take_along_axis(
                                page_table, (lengths // page)[:, None],
                                axis=1)[:, 0],
                            cfg.total_pages)
            off = lengths % page
            if quant:
                # int8 pages (docs/quantization.md §Serving memory
                # hierarchy): read-modify-write ONLY the page holding
                # this step's position — dequantize it, insert the new
                # row, requantize under a monotone per-page scale (an
                # unchanged page round-trips exactly; see
                # ops.quantized.quantize_pages) — then attend over the
                # dequantized pool.  Both the flash and jnp paths run
                # through the self_attend hook so the quantize-then-
                # attend order (and hence the tokens) agree.
                from bigdl_tpu.ops.flash_attention import \
                    paged_decode_attention
                from bigdl_tpu.ops.quantized import quantize_pages

                kv = {"k": kv_k, "v": kv_v, "sk": kv_sk, "sv": kv_sv}
                B = lengths.shape[0]
                rows = jnp.arange(B)
                K = n_blocks * page
                h, hd = adapter.num_heads, adapter.head_dim

                def rmw(pool, scales, i, new):
                    floor = scales[i, wid]                      # (B,)
                    pg = (pool[i, wid].astype(jnp.float32)
                          * floor[:, None, None, None])      # (B,h,p,hd)
                    pg = pg.at[rows, :, off].set(new[:, :, 0])
                    q, s = quantize_pages(pg, floor_scales=floor)
                    return (pool.at[i, wid].set(q, mode="drop"),
                            scales.at[i, wid].set(s, mode="drop"))

                def self_attend(i, q, k_new, v_new):
                    kv["k"], kv["sk"] = rmw(kv["k"], kv["sk"], i, k_new)
                    kv["v"], kv["sv"] = rmw(kv["v"], kv["sv"], i, v_new)
                    if use_flash:
                        out = paged_decode_attention(
                            q[:, :, 0], kv["k"][i], kv["v"][i], pt,
                            lengths, k_scales=kv["sk"][i],
                            v_scales=kv["sv"][i])
                        return out.astype(jnp.float32)[:, :, None]
                    # gathered-jnp reference: dequantize this layer's
                    # pages and attend over the contiguous view — the
                    # kernel-vs-jnp agreement surface for int8
                    def deq(pool, scales):
                        g = (pool[i][pt].astype(jnp.float32)
                             * scales[i][pt][..., None, None, None])
                        return g.transpose(0, 2, 1, 3, 4).reshape(
                            B, h, K, hd)

                    valid = (jnp.arange(K)[None, :]
                             <= lengths[:, None])[:, None, :]
                    return adapter._attend(q, deq(kv["k"], kv["sk"]),
                                           deq(kv["v"], kv["sv"]),
                                           valid)

                logits, _, _, _, _ = adapter.chunk_forward(
                    adapter.params, last_tokens[:, None], lengths, None,
                    None, ctx_bufs, self_attend=self_attend)
                kv_k, kv_v = kv["k"], kv["v"]
                kv_sk, kv_sv = kv["sk"], kv["sv"]
            elif use_flash:
                # paged flash path: scatter each layer's K/V into the
                # pages FIRST, then run the single-query Pallas kernel
                # straight off the page pool — no gathered cache copy
                from bigdl_tpu.ops.flash_attention import \
                    paged_decode_attention

                kv = {"k": kv_k, "v": kv_v}

                def self_attend(i, q, k_new, v_new):
                    kv["k"] = kv["k"].at[i, wid, :, off].set(
                        k_new[:, :, 0].astype(kv_k.dtype), mode="drop")
                    kv["v"] = kv["v"].at[i, wid, :, off].set(
                        v_new[:, :, 0].astype(kv_v.dtype), mode="drop")
                    out = paged_decode_attention(
                        q[:, :, 0], kv["k"][i], kv["v"][i], pt, lengths)
                    return out.astype(jnp.float32)[:, :, None]

                logits, _, _, _, _ = adapter.chunk_forward(
                    adapter.params, last_tokens[:, None], lengths, None,
                    None, ctx_bufs, self_attend=self_attend)
                kv_k, kv_v = kv["k"], kv["v"]
            else:
                kbuf = self._gather(kv_k, pt)
                vbuf = self._gather(kv_v, pt)
                logits, _, _, k_new, v_new = adapter.chunk_forward(
                    adapter.params, last_tokens[:, None], lengths, kbuf,
                    vbuf, ctx_bufs)
                kv_k = kv_k.at[:, wid, :, off].set(
                    k_new[:, :, :, 0].astype(kv_k.dtype), mode="drop")
                kv_v = kv_v.at[:, wid, :, off].set(
                    v_new[:, :, :, 0].astype(kv_v.dtype), mode="drop")
            tok, logp = _select_tokens(logits[:, 0], keys, lengths + 1,
                                       temps, top_ks, top_ps)
            return kv_k, kv_v, kv_sk, kv_sv, tok, logp

        fn = jax.jit(step, donate_argnums=(0, 1, 2, 3))
        self._step_fns[n_blocks] = fn
        return fn

    def _prefill_fn(self, n_blocks: int):
        """Prefill one chunk for up to ``prefill_batch`` slots in ONE
        program call: attends over the pages written so far, scatters
        every row's chunk K/V into its slot's pages, and selects the
        FIRST generated token from the logits at ``last_index`` (only
        meaningful for rows on their final chunk).  The batch is padded
        to exactly ``prefill_batch`` rows (inactive padding rows write
        nowhere) — one compiled program per cache bucket, and >= 2 rows
        keeps the bit-parity rule.  Per-row ``ctx`` arrives stacked
        (leading dim = prefill_batch)."""
        fn = self._prefill_fns.get(n_blocks)
        if fn is not None:
            return fn
        cfg = self.cfg
        adapter = self.adapter
        page = cfg.page_size
        C = cfg.prompt_chunk
        quant = self._quant_kv

        base_key = jnp.asarray(np.asarray(self._base_key))

        def prefill(kv_k, kv_v, kv_sk, kv_sv, ctx_bufs, slot_idx,
                    pt_rows, tokens, position, last_index, active, seeds,
                    temps, top_ks, top_ps):
            keys = jax.vmap(jax.random.fold_in)(
                jnp.broadcast_to(base_key, (seeds.shape[0], 2)), seeds)
            pt = pt_rows[:, :n_blocks]
            if quant:
                kbuf = self._gather_deq(kv_k, kv_sk, pt)
                vbuf = self._gather_deq(kv_v, kv_sv, pt)
            else:
                kbuf = self._gather(kv_k, pt)
                vbuf = self._gather(kv_v, pt)
            ctx = {k: v[slot_idx] for k, v in ctx_bufs.items()}
            logits, kbuf, vbuf, k_new, v_new = adapter.chunk_forward(
                adapter.params, tokens, position, kbuf, vbuf, ctx)
            last = jnp.take_along_axis(logits,
                                       last_index[:, None, None],
                                       axis=1)[:, 0]              # (B, V)
            sel_pos = position + last_index + 1
            tok, logp = _select_tokens(last, keys, sel_pos, temps,
                                       top_ks, top_ps)
            if quant:
                # whole-page requantize-write-back of ONLY the pages
                # this chunk touched: rows past a slot's allocated count
                # may reference pages another slot owns now (the table
                # is not cleared on release), and the leading rows may
                # be shared prefix-cache pages — neither may be written.
                # Untouched positions inside a touched page came from
                # the dequantized gather, so under the monotone scale
                # floor they requantize exactly (quantize_pages).
                from bigdl_tpu.ops.quantized import quantize_pages

                B = tokens.shape[0]
                L, h, hd = (adapter.num_layers, adapter.num_heads,
                            adapter.head_dim)
                pg0 = jnp.arange(n_blocks)[None, :] * page       # (1,nb)
                lim = jnp.minimum(position + C, cfg.cap)[:, None]
                mask = (active[:, None] & (pg0 < lim)
                        & (pg0 + page > position[:, None]))      # (B,nb)
                pidq = jnp.where(mask, pt, cfg.total_pages)
                floors_k = kv_sk[:, pt]                        # (L,B,nb)
                floors_v = kv_sv[:, pt]

                def wb(pool, scales, buf, floors):
                    pages = buf.reshape(B, L, h, n_blocks, page,
                                        hd).transpose(1, 0, 3, 2, 4, 5)
                    q, s = quantize_pages(pages, floor_scales=floors)
                    return (pool.at[:, pidq].set(q, mode="drop"),
                            scales.at[:, pidq].set(s, mode="drop"))

                kv_k, kv_sk = wb(kv_k, kv_sk, kbuf, floors_k)
                kv_v, kv_sv = wb(kv_v, kv_sv, vbuf, floors_v)
                return kv_k, kv_v, kv_sk, kv_sv, tok, logp
            # scatter each row's chunk into its pages; padding rows and
            # positions past the slot cap (padded final-chunk tails)
            # drop
            pos_c = position[:, None] + jnp.arange(C)[None, :]   # (B, C)
            pid = jnp.take_along_axis(
                pt_rows, jnp.clip(pos_c // page, 0,
                                  cfg.pages_per_slot - 1), axis=1)
            ok = active[:, None] & (pos_c < cfg.cap)
            pid = jnp.where(ok, pid, cfg.total_pages)
            off = pos_c % page
            # kv (L, P, h, page, hd) at [:, pid (B,C), :, off (B,C)]
            # -> (B, C, L, h, hd) value layout
            kv_k = kv_k.at[:, pid, :, off].set(
                k_new.transpose(0, 3, 1, 2, 4).astype(kv_k.dtype),
                mode="drop")
            kv_v = kv_v.at[:, pid, :, off].set(
                v_new.transpose(0, 3, 1, 2, 4).astype(kv_v.dtype),
                mode="drop")
            return kv_k, kv_v, kv_sk, kv_sv, tok, logp

        fn = jax.jit(prefill, donate_argnums=(0, 1, 2, 3))
        self._prefill_fns[n_blocks] = fn
        return fn

    # -- speculative programs (docs/serving.md §Speculative decoding) -------
    def _draft_fn(self, n_blocks: int):
        """Draft ``k+1`` tokens per active slot with the block-sparse
        twin over the f32 draft page pool: gather the slot's draft
        cache once, ``lax.scan`` k+1 single-token steps through
        ``chunk_forward(model=draft)``, then scatter the chunk of fresh
        draft K/V back into the pool.  k+1 steps (not k) because step
        ``j`` writes draft KV at position ``lengths+j`` — the extra
        step fills the cache hole at ``lengths+k`` the full-accept
        bonus token needs on the NEXT iteration.  Selection goes
        through ``_select_tokens`` with the same keys/positions the
        verify uses, so at temperature>0 a close draft samples the same
        token (shared-Gumbel coupling) and acceptance stays high.

        With ``SpecConfig.draft_window=W`` (and a cache bucket wider
        than W) the scan carries a RING of the last W positions'
        draft K/V instead of the full gathered cache: slot ``q % W``
        holds position ``q``, each step overwrites one slot and
        attends the whole ring under a ``q >= 0`` mask.  The draft's
        per-step attention traffic is then O(W) however long the
        sequence grows — the asymmetry speculation lives on, since
        the target still re-reads its full cache but only once per
        k+1 tokens (the verify)."""
        fn = self._draft_fns.get(n_blocks)
        if fn is not None:
            return fn
        cfg = self.cfg
        adapter = self.adapter
        page = cfg.page_size
        k_spec = self._spec.k
        W = self._spec.draft_window
        windowed = W is not None and int(W) < n_blocks * page
        draft_model = self._draft_model
        base_key = jnp.asarray(np.asarray(self._base_key))

        def draft(dr_k, dr_v, page_table, lengths, last_tokens, active,
                  seeds, temps, top_ks, top_ps):
            B = lengths.shape[0]
            keys = jax.vmap(jax.random.fold_in)(
                jnp.broadcast_to(base_key, (B, 2)), seeds)
            pt = page_table[:, :n_blocks]
            if windowed:
                # seed ring slot j with the LAST cached position
                # congruent to j mod W (negative = not cached yet,
                # masked out at attend time)
                q_seed = ((lengths - 1)[:, None]
                          - ((lengths - 1)[:, None] - jnp.arange(W))
                          % W)                               # (B, W)
                cell = jnp.clip(q_seed, 0, cfg.cap - 1)
                pid = jnp.take_along_axis(
                    pt, jnp.clip(cell // page, 0, n_blocks - 1), axis=1)
                off = cell % page
                rows = jnp.arange(B)

                def seed(pool):
                    g = pool[:, pid, :, off]      # (B, W, L, h, hd)
                    return g.transpose(2, 0, 3, 1, 4)  # (L, B, h, W, hd)

                rk, rv = seed(dr_k), seed(dr_v)

                def body(carry, _):
                    rk, rv, pos, last = carry
                    ring = {"k": rk, "v": rv}
                    slot = pos % W
                    # slot j holds position pos - ((pos - j) % W); only
                    # q >= 0 rows are real (short sequences)
                    q_j = (pos[:, None]
                           - (pos[:, None] - jnp.arange(W)) % W)
                    ok = (q_j >= 0)[:, None, :]            # (B, 1, W)

                    def self_attend(i, q, k_new, v_new):
                        ring["k"] = ring["k"].at[i, rows, :, slot].set(
                            k_new[:, :, 0])
                        ring["v"] = ring["v"].at[i, rows, :, slot].set(
                            v_new[:, :, 0])
                        return adapter._attend(q, ring["k"][i],
                                               ring["v"][i], ok)

                    logits, _, _, k_new, v_new = adapter.chunk_forward(
                        adapter.params, last[:, None], pos, None, None,
                        {}, self_attend=self_attend, model=draft_model)
                    tok, _ = _select_tokens(logits[:, 0], keys, pos + 1,
                                            temps, top_ks, top_ps)
                    return ((ring["k"], ring["v"], pos + 1, tok),
                            (tok, k_new[:, :, :, 0], v_new[:, :, :, 0]))

                (_, _, _, _), (toks, k_news, v_news) = jax.lax.scan(
                    body, (rk, rv, lengths, last_tokens), None,
                    length=k_spec + 1)
            else:
                kbuf = self._gather(dr_k, pt)
                vbuf = self._gather(dr_v, pt)

                def body(carry, _):
                    kbuf, vbuf, pos, last = carry
                    logits, kbuf, vbuf, k_new, v_new = \
                        adapter.chunk_forward(
                            adapter.params, last[:, None], pos, kbuf,
                            vbuf, {}, model=draft_model)
                    tok, _ = _select_tokens(logits[:, 0], keys, pos + 1,
                                            temps, top_ks, top_ps)
                    return ((kbuf, vbuf, pos + 1, tok),
                            (tok, k_new[:, :, :, 0], v_new[:, :, :, 0]))

                (_, _, _, _), (toks, k_news, v_news) = jax.lax.scan(
                    body, (kbuf, vbuf, lengths, last_tokens), None,
                    length=k_spec + 1)
            # persist the fresh chunk into the draft pool with one
            # page-granular write (k_news (C, B, L, h, hd) -> the
            # helper's (L, B, h, C, hd) layout); inactive rows and
            # positions past the cap drop
            dr_k = self._write_chunk_pages(
                dr_k, jnp.transpose(k_news, (2, 1, 3, 0, 4)),
                page_table, lengths, active)
            dr_v = self._write_chunk_pages(
                dr_v, jnp.transpose(v_news, (2, 1, 3, 0, 4)),
                page_table, lengths, active)
            return dr_k, dr_v, jnp.moveaxis(toks, 0, 1)       # (B, C)

        fn = jax.jit(draft, donate_argnums=(0, 1))
        self._draft_fns[n_blocks] = fn
        return fn

    def _verify_fn(self, n_blocks: int, force_scan: bool = False):
        """ONE target-model call scoring the whole drafted chunk
        ``[last_token, d_1..d_k]`` at positions ``[lengths..lengths+k]``
        and returning the target's selections for positions
        ``lengths+1..lengths+k+1`` — the tokens the spec-off engine
        would have emitted.

        Two tracings behind one signature, picked by
        ``SpecConfig.verify_impl``: the scan path runs k+1 single-token
        steps that mirror :meth:`_step_fn` OP-FOR-OP (same shapes, same
        pool writes, same selection call), so spec-on output is
        byte-identical to spec-off by construction — one dispatch
        replacing k+1 is where its speedup lives, not a changed
        computation.  The chunk path instead scatters the whole chunk's
        K/V and attends all k+1 queries in one multi-query pass
        (``paged_verify_attention`` on TPU, a gathered causal-staircase
        jnp attention elsewhere) — ~(k+1)x fewer ops, token-stream
        parity with logp allclose-not-bitwise, exactly like the
        spec-off flash path's own contract.  int8 KV always takes the
        scan path (page RMW is per-position).

        ``force_scan`` routes one iteration to the scan tracing even
        when chunk is configured: the chunk attention's last-ulp logit
        drift is harmless under greedy argmax but the top-k/top-p
        threshold masks are DISCONTINUOUS in it (a logit within an ulp
        of the kth value flips in or out of the candidate set), so any
        iteration with a sampled (temperature>0) slot takes the scan
        program and seeded parity stays unconditional.  Both tracings
        join warmup()'s closed set — the fallback is never a
        recompile."""
        cfg = self.cfg
        quant = self._quant_kv
        use_flash = self._use_flash()
        impl = self._spec.verify_impl
        chunk_mode = (not quant) and not force_scan and (
            use_flash if impl == "auto" else impl == "chunk")
        fn = self._verify_fns.get((n_blocks, chunk_mode))
        if fn is not None:
            return fn
        adapter = self.adapter
        page = cfg.page_size
        C = self._spec.k + 1
        base_key = jnp.asarray(np.asarray(self._base_key))

        def verify(kv_k, kv_v, kv_sk, kv_sv, page_table, last_tokens,
                   d_toks, lengths, active, seeds, temps, top_ks,
                   top_ps):
            # the verify row [t_L, d_0..d_{k-1}] is assembled ON DEVICE
            # from the draft program's output, so the engine can enqueue
            # this program without first syncing the draft tokens back
            # to the host — the two dispatches overlap with the host's
            # acceptance bookkeeping
            tokens = jnp.concatenate(
                [last_tokens[:, None].astype(jnp.int32),
                 d_toks[:, :C - 1]], axis=1)
            B = tokens.shape[0]
            keys = jax.vmap(jax.random.fold_in)(
                jnp.broadcast_to(base_key, (B, 2)), seeds)
            pt = page_table[:, :n_blocks]
            if chunk_mode:
                # multi-query chunk path: scatter the chunk's K/V into
                # the pages per layer, then verify straight off the
                # pool (ops.flash_attention.paged_verify_attention)
                from bigdl_tpu.ops.flash_attention import \
                    paged_verify_attention

                pos_c = lengths[:, None] + jnp.arange(C)[None, :]
                pid = jnp.take_along_axis(
                    page_table, jnp.clip(pos_c // page, 0,
                                         cfg.pages_per_slot - 1),
                    axis=1)
                ok = active[:, None] & (pos_c < cfg.cap)
                h, hd = adapter.num_heads, adapter.head_dim
                K = n_blocks * page

                if use_flash:
                    pid = jnp.where(ok, pid, cfg.total_pages)
                    off = pos_c % page
                    kv = {"k": kv_k, "v": kv_v}

                    def self_attend(i, q, k_new, v_new):
                        kv["k"] = kv["k"].at[i, pid, :, off].set(
                            k_new.transpose(0, 2, 1, 3).astype(
                                kv_k.dtype), mode="drop")
                        kv["v"] = kv["v"].at[i, pid, :, off].set(
                            v_new.transpose(0, 2, 1, 3).astype(
                                kv_v.dtype), mode="drop")
                        out = paged_verify_attention(
                            q, kv["k"][i], kv["v"][i], pt, lengths)
                        return out.astype(jnp.float32)

                    logits, _, _, _, _ = adapter.chunk_forward(
                        adapter.params, tokens, lengths, None, None,
                        {}, self_attend=self_attend)
                    out_k, out_v = kv["k"], kv["v"]
                else:
                    # jnp chunk: attend the in-flight chunk K/V from
                    # REGISTERS (old pool keys strictly pre-chunk, the
                    # chunk's own keys under a causal staircase),
                    # merging the two softmaxes flash-style rather than
                    # concatenating buffers (a concat materializes
                    # (B,h,C,K+C) copies per layer — measured, it
                    # dominated the call); no cell-granular pool
                    # scatter on the hot path either — the pool write
                    # happens ONCE below, page-granular
                    news = []
                    scale = 1.0 / np.sqrt(float(hd))
                    old_ok = (jnp.arange(K)[None, None, None, :]
                              < lengths[:, None, None, None])
                    stair = (jnp.arange(C)[None, :]
                             <= jnp.arange(C)[:, None])  # (C, C)

                    # contractions run with (b, h) flattened into one
                    # batch dim — XLA:CPU dispatches a (B*h)-batched
                    # 3D dot far better than the 4D einsum (2.2x at
                    # these shapes); the math is identical
                    dn_k = (((2,), (2,)), ((0,), (0,)))
                    dn_v = (((2,), (1,)), ((0,), (0,)))

                    def self_attend(i, q, k_new, v_new):
                        news.append((k_new, v_new))      # (B, h, C, hd)
                        kb = kv_k[i][pt].transpose(
                            0, 2, 1, 3, 4).reshape(B * h, K, hd)
                        vb = kv_v[i][pt].transpose(
                            0, 2, 1, 3, 4).reshape(B * h, K, hd)
                        qf = (q.astype(jnp.float32) * scale).reshape(
                            B * h, C, hd)
                        s_old = jnp.where(
                            old_ok,
                            jax.lax.dot_general(
                                qf, kb, dn_k,
                                preferred_element_type=jnp.float32
                            ).reshape(B, h, C, K),
                            _NEG_INF)
                        s_new = jnp.where(
                            stair[None, None],
                            jax.lax.dot_general(
                                qf, k_new.reshape(B * h, C, hd), dn_k,
                                preferred_element_type=jnp.float32
                            ).reshape(B, h, C, C),
                            _NEG_INF)
                        # each query attends at least its own chunk key
                        # (the staircase diagonal), so m is finite
                        m = jnp.maximum(s_old.max(-1, keepdims=True),
                                        s_new.max(-1, keepdims=True))
                        eo = jnp.exp(s_old - m)
                        en = jnp.exp(s_new - m)
                        den = (eo.sum(-1, keepdims=True)
                               + en.sum(-1, keepdims=True))
                        out = (jax.lax.dot_general(
                            eo.reshape(B * h, C, K), vb, dn_v,
                            preferred_element_type=jnp.float32)
                            + jax.lax.dot_general(
                                en.reshape(B * h, C, C),
                                v_new.reshape(B * h, C, hd), dn_v,
                                preferred_element_type=jnp.float32))
                        return out.reshape(B, h, C, hd) / den

                    logits, _, _, _, _ = adapter.chunk_forward(
                        adapter.params, tokens, lengths, None, None,
                        {}, self_attend=self_attend)
                    out_k = self._write_chunk_pages(
                        kv_k, jnp.stack([kn for kn, _ in news]),
                        page_table, lengths, active)
                    out_v = self._write_chunk_pages(
                        kv_v, jnp.stack([vn for _, vn in news]),
                        page_table, lengths, active)
                sel_pos = (pos_c + 1).reshape(-1)
                tok, logp = _select_tokens(
                    logits.reshape(B * C, -1),
                    jnp.repeat(keys, C, axis=0), sel_pos,
                    jnp.repeat(temps, C), jnp.repeat(top_ks, C),
                    jnp.repeat(top_ps, C))
                return (out_k, out_v, kv_sk, kv_sv,
                        tok.reshape(B, C), logp.reshape(B, C))

            # sequential-exact path: k+1 _step_fn bodies under one
            # lax.scan — fed tokens are the PREDETERMINED chunk, so
            # there is no data-dependent control flow to trace
            rows = jnp.arange(B)
            K = n_blocks * page
            h, hd = adapter.num_heads, adapter.head_dim

            def body(carry, tok_j):
                kv_k, kv_v, kv_sk, kv_sv, pos = carry
                wid = jnp.where(active,
                                jnp.take_along_axis(
                                    page_table, (pos // page)[:, None],
                                    axis=1)[:, 0],
                                cfg.total_pages)
                off = pos % page
                if quant:
                    from bigdl_tpu.ops.flash_attention import \
                        paged_decode_attention
                    from bigdl_tpu.ops.quantized import quantize_pages

                    kv = {"k": kv_k, "v": kv_v, "sk": kv_sk,
                          "sv": kv_sv}

                    def rmw(pool, scales, i, new):
                        floor = scales[i, wid]
                        pg = (pool[i, wid].astype(jnp.float32)
                              * floor[:, None, None, None])
                        pg = pg.at[rows, :, off].set(new[:, :, 0])
                        q, s = quantize_pages(pg, floor_scales=floor)
                        return (pool.at[i, wid].set(q, mode="drop"),
                                scales.at[i, wid].set(s, mode="drop"))

                    def self_attend(i, q, k_new, v_new):
                        kv["k"], kv["sk"] = rmw(kv["k"], kv["sk"], i,
                                                k_new)
                        kv["v"], kv["sv"] = rmw(kv["v"], kv["sv"], i,
                                                v_new)
                        if use_flash:
                            out = paged_decode_attention(
                                q[:, :, 0], kv["k"][i], kv["v"][i], pt,
                                pos, k_scales=kv["sk"][i],
                                v_scales=kv["sv"][i])
                            return out.astype(jnp.float32)[:, :, None]

                        def deq(pool, scales):
                            g = (pool[i][pt].astype(jnp.float32)
                                 * scales[i][pt][..., None, None, None])
                            return g.transpose(0, 2, 1, 3, 4).reshape(
                                B, h, K, hd)

                        valid = (jnp.arange(K)[None, :]
                                 <= pos[:, None])[:, None, :]
                        return adapter._attend(
                            q, deq(kv["k"], kv["sk"]),
                            deq(kv["v"], kv["sv"]), valid)

                    logits, _, _, _, _ = adapter.chunk_forward(
                        adapter.params, tok_j[:, None], pos, None,
                        None, {}, self_attend=self_attend)
                    kv_k, kv_v = kv["k"], kv["v"]
                    kv_sk, kv_sv = kv["sk"], kv["sv"]
                else:
                    kbuf = self._gather(kv_k, pt)
                    vbuf = self._gather(kv_v, pt)
                    logits, _, _, k_new, v_new = adapter.chunk_forward(
                        adapter.params, tok_j[:, None], pos, kbuf,
                        vbuf, {})
                    kv_k = kv_k.at[:, wid, :, off].set(
                        k_new[:, :, :, 0].astype(kv_k.dtype),
                        mode="drop")
                    kv_v = kv_v.at[:, wid, :, off].set(
                        v_new[:, :, :, 0].astype(kv_v.dtype),
                        mode="drop")
                tok, logp = _select_tokens(logits[:, 0], keys, pos + 1,
                                           temps, top_ks, top_ps)
                return ((kv_k, kv_v, kv_sk, kv_sv, pos + 1),
                        (tok, logp))

            carry, (toks, logps) = jax.lax.scan(
                body, (kv_k, kv_v, kv_sk, kv_sv, lengths),
                jnp.moveaxis(tokens, 0, 1))
            kv_k, kv_v, kv_sk, kv_sv, _ = carry
            return (kv_k, kv_v, kv_sk, kv_sv,
                    jnp.moveaxis(toks, 0, 1), jnp.moveaxis(logps, 0, 1))

        fn = jax.jit(verify, donate_argnums=(0, 1, 2, 3))
        self._verify_fns[(n_blocks, chunk_mode)] = fn
        return fn

    def _draft_prefill_fn(self, n_blocks: int):
        """Mirror of the f32 prefill scatter for the DRAFT pool: the
        draft twin consumes each prompt chunk so a freshly admitted (or
        mid-flight) request has draft KV for its whole prompt before
        its first draft step.  No token selection — the first generated
        token is the TARGET prefill's, identical to spec-off.  A
        handoff-imported slot skips this (its draft pages stay cold:
        drafts start uninformed, acceptance recovers as positions
        fill in; correctness never depends on draft contents)."""
        fn = self._draft_prefill_fns.get(n_blocks)
        if fn is not None:
            return fn
        cfg = self.cfg
        adapter = self.adapter
        page = cfg.page_size
        C = cfg.prompt_chunk
        draft_model = self._draft_model

        def draft_prefill(dr_k, dr_v, pt_rows, tokens, position,
                          active):
            pt = pt_rows[:, :n_blocks]
            kbuf = self._gather(dr_k, pt)
            vbuf = self._gather(dr_v, pt)
            _, _, _, k_new, v_new = adapter.chunk_forward(
                adapter.params, tokens, position, kbuf, vbuf, {},
                model=draft_model)
            pos_c = position[:, None] + jnp.arange(C)[None, :]
            pid = jnp.take_along_axis(
                pt_rows, jnp.clip(pos_c // page, 0,
                                  cfg.pages_per_slot - 1), axis=1)
            ok = active[:, None] & (pos_c < cfg.cap)
            pid = jnp.where(ok, pid, cfg.total_pages)
            off = pos_c % page
            dr_k = dr_k.at[:, pid, :, off].set(
                k_new.transpose(0, 3, 1, 2, 4), mode="drop")
            dr_v = dr_v.at[:, pid, :, off].set(
                v_new.transpose(0, 3, 1, 2, 4), mode="drop")
            return dr_k, dr_v

        fn = jax.jit(draft_prefill, donate_argnums=(0, 1))
        self._draft_prefill_fns[n_blocks] = fn
        return fn

    def _ctx_write(self):
        if self._ctx_write_fn is None:
            def write(bufs, slot, values):
                return {k: jax.lax.dynamic_update_slice(
                    bufs[k], values[k][None].astype(bufs[k].dtype),
                    (slot,) + (0,) * values[k].ndim)
                    for k in bufs}

            self._ctx_write_fn = jax.jit(write, donate_argnums=(0,))
        return self._ctx_write_fn

    def _import_write(self):
        """Scatter a handoff's host KV page images into the pool.  The
        host side is padded to a fixed ``pages_per_slot`` page count
        (surplus rows carry an out-of-range page id and drop), so every
        import — any prompt length — runs ONE compiled program: the
        closed-compile-set discipline holds across the fleet path."""
        if self._import_fn is None:
            def write(kv_k, kv_v, kv_sk, kv_sv, pids, k_host, v_host,
                      sk_host, sv_host):
                # (L, P, h, page, hd) at [:, pids (PPS,)] takes the
                # (L, PPS, h, page, hd) view the host image is shaped as
                kv_k = kv_k.at[:, pids].set(k_host.astype(kv_k.dtype),
                                            mode="drop")
                kv_v = kv_v.at[:, pids].set(v_host.astype(kv_v.dtype),
                                            mode="drop")
                kv_sk = kv_sk.at[:, pids].set(sk_host, mode="drop")
                kv_sv = kv_sv.at[:, pids].set(sv_host, mode="drop")
                return kv_k, kv_v, kv_sk, kv_sv

            self._import_fn = jax.jit(write,
                                      donate_argnums=(0, 1, 2, 3))
        return self._import_fn

    # -- engine loop --------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            occupied = any(s is not None for s in self._slots)
            with self._cv:
                if not self._heap and not occupied:
                    self._cv.wait(0.2)
                    continue
            try:
                with self._iter_lock:
                    now = time.time()
                    self._sweep_cancelled()
                    self._expire(now)
                    self._admit(now)
                    did = self._decode_step()
                    # one prefill call per EMITTED token, not per
                    # iteration: a speculative iteration advances the
                    # decode streams up to k+1 tokens, so a prefilling
                    # slot gets the same interleave bandwidth it would
                    # under plain decode — otherwise admission latency
                    # stretches by the whole chunk factor
                    for _ in range(1 if self._spec is None
                                   else self._spec.k + 1):
                        pf = self._prefill_one()
                        did = pf or did
                        if not pf:
                            break
                if not did:
                    # queued work blocked on slots/pages (or an empty
                    # beat between admission and prefill): wait for a
                    # release/submit notify instead of spinning
                    with self._cv:
                        self._cv.wait(0.05)
            except Exception as e:  # noqa: BLE001 — the engine must
                # outlive one bad batch: fail the in-flight requests
                # with an explicit verdict and keep serving
                log.error("decode engine iteration failed: %s", e,
                          exc_info=True)
                with self._iter_lock:
                    for s, seq in enumerate(self._slots):
                        if seq is not None:
                            self._finish_error(seq.req, e)
                            self._release_slot(s)

    def _expire(self, now: float) -> None:
        """Deadline enforcement at BOTH granularities: queued requests
        are dropped at slot pickup (the PR 8 discipline), and ACTIVE
        slots are re-checked per token so an expired streaming request
        frees its slot and pages immediately instead of decoding to
        ``max_new_tokens``."""
        expired_q = []
        with self._cv:
            # the heap is keyed by deadline, so expired requests sit at
            # the head — O(expired) per sweep, not O(queue)
            while self._heap and self._heap[0][0] <= now:
                expired_q.append(heapq.heappop(self._heap)[2])
        for req in expired_q:
            self.events.append(("expire_queued", req.rid))
            self._finish_expired(req, now)
        for s, seq in enumerate(self._slots):
            if seq is not None and not seq.done \
                    and seq.req.deadline_t <= now:
                self.events.append(("expire", seq.req.rid, s))
                self._finish_expired(seq.req, now, seq=seq)
                self._release_slot(s)

    def _pages_needed(self, prompt_len: int, max_new: int,
                      start: int = 0) -> int:
        """Worst-case page rows the slot's page table will reference.
        ``start`` is where prefill resumes (the prefix-cache attach
        length): chunks then run from ``start``, so the padded final
        chunk can reach past the cold padded extent — the reservation
        must cover it or a padded-tail scatter could pop an
        unreserved page."""
        cfg = self.cfg
        C = cfg.prompt_chunk
        # under speculation every iteration writes up to k positions
        # past the emitted length (draft lookahead + verify chunk), so
        # the worst-case reservation grows by k — admission-time
        # reservation is what keeps _ensure_pages infallible mid-flight
        spec_k = self._spec.k if self._spec is not None else 0
        padded_prompt = min(start + -(-(prompt_len - start) // C) * C,
                            cfg.cap)
        worst = min(max(padded_prompt, prompt_len + max_new + spec_k),
                    cfg.cap)
        return -(-worst // cfg.page_size)

    def _admit(self, now: float) -> None:
        cfg = self.cfg
        if not cfg.continuous and any(s is not None for s in self._slots):
            return   # whole-batch-restart baseline: wait for the gang
        while True:
            free = [i for i, s in enumerate(self._slots) if s is None]
            if not free:
                return
            with self._cv:
                if not self._heap:
                    return
                d, _, req = heapq.heappop(self._heap)
                max_new = min(req.max_new_tokens or cfg.max_new_tokens,
                              cfg.cap - 1)
                self._cv.notify_all()
            try:
                if req.prepared is None:
                    # cache the prepared form ON the request: a page-
                    # pressure push-back must not re-run the adapter's
                    # prepare (for seq2seq that is a full encoder
                    # forward) on every engine iteration
                    req.prepared = self.adapter.prepare(req.tokens)
                prompt, ctx = req.prepared
            except Exception as e:  # noqa: BLE001 — bad request only
                self._finish_error(req, e)
                continue
            if len(prompt) == 0:
                self._finish_error(req, ValueError(
                    "adapter produced an empty decoder prompt"))
                continue
            max_new = min(max_new, cfg.cap - len(prompt))
            if max_new <= 0:
                self._finish_error(req, ValueError(
                    f"prompt of {len(prompt)} tokens leaves no room to "
                    f"generate within the cache cap {cfg.cap}"))
                continue
            cache = self._prefix_cache
            attach = None
            if cache is not None and req.handoff is None:
                attach = cache.match(prompt)
            shared = len(attach.pages) if attach is not None else 0
            attach_len = len(attach.key) if attach is not None else 0
            # owned pages only: the shared prefix rows are the cache's
            need = max(self._pages_needed(len(prompt), max_new,
                                          start=attach_len) - shared, 0)
            short = need - (len(self._free_pages) - self._reserved_pages)
            if short > 0 and cache is not None:
                # out of pages: reclaim idle cached prefixes (never a
                # page a live slot references — eviction skips entries
                # with attached slots, and the entry being attached
                # here is shielded)
                freed = cache.evict(short, protect=attach)
                if freed:
                    self._free_pages.extend(freed)
                    self.metrics.inc(
                        "serving.fleet.prefix_cache_evicted_pages",
                        len(freed))
            if len(self._free_pages) - self._reserved_pages < need:
                # not enough reservable pages: push back and wait for a
                # mid-flight release (ordering preserved — same key)
                with self._cv:
                    heapq.heappush(self._heap, (d, req.seq, req))
                return
            s = free[0]
            seq = _ActiveSeq(req, prompt, ctx, reserved=need,
                             max_new=max_new)
            self._reserved_pages += need
            self._slots[s] = seq
            self._lengths[s] = 0
            self._last_tokens[s] = 0
            self._active_mask[s] = False          # active once prefilled
            self._seeds[s] = np.int32(req.seed)
            self._temps[s] = np.float32(req.temperature)
            self._top_ks[s] = np.int32(req.top_k)
            self._top_ps[s] = np.float32(req.top_p)
            if attach is not None:
                # map the cached pages read-only into the leading page-
                # table rows; prefill resumes at the attach boundary
                # (strictly < len(prompt), so the first-token-selecting
                # final chunk always runs here).  Copy-on-extend: writes
                # only ever target rows >= len(shared)
                cache.attach(attach)
                seq.shared = list(attach.pages)
                seq.shared_entry = attach
                seq.prefill_pos = attach_len
                self._page_table[s, :shared] = attach.pages
                self.metrics.inc("serving.fleet.prefix_cache_hits")
                self.events.append(("prefix_attach", req.rid, s,
                                    attach_len))
            elif cache is not None and req.handoff is None:
                cache.record_miss()
                self.metrics.inc("serving.fleet.prefix_cache_misses")
            if ctx:
                vals = {k: v for k, v in ctx.items()}
                self._ctx_bufs = self._ctx_write()(self._ctx_bufs,
                                                   s, vals)
            self.stats["requests"] += 1
            self.metrics.inc("serving.decode.requests")
            self.events.append(("admit", req.rid, s))
            if req.handoff is not None:
                self._import_handoff(s, seq, req)
            tr = trace.active()
            if tr is not None:
                # submit -> slot claim: where a queued stream's time went
                # BEFORE any chip work (docs/observability.md §Decode
                # timelines); correlated by request_id like every
                # serving span
                tr.add_event("decode/admission", req.admit_t, time.time(),
                             request_id=req.rid, slot=s,
                             tenant=req.tenant)

    def _ensure_pages(self, s: int, upto_tokens: int) -> None:
        """Allocate pages for slot ``s`` covering cache positions
        ``[0, upto_tokens)`` — lazily, inside the admission-time
        reservation, so allocation can never fail mid-flight."""
        seq = self._slots[s]
        need = -(-min(upto_tokens, self.cfg.cap) // self.cfg.page_size)
        shared = len(seq.shared)   # prefix-cache rows lead the table
        while shared + len(seq.pages) < need:
            pid = self._free_pages.pop()
            self._reserved_pages -= 1
            self._page_table[s, shared + len(seq.pages)] = pid
            seq.pages.append(pid)
            if self._quant_kv:
                self._fresh_pages.append(pid)

    def _release_slot(self, s: int) -> None:
        seq = self._slots[s]
        if seq is None:
            return
        cache = self._prefix_cache
        pages = seq.pages
        if cache is not None and not seq.shared:
            # donate the page-aligned PROMPT prefix of a cold request:
            # positions < prefill_pos hold exact prompt K/V (decode
            # writes land at >= prompt_len, padded prefill tails at
            # >= prompt_len too), so whole covered pages are reusable
            # byte-for-byte by any prompt sharing the prefix.  Attached
            # requests don't donate — their prefix is already cached.
            n = min(seq.prefill_pos, len(seq.prompt)) \
                // self.cfg.page_size
            if n > 0 and cache.insert(
                    seq.prompt[:n * self.cfg.page_size], pages[:n],
                    page_dtype=self.cfg.kv_dtype):
                self.events.append(("prefix_donate", seq.req.rid, n))
                pages = pages[n:]   # ownership moved to the cache
        self._free_pages.extend(pages)
        self._reserved_pages -= max(seq.reserved - len(seq.pages), 0)
        if seq.shared_entry is not None:
            cache.detach(seq.shared_entry)
        self._slots[s] = None
        self._active_mask[s] = False
        self._lengths[s] = 0
        self.events.append(("release", seq.req.rid, s))
        with self._cv:
            self._cv.notify_all()

    # -- prefill ------------------------------------------------------------
    def _prefill_one(self) -> bool:
        """Run at most ONE prefill call per engine iteration — up to
        ``prefill_batch`` slots advance one chunk each.  The one-call-
        per-iteration interleave keeps a long prompt from ever stalling
        the decode batch; the co-batching keeps admission-heavy traffic
        from becoming dispatch-bound on prefill."""
        cfg = self.cfg
        cand = sorted(
            (self._slots[s].req.seq, s) for s in range(cfg.slots)
            if self._slots[s] is not None and self._slots[s].prefilling)
        if not cand:
            return False
        picked = [s for _, s in cand[:cfg.prefill_batch]]
        B, C = cfg.prefill_batch, cfg.prompt_chunk
        sc = self._prefill_scratch
        if sc is None:
            # jit copies host arrays to device at dispatch, so the
            # scratch block is safely reusable across calls
            sc = self._prefill_scratch = {
                "tokens": np.zeros((B, C), np.int32),
                "position": np.zeros((B,), np.int32),
                "last_index": np.zeros((B,), np.int32),
                "active": np.zeros((B,), bool),
                "seeds": np.zeros((B,), np.int32),
                "temps": np.zeros((B,), np.float32),
                "top_ks": np.zeros((B,), np.int32),
                "top_ps": np.ones((B,), np.float32),
                "slot_idx": np.zeros((B,), np.int32),
                "pt_rows": np.zeros((B, cfg.pages_per_slot), np.int32),
            }
        sc["tokens"][:] = 0
        sc["active"][:] = False
        rows = []              # (b, s, real, final)
        max_need = 1
        for b, s in enumerate(picked):
            seq = self._slots[s]
            p0 = seq.prefill_pos
            chunk = seq.prompt[p0:p0 + C]
            real = len(chunk)
            sc["tokens"][b, :real] = chunk
            sc["position"][b] = p0
            sc["last_index"][b] = real - 1
            sc["active"][b] = True
            sc["seeds"][b] = np.int32(seq.req.seed)
            sc["temps"][b] = seq.req.temperature
            sc["top_ks"][b] = seq.req.top_k
            sc["top_ps"][b] = seq.req.top_p
            sc["slot_idx"][b] = s
            self._ensure_pages(s, min(p0 + C, cfg.cap))
            sc["pt_rows"][b] = self._page_table[s]
            rows.append((b, s, real, (p0 + real) >= len(seq.prompt)))
            max_need = max(max_need, min(p0 + C, cfg.cap))
        nb = cfg.bucket_pages(max_need)
        self._flush_fresh_scales()
        t0 = time.time()
        kv_k, kv_v, kv_sk, kv_sv, tok, logp = self._prefill_fn(nb)(
            self._kv_k, self._kv_v, self._kv_sk, self._kv_sv,
            self._ctx_bufs, sc["slot_idx"], sc["pt_rows"], sc["tokens"],
            sc["position"], sc["last_index"], sc["active"], sc["seeds"],
            sc["temps"], sc["top_ks"], sc["top_ps"])
        self._kv_k, self._kv_v = kv_k, kv_v
        self._kv_sk, self._kv_sv = kv_sk, kv_sv
        if self._spec is not None:
            # the draft twin consumes the same chunk rows so its page
            # pool tracks the prompt position-for-position
            self._dr_k, self._dr_v = self._draft_prefill_fn(nb)(
                self._dr_k, self._dr_v, sc["pt_rows"], sc["tokens"],
                sc["position"], sc["active"])
        toks = np.asarray(tok)
        logps = np.asarray(logp, np.float32)
        now = time.time()
        self.stats["prefill_chunks"] += len(rows)
        self.metrics.inc("serving.decode.prefill_chunks", len(rows))
        self.events.append(("prefill_chunk",
                            [self._slots[s].req.rid for _, s, _, _
                             in rows]))
        tr = trace.active()
        for b, s, real, final in rows:
            seq = self._slots[s]
            if tr is not None:
                # one event per co-batched row: the rows share the wall
                # window of the single prefill call, each joined to its
                # own request by request_id
                tr.add_event("decode/prefill_chunk", t0, now,
                             request_id=seq.req.rid, slot=s,
                             chunk_start=seq.prefill_pos, tokens=real)
            seq.prefill_pos += real
            if final:
                self._lengths[s] = len(seq.prompt)
                self._emit_token(s, seq, int(toks[b]), logps[b], now)
        self.metrics.observe("serving.decode.prefill_s", now - t0)
        return True

    # -- decode -------------------------------------------------------------
    def _decode_step(self) -> bool:
        cfg = self.cfg
        if self._spec is not None:
            return self._spec_step()
        if not cfg.continuous and any(
                s is not None and s.prefilling for s in self._slots):
            # whole-batch-restart mode: the legacy scan only starts
            # once every prompt in the batch is processed — no decode
            # step may run until the whole wave finished prefill (or a
            # late-prefilling member would lose horizon steps)
            return False
        active = [s for s in range(cfg.slots) if self._active_mask[s]]
        occupied = [s for s in range(cfg.slots)
                    if self._slots[s] is not None]
        # whole-batch-restart mode: the wave steps the full horizon even
        # after every row finished (a fixed-length scan cannot exit
        # early) — finished rows ride along inactive, seats held
        static_wave = not cfg.continuous and occupied
        if not active and not static_wave:
            return False
        # chaos seam: a decode worker dying (os._exit) with streams in
        # flight — the pool proxy must fail the streams over
        faults.fire("fleet_worker_kill")
        for s in active:
            self._ensure_pages(s, int(self._lengths[s]) + 1)
        ref = active if active else occupied
        nb = cfg.bucket_pages(int(self._lengths[ref].max()) + 1)
        self._flush_fresh_scales()
        t0 = time.time()
        kv_k, kv_v, kv_sk, kv_sv, toks, logps = self._step_fn(nb)(
            self._kv_k, self._kv_v, self._kv_sk, self._kv_sv,
            self._ctx_bufs, self._page_table, self._lengths,
            self._last_tokens, self._active_mask, self._seeds,
            self._temps, self._top_ks, self._top_ps)
        self._kv_k, self._kv_v = kv_k, kv_v
        self._kv_sk, self._kv_sv = kv_sk, kv_sv
        toks = np.asarray(toks)
        logps = np.asarray(logps, np.float32)
        now = time.time()
        self.stats["steps"] += 1
        self.metrics.inc("serving.decode.steps")
        self.events.append(("decode_step", len(active), nb))
        if active and self._last_step_t:
            # every active slot streams one token per step, so the
            # inter-token latency of EVERY in-flight sequence is the
            # step gap — one observation per step, not one per token
            self.metrics.observe("serving.decode.inter_token_s",
                                 now - self._last_step_t)
        self._last_step_t = now
        n_tok = 0
        tr = trace.active()
        for s in active:
            seq = self._slots[s]
            self._lengths[s] += 1          # last_token's K/V just landed
            self._emit_token(s, seq, int(toks[s]), logps[s], now)
            if tr is not None:
                # per-token step event: every in-flight stream advanced
                # one token inside this step's wall window
                tr.add_event("decode/token_step", t0, now,
                             request_id=seq.req.rid, slot=s,
                             index=len(seq.generated) - 1)
            n_tok += 1
        self._tokens_window.append((now, n_tok))
        self.stats["tokens"] += n_tok
        self.metrics.inc("serving.decode.tokens_total", n_tok)
        self.metrics.observe("serving.decode.step_s", now - t0)
        if not cfg.continuous:
            if self._wave_steps == 0:
                # the wave's scan horizon: the longest member's request
                # (the legacy scan ran max_len steps for everyone; a
                # member asking for more than the config default must
                # not be truncated by its seat-mates)
                self._wave_horizon = max(
                    (s.max_new for s in self._slots if s is not None),
                    default=cfg.max_new_tokens)
            self._wave_steps += 1
            if self._wave_steps >= self._wave_horizon:
                # scan horizon reached: the whole wave restarts at once
                for s in range(cfg.slots):
                    seq = self._slots[s]
                    if seq is not None and not seq.done:
                        self._finish_ok(s, seq, "length")  # defensive
                    if self._slots[s] is not None:
                        self._release_slot(s)
                self._wave_steps = 0
        self._export_gauges(now)
        return True

    def _spec_step(self) -> bool:
        """One speculative iteration: draft k (+1 cache-filling) tokens
        with the sparse twin, verify the chunk with ONE target call,
        then accept the longest agreeing prefix on the host.  Emitted
        tokens are ALWAYS the verify's target selections — the drafted
        token at index j only gates whether the selection CONDITIONED
        on it (index j+1 onward) is usable — so the accepted stream is
        the spec-off stream by construction; speculation only changes
        how many tokens one iteration yields (1 mismatch-correction up
        to k+1 on full agreement, the bonus token included)."""
        cfg = self.cfg
        k = self._spec.k
        active = [s for s in range(cfg.slots) if self._active_mask[s]]
        if not active:
            return False
        faults.fire("fleet_worker_kill")
        for s in active:
            self._ensure_pages(s, min(int(self._lengths[s]) + 1 + k,
                                      cfg.cap))
        nb = cfg.bucket_pages(
            min(int(self._lengths[active].max()) + 1 + k, cfg.cap))
        self._flush_fresh_scales()
        t0 = time.time()
        dr_k, dr_v, d_toks = self._draft_fn(nb)(
            self._dr_k, self._dr_v, self._page_table, self._lengths,
            self._last_tokens, self._active_mask, self._seeds,
            self._temps, self._top_ks, self._top_ps)
        self._dr_k, self._dr_v = dr_k, dr_v
        # enqueue the verify BEHIND the still-running draft — it
        # consumes d_toks on device (the verify row is assembled inside
        # the program), so no host sync sits between the two dispatches.
        # Any sampled slot in the batch routes the iteration to the
        # scan tracing: top-k/top-p thresholds are discontinuous in
        # the chunk attention's ulp drift (see _verify_fn)
        sampled = bool(np.any(np.asarray(self._temps)[active] > 0.0))
        kv_k, kv_v, kv_sk, kv_sv, g_toks, g_logps = self._verify_fn(
            nb, force_scan=sampled)(
            self._kv_k, self._kv_v, self._kv_sk, self._kv_sv,
            self._page_table, self._last_tokens, d_toks, self._lengths,
            self._active_mask, self._seeds, self._temps, self._top_ks,
            self._top_ps)
        self._kv_k, self._kv_v = kv_k, kv_v
        self._kv_sk, self._kv_sv = kv_sk, kv_sv
        jax.block_until_ready(d_toks)   # draft done (verify may still run)
        t1 = time.time()
        d_host = np.asarray(d_toks)                          # (S, k+1)
        g_toks = np.asarray(g_toks)
        g_logps = np.asarray(g_logps, np.float32)
        now = time.time()
        self.stats["steps"] += 1
        self.metrics.inc("serving.decode.steps")
        self.metrics.observe("serving.decode.spec_draft_step_s",
                             t1 - t0)
        self.metrics.observe("serving.decode.spec_verify_step_s",
                             now - t1)
        self.events.append(("spec_step", len(active), nb))
        if self._last_step_t:
            # under speculation the step gap covers up to k+1 tokens
            # per stream — still the honest stream-stall figure
            self.metrics.observe("serving.decode.inter_token_s",
                                 now - self._last_step_t)
        self._last_step_t = now
        n_tok = 0
        drafted = accepted = rejected = 0
        tr = trace.active()
        for s in active:
            seq = self._slots[s]
            emitted = 0
            mismatch = False
            for j in range(k + 1):
                if j >= 1 and int(d_host[s, j - 1]) != int(
                        g_toks[s, j - 1]):
                    # the token fed at query j disagreed with the
                    # target's selection for that position (which was
                    # already emitted as the correction): everything
                    # from j on is conditioned on a token the target
                    # did not pick — stale pool K/V past ``lengths`` is
                    # overwritten before the next iteration attends
                    mismatch = True
                    break
                self._lengths[s] += 1   # the fed token's K/V landed
                self._emit_token(s, seq, int(g_toks[s, j]),
                                 g_logps[s, j], now)
                emitted += 1
                n_tok += 1
                if self._slots[s] is not seq or seq.done:
                    break               # eos / length freed the slot
            # accepted = draft tokens the target agreed with; rejected
            # = mismatch only (at most 1 per chunk — it ends the
            # chunk).  Drafts past an eos/length finish were never
            # adjudicated: they count as drafted (wasted work shows in
            # drafted - accepted - rejected) but not rejected, so a
            # dense twin (sparsity=0.0) pins acceptance at exactly 1.0
            acc = min(max(emitted - 1, 0), k)
            drafted += k
            accepted += acc
            rejected += 1 if mismatch else 0
            if tr is not None:
                tr.add_event("decode/spec_step", t0, now,
                             request_id=seq.req.rid, slot=s,
                             emitted=emitted, accepted=acc)
        self.stats["tokens"] += n_tok
        self.stats["spec_drafted"] += drafted
        self.stats["spec_accepted"] += accepted
        self.stats["spec_rejected"] += rejected
        self.metrics.inc("serving.decode.tokens_total", n_tok)
        self.metrics.inc("serving.decode.spec_drafted_tokens", drafted)
        self.metrics.inc("serving.decode.spec_accepted_tokens",
                         accepted)
        self.metrics.inc("serving.decode.spec_rejected_tokens",
                         rejected)
        self._accept_window.append((now, accepted, accepted + rejected))
        self._tokens_window.append((now, n_tok))
        self.metrics.observe("serving.decode.step_s", now - t0)
        self._export_gauges(now)
        return True

    def _emit_token(self, s: int, seq: _ActiveSeq, tok: int,
                    logp: np.float32, now: float) -> None:
        req = seq.req
        if not seq.generated:
            seq.first_token_t = now
            seq.first_logp = np.float32(logp)
            self.metrics.observe("serving.decode.ttft_s",
                                 now - req.admit_t)
        seq.last_token_t = now
        seq.generated.append(tok)
        seq.logp = np.float32(seq.logp + logp)
        seq.last_logp = np.float32(logp)
        if req.on_token is not None:
            try:
                req.on_token(req.rid, tok, len(seq.generated) - 1)
            except Exception:  # noqa: BLE001 — a slow/broken stream
                pass           # consumer must not kill the engine
        if tok == self.cfg.eos_id:
            self._finish_ok(s, seq, "eos")
        elif len(seq.generated) >= seq.max_new:
            self._finish_ok(s, seq, "length")
        else:
            self._last_tokens[s] = tok
            self._active_mask[s] = True

    def _finish_ok(self, s: int, seq: _ActiveSeq, reason: str) -> None:
        req = seq.req
        req.result = DecodeResult(
            tokens=np.asarray(seq.generated, np.int32),
            logp=float(seq.logp), prompt_len=len(seq.prompt),
            ttft_s=seq.first_token_t - req.admit_t,
            finish_reason=reason)
        if req.export_kv:
            # harvest BEFORE the slot releases its pages: copy the
            # prompt's KV page images to host for the fleet handoff
            self._harvest_kv(s, seq)
        self.stats["completed"] += 1
        self.metrics.inc("serving.decode.completed")
        tr = trace.active()
        if tr is not None:
            t = time.time()
            tr.add_event("decode/publish", t, t, request_id=req.rid,
                         finish_reason=reason,
                         tokens=len(seq.generated))
        if self.cfg.continuous:
            self._release_slot(s)
        else:
            # whole-batch-restart mode: the answer is out, but the SEAT
            # is held to the scan horizon — that is the baseline's cost
            seq.done = True
            self._active_mask[s] = False
        req._event.set()
        if req.on_done is not None:
            try:
                req.on_done(req)
            except Exception:  # noqa: BLE001
                pass

    def _harvest_kv(self, s: int, seq: _ActiveSeq) -> None:
        """Export side of the prefill/decode split: copy the pages
        covering the prompt to host, exactly as float32.  Reads shared
        prefix-cache rows too (read-only), so an attached prefill still
        exports a complete image."""
        cfg = self.cfg
        req = seq.req
        plen = len(seq.prompt)
        n = -(-plen // cfg.page_size)
        # fixed-width gather (surplus rows repeat page 0 and are sliced
        # off on host) so every export — any prompt length — reuses ONE
        # compiled gather: the closed-compile-set discipline again
        pids = np.zeros((cfg.pages_per_slot,), np.int32)
        pids[:n] = self._page_table[s, :n]
        # pages travel in their stored dtype (int8 handoffs are ~4x
        # smaller on the wire); int8 adds the per-(layer, page) scales
        k = np.asarray(self._kv_k[:, pids])[:, :n]
        v = np.asarray(self._kv_v[:, pids])[:, :n]
        req.kv_export = {
            "tokens": np.asarray(seq.prompt, np.int32),
            "first_token": int(seq.generated[0]),
            "first_logp": float(seq.first_logp),
            "temperature": float(req.temperature),
            "top_k": int(req.top_k),
            "top_p": float(req.top_p),
            "seed": int(req.seed),
            "request_id": req.rid,
            "kv_dtype": cfg.kv_dtype,
            "k": k,
            "v": v,
        }
        if self._quant_kv:
            req.kv_export["k_scales"] = np.asarray(
                self._kv_sk[:, pids], np.float32)[:, :n]
            req.kv_export["v_scales"] = np.asarray(
                self._kv_sv[:, pids], np.float32)[:, :n]
        self.stats["kv_exports"] += 1
        self.metrics.inc("serving.fleet.kv_exports")
        self.events.append(("kv_export", req.rid, int(n)))

    def _import_handoff(self, s: int, seq: _ActiveSeq,
                        req: DecodeRequest) -> None:
        """Decode side of the split: materialize pages for the prompt,
        scatter the transferred float32 images into them, and emit the
        prefill worker's first token.  The slot then decodes exactly as
        if the prefill had run locally — same pages-to-positions map,
        same bytes, same counter-based sampling keys."""
        cfg = self.cfg
        h = req.handoff
        plen = len(seq.prompt)
        n = -(-plen // cfg.page_size)
        self._ensure_pages(s, plen)
        self._flush_fresh_scales()
        pids = np.full((cfg.pages_per_slot,), cfg.total_pages, np.int32)
        pids[:n] = self._page_table[s, :n]
        a = self.adapter
        shape = (a.num_layers, cfg.pages_per_slot, a.num_heads,
                 cfg.page_size, a.head_dim)
        dt = np.int8 if self._quant_kv else np.float32
        k_host = np.zeros(shape, dt)
        v_host = np.zeros(shape, dt)
        k_host[:, :n] = np.asarray(h["k"], dt)
        v_host[:, :n] = np.asarray(h["v"], dt)
        sk_host = np.zeros((a.num_layers, cfg.pages_per_slot),
                           np.float32)
        sv_host = np.zeros_like(sk_host)
        if self._quant_kv:
            sk_host[:, :n] = np.asarray(h["k_scales"], np.float32)
            sv_host[:, :n] = np.asarray(h["v_scales"], np.float32)
        (self._kv_k, self._kv_v, self._kv_sk,
         self._kv_sv) = self._import_write()(
            self._kv_k, self._kv_v, self._kv_sk, self._kv_sv, pids,
            k_host, v_host, sk_host, sv_host)
        seq.prefill_pos = plen
        self._lengths[s] = plen
        self.stats["kv_imports"] += 1
        self.metrics.inc("serving.fleet.kv_imports")
        self.events.append(("kv_import", req.rid, s, int(n)))
        self._emit_token(s, seq, int(h["first_token"]),
                         np.float32(h["first_logp"]), time.time())

    def _finish_error(self, req: DecodeRequest, err: Exception) -> None:
        req.error = err
        req._event.set()
        if req.on_done is not None:
            try:
                req.on_done(req)
            except Exception:  # noqa: BLE001
                pass

    def _finish_expired(self, req: DecodeRequest, now: float,
                        seq: Optional[_ActiveSeq] = None) -> None:
        from bigdl_tpu.serving.server import DeadlineExceededError

        self.stats["expired"] += 1
        self.metrics.inc("serving.decode.expired")
        tr = trace.active()
        if tr is not None:
            tr.add_event("decode/publish", now, now, request_id=req.rid,
                         finish_reason="expired")
        err = DeadlineExceededError(req.rid, now - req.admit_t)
        if seq is not None and seq.generated:
            # a streaming request that already produced tokens: the
            # partial result rides on the error for the caller's framing
            err.partial_tokens = np.asarray(seq.generated, np.int32)
        self._finish_error(req, err)

    def _export_gauges(self, now: float) -> None:
        if now - self._gauge_t < 0.05:   # gauge freshness beats paying
            return                       # registry locks on every step
        self._gauge_t = now
        cfg = self.cfg
        self.metrics.gauge("serving.decode.slot_occupancy",
                           float(sum(s is not None for s in self._slots))
                           / cfg.slots)
        used = cfg.total_pages - len(self._free_pages)
        self.metrics.gauge("serving.decode.page_utilization",
                           used / cfg.total_pages)
        self.metrics.gauge("serving.decode.queue_depth",
                           self.queue_depth())
        # constant per engine, but exported so one scrape answers "what
        # does a page cost here" without reading config: int8 pools
        # report ~4x smaller pages (+ the per-page scale pair)
        self.metrics.gauge("serving.decode.kv_bytes_per_page",
                           float(self.kv_bytes_per_page()))
        if self._prefix_cache is not None:
            st = self._prefix_cache.stats()
            self.metrics.gauge("serving.fleet.prefix_cache_pages",
                               st["pages"])
            self.metrics.gauge("serving.fleet.prefix_cache_entries",
                               st["entries"])
        window = [(t, n) for t, n in self._tokens_window
                  if now - t <= 2.0]
        if len(window) >= 2:
            span = now - window[0][0]
            if span > 0:
                self.metrics.gauge("serving.decode.tokens_per_s",
                                   sum(n for _, n in window) / span)
        if self._spec is not None:
            w = [(t, a, d) for t, a, d in self._accept_window
                 if now - t <= 2.0]
            total = sum(d for _, _, d in w)
            if total:
                self.metrics.gauge(
                    "serving.decode.spec_accept_rate",
                    sum(a for _, a, _ in w) / total)

    # -- the one-scan whole-sequence parity reference -----------------------
    def static_generate(self, requests: Sequence[DecodeRequest]
                        ) -> List[DecodeResult]:
        """The byte-identical reference: each request decoded by the
        same chunked prefill followed by ONE ``lax.scan`` over a
        contiguous whole-sequence KV cache (no pages, no slots, no
        scheduling).  Mirrors the PR 8 ``continuous=False`` pattern:
        this path exists to pin the engine's numerics, not to be fast.

        Every request runs at batch 2 (the row duplicated) so every
        matmul keeps >= 2 rows — the same XLA reduction path the
        S-slot engine programs take (see the module docstring)."""
        out = []
        for req in requests:
            prompt, ctx = self.adapter.prepare(req.tokens)
            max_new = min(req.max_new_tokens or self.cfg.max_new_tokens,
                          self.cfg.cap - len(prompt))
            out.append(self._static_one(req, prompt, ctx, max_new))
        return out

    def _static_one(self, req: DecodeRequest, prompt: np.ndarray, ctx,
                    max_new: int) -> DecodeResult:
        cfg = self.cfg
        adapter = self.adapter
        L, h, hd = adapter.num_layers, adapter.num_heads, adapter.head_dim
        B = 2                                  # duplicated row (>= 2 rows)
        Kcap = cfg.cap
        kbuf = jnp.zeros((B, L, h, Kcap, hd), jnp.float32)
        vbuf = jnp.zeros_like(kbuf)
        ctx2 = {k: jnp.stack([v, v]) for k, v in (ctx or {}).items()}
        key = np.asarray(jax.random.fold_in(self._base_key,
                                            int(req.seed)), np.uint32)
        keys2 = jnp.asarray(np.stack([key, key]))
        temps = jnp.full((B,), req.temperature, jnp.float32)
        top_ks = jnp.full((B,), req.top_k, jnp.int32)
        top_ps = jnp.full((B,), req.top_p, jnp.float32)
        C = cfg.prompt_chunk
        first_tok = first_lp = None
        t_admit = time.time()
        for p0 in range(0, len(prompt), C):
            chunk = prompt[p0:p0 + C]
            real = len(chunk)
            if real < C:
                chunk = np.concatenate([chunk,
                                        np.zeros((C - real,), np.int32)])
            fn = self._static_prefill(C)
            kbuf, vbuf, tok, logp = fn(
                kbuf, vbuf, ctx2, jnp.asarray(np.stack([chunk, chunk])),
                jnp.full((B,), p0, jnp.int32),
                jnp.full((B,), real - 1, jnp.int32),
                keys2, temps, top_ks, top_ps)
            first_tok, first_lp = tok, logp
        scan = self._static_scan(max_new)
        toks, logps = scan(kbuf, vbuf, ctx2,
                           jnp.full((B,), len(prompt), jnp.int32),
                           first_tok, keys2, temps, top_ks, top_ps)
        toks = np.asarray(toks)[:, 0]           # (steps,) row 0
        logps = np.asarray(logps, np.float32)[:, 0]
        gen = [int(np.asarray(first_tok)[0])]
        total = np.float32(np.asarray(first_lp, np.float32)[0])
        reason = "length"
        if gen[0] == cfg.eos_id:
            reason = "eos"
        else:
            for t, lp in zip(toks, logps):
                gen.append(int(t))
                total = np.float32(total + lp)
                if int(t) == cfg.eos_id:
                    reason = "eos"
                    break
                if len(gen) >= max_new:
                    break
        return DecodeResult(tokens=np.asarray(gen, np.int32),
                            logp=float(total), prompt_len=len(prompt),
                            ttft_s=time.time() - t_admit,
                            finish_reason=reason)

    def _static_prefill(self, C: int):
        key = (C, 0)
        fn = self._static_prefill_fns.get(key)
        if fn is not None:
            return fn
        adapter = self.adapter
        cap = self.cfg.cap

        def prefill(kbuf, vbuf, ctx, tokens, position, last_index, keys,
                    temps, top_ks, top_ps):
            logits, kbuf, vbuf, _, _ = adapter.chunk_forward(
                adapter.params, tokens, position, kbuf, vbuf, ctx)
            last = jnp.take_along_axis(logits, last_index[:, None, None],
                                       axis=1)[:, 0]
            tok, logp = _select_tokens(last, keys,
                                       position + last_index + 1,
                                       temps, top_ks, top_ps)
            return kbuf, vbuf, tok, logp

        fn = jax.jit(prefill)
        self._static_prefill_fns[key] = fn
        return fn

    def _static_scan(self, max_new: int):
        fn = self._static_scan_fns.get(max_new)
        if fn is not None:
            return fn
        adapter = self.adapter
        eos = self.cfg.eos_id

        def run(kbuf, vbuf, ctx, position, first_tok, keys, temps,
                top_ks, top_ps):
            def body(carry, _):
                kbuf, vbuf, pos, last, done, = carry
                logits, kbuf, vbuf, _, _ = adapter.chunk_forward(
                    adapter.params, last[:, None], pos, kbuf, vbuf, ctx)
                tok, logp = _select_tokens(logits[:, 0], keys, pos + 1,
                                           temps, top_ks, top_ps)
                tok = jnp.where(done, eos, tok)
                logp = jnp.where(done, 0.0, logp)
                done = done | (tok == eos)
                return (kbuf, vbuf, pos + 1, tok, done), (tok, logp)

            done0 = first_tok == eos
            (_, _, _, _, _), (toks, logps) = jax.lax.scan(
                body, (kbuf, vbuf, position, first_tok, done0),
                None, length=max(max_new - 1, 0))
            return toks, logps

        fn = jax.jit(run)
        self._static_scan_fns[max_new] = fn
        return fn
