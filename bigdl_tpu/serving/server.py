"""ServingServer — the Cluster-Serving streaming engine.

Reference analog (unverified — mount empty): ``scala/serving/.../
ClusterServing.scala`` + ``engine/FlinkRedisSource/Sink``: pop a batch of
requests from a Redis list, dynamic-batch up to ``batch_size`` within a
timeout, run ``InferenceModel.doPredict``, write each result back keyed by
request id.

TPU-native: the transport is an in-process (or file-backed) queue pair —
Redis/Flink are cluster plumbing, not capability — while the batching loop,
backpressure and at-least-once result delivery semantics match.  Client
threads only enqueue; the engine owns the chip.

Continuous batching (docs/serving.md §Continuous batching): the engine is
TWO threads with a double-buffered handoff.  The *assembler* builds the
next batch — popping per-model heaps in deadline order, so a near-expiry
request jumps the window — WHILE the *predict* thread runs the current
one; assembly time hides under predict time instead of stalling behind
it, which is what turns the fixed-window loop's 21× p99/p50 tail ratio
into throughput.  Wakeup is event-driven (one condition variable fed by
``enqueue``): no polling loop, no idle CPU burn, no 50 ms of avoidable
sparse-traffic latency.  The legacy fixed-window loop survives behind
``ServingConfig(continuous=False)`` as the parity reference.

Multi-tenancy: a model registry (``register_model``) gives every model its
own bounded admission heap, weighted stride scheduling across tenants
sharing the one predict engine, per-tenant degradation/fallback, and
per-tenant ``serving.tenant.<name>.*`` latency/queue metrics — one
``/metrics`` scrape shows every tenant's SLO.

Request lifecycle (docs/serving.md has the state machine): every request
carries an admission time and an absolute deadline from ``enqueue``
through the queue into the batch loop.  Admission fails fast — a full
queue sheds (``ServiceUnavailableError``, never an unbounded block), a
degraded tenant sheds (half-open probing excepted) — and the engine drops
expired requests BEFORE predict so a slow model never spends chip time
answering a client that already gave up.  Completed results live in a
TTL'd table so an abandoned ``query`` cannot leak entries forever, and
shutdown is explicit: ``drain()`` finishes queued work, plain ``stop()``
fails it with ``RequestDroppedError`` — queued requests are never
silently discarded.
"""

import heapq
import math
import re
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from bigdl_tpu.obs import flight, trace
from bigdl_tpu.optim.metrics import Metrics, Timer, global_metrics
from bigdl_tpu.resilience import faults
from bigdl_tpu.serving.inference_model import InferenceModel
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.serving")

# registered model names become metric names and URL/JSON tokens; keep
# them to the same header-safe grammar as request ids
MODEL_NAME_RE = re.compile(r"[A-Za-z0-9._\-]{1,64}")

DEFAULT_MODEL = "default"


@dataclass
class ServingConfig:
    """Reference config.yaml surface: modelPath, batchSize, timeout."""

    batch_size: int = 32
    batch_timeout_s: float = 0.005
    queue_capacity: int = 4096
    # the engine mode: continuous (assembler builds the next batch while
    # predict runs the current one) vs the legacy fixed-window loop kept
    # as the parity/regression reference
    continuous: bool = True
    # cap on stacked rows per predict batch; None derives it from the
    # model's largest batch bucket so one batch is one compiled program
    max_batch_rows: Optional[int] = None
    # graceful degradation: after this many CONSECUTIVE failed predict
    # batches a TENANT is 'degraded' — it serves from its last-good
    # fallback model if one is set, and sheds new load otherwise
    degraded_after_failures: int = 3
    # half-open probing while degraded WITHOUT a fallback: one request per
    # interval is admitted as a probe so a recovered model can clear
    # degradation by itself (otherwise shedding is permanent: recovery
    # only happens inside _process, which needs an admitted request)
    degraded_probe_interval_s: float = 1.0
    # -- request lifecycle --------------------------------------------------
    # deadline stamped at admission when the caller passes none; None means
    # requests never expire (the pre-lifecycle behavior)
    default_deadline_s: Optional[float] = None
    # how long enqueue may wait on a FULL queue before shedding; 0 sheds
    # immediately.  Bounded by construction — there is no blocking mode
    enqueue_block_s: float = 0.0
    # Retry-After hint attached to sheds (HTTP 429 surfaces it verbatim)
    retry_after_s: float = 1.0
    # completed-but-never-queried results are GC'd after this long; the
    # sweep runs on the engine thread between batches
    result_ttl_s: float = 60.0
    result_gc_interval_s: float = 1.0
    # default budget for stop(drain=True) / drain()
    drain_timeout_s: float = 10.0
    # declarative per-tenant SLOs (docs/observability.md §SLOs & burn
    # rates): a list of spec dicts ({"tenant", "objectives", "window_s"}),
    # inline JSON, or a JSON file path — same grammar as
    # BIGDL_TPU_SLO_SPECS, which applies when this is None.  Evaluation
    # piggybacks on the engine's result-GC tick; burn rates export as
    # slo.* gauges and feed the pool autoscaler's health signal
    slo: Optional[Any] = None
    slo_alert_burn: float = 1.0


class ServiceUnavailableError(RuntimeError):
    """Raised by ``enqueue`` when the server cannot accept the request —
    degraded with no fallback model, queue full (backpressure), draining,
    or stopped — so callers fail fast at admission and retry another
    replica instead of queueing into one that cannot answer.
    ``retry_after`` is the backoff hint (HTTP 429 ``Retry-After``)."""

    def __init__(self, msg: str, retry_after: float = 1.0):
        super().__init__(msg)
        self.retry_after = retry_after


class DeadlineExceededError(TimeoutError):
    """Delivered to ``query`` when the request's deadline passed while it
    waited in the queue — the batch loop dropped it before predict."""

    def __init__(self, rid: str, waited_s: float):
        super().__init__(
            f"request {rid} expired after {waited_s:.3f}s in queue "
            "(deadline passed before predict)")
        self.rid = rid
        self.waited_s = waited_s


class RequestDroppedError(RuntimeError):
    """Delivered to ``query`` for requests still queued when the server
    stopped without (or past) a drain — an explicit verdict, never a
    silent drop."""

    def __init__(self, rid: str):
        super().__init__(f"request {rid} dropped: server stopped before it "
                         "was processed")
        self.rid = rid


@dataclass
class _Request:
    """One queued request: payload + lifecycle timestamps (absolute)."""

    rid: str
    arr: np.ndarray
    admit_t: float
    deadline_t: float  # math.inf when the request never expires
    model: str = DEFAULT_MODEL
    seq: int = 0       # admission order — the deadline-heap tiebreak

    @property
    def rows(self) -> int:
        return self.arr.shape[0] if self.arr.ndim > 1 else 1


@dataclass
class _Tenant:
    """One registered model: its admission heap + scheduling and
    degradation state.  Heap entries are ``(deadline_t, seq, req)`` so
    near-expiry requests sort first and no-deadline requests stay FIFO."""

    name: str
    model: Any
    weight: float = 1.0
    fallback: Optional[Any] = None
    heap: List = field(default_factory=list)
    # stride-scheduling position: the assembler serves the tenant with the
    # lowest pass value; serving k requests advances it by k/weight, so
    # long-run service is proportional to weight
    pass_value: float = 0.0
    degraded: bool = False
    consecutive_failures: int = 0
    last_probe_t: float = 0.0

    def rows_cap(self, cfg: ServingConfig) -> Optional[int]:
        if cfg.max_batch_rows is not None:
            return cfg.max_batch_rows
        buckets = getattr(self.model, "buckets", None)
        return max(buckets) if buckets else None


class _QueueView:
    """Read-only queue facade: ``qsize``/``empty`` over the per-tenant
    heaps, so callers (and tests) that watched the old ``queue.Queue``
    keep one stable surface."""

    def __init__(self, srv: "ServingServer"):
        self._srv = srv

    def qsize(self) -> int:
        with self._srv._work_cv:
            return sum(len(t.heap) for t in self._srv._tenants.values())

    def empty(self) -> bool:
        return self.qsize() == 0


class ServingServer:
    """per-model heaps -> continuous batch assembly -> jitted predict ->
    result table.

    Resilience posture (reference Cluster-Serving keeps serving while a
    replica restarts): a streak of predict failures flips a tenant to
    DEGRADED.  Degraded with a fallback model (``set_fallback_model`` —
    typically the previous good version) keeps answering from it;
    degraded without one sheds that tenant's new load at ``enqueue`` so
    callers retry another replica — other tenants are unaffected.
    ``reload_model`` installs a restarted replica's model and clears
    degradation.

    Every lifecycle event (shed, expiry, drain, drop, GC) lands in
    ``stats`` and — namespaced ``serving.*`` — in the process
    :class:`~bigdl_tpu.optim.metrics.Metrics` registry, so ``/health``
    and training-side metric consumers see the same counters."""

    def __init__(self, model: Optional[InferenceModel] = None,
                 config: Optional[ServingConfig] = None,
                 metrics: Optional[Metrics] = None,
                 models: Optional[Dict[str, Any]] = None):
        self.config = config or ServingConfig()
        self.metrics = metrics or global_metrics()
        self._results: Dict[str, Any] = {}
        self._result_expiry: Dict[str, float] = {}
        # rids admitted but not yet published — with caller-supplied ids
        # (X-Request-Id) a duplicate of an IN-FLIGHT id must be rejected
        # at admission, or two waiters would race one _results slot
        self._pending: set = set()
        # the generate subset of _pending: decode requests live in the
        # engine's slot scheduler, not the tenant heaps, so backlog()
        # would otherwise go blind to them the moment they are admitted
        self._generate_pending: set = set()
        self._result_cv = threading.Condition()
        self._last_gc_t = 0.0
        self._stop = threading.Event()
        self._draining = False
        self._busy = False  # engine is expiring/predicting a batch
        self._threads: List[threading.Thread] = []
        self._probe_lock = threading.Lock()
        # -- work board: tenant heaps + the double-buffered handoff slot.
        # ONE condition carries every engine wakeup: enqueue (new work),
        # batch handoff (slot filled), predict going idle (slot free),
        # heap pops (queue room for bounded enqueue waiters), stop.
        self._work_cv = threading.Condition()
        self._tenants: Dict[str, _Tenant] = {}
        self._slot: Optional[List[_Request]] = None
        self._predict_waiting = False
        self._assembling_n = 0   # requests popped into a batch being built
        self._seq_n = 0
        self._predict_ema_s = 0.01  # urgency horizon for deadline jumps
        self._in = _QueueView(self)
        # fleet role (docs/serving.md §Decode fleet): "both" serves
        # everything; "prefill" workers run chunked prefill and hand KV
        # pages off; "decode" workers run the token loop.  Advisory — the
        # pool proxy routes on it via /health; the server itself never
        # refuses work, so a mis-roled request still gets an answer
        self.role = "both"
        if models:
            for name, m in models.items():
                self.register_model(name, m)
            self._default_name = DEFAULT_MODEL if DEFAULT_MODEL in models \
                else next(iter(models))
        elif model is not None:
            self.register_model(DEFAULT_MODEL, model)
            self._default_name = DEFAULT_MODEL
        else:
            raise ValueError("need a model (or models={name: model, ...})")
        self._stats_lock = threading.Lock()
        self.stats = {"batches": 0, "requests": 0, "failed_batches": 0,
                      "fallback_batches": 0, "shed_requests": 0,
                      "expired_requests": 0, "drained_requests": 0,
                      "dropped_requests": 0, "results_gc": 0}
        # migrated-in KV handoffs parked until the pool proxy re-places
        # the stream here with resume_from (docs/serving.md §Fleet fault
        # tolerance): rid -> (park time, handoff dict).  Bounded + TTL'd
        # — an orphaned park (proxy never resumed) must not pin host KV
        # images forever
        self._parked: Dict[str, tuple] = {}
        self._parked_lock = threading.Lock()
        # /metrics HELP lines for the lifecycle counters a fleet alerts on
        # (obs.export renders describe() strings next to # TYPE)
        self.metrics.describe("serving.shed_requests",
                              "requests rejected at admission "
                              "(backpressure/degraded/draining)")
        self.metrics.describe("serving.expired_requests",
                              "requests dropped before predict: deadline "
                              "already expired")
        self.metrics.describe("serving.predict_s",
                              "model predict wall time per batch")
        self.metrics.describe("serving.queue_wait_s",
                              "admission-to-predict queue wait per request "
                              "(latency_s minus this is predict+publish)")
        self.metrics.describe("serving.batch_occupancy",
                              "cumulative avg batch fill / batch_size")
        self.metrics.describe("serving.queue_depth",
                              "requests queued across all model heaps")
        # declarative SLOs: explicit config wins, BIGDL_TPU_SLO_SPECS
        # applies fleet-wide; a bad spec degrades observability only
        self.slo = None
        try:
            if self.config.slo is not None:
                from bigdl_tpu.obs.slo import SLOEvaluator

                self.slo = SLOEvaluator(
                    self.config.slo, metrics=self.metrics,
                    alert_burn=self.config.slo_alert_burn)
            else:
                from bigdl_tpu.obs.slo import evaluator_from_env

                self.slo = evaluator_from_env(
                    metrics=self.metrics,
                    alert_burn=self.config.slo_alert_burn)
        except Exception as e:  # noqa: BLE001 — serving must start anyway
            log.error("SLO spec unusable (%s); SLO evaluation disabled", e)

    # -- model registry -----------------------------------------------------
    def register_model(self, name: str, model: Any,
                       weight: float = 1.0) -> "ServingServer":
        """Add a tenant: its own bounded queue and SLO accounting, sharing
        this engine's predict loop under weighted admission."""
        if not MODEL_NAME_RE.fullmatch(name):
            raise ValueError(f"bad model name {name!r}: must match "
                             "[A-Za-z0-9._-]{1,64}")
        if weight <= 0:
            raise ValueError(f"model weight must be > 0, got {weight}")
        with self._work_cv:
            if name in self._tenants:
                raise ValueError(f"model {name!r} already registered; use "
                                 "reload_model to replace it")
            t = _Tenant(name, model, float(weight))
            # join the stride rotation at the current frontier: a new
            # tenant must not replay the service its peers already used
            if self._tenants:
                t.pass_value = max(x.pass_value
                                   for x in self._tenants.values())
            self._tenants[name] = t
        self.metrics.describe(f"serving.tenant.{name}.latency_s",
                              f"model {name}: admission-to-publish latency")
        return self

    def unregister_model(self, name: str) -> None:
        """Remove a tenant; its queued requests get an explicit
        :class:`RequestDroppedError` — never a silent drop."""
        if name == self._default_name:
            raise ValueError(f"cannot unregister the default model {name!r}")
        with self._work_cv:
            t = self._tenants.pop(name, None)
            reqs = [r for _, _, r in t.heap] if t else []
            self._work_cv.notify_all()
        if reqs:
            self._deliver_dropped(reqs)

    def models(self) -> Dict[str, dict]:
        """Registry snapshot for ``GET /models`` and the autoscaler."""
        with self._work_cv:
            return {t.name: {"weight": t.weight, "degraded": t.degraded,
                             "queue_depth": len(t.heap),
                             "default": t.name == self._default_name,
                             "fallback": t.fallback is not None}
                    for t in self._tenants.values()}

    def backlog(self) -> int:
        """Admitted requests not yet answered: tenant heaps + the
        assembled handoff slot + a batch mid-assembly + generate
        requests living in the decode engine.  THE autoscaling and
        fleet-routing pressure signal — the heaps alone go quiet once
        the double buffer absorbs a backlog, and generate requests
        never touch the heaps at all (``_QueueView.qsize`` stays
        heap-only: it is the bounded-admission capacity the enqueue
        path enforces)."""
        with self._result_cv:
            generating = len(self._generate_pending)
        with self._work_cv:
            return (sum(len(t.heap) for t in self._tenants.values())
                    + (len(self._slot) if self._slot else 0)
                    + self._assembling_n + generating)

    def decode_pressure(self) -> Dict[str, Any]:
        """Aggregated decode-engine capacity across tenants — the
        ``decode`` block of ``/health`` the fleet router places
        ``/generate`` by (docs/serving.md §Decode fleet).  Only engines
        already built are consulted (a Seq2SeqService's lazy engine is
        not forced into existence by a health probe); numeric fields sum
        across tenants, and ``generate_inflight`` counts admitted
        generate requests not yet resolved."""
        agg: Dict[str, Any] = {}
        for t in list(self._tenants.values()):
            engine = getattr(t.model, "decode_engine", None)
            pressure = getattr(engine, "decode_pressure", None)
            if pressure is None:
                continue
            for k, v in pressure().items():
                if k == "kv_bytes_per_page":
                    # a per-page PROPERTY, not a capacity count: summing
                    # across tenants would inflate it.  Report the max —
                    # the conservative per-page cost for the router
                    agg[k] = max(agg.get(k, 0), v)
                elif k == "page_dtype":
                    # tenants should agree; if they don't, say so rather
                    # than letting the first tenant's dtype win and the
                    # router misprice the rest
                    agg[k] = v if agg.get(k, v) == v else "mixed"
                elif isinstance(v, (int, float)):
                    agg[k] = agg.get(k, 0) + v
                elif k not in agg:   # e.g. the prefix_cache stats dict
                    agg[k] = v
        with self._result_cv:
            agg["generate_inflight"] = len(self._generate_pending)
        return agg

    def slo_health(self) -> float:
        """The SLO health score in [0, 1] (1.0 with no evaluator or no
        verdict yet) — consulted by ``/health``, the pool autoscaler, and
        operator degradation tooling (docs/observability.md §SLOs &
        burn rates)."""
        return self.slo.health_score() if self.slo is not None else 1.0

    def _tenant_series(self, name: str, kind: str, value: float = 1.0
                       ) -> None:
        """One per-tenant signal, recorded BOTH ways: the legacy
        name-embedded ``serving.tenant.<name>.<kind>`` series (deprecated
        alias, kept one release) and the label-form family
        (``serving.tenant_latency_seconds{tenant="..."}`` — the form a
        fleet's Prometheus can aggregate across)."""
        lb = {"tenant": name}
        if kind == "latency":
            self.metrics.observe(f"serving.tenant.{name}.latency_s", value)
            self.metrics.observe("serving.tenant_latency_seconds", value,
                                 labels=lb)
        elif kind == "queue_wait":
            self.metrics.observe(f"serving.tenant.{name}.queue_wait_s",
                                 value)
            self.metrics.observe("serving.tenant_queue_wait_seconds",
                                 value, labels=lb)
        elif kind == "ttft":
            self.metrics.observe(f"serving.tenant.{name}.ttft_s", value)
            self.metrics.observe("serving.tenant_ttft_seconds", value,
                                 labels=lb)
        elif kind == "queue_depth":
            self.metrics.gauge(f"serving.tenant.{name}.queue_depth", value)
            self.metrics.gauge("serving.tenant_queue_depth", value,
                               labels=lb)
        elif kind == "requests":
            self.metrics.inc(f"serving.tenant.{name}.requests", value)
            self.metrics.inc("serving.tenant_requests_total", value,
                             labels=lb)
        elif kind == "expired":
            self.metrics.inc(f"serving.tenant.{name}.expired", value)
            self.metrics.inc("serving.tenant_expired_total", value,
                             labels=lb)
        elif kind == "failed":
            self.metrics.inc(f"serving.tenant.{name}.failed", value)
            self.metrics.inc("serving.tenant_failed_total", value,
                             labels=lb)
        else:  # pragma: no cover — programming error, not data
            raise ValueError(f"unknown tenant series kind {kind!r}")

    def _default(self) -> _Tenant:
        return self._tenants[self._default_name]

    # single-model compatibility surface: the pre-registry API (and the
    # chaos suite) reads/writes these on the server itself
    @property
    def model(self):
        return self._default().model

    @model.setter
    def model(self, m) -> None:
        self._default().model = m

    @property
    def degraded(self) -> bool:
        return self._default().degraded

    @degraded.setter
    def degraded(self, v: bool) -> None:
        self._default().degraded = v

    @property
    def _fallback_model(self):
        return self._default().fallback

    @_fallback_model.setter
    def _fallback_model(self, m) -> None:
        self._default().fallback = m

    @property
    def _last_probe_t(self) -> float:
        return self._default().last_probe_t

    @_last_probe_t.setter
    def _last_probe_t(self, t: float) -> None:
        self._default().last_probe_t = t

    @property
    def _consecutive_failures(self) -> int:
        return self._default().consecutive_failures

    @_consecutive_failures.setter
    def _consecutive_failures(self, n: int) -> None:
        self._default().consecutive_failures = n

    def _count(self, name: str, n: int = 1) -> None:
        # client threads and the engine thread both count; += on a dict
        # entry is not atomic, and tests assert exact counter values
        with self._stats_lock:
            self.stats[name] += n
            self.metrics.inc(f"serving.{name}", n)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ServingServer":
        if self.config.continuous:
            self._threads = [
                threading.Thread(target=self._assemble_run, daemon=True,
                                 name="serving-assembler"),
                threading.Thread(target=self._predict_run, daemon=True,
                                 name="serving-predict"),
            ]
        else:
            self._threads = [threading.Thread(target=self._run_fixed,
                                              daemon=True,
                                              name="serving-engine")]
        for t in self._threads:
            t.start()
        return self

    def _work_pending(self) -> bool:
        """Anything still owed an answer: queued, being assembled, parked
        in the handoff slot, or in predict."""
        with self._work_cv:
            return (self._busy or self._slot is not None
                    or self._assembling_n > 0
                    or any(t.heap for t in self._tenants.values()))

    def drain(self, timeout: Optional[float] = None) -> Dict[str, int]:
        """Graceful shutdown: stop admitting, let the engine finish queued
        and in-flight work within ``timeout``, then stop.  Requests still
        queued when the budget runs out get an explicit
        :class:`RequestDroppedError`.  Returns ``{"drained": n, "dropped":
        m}`` for the caller's log line."""
        timeout = self.config.drain_timeout_s if timeout is None else timeout
        self._draining = True
        t_end = time.time() + timeout
        drained_from = self.stats["requests"]
        while time.time() < t_end:
            if not self._work_pending():
                break
            time.sleep(0.005)
        self._shutdown_threads(join_timeout=max(timeout, 5))
        dropped = self._fail_queued()
        drained = self.stats["requests"] - drained_from
        self._count("drained_requests", drained)
        if dropped:
            log.warning("serving drain: budget exhausted, %d queued "
                        "requests dropped with explicit errors", dropped)
        return {"drained": drained, "dropped": dropped}

    def stop(self, drain: bool = False,
             timeout: Optional[float] = None) -> None:
        """Stop the engine.  ``drain=True`` finishes queued work first
        (see :meth:`drain`); otherwise queued requests are failed
        explicitly with :class:`RequestDroppedError` — never silently
        discarded."""
        if drain:
            self.drain(timeout)
            return
        self._draining = True
        self._shutdown_threads(join_timeout=5)
        self._fail_queued()

    def _shutdown_threads(self, join_timeout: float) -> None:
        self._stop.set()
        with self._work_cv:
            self._work_cv.notify_all()
        for t in self._threads:
            t.join(timeout=join_timeout)

    def _deliver_dropped(self, reqs: List[_Request]) -> int:
        now = time.time()
        with self._result_cv:
            for req in reqs:
                self._results[req.rid] = RequestDroppedError(req.rid)
                self._result_expiry[req.rid] = now + self.config.result_ttl_s
                self._pending.discard(req.rid)
            if reqs:
                self._result_cv.notify_all()
        if reqs:
            self._count("dropped_requests", len(reqs))
            flight.record("serving_requests_dropped", count=len(reqs))
        return len(reqs)

    def _fail_queued(self) -> int:
        """Deliver RequestDroppedError to everything still queued —
        including a batch parked in the handoff slot."""
        with self._work_cv:
            reqs: List[_Request] = []
            for t in self._tenants.values():
                reqs.extend(r for _, _, r in t.heap)
                t.heap.clear()
            if self._slot is not None:
                reqs.extend(self._slot)
                self._slot = None
            self._work_cv.notify_all()
        return self._deliver_dropped(reqs)

    # -- degradation control ------------------------------------------------
    def set_fallback_model(self, model: Any,
                           name: Optional[str] = None) -> "ServingServer":
        """Register the last-good model; while degraded, that tenant's
        batches are served from it instead of failing."""
        self._tenants[name or self._default_name].fallback = model
        return self

    def reload_model(self, model: Any, name: Optional[str] = None) -> None:
        """Install a (restarted) replica's model; the old primary becomes
        the fallback and degradation clears."""
        t = self._tenants[name or self._default_name]
        t.fallback = t.model if not t.degraded else t.fallback
        t.model = model
        t.consecutive_failures = 0
        if t.degraded:
            log.info("serving: model %s reloaded; leaving degraded mode",
                     t.name)
            flight.record("serving_recovered", via="reload_model",
                          model=t.name)
        t.degraded = False

    def predict_inline(self, name: str, arr: np.ndarray) -> np.ndarray:
        """Run one batch through tenant ``name``'s model ON THE CALLER'S
        thread — the pipeline fast path for a stage whose input was
        already produced by an admitted request (recall -> ranking in
        ``friesian/pipeline.py``): candidates never re-enter admission,
        so an accepted recommend cannot be shed halfway through by its
        own second stage.  Tenant health accounting matches the engine
        loop — success clears the failure streak (and degradation),
        failures feed the degradation threshold and the fallback model
        answers when one exists; degraded-without-fallback sheds with
        :class:`ServiceUnavailableError` like admission would."""
        cfg = self.config
        with self._work_cv:
            tenant = self._tenants.get(name)
        if tenant is None:
            raise KeyError(
                f"unknown model {name!r}; registered: "
                f"{sorted(self._tenants)}")
        if tenant.degraded and tenant.fallback is None:
            self._count("shed_requests")
            raise ServiceUnavailableError(
                f"model {name!r} is degraded with no fallback; retry "
                "against another replica", retry_after=cfg.retry_after_s)
        stacked = np.asarray(arr)
        n = int(stacked.shape[0]) if stacked.ndim else 1
        use_fallback = tenant.degraded and tenant.fallback is not None
        primary = tenant.fallback if use_fallback else tenant.model
        t0 = time.time()
        out = None
        try:
            out = primary.predict(stacked)
            tenant.consecutive_failures = 0
            if not use_fallback and tenant.degraded:
                log.info("serving: inline predict recovered; %s leaving "
                         "degraded mode", tenant.name)
                tenant.degraded = False
                flight.record("serving_recovered",
                              via="predict_inline_success",
                              model=tenant.name)
        except Exception as e:
            tenant.consecutive_failures += 1
            self._count("failed_batches")
            if (not tenant.degraded and tenant.consecutive_failures
                    >= cfg.degraded_after_failures):
                tenant.degraded = True
                log.error(
                    "serving: %d consecutive predict failures — model %s "
                    "DEGRADED (%s)", tenant.consecutive_failures,
                    tenant.name,
                    "serving from fallback model"
                    if tenant.fallback is not None
                    else "no fallback: shedding new load")
                flight.record(
                    "serving_degraded", model=tenant.name,
                    consecutive_failures=tenant.consecutive_failures,
                    fallback=tenant.fallback is not None, error=str(e))
            if not use_fallback and tenant.fallback is not None:
                try:
                    out = tenant.fallback.predict(stacked)
                    use_fallback = True
                except Exception as e2:
                    log.error("inline fallback predict also failed: %s",
                              e2)
            if out is None:
                self._tenant_series(name, "failed", float(n))
                raise
        if use_fallback:
            self._count("fallback_batches")
        lat = time.time() - t0
        self._count("batches")
        self._count("requests", n)
        self._tenant_series(name, "requests", float(n))
        self._tenant_series(name, "latency", lat)
        return np.asarray(out)

    # -- client side --------------------------------------------------------
    def enqueue(self, arr: np.ndarray, request_id: Optional[str] = None,
                deadline_s: Optional[float] = None,
                model: Optional[str] = None) -> str:
        """Admit one request for ``model`` (default tenant when None).
        Never blocks beyond ``config.enqueue_block_s``: a full queue, a
        draining/stopped server, or tenant degradation without fallback
        all raise :class:`ServiceUnavailableError` at admission (counted
        as ``shed_requests``).  ``deadline_s`` is relative to now; it
        defaults to ``config.default_deadline_s`` (None = no expiry)."""
        cfg = self.config
        if self._draining or self._stop.is_set():
            self._count("shed_requests")
            raise ServiceUnavailableError(
                "server is draining/stopped; retry against another replica",
                retry_after=cfg.retry_after_s)
        name = model or self._default_name
        tenant = self._tenants.get(name)
        if tenant is None:
            raise KeyError(
                f"unknown model {name!r}; registered: "
                f"{sorted(self._tenants)}")
        if tenant.degraded and tenant.fallback is None:
            # half-open: admit one probe per interval so a recovered
            # model can clear degradation; shed everything else —
            # admission-time fast-fail beats letting the request rot in
            # the queue until the client timeout
            with self._probe_lock:  # check-then-set: exactly ONE probe
                #                     per interval across client threads
                now = time.time()
                is_probe = (now - tenant.last_probe_t
                            >= cfg.degraded_probe_interval_s)
                if is_probe:
                    tenant.last_probe_t = now
                else:
                    self._count("shed_requests")
            if not is_probe:
                raise ServiceUnavailableError(
                    f"model {name} degraded (predict failing) and no "
                    "fallback; shedding load — retry against another "
                    "replica", retry_after=cfg.retry_after_s)
        rid = request_id or uuid.uuid4().hex
        now = time.time()
        if deadline_s is None:
            deadline_s = cfg.default_deadline_s
        deadline_t = now + deadline_s if deadline_s is not None else math.inf
        req = _Request(rid, np.asarray(arr), now, deadline_t, model=name)
        with self._result_cv:
            if rid in self._pending:
                # still in flight: two waiters must not race one result
                # slot — retryable conflict (HTTP 409 upstream); resolves
                # as soon as the first attempt publishes
                raise ValueError(
                    f"request id {rid!r} is already in flight; "
                    "request ids must be unique per outstanding request")
            # completed but never fetched (first waiter gone, or an id
            # deliberately reused with a NEW payload): discard the stale
            # verdict and recompute — adopting it would silently answer
            # the new payload with the old prediction
            self._results.pop(rid, None)
            self._result_expiry.pop(rid, None)
            self._pending.add(rid)
        with trace.span("serving/enqueue", request_id=rid, model=name):
            admitted = self._admit(tenant, req)
        if not admitted:
            with self._result_cv:
                self._pending.discard(rid)
            self._count("shed_requests")
            raise ServiceUnavailableError(
                f"request queue full ({cfg.queue_capacity}); shedding load "
                "— retry after backoff", retry_after=cfg.retry_after_s)
        if self._stop.is_set():
            # raced stop(): the engine may already be gone and _fail_queued
            # past — sweep again so THIS request still gets an explicit
            # verdict (either the engine processed it or it is now failed)
            self._fail_queued()
        return rid

    def _admit(self, tenant: _Tenant, req: _Request) -> bool:
        """Push into the tenant heap, bounded by ``queue_capacity``; waits
        at most ``enqueue_block_s`` for room (0 = immediate verdict).
        The push notifies the assembler — THE event-driven wakeup."""
        cfg = self.config
        t_end = time.time() + cfg.enqueue_block_s
        with self._work_cv:
            while len(tenant.heap) >= cfg.queue_capacity:
                remaining = t_end - time.time()
                if remaining <= 0 or self._stop.is_set() or self._draining:
                    return False
                self._work_cv.wait(remaining)
            req.seq = self._seq_n = self._seq_n + 1
            heapq.heappush(tenant.heap, (req.deadline_t, req.seq, req))
            self._work_cv.notify_all()
        return True

    def enqueue_generate(self, tokens=None, request_id: Optional[str] = None,
                         deadline_s: Optional[float] = None,
                         model: Optional[str] = None,
                         max_new_tokens: Optional[int] = None,
                         temperature: float = 0.0, top_k: int = 0,
                         top_p: float = 1.0, seed: int = 0,
                         on_token=None, handoff: Optional[dict] = None
                         ) -> str:
        """Admit one GENERATE request for ``model``'s continuous decode
        engine (docs/serving.md §Autoregressive decode).  Admission
        mirrors :meth:`enqueue` — draining/degraded/duplicate-id checks,
        deadline stamped here — but the request then lives in the decode
        engine's slot scheduler, not the predict batch heaps: tokens
        stream via ``on_token`` (engine thread) and the final token
        array lands in the result table for :meth:`query`.  Per-token
        deadline enforcement is the engine's: an expired streaming
        request frees its slot immediately and resolves as
        :class:`DeadlineExceededError` (counted under
        ``serving.tenant.<name>.expired``).

        ``handoff`` (docs/serving.md §Decode fleet) is an unpacked KV
        handoff from a ``role="prefill"`` worker: tokens and sampling
        params come from it (the decode must resume under exactly the
        sampling the prefill worker selected the first token with),
        prefill is skipped entirely — the engine imports the shipped
        pages and resumes decode byte-identically to having prefilled
        locally."""
        import math as _math

        from bigdl_tpu.serving.decode_engine import DecodeRequest

        cfg = self.config
        if handoff is not None:
            tokens = handoff["tokens"]
            temperature = handoff.get("temperature", temperature)
            top_k = handoff.get("top_k", top_k)
            top_p = handoff.get("top_p", top_p)
            seed = handoff.get("seed", seed)
        elif tokens is None:
            raise ValueError("enqueue_generate needs tokens (or a handoff)")
        if self._draining or self._stop.is_set():
            self._count("shed_requests")
            raise ServiceUnavailableError(
                "server is draining/stopped; retry against another replica",
                retry_after=cfg.retry_after_s)
        name = model or self._default_name
        tenant = self._tenants.get(name)
        if tenant is None:
            raise KeyError(
                f"unknown model {name!r}; registered: "
                f"{sorted(self._tenants)}")
        engine = getattr(tenant.model, "decode_engine", None)
        if engine is None and hasattr(tenant.model, "_engine"):
            # Seq2SeqService builds its engine lazily on first use — a
            # freshly registered tenant must still serve generates
            engine = tenant.model._engine()
        if engine is None:
            raise TypeError(
                f"model {name!r} has no decode engine; serve it from an "
                "InferenceModel(decode=DecodeConfig(...)) or a "
                "Seq2SeqService")
        if tenant.degraded and tenant.fallback is None:
            self._count("shed_requests")
            raise ServiceUnavailableError(
                f"model {name} degraded; shedding generate load",
                retry_after=cfg.retry_after_s)
        rid = request_id or uuid.uuid4().hex
        now = time.time()
        if deadline_s is None:
            deadline_s = cfg.default_deadline_s
        deadline_t = now + deadline_s if deadline_s is not None \
            else _math.inf
        with self._result_cv:
            if rid in self._pending:
                raise ValueError(
                    f"request id {rid!r} is already in flight; "
                    "request ids must be unique per outstanding request")
            self._results.pop(rid, None)
            self._result_expiry.pop(rid, None)
            self._pending.add(rid)
            self._generate_pending.add(rid)

        def _done(req: DecodeRequest) -> None:
            done_t = time.time()
            if req.error is not None:
                if isinstance(req.error, DeadlineExceededError):
                    self._count("expired_requests")
                    self._tenant_series(name, "expired")
                    flight.record("serving_deadline_drop", count=1,
                                  request_ids=[rid], decode=True)
                verdict: Any = req.error
            else:
                verdict = req.result.tokens
                lat = done_t - req.admit_t
                self.metrics.observe("serving.latency_s", lat)
                self._tenant_series(name, "latency", lat)
                if req.result.ttft_s >= 0:
                    # the decode tail the ttft_p* SLO objectives read
                    self._tenant_series(name, "ttft", req.result.ttft_s)
                self._count("requests")
                self._tenant_series(name, "requests")
            ttl = done_t + cfg.result_ttl_s
            with self._result_cv:
                self._results[rid] = verdict
                self._result_expiry[rid] = ttl
                self._pending.discard(rid)
                self._generate_pending.discard(rid)
                self._result_cv.notify_all()

        req = DecodeRequest(
            tokens=np.asarray(tokens, np.int32), rid=rid, tenant=name,
            max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, seed=seed, deadline_t=deadline_t,
            on_token=on_token, on_done=_done, handoff=handoff)
        with trace.span("serving/enqueue_generate", request_id=rid,
                        model=name):
            try:
                engine.submit(req)
            except RuntimeError as e:
                with self._result_cv:
                    self._pending.discard(rid)
                    self._generate_pending.discard(rid)
                self._count("shed_requests")
                raise ServiceUnavailableError(
                    f"decode queue full: {e}",
                    retry_after=cfg.retry_after_s)
            except Exception:
                # submit-time rejection (e.g. prompt over the cache
                # cap): the id must not stay poisoned in _pending
                with self._result_cv:
                    self._pending.discard(rid)
                    self._generate_pending.discard(rid)
                raise
        return rid

    def prefill_handoff(self, tokens, request_id: Optional[str] = None,
                        model: Optional[str] = None,
                        temperature: float = 0.0, top_k: int = 0,
                        top_p: float = 1.0, seed: int = 0,
                        timeout: float = 30.0) -> dict:
        """Run the prefill half of a split generate request and return
        the KV handoff dict (docs/serving.md §Decode fleet) — what a
        ``role="prefill"`` worker serves at ``POST /fleet/prefill``.

        Synchronous by design: the engine selects the first token during
        the final prefill chunk (one decode step of work), so the caller
        gets tokens + first token + the float32 page images in one call
        and ships them to a decode worker via
        :func:`~bigdl_tpu.serving.fleet.handoff.pack_handoff`.  The
        request never enters the result table — the decode worker owns
        the client-visible request id."""
        from bigdl_tpu.serving.decode_engine import DecodeRequest

        cfg = self.config
        if self._draining or self._stop.is_set():
            self._count("shed_requests")
            raise ServiceUnavailableError(
                "server is draining/stopped; retry against another replica",
                retry_after=cfg.retry_after_s)
        name = model or self._default_name
        tenant = self._tenants.get(name)
        if tenant is None:
            raise KeyError(
                f"unknown model {name!r}; registered: "
                f"{sorted(self._tenants)}")
        engine = getattr(tenant.model, "decode_engine", None)
        if engine is None and hasattr(tenant.model, "_engine"):
            engine = tenant.model._engine()
        if engine is None:
            raise TypeError(
                f"model {name!r} has no decode engine; cannot prefill")
        req = DecodeRequest(
            tokens=np.asarray(tokens, np.int32),
            rid=request_id or uuid.uuid4().hex, tenant=name,
            max_new_tokens=1, temperature=temperature, top_k=top_k,
            top_p=top_p, seed=seed, export_kv=True)
        with trace.span("serving/prefill_handoff", request_id=req.rid,
                        model=name):
            try:
                engine.submit(req)
            except RuntimeError as e:
                self._count("shed_requests")
                raise ServiceUnavailableError(
                    f"decode queue full: {e}", retry_after=cfg.retry_after_s)
            req.wait(timeout)
        if req.error is not None:
            raise req.error
        if req.kv_export is None:  # pragma: no cover - engine bug guard
            raise RuntimeError("prefill finished without a KV export")
        return req.kv_export

    # -- fleet fault tolerance (docs/serving.md §Fleet fault tolerance) ------
    def _engine_for(self, model: Optional[str] = None):
        """The decode engine serving ``model`` (default tenant when
        None), or None when the tenant has no engine built."""
        tenant = self._tenants.get(model or self._default_name)
        if tenant is None:
            return None
        engine = getattr(tenant.model, "decode_engine", None)
        if engine is None and hasattr(tenant.model, "_engine"):
            engine = tenant.model._engine()
        return engine

    def decode_config(self, model: Optional[str] = None):
        """The decode engine's config (cap, max_new_tokens, eos_id) —
        what the frontend's resume_from math needs to reproduce the
        original run's effective token budget."""
        engine = self._engine_for(model)
        return None if engine is None else engine.cfg

    def cancel_generate(self, request_id: str,
                        reason: str = "cancelled") -> None:
        """Cancel an in-flight generate on every tenant engine that
        might hold it — the client went away (broken pipe on the
        stream) or the slot migrated.  Unknown ids are a no-op."""
        for t in list(self._tenants.values()):
            engine = getattr(t.model, "decode_engine", None)
            if engine is not None and hasattr(engine, "cancel"):
                engine.cancel(request_id, reason)

    _PARKED_MAX = 32
    _PARKED_TTL_S = 120.0

    def park_handoff(self, handoff: dict) -> str:
        """Hold a migrated-in KV handoff until the proxy re-places its
        stream here (``POST /fleet/import`` body).  Returns the parked
        request id."""
        rid = str(handoff.get("request_id") or uuid.uuid4().hex)
        now = time.time()
        with self._parked_lock:
            stale = [r for r, (t, _) in self._parked.items()
                     if now - t > self._PARKED_TTL_S]
            for r in stale:
                del self._parked[r]
            while len(self._parked) >= self._PARKED_MAX:
                oldest = min(self._parked, key=lambda r: self._parked[r][0])
                del self._parked[oldest]
            self._parked[rid] = (now, handoff)
        self.metrics.inc("serving.fleet.parked_handoffs")
        return rid

    def take_parked(self, request_id: str) -> Optional[dict]:
        """Pop a parked migration handoff for adoption (returns None
        when absent or expired — the resume falls back to re-prefill)."""
        with self._parked_lock:
            item = self._parked.pop(request_id, None)
        if item is None:
            return None
        t, handoff = item
        if time.time() - t > self._PARKED_TTL_S:
            return None
        return handoff

    def drain_decode(self, peers: List[str],
                     model: Optional[str] = None,
                     timeout: float = 10.0,
                     evict: bool = True) -> Dict[str, Any]:
        """Live-drain this worker's decode state (docs/serving.md
        §Fleet fault tolerance): freeze-and-export every migratable
        slot, ship each as a BDLFKV1 blob to a peer's ``/fleet/import``
        (round-robin over ``peers``), then evict the frozen slots so
        their streams abort and the pool proxy fails them over — onto
        the peer that parked the state, which adopts it instead of
        re-prefilling.  A failed ship (or a ``fleet_handoff_corrupt``
        injection) degrades to the re-prefill failover path: the
        request is never dropped, it just pays a re-prefill.

        With ``evict=False`` the frozen slots are left in place and
        their rids returned under ``"frozen"`` — the pool uses the
        two-phase form (ship, record the migration map, THEN evict) so
        its failover path already knows the adopting peer when the
        victim's streams abort."""
        import urllib.request

        from bigdl_tpu.serving.fleet.handoff import pack_handoff

        engine = self._engine_for(model)
        if engine is None or not hasattr(engine, "migrate_live_slots"):
            return {"migrated": {}, "failed": [], "frozen": []}
        exports, frozen, leftover = engine.migrate_live_slots()
        migrated: Dict[str, str] = {}
        failed: List[str] = list(leftover)
        for i, h in enumerate(exports):
            rid = str(h["request_id"])
            blob = pack_handoff(h)
            try:
                # chaos seam: a corrupted migration blob — the peer's
                # hardened unpack rejects it and the stream recovers
                # through re-prefill failover instead
                faults.fire("fleet_handoff_corrupt")
            except faults.HandoffCorruptFault:
                blob = b"XXXXXXXX" + blob[8:]
            shipped = None
            for j in range(len(peers)):
                peer = peers[(i + j) % len(peers)]
                try:
                    req = urllib.request.Request(
                        peer.rstrip("/") + "/fleet/import", data=blob,
                        headers={"Content-Type":
                                 "application/octet-stream"})
                    with urllib.request.urlopen(
                            req, timeout=timeout) as resp:
                        if resp.status == 200:
                            shipped = peer
                            break
                except Exception as e:  # noqa: BLE001 — degrade, never drop
                    log.warning("KV migration of %s to %s failed: %s",
                                rid, peer, e)
            if shipped is None:
                failed.append(rid)
            else:
                migrated[rid] = shipped
        if evict:
            for rid in frozen:
                engine.cancel(rid, "migrated")
        for rid in leftover:
            engine.cancel(rid, "migrated")
        self.metrics.inc("serving.fleet.migrations", len(migrated))
        flight.record("fleet_drain", migrated=len(migrated),
                      failed=len(failed),
                      request_ids=sorted(migrated))
        return {"migrated": migrated, "failed": failed,
                "frozen": [] if evict else frozen}

    def evict_migrated(self, request_ids: List[str]) -> None:
        """Phase two of a two-phase drain: evict the frozen slots whose
        state already shipped (their streams abort and fail over)."""
        for t in list(self._tenants.values()):
            engine = getattr(t.model, "decode_engine", None)
            if engine is not None and hasattr(engine, "cancel"):
                for rid in request_ids:
                    engine.cancel(rid, "migrated")

    def query(self, request_id: str, timeout: float = 30.0) -> np.ndarray:
        deadline = time.time() + timeout
        with self._result_cv:
            while request_id not in self._results:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(f"result {request_id} not ready")
                self._result_cv.wait(remaining)
            res = self._results.pop(request_id)
            self._result_expiry.pop(request_id, None)
        if isinstance(res, Exception):
            raise res
        return res

    # -- engine: continuous batching ----------------------------------------
    def _pick_tenant(self, now: float) -> Optional[_Tenant]:
        """Weighted, deadline-aware admission (caller holds ``_work_cv``):
        a tenant whose head request is about to expire jumps the weighted
        rotation (earliest deadline first); otherwise stride scheduling —
        lowest pass value — shares the engine by weight."""
        ts = [t for t in self._tenants.values() if t.heap]
        if not ts:
            return None
        horizon = now + self.config.batch_timeout_s \
            + 2 * self._predict_ema_s
        urgent = [t for t in ts if t.heap[0][0] <= horizon]
        if urgent:
            return min(urgent, key=lambda t: t.heap[0][0])
        return min(ts, key=lambda t: (t.pass_value, t.name))

    def _assemble_run(self) -> None:
        """Assembler half of the engine: builds the NEXT batch while the
        predict thread runs the current one, handing off through the
        single-slot buffer.  Exactly one batch ahead: more buffering would
        defeat deadline ordering and inflate effective queue depth."""
        cv = self._work_cv
        while True:
            with cv:
                while not self._stop.is_set() and (
                        self._slot is not None
                        or not any(t.heap
                                   for t in self._tenants.values())):
                    cv.wait()
                if self._stop.is_set():
                    return
                tenant = self._pick_tenant(time.time())
                batch = self._fill_batch(tenant)
                self._assembling_n = 0
                if batch is None:   # stopped mid-fill; requests back home
                    return
                tenant.pass_value += len(batch) / tenant.weight
                self._slot = batch
                cv.notify_all()

    def _fill_batch(self, tenant: _Tenant) -> Optional[List[_Request]]:
        """Build one batch from ``tenant``'s heap (caller holds
        ``_work_cv``; waits release it).  Pops in deadline order; caps at
        ``batch_size`` requests and the model's largest bucket in rows so
        one batch maps onto one compiled program.  While predict is busy
        it keeps accumulating — assembly hides under predict — and once
        predict is waiting it holds the ``batch_timeout_s`` window open
        for stragglers, cut short when a batched deadline would not
        survive the wait."""
        cfg = self.config
        cv = self._work_cv
        rows_cap = tenant.rows_cap(cfg)
        batch: List[_Request] = []
        rows = 0
        t_first = time.time()
        while True:
            if self._stop.is_set():
                # push the partial batch back for _fail_queued's sweep
                for req in batch:
                    heapq.heappush(tenant.heap,
                                   (req.deadline_t, req.seq, req))
                return None
            popped = False
            while tenant.heap and len(batch) < cfg.batch_size:
                r = tenant.heap[0][2].rows
                if batch and rows_cap is not None and rows + r > rows_cap:
                    break
                _, _, req = heapq.heappop(tenant.heap)
                batch.append(req)
                rows += r
                popped = True
            if popped:
                self._assembling_n = len(batch)
                cv.notify_all()   # queue room for bounded-enqueue waiters
            if len(batch) >= cfg.batch_size:
                return batch
            if (tenant.heap and rows_cap is not None
                    and rows + tenant.heap[0][2].rows > rows_cap):
                return batch      # row bucket full
            now = time.time()
            if not self._predict_waiting:
                # predict is busy: keep the window open and accumulate;
                # woken by enqueue or by predict going idle
                cv.wait(0.05)
                continue
            remaining = cfg.batch_timeout_s - (now - t_first)
            if remaining <= 0:
                return batch
            urgent_t = min(r.deadline_t for r in batch)
            if urgent_t <= now + remaining:
                return batch      # near-expiry request jumps the window
            cv.wait(remaining)

    def _predict_run(self) -> None:
        """Predict half of the engine: takes batches from the handoff
        slot, expires what died in queue, runs predict, publishes.  Idle
        waits double as the result-table GC tick."""
        cv = self._work_cv
        while True:
            batch = None
            with cv:
                if self._stop.is_set():
                    return
                if self._slot is None:
                    self._predict_waiting = True
                    cv.notify_all()   # assembler: window may close now
                    cv.wait(self.config.result_gc_interval_s)
                if self._slot is not None:
                    batch = self._slot
                    self._slot = None
                    self._predict_waiting = False
                    self._busy = True   # set under the lock: drain's
                    #                     work-pending probe must never
                    #                     catch the gap between slot and
                    #                     busy
                    cv.notify_all()
            self._gc_results()
            if batch is None:
                continue
            try:
                batch = self._expire(batch)
                if batch:
                    self._process_guarded(batch)
            finally:
                self._busy = False

    # -- engine: legacy fixed-window loop (parity reference) -----------------
    def _run_fixed(self) -> None:
        """The pre-continuous engine: fill a window, then block on predict
        before touching the queue again.  Kept behind
        ``ServingConfig(continuous=False)`` as the batching-parity and
        perf A/B reference."""
        cfg = self.config
        cv = self._work_cv
        while not self._stop.is_set():
            self._gc_results()
            with cv:
                tenant = self._pick_tenant(time.time())
                if tenant is None:
                    cv.wait(0.05)
                    tenant = self._pick_tenant(time.time())
                    if tenant is None:
                        continue
                _, _, first = heapq.heappop(tenant.heap)
                batch = [first]
                cv.notify_all()
            t0 = time.time()
            while (len(batch) < cfg.batch_size
                   and time.time() - t0 < cfg.batch_timeout_s):
                with cv:
                    if tenant.heap:
                        batch.append(heapq.heappop(tenant.heap)[2])
                        cv.notify_all()
                        continue
                time.sleep(0.0005)
            batch = self._expire(batch)
            if not batch:
                continue
            self._busy = True
            try:
                self._process_guarded(batch)
            finally:
                self._busy = False

    def _process_guarded(self, batch: List[_Request]) -> None:
        try:
            self._process(batch)
        except Exception as e:  # noqa: BLE001 — engine must outlive
            # any single batch: a concatenate error (shape-mismatched
            # co-batched requests) or a raise-mode injected fault
            # outside _process's own predict handler would otherwise
            # kill the engine thread and zombify the server
            log.error("serving batch failed outside predict: %s", e)
            self._count("failed_batches")
            self._tenant_series(batch[0].model, "failed", len(batch))
            self._publish([r.rid for r in batch],
                          [1] * len(batch), None, error=e)

    def _gc_results(self) -> None:
        """TTL sweep over the result table: a client that abandoned its
        ``query`` (timeout, disconnect) must not leak its entry forever.
        The SLO evaluator piggybacks on the same engine-thread tick (its
        own rate limit inside) — no extra thread, and burn rates stay
        fresh exactly as long as the engine is alive."""
        if self.slo is not None:
            try:
                self.slo.maybe_evaluate()
            except Exception as e:  # noqa: BLE001 — never stall serving
                log.warning("SLO evaluation failed: %s", e)
        now = time.time()
        if now - self._last_gc_t < self.config.result_gc_interval_s:
            return
        self._last_gc_t = now
        with self._result_cv:
            stale = [rid for rid, t in self._result_expiry.items()
                     if t <= now]
            for rid in stale:
                self._results.pop(rid, None)
                self._result_expiry.pop(rid, None)
        if stale:
            self._count("results_gc", len(stale))
            log.info("serving: GC'd %d abandoned results", len(stale))

    def _expire(self, batch) -> list:
        """Drop requests whose deadline passed while queued — BEFORE
        predict, so expired work never reaches the chip.  Each gets an
        explicit DeadlineExceededError result."""
        now = time.time()
        live, expired = [], []
        for req in batch:
            (expired if req.deadline_t <= now else live).append(req)
        if expired:
            ttl = now + self.config.result_ttl_s
            with self._result_cv:
                for req in expired:
                    self._results[req.rid] = DeadlineExceededError(
                        req.rid, now - req.admit_t)
                    self._result_expiry[req.rid] = ttl
                    self._pending.discard(req.rid)
                self._result_cv.notify_all()
            self._count("expired_requests", len(expired))
            # batches are single-tenant (_fill_batch pops one heap), so
            # one inc attributes the whole drop — the per-tenant SLO
            # surface must say WHOSE deadlines are expiring
            self._tenant_series(expired[0].model, "expired", len(expired))
            flight.record("serving_deadline_drop", count=len(expired),
                          request_ids=[r.rid for r in expired])
        return live

    def _process(self, batch) -> None:
        # attrs (the O(batch) rid join, specifically) are built only when
        # a tracer is installed — tracing off must stay a None check
        tenant = self._tenants[batch[0].model]
        tr = trace.active()
        if tr is None:
            return self._process_traced(batch, tenant, None)
        with tr.span("serving/batch", batch_size=len(batch),
                     model=tenant.name,
                     request_ids=",".join(r.rid for r in batch)):
            self._process_traced(batch, tenant, tr)

    def _process_traced(self, batch, tenant: _Tenant, tr) -> None:
        cfg = self.config
        rids = [r.rid for r in batch]
        sizes = [r.rows for r in batch]
        arrs = [r.arr if r.arr.ndim > 1 else r.arr[None] for r in batch]
        stacked = np.concatenate(arrs, axis=0)
        t_predict = time.time()
        for r in batch:
            # admission→predict-start wait: the tail's wait-vs-predict
            # decomposition (mirrors the train-side attribution model)
            wait = t_predict - r.admit_t
            self.metrics.observe("serving.queue_wait_s", wait)
            self._tenant_series(tenant.name, "queue_wait", wait)
        # chaos seams (docs/serving.md): a slow batch delays the loop so
        # queued requests expire; a worker kill takes the process down
        # mid-request (the pool's breaker/supervisor must absorb it)
        faults.fire("serving_slow_batch")
        faults.fire("serving_worker_kill")
        use_fallback = tenant.degraded and tenant.fallback is not None
        primary = tenant.fallback if use_fallback else tenant.model
        out = None
        try:
            pred_span = trace.NULL_SPAN if tr is None else tr.span(
                "serving/predict", batch_size=len(batch),
                model=tenant.name, request_ids=",".join(rids))
            with pred_span, Timer(self.metrics, "serving.predict_s"):
                faults.fire("serving_predict_fail")
                out = primary.predict(stacked)
            tenant.consecutive_failures = 0
            if not use_fallback and tenant.degraded:
                log.info("serving: predict recovered; %s leaving degraded "
                         "mode", tenant.name)
                tenant.degraded = False
                flight.record("serving_recovered", via="predict_success",
                              model=tenant.name)
        except Exception as e:
            tenant.consecutive_failures += 1
            self._count("failed_batches")
            if (not tenant.degraded and tenant.consecutive_failures
                    >= cfg.degraded_after_failures):
                tenant.degraded = True
                log.error(
                    "serving: %d consecutive predict failures — model %s "
                    "DEGRADED (%s)", tenant.consecutive_failures,
                    tenant.name,
                    "serving from fallback model"
                    if tenant.fallback is not None
                    else "no fallback: shedding new load")
                flight.record(
                    "serving_degraded", model=tenant.name,
                    consecutive_failures=tenant.consecutive_failures,
                    fallback=tenant.fallback is not None,
                    error=str(e))
            if not use_fallback and tenant.fallback is not None:
                # last-good model answers THIS batch too, not just the
                # post-degradation ones — a waiter should not pay for the
                # primary's death with an error when a fallback exists
                try:
                    out = tenant.fallback.predict(stacked)
                    use_fallback = True
                except Exception as e2:
                    log.error("fallback predict also failed: %s", e2)
            if out is None:
                log.error("predict failed: %s", e)
                # the availability half of the tenant's SLO: failed
                # requests count against the error budget
                self._tenant_series(tenant.name, "failed", len(batch))
                self._publish(rids, sizes, None, error=e)
                return
        if use_fallback:
            self._count("fallback_batches")
        self._publish(rids, sizes, out)
        now = time.time()
        # EMA of predict wall time: the assembler's deadline-urgency
        # horizon (how long a queued request is likely to wait)
        self._predict_ema_s = (0.8 * self._predict_ema_s
                               + 0.2 * (now - t_predict))
        for r in batch:
            # admission→publish latency; the p50/p95/p99 surface /metrics
            # exports as a Prometheus histogram — per tenant too, so one
            # scrape shows every model's SLO
            lat = now - r.admit_t
            self.metrics.observe("serving.latency_s", lat)
            self._tenant_series(tenant.name, "latency", lat)
        self._count("batches")
        self._count("requests", len(batch))
        self._tenant_series(tenant.name, "requests", len(batch))
        with self._stats_lock:
            occ = (self.stats["requests"] / self.stats["batches"]
                   / max(cfg.batch_size, 1))
        self.metrics.gauge("serving.batch_occupancy", occ)
        self.metrics.gauge("serving.queue_depth", self._in.qsize())
        self.metrics.gauge("serving.backlog", self.backlog())
        self._tenant_series(tenant.name, "queue_depth", len(tenant.heap))

    def _publish(self, rids, sizes, out, error: Optional[Exception] = None
                 ) -> None:
        ttl = time.time() + self.config.result_ttl_s
        ofs = 0
        tr = trace.active()
        pub_span = trace.NULL_SPAN if tr is None else tr.span(
            "serving/publish", request_ids=",".join(rids),
            error=error is not None)
        with pub_span, self._result_cv:
            for rid, n in zip(rids, sizes):
                if error is not None:
                    self._results[rid] = error
                else:
                    self._results[rid] = out[ofs:ofs + n]
                    ofs += n
                self._result_expiry[rid] = ttl
                self._pending.discard(rid)
            self._result_cv.notify_all()
