"""ServingServer — the Cluster-Serving streaming engine.

Reference analog (unverified — mount empty): ``scala/serving/.../
ClusterServing.scala`` + ``engine/FlinkRedisSource/Sink``: pop a batch of
requests from a Redis list, dynamic-batch up to ``batch_size`` within a
timeout, run ``InferenceModel.doPredict``, write each result back keyed by
request id.

TPU-native: the transport is an in-process (or file-backed) queue pair —
Redis/Flink are cluster plumbing, not capability — while the batching loop,
backpressure and at-least-once result delivery semantics match.  A
dispatcher thread owns the chip; client threads only enqueue.
"""

import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

from bigdl_tpu.serving.inference_model import InferenceModel
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.serving")


@dataclass
class ServingConfig:
    """Reference config.yaml surface: modelPath, batchSize, timeout."""

    batch_size: int = 32
    batch_timeout_s: float = 0.005
    queue_capacity: int = 4096
    # graceful degradation: after this many CONSECUTIVE failed predict
    # batches the server is 'degraded' — it serves from the last-good
    # fallback model if one is set, and sheds new load otherwise
    degraded_after_failures: int = 3
    # half-open probing while degraded WITHOUT a fallback: one request per
    # interval is admitted as a probe so a recovered model can clear
    # degradation by itself (otherwise shedding is permanent: recovery
    # only happens inside _process, which needs an admitted request)
    degraded_probe_interval_s: float = 1.0


class ServiceUnavailableError(RuntimeError):
    """Raised by ``enqueue`` while the server is degraded with no
    fallback model — fail fast at admission instead of queueing requests
    into a replica that cannot answer them (load shedding)."""


class ServingServer:
    """queue -> dynamic batch -> jitted predict -> result table.

    Resilience posture (reference Cluster-Serving keeps serving while a
    replica restarts): a streak of predict failures flips the server to
    DEGRADED.  Degraded with a fallback model (``set_fallback_model`` —
    typically the previous good version) keeps answering from it;
    degraded without one sheds new load at ``enqueue`` so callers retry
    another replica.  ``reload_model`` installs a restarted replica's
    model and clears degradation."""

    def __init__(self, model: InferenceModel,
                 config: Optional[ServingConfig] = None):
        self.model = model
        self.config = config or ServingConfig()
        self._in: "queue.Queue[Tuple[str, np.ndarray]]" = queue.Queue(
            self.config.queue_capacity)
        self._results: Dict[str, np.ndarray] = {}
        self._result_cv = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fallback_model: Optional[InferenceModel] = None
        self._consecutive_failures = 0
        self._last_probe_t = 0.0
        self._probe_lock = threading.Lock()
        self.degraded = False
        self.stats = {"batches": 0, "requests": 0, "failed_batches": 0,
                      "fallback_batches": 0, "shed_requests": 0}

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ServingServer":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- degradation control ------------------------------------------------
    def set_fallback_model(self, model: InferenceModel) -> "ServingServer":
        """Register the last-good model; while degraded, batches are served
        from it instead of failing."""
        self._fallback_model = model
        return self

    def reload_model(self, model: InferenceModel) -> None:
        """Install a (restarted) replica's model; the old primary becomes
        the fallback and degradation clears."""
        self._fallback_model = self.model if not self.degraded \
            else self._fallback_model
        self.model = model
        self._consecutive_failures = 0
        if self.degraded:
            log.info("serving: model reloaded; leaving degraded mode")
        self.degraded = False

    # -- client side --------------------------------------------------------
    def enqueue(self, arr: np.ndarray, request_id: Optional[str] = None
                ) -> str:
        if self.degraded and self._fallback_model is None:
            # half-open: admit one probe per interval so a recovered
            # model can clear degradation; shed everything else —
            # admission-time fast-fail beats letting the request rot in
            # the queue until the client timeout
            with self._probe_lock:  # check-then-set: exactly ONE probe
                #                     per interval across client threads
                now = time.time()
                is_probe = (now - self._last_probe_t
                            >= self.config.degraded_probe_interval_s)
                if is_probe:
                    self._last_probe_t = now
                else:
                    self.stats["shed_requests"] += 1
            if not is_probe:
                raise ServiceUnavailableError(
                    "server degraded (predict failing) and no fallback "
                    "model; shedding load — retry against another replica")
        rid = request_id or uuid.uuid4().hex
        self._in.put((rid, np.asarray(arr)))
        return rid

    def query(self, request_id: str, timeout: float = 30.0) -> np.ndarray:
        deadline = time.time() + timeout
        with self._result_cv:
            while request_id not in self._results:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(f"result {request_id} not ready")
                self._result_cv.wait(remaining)
            res = self._results.pop(request_id)
        if isinstance(res, Exception):
            raise res
        return res

    # -- engine loop --------------------------------------------------------
    def _run(self) -> None:
        cfg = self.config
        while not self._stop.is_set():
            batch = []
            try:
                batch.append(self._in.get(timeout=0.05))
            except queue.Empty:
                continue
            t0 = time.time()
            while (len(batch) < cfg.batch_size
                   and time.time() - t0 < cfg.batch_timeout_s):
                try:
                    batch.append(self._in.get_nowait())
                except queue.Empty:
                    time.sleep(0.0005)
            self._process(batch)

    def _process(self, batch) -> None:
        rids = [r for r, _ in batch]
        sizes = [a.shape[0] if a.ndim > 1 else 1 for _, a in batch]
        arrs = [a if a.ndim > 1 else a[None] for _, a in batch]
        stacked = np.concatenate(arrs, axis=0)
        use_fallback = self.degraded and self._fallback_model is not None
        primary = self._fallback_model if use_fallback else self.model
        out = None
        try:
            out = primary.predict(stacked)
            self._consecutive_failures = 0
            if not use_fallback and self.degraded:
                log.info("serving: predict recovered; leaving degraded mode")
                self.degraded = False
        except Exception as e:
            self._consecutive_failures += 1
            self.stats["failed_batches"] += 1
            if (not self.degraded and self._consecutive_failures
                    >= self.config.degraded_after_failures):
                self.degraded = True
                log.error(
                    "serving: %d consecutive predict failures — DEGRADED "
                    "(%s)", self._consecutive_failures,
                    "serving from fallback model"
                    if self._fallback_model is not None
                    else "no fallback: shedding new load")
            if not use_fallback and self._fallback_model is not None:
                # last-good model answers THIS batch too, not just the
                # post-degradation ones — a waiter should not pay for the
                # primary's death with an error when a fallback exists
                try:
                    out = self._fallback_model.predict(stacked)
                    use_fallback = True
                except Exception as e2:
                    log.error("fallback predict also failed: %s", e2)
            if out is None:
                log.error("predict failed: %s", e)
                with self._result_cv:
                    for rid in rids:
                        self._results[rid] = e  # type: ignore[assignment]
                    self._result_cv.notify_all()
                return
        if use_fallback:
            self.stats["fallback_batches"] += 1
        ofs = 0
        with self._result_cv:
            for rid, n in zip(rids, sizes):
                self._results[rid] = out[ofs:ofs + n]
                ofs += n
            self._result_cv.notify_all()
        self.stats["batches"] += 1
        self.stats["requests"] += len(batch)
