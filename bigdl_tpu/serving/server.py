"""ServingServer — the Cluster-Serving streaming engine.

Reference analog (unverified — mount empty): ``scala/serving/.../
ClusterServing.scala`` + ``engine/FlinkRedisSource/Sink``: pop a batch of
requests from a Redis list, dynamic-batch up to ``batch_size`` within a
timeout, run ``InferenceModel.doPredict``, write each result back keyed by
request id.

TPU-native: the transport is an in-process (or file-backed) queue pair —
Redis/Flink are cluster plumbing, not capability — while the batching loop,
backpressure and at-least-once result delivery semantics match.  A
dispatcher thread owns the chip; client threads only enqueue.

Request lifecycle (docs/serving.md has the state machine): every request
carries an admission time and an absolute deadline from ``enqueue`` through
the queue into the batch loop.  Admission fails fast — a full queue sheds
(``ServiceUnavailableError``, never an unbounded block), a degraded server
sheds (half-open probing excepted) — and the batch loop drops expired
requests BEFORE predict so a slow model never spends chip time answering a
client that already gave up.  Completed results live in a TTL'd table so an
abandoned ``query`` cannot leak entries forever, and shutdown is explicit:
``drain()`` finishes queued work, plain ``stop()`` fails it with
``RequestDroppedError`` — queued requests are never silently discarded.
"""

import math
import queue
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

from bigdl_tpu.obs import flight, trace
from bigdl_tpu.optim.metrics import Metrics, Timer, global_metrics
from bigdl_tpu.resilience import faults
from bigdl_tpu.serving.inference_model import InferenceModel
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.serving")


@dataclass
class ServingConfig:
    """Reference config.yaml surface: modelPath, batchSize, timeout."""

    batch_size: int = 32
    batch_timeout_s: float = 0.005
    queue_capacity: int = 4096
    # graceful degradation: after this many CONSECUTIVE failed predict
    # batches the server is 'degraded' — it serves from the last-good
    # fallback model if one is set, and sheds new load otherwise
    degraded_after_failures: int = 3
    # half-open probing while degraded WITHOUT a fallback: one request per
    # interval is admitted as a probe so a recovered model can clear
    # degradation by itself (otherwise shedding is permanent: recovery
    # only happens inside _process, which needs an admitted request)
    degraded_probe_interval_s: float = 1.0
    # -- request lifecycle --------------------------------------------------
    # deadline stamped at admission when the caller passes none; None means
    # requests never expire (the pre-lifecycle behavior)
    default_deadline_s: Optional[float] = None
    # how long enqueue may wait on a FULL queue before shedding; 0 sheds
    # immediately.  Bounded by construction — there is no blocking mode
    enqueue_block_s: float = 0.0
    # Retry-After hint attached to sheds (HTTP 429 surfaces it verbatim)
    retry_after_s: float = 1.0
    # completed-but-never-queried results are GC'd after this long; the
    # sweep runs on the engine thread between batches
    result_ttl_s: float = 60.0
    result_gc_interval_s: float = 1.0
    # default budget for stop(drain=True) / drain()
    drain_timeout_s: float = 10.0


class ServiceUnavailableError(RuntimeError):
    """Raised by ``enqueue`` when the server cannot accept the request —
    degraded with no fallback model, queue full (backpressure), draining,
    or stopped — so callers fail fast at admission and retry another
    replica instead of queueing into one that cannot answer.
    ``retry_after`` is the backoff hint (HTTP 429 ``Retry-After``)."""

    def __init__(self, msg: str, retry_after: float = 1.0):
        super().__init__(msg)
        self.retry_after = retry_after


class DeadlineExceededError(TimeoutError):
    """Delivered to ``query`` when the request's deadline passed while it
    waited in the queue — the batch loop dropped it before predict."""

    def __init__(self, rid: str, waited_s: float):
        super().__init__(
            f"request {rid} expired after {waited_s:.3f}s in queue "
            "(deadline passed before predict)")
        self.rid = rid
        self.waited_s = waited_s


class RequestDroppedError(RuntimeError):
    """Delivered to ``query`` for requests still queued when the server
    stopped without (or past) a drain — an explicit verdict, never a
    silent drop."""

    def __init__(self, rid: str):
        super().__init__(f"request {rid} dropped: server stopped before it "
                         "was processed")
        self.rid = rid


@dataclass
class _Request:
    """One queued request: payload + lifecycle timestamps (absolute)."""

    rid: str
    arr: np.ndarray
    admit_t: float
    deadline_t: float  # math.inf when the request never expires


class ServingServer:
    """queue -> dynamic batch -> jitted predict -> result table.

    Resilience posture (reference Cluster-Serving keeps serving while a
    replica restarts): a streak of predict failures flips the server to
    DEGRADED.  Degraded with a fallback model (``set_fallback_model`` —
    typically the previous good version) keeps answering from it;
    degraded without one sheds new load at ``enqueue`` so callers retry
    another replica.  ``reload_model`` installs a restarted replica's
    model and clears degradation.

    Every lifecycle event (shed, expiry, drain, drop, GC) lands in
    ``stats`` and — namespaced ``serving.*`` — in the process
    :class:`~bigdl_tpu.optim.metrics.Metrics` registry, so ``/health``
    and training-side metric consumers see the same counters."""

    def __init__(self, model: InferenceModel,
                 config: Optional[ServingConfig] = None,
                 metrics: Optional[Metrics] = None):
        self.model = model
        self.config = config or ServingConfig()
        self.metrics = metrics or global_metrics()
        self._in: "queue.Queue[_Request]" = queue.Queue(
            self.config.queue_capacity)
        self._results: Dict[str, Any] = {}
        self._result_expiry: Dict[str, float] = {}
        # rids admitted but not yet published — with caller-supplied ids
        # (X-Request-Id) a duplicate of an IN-FLIGHT id must be rejected
        # at admission, or two waiters would race one _results slot
        self._pending: set = set()
        self._result_cv = threading.Condition()
        self._last_gc_t = 0.0
        self._stop = threading.Event()
        self._draining = False
        self._busy = False  # engine thread is inside _process
        self._thread: Optional[threading.Thread] = None
        self._fallback_model: Optional[InferenceModel] = None
        self._consecutive_failures = 0
        self._last_probe_t = 0.0
        self._probe_lock = threading.Lock()
        self.degraded = False
        self._stats_lock = threading.Lock()
        self.stats = {"batches": 0, "requests": 0, "failed_batches": 0,
                      "fallback_batches": 0, "shed_requests": 0,
                      "expired_requests": 0, "drained_requests": 0,
                      "dropped_requests": 0, "results_gc": 0}
        # /metrics HELP lines for the lifecycle counters a fleet alerts on
        # (obs.export renders describe() strings next to # TYPE)
        self.metrics.describe("serving.shed_requests",
                              "requests rejected at admission "
                              "(backpressure/degraded/draining)")
        self.metrics.describe("serving.expired_requests",
                              "requests dropped before predict: deadline "
                              "already expired")
        self.metrics.describe("serving.predict_s",
                              "model predict wall time per batch")

    def _count(self, name: str, n: int = 1) -> None:
        # client threads and the engine thread both count; += on a dict
        # entry is not atomic, and tests assert exact counter values
        with self._stats_lock:
            self.stats[name] += n
            self.metrics.inc(f"serving.{name}", n)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ServingServer":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def drain(self, timeout: Optional[float] = None) -> Dict[str, int]:
        """Graceful shutdown: stop admitting, let the engine finish queued
        and in-flight work within ``timeout``, then stop.  Requests still
        queued when the budget runs out get an explicit
        :class:`RequestDroppedError`.  Returns ``{"drained": n, "dropped":
        m}`` for the caller's log line."""
        timeout = self.config.drain_timeout_s if timeout is None else timeout
        self._draining = True
        t_end = time.time() + timeout
        drained_from = self.stats["requests"]
        while time.time() < t_end:
            if self._in.empty() and not self._busy:
                break
            time.sleep(0.005)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(timeout, 5))
        dropped = self._fail_queued()
        drained = self.stats["requests"] - drained_from
        self._count("drained_requests", drained)
        if dropped:
            log.warning("serving drain: budget exhausted, %d queued "
                        "requests dropped with explicit errors", dropped)
        return {"drained": drained, "dropped": dropped}

    def stop(self, drain: bool = False,
             timeout: Optional[float] = None) -> None:
        """Stop the engine.  ``drain=True`` finishes queued work first
        (see :meth:`drain`); otherwise queued requests are failed
        explicitly with :class:`RequestDroppedError` — never silently
        discarded."""
        if drain:
            self.drain(timeout)
            return
        self._draining = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._fail_queued()

    def _fail_queued(self) -> int:
        """Deliver RequestDroppedError to everything still queued."""
        dropped = 0
        now = time.time()
        with self._result_cv:
            while True:
                try:
                    req = self._in.get_nowait()
                except queue.Empty:
                    break
                self._results[req.rid] = RequestDroppedError(req.rid)
                self._result_expiry[req.rid] = now + self.config.result_ttl_s
                self._pending.discard(req.rid)
                dropped += 1
            if dropped:
                self._result_cv.notify_all()
        if dropped:
            self._count("dropped_requests", dropped)
            flight.record("serving_requests_dropped", count=dropped)
        return dropped

    # -- degradation control ------------------------------------------------
    def set_fallback_model(self, model: InferenceModel) -> "ServingServer":
        """Register the last-good model; while degraded, batches are served
        from it instead of failing."""
        self._fallback_model = model
        return self

    def reload_model(self, model: InferenceModel) -> None:
        """Install a (restarted) replica's model; the old primary becomes
        the fallback and degradation clears."""
        self._fallback_model = self.model if not self.degraded \
            else self._fallback_model
        self.model = model
        self._consecutive_failures = 0
        if self.degraded:
            log.info("serving: model reloaded; leaving degraded mode")
            flight.record("serving_recovered", via="reload_model")
        self.degraded = False

    # -- client side --------------------------------------------------------
    def enqueue(self, arr: np.ndarray, request_id: Optional[str] = None,
                deadline_s: Optional[float] = None) -> str:
        """Admit one request.  Never blocks beyond
        ``config.enqueue_block_s``: a full queue, a draining/stopped
        server, or degradation without fallback all raise
        :class:`ServiceUnavailableError` at admission (counted as
        ``shed_requests``).  ``deadline_s`` is relative to now; it
        defaults to ``config.default_deadline_s`` (None = no expiry)."""
        cfg = self.config
        if self._draining or self._stop.is_set():
            self._count("shed_requests")
            raise ServiceUnavailableError(
                "server is draining/stopped; retry against another replica",
                retry_after=cfg.retry_after_s)
        if self.degraded and self._fallback_model is None:
            # half-open: admit one probe per interval so a recovered
            # model can clear degradation; shed everything else —
            # admission-time fast-fail beats letting the request rot in
            # the queue until the client timeout
            with self._probe_lock:  # check-then-set: exactly ONE probe
                #                     per interval across client threads
                now = time.time()
                is_probe = (now - self._last_probe_t
                            >= cfg.degraded_probe_interval_s)
                if is_probe:
                    self._last_probe_t = now
                else:
                    self._count("shed_requests")
            if not is_probe:
                raise ServiceUnavailableError(
                    "server degraded (predict failing) and no fallback "
                    "model; shedding load — retry against another replica",
                    retry_after=cfg.retry_after_s)
        rid = request_id or uuid.uuid4().hex
        now = time.time()
        if deadline_s is None:
            deadline_s = cfg.default_deadline_s
        deadline_t = now + deadline_s if deadline_s is not None else math.inf
        req = _Request(rid, np.asarray(arr), now, deadline_t)
        with self._result_cv:
            if rid in self._pending:
                # still in flight: two waiters must not race one result
                # slot — retryable conflict (HTTP 409 upstream); resolves
                # as soon as the first attempt publishes
                raise ValueError(
                    f"request id {rid!r} is already in flight; "
                    "request ids must be unique per outstanding request")
            # completed but never fetched (first waiter gone, or an id
            # deliberately reused with a NEW payload): discard the stale
            # verdict and recompute — adopting it would silently answer
            # the new payload with the old prediction
            self._results.pop(rid, None)
            self._result_expiry.pop(rid, None)
            self._pending.add(rid)
        try:
            with trace.span("serving/enqueue", request_id=rid):
                if cfg.enqueue_block_s > 0:
                    self._in.put(req, timeout=cfg.enqueue_block_s)
                else:
                    self._in.put_nowait(req)
        except queue.Full:
            with self._result_cv:
                self._pending.discard(rid)
            self._count("shed_requests")
            raise ServiceUnavailableError(
                f"request queue full ({cfg.queue_capacity}); shedding load "
                "— retry after backoff", retry_after=cfg.retry_after_s)
        if self._stop.is_set():
            # raced stop(): the engine may already be gone and _fail_queued
            # past — sweep again so THIS request still gets an explicit
            # verdict (either the engine processed it or it is now failed)
            self._fail_queued()
        return rid

    def query(self, request_id: str, timeout: float = 30.0) -> np.ndarray:
        deadline = time.time() + timeout
        with self._result_cv:
            while request_id not in self._results:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(f"result {request_id} not ready")
                self._result_cv.wait(remaining)
            res = self._results.pop(request_id)
            self._result_expiry.pop(request_id, None)
        if isinstance(res, Exception):
            raise res
        return res

    # -- engine loop --------------------------------------------------------
    def _run(self) -> None:
        cfg = self.config
        while not self._stop.is_set():
            self._gc_results()
            batch = []
            try:
                batch.append(self._in.get(timeout=0.05))
            except queue.Empty:
                continue
            t0 = time.time()
            while (len(batch) < cfg.batch_size
                   and time.time() - t0 < cfg.batch_timeout_s):
                try:
                    batch.append(self._in.get_nowait())
                except queue.Empty:
                    time.sleep(0.0005)
            batch = self._expire(batch)
            if not batch:
                continue
            self._busy = True
            try:
                self._process(batch)
            except Exception as e:  # noqa: BLE001 — engine must outlive
                # any single batch: a concatenate error (shape-mismatched
                # co-batched requests) or a raise-mode injected fault
                # outside _process's own predict handler would otherwise
                # kill the dispatcher thread and zombify the server
                log.error("serving batch failed outside predict: %s", e)
                self._count("failed_batches")
                self._publish([r.rid for r in batch],
                              [1] * len(batch), None, error=e)
            finally:
                self._busy = False

    def _gc_results(self) -> None:
        """TTL sweep over the result table: a client that abandoned its
        ``query`` (timeout, disconnect) must not leak its entry forever."""
        now = time.time()
        if now - self._last_gc_t < self.config.result_gc_interval_s:
            return
        self._last_gc_t = now
        with self._result_cv:
            stale = [rid for rid, t in self._result_expiry.items()
                     if t <= now]
            for rid in stale:
                self._results.pop(rid, None)
                self._result_expiry.pop(rid, None)
        if stale:
            self._count("results_gc", len(stale))
            log.info("serving: GC'd %d abandoned results", len(stale))

    def _expire(self, batch) -> list:
        """Drop requests whose deadline passed while queued — BEFORE
        predict, so expired work never reaches the chip.  Each gets an
        explicit DeadlineExceededError result."""
        now = time.time()
        live, expired = [], []
        for req in batch:
            (expired if req.deadline_t <= now else live).append(req)
        if expired:
            ttl = now + self.config.result_ttl_s
            with self._result_cv:
                for req in expired:
                    self._results[req.rid] = DeadlineExceededError(
                        req.rid, now - req.admit_t)
                    self._result_expiry[req.rid] = ttl
                    self._pending.discard(req.rid)
                self._result_cv.notify_all()
            self._count("expired_requests", len(expired))
            flight.record("serving_deadline_drop", count=len(expired),
                          request_ids=[r.rid for r in expired])
        return live

    def _process(self, batch) -> None:
        # attrs (the O(batch) rid join, specifically) are built only when
        # a tracer is installed — tracing off must stay a None check
        tr = trace.active()
        if tr is None:
            return self._process_traced(batch, None)
        with tr.span("serving/batch", batch_size=len(batch),
                     request_ids=",".join(r.rid for r in batch)):
            self._process_traced(batch, tr)

    def _process_traced(self, batch, tr) -> None:
        rids = [r.rid for r in batch]
        sizes = [r.arr.shape[0] if r.arr.ndim > 1 else 1 for r in batch]
        arrs = [r.arr if r.arr.ndim > 1 else r.arr[None] for r in batch]
        stacked = np.concatenate(arrs, axis=0)
        # chaos seams (docs/serving.md): a slow batch delays the loop so
        # queued requests expire; a worker kill takes the process down
        # mid-request (the pool's breaker/supervisor must absorb it)
        faults.fire("serving_slow_batch")
        faults.fire("serving_worker_kill")
        use_fallback = self.degraded and self._fallback_model is not None
        primary = self._fallback_model if use_fallback else self.model
        out = None
        try:
            pred_span = trace.NULL_SPAN if tr is None else tr.span(
                "serving/predict", batch_size=len(batch),
                request_ids=",".join(rids))
            with pred_span, Timer(self.metrics, "serving.predict_s"):
                faults.fire("serving_predict_fail")
                out = primary.predict(stacked)
            self._consecutive_failures = 0
            if not use_fallback and self.degraded:
                log.info("serving: predict recovered; leaving degraded mode")
                self.degraded = False
                flight.record("serving_recovered", via="predict_success")
        except Exception as e:
            self._consecutive_failures += 1
            self._count("failed_batches")
            if (not self.degraded and self._consecutive_failures
                    >= self.config.degraded_after_failures):
                self.degraded = True
                log.error(
                    "serving: %d consecutive predict failures — DEGRADED "
                    "(%s)", self._consecutive_failures,
                    "serving from fallback model"
                    if self._fallback_model is not None
                    else "no fallback: shedding new load")
                flight.record(
                    "serving_degraded",
                    consecutive_failures=self._consecutive_failures,
                    fallback=self._fallback_model is not None,
                    error=str(e))
            if not use_fallback and self._fallback_model is not None:
                # last-good model answers THIS batch too, not just the
                # post-degradation ones — a waiter should not pay for the
                # primary's death with an error when a fallback exists
                try:
                    out = self._fallback_model.predict(stacked)
                    use_fallback = True
                except Exception as e2:
                    log.error("fallback predict also failed: %s", e2)
            if out is None:
                log.error("predict failed: %s", e)
                self._publish(rids, sizes, None, error=e)
                return
        if use_fallback:
            self._count("fallback_batches")
        self._publish(rids, sizes, out)
        now = time.time()
        for r in batch:
            # admission→publish latency; the p50/p95/p99 surface /metrics
            # exports as a Prometheus histogram
            self.metrics.observe("serving.latency_s", now - r.admit_t)
        self._count("batches")
        self._count("requests", len(batch))

    def _publish(self, rids, sizes, out, error: Optional[Exception] = None
                 ) -> None:
        ttl = time.time() + self.config.result_ttl_s
        ofs = 0
        tr = trace.active()
        pub_span = trace.NULL_SPAN if tr is None else tr.span(
            "serving/publish", request_ids=",".join(rids),
            error=error is not None)
        with pub_span, self._result_cv:
            for rid, n in zip(rids, sizes):
                if error is not None:
                    self._results[rid] = error
                else:
                    self._results[rid] = out[ofs:ofs + n]
                    ofs += n
                self._result_expiry[rid] = ttl
                self._pending.discard(rid)
            self._result_cv.notify_all()
