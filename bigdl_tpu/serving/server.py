"""ServingServer — the Cluster-Serving streaming engine.

Reference analog (unverified — mount empty): ``scala/serving/.../
ClusterServing.scala`` + ``engine/FlinkRedisSource/Sink``: pop a batch of
requests from a Redis list, dynamic-batch up to ``batch_size`` within a
timeout, run ``InferenceModel.doPredict``, write each result back keyed by
request id.

TPU-native: the transport is an in-process (or file-backed) queue pair —
Redis/Flink are cluster plumbing, not capability — while the batching loop,
backpressure and at-least-once result delivery semantics match.  A
dispatcher thread owns the chip; client threads only enqueue.
"""

import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

from bigdl_tpu.serving.inference_model import InferenceModel
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.serving")


@dataclass
class ServingConfig:
    """Reference config.yaml surface: modelPath, batchSize, timeout."""

    batch_size: int = 32
    batch_timeout_s: float = 0.005
    queue_capacity: int = 4096


class ServingServer:
    """queue -> dynamic batch -> jitted predict -> result table."""

    def __init__(self, model: InferenceModel,
                 config: Optional[ServingConfig] = None):
        self.model = model
        self.config = config or ServingConfig()
        self._in: "queue.Queue[Tuple[str, np.ndarray]]" = queue.Queue(
            self.config.queue_capacity)
        self._results: Dict[str, np.ndarray] = {}
        self._result_cv = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stats = {"batches": 0, "requests": 0}

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ServingServer":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- client side --------------------------------------------------------
    def enqueue(self, arr: np.ndarray, request_id: Optional[str] = None
                ) -> str:
        rid = request_id or uuid.uuid4().hex
        self._in.put((rid, np.asarray(arr)))
        return rid

    def query(self, request_id: str, timeout: float = 30.0) -> np.ndarray:
        deadline = time.time() + timeout
        with self._result_cv:
            while request_id not in self._results:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(f"result {request_id} not ready")
                self._result_cv.wait(remaining)
            res = self._results.pop(request_id)
        if isinstance(res, Exception):
            raise res
        return res

    # -- engine loop --------------------------------------------------------
    def _run(self) -> None:
        cfg = self.config
        while not self._stop.is_set():
            batch = []
            try:
                batch.append(self._in.get(timeout=0.05))
            except queue.Empty:
                continue
            t0 = time.time()
            while (len(batch) < cfg.batch_size
                   and time.time() - t0 < cfg.batch_timeout_s):
                try:
                    batch.append(self._in.get_nowait())
                except queue.Empty:
                    time.sleep(0.0005)
            self._process(batch)

    def _process(self, batch) -> None:
        rids = [r for r, _ in batch]
        sizes = [a.shape[0] if a.ndim > 1 else 1 for _, a in batch]
        arrs = [a if a.ndim > 1 else a[None] for _, a in batch]
        stacked = np.concatenate(arrs, axis=0)
        try:
            out = self.model.predict(stacked)
        except Exception as e:  # deliver the failure to every waiter
            log.error("predict failed: %s", e)
            with self._result_cv:
                for rid in rids:
                    self._results[rid] = e  # type: ignore[assignment]
                self._result_cv.notify_all()
            return
        ofs = 0
        with self._result_cv:
            for rid, n in zip(rids, sizes):
                self._results[rid] = out[ofs:ofs + n]
                ofs += n
            self._result_cv.notify_all()
        self.stats["batches"] += 1
        self.stats["requests"] += len(batch)
