"""Seq2Seq decode service — serving for translation Transformers.

Reference analog: Cluster Serving's ``InferenceModel`` holds
classification models; its Seq2Seq story (``models/rnn`` +
``SequenceBeamSearch``) never got a serving surface.  Here decode IS
servable, and — since the token-level rebuild (docs/serving.md
§Autoregressive decode) — CONTINUOUS: greedy and sampled requests run
through the paged-KV :class:`~bigdl_tpu.serving.decode_engine.
DecodeEngine` one model step at a time, so a short translation frees
its sequence slot mid-flight instead of holding a batch seat until the
longest row finishes.

``continuous=False`` keeps the one-scan whole-sequence decode as the
byte-identical parity reference (the PR 8 ``continuous=False``
pattern): same encoder programs, same chunk/selection math, one
``lax.scan`` per request over a contiguous cache.  Beam search
(``beam_size > 1``) stays on the legacy bucketed whole-batch path —
beams reorder the cache every step, which the slot engine does not
model.
"""

import itertools
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

import jax


class Seq2SeqService:
    """Holds a translation-mode :class:`~bigdl_tpu.nn.Transformer` and
    serves ``translate(src_batch)``.

    ``beam_size=0`` → KV-cached greedy through the continuous decode
    engine (the fast path); ``>0`` → beam search with GNMT length
    penalty (legacy whole-batch scan); ``sample=True`` → stochastic
    decode (temperature / top-k / nucleus top-p) with a per-REQUEST key
    fold, so repeated requests differ and the continuous engine's
    output is independent of co-scheduled traffic."""

    BATCH_BUCKETS: Tuple[int, ...] = (1, 4, 16, 64)

    def __init__(self, model, params, bos_id: int, eos_id: int,
                 max_len: int = 32, beam_size: int = 0,
                 batch_buckets: Optional[Sequence[int]] = None,
                 sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 continuous: bool = True,
                 src_buckets: Sequence[int] = (8, 16, 32, 64),
                 decode_config=None):
        if sample and beam_size and beam_size > 1:
            raise ValueError("sample=True and beam_size>1 are exclusive")
        if model.mode != "translation":
            raise ValueError("Seq2SeqService needs a translation-mode "
                             "Transformer")
        self.model = model
        self.params = params
        self.bos_id, self.eos_id = bos_id, eos_id
        self.max_len = max_len
        self.beam_size = beam_size
        self.buckets = tuple(batch_buckets or self.BATCH_BUCKETS)
        self.sample = bool(sample)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.continuous = bool(continuous)
        self.src_buckets = tuple(sorted(src_buckets))
        self._seed = jax.random.PRNGKey(seed)
        self._seed_int = int(seed)
        # itertools.count.__next__ is atomic under the GIL: the threaded
        # serving frontends must never hand two requests the same fold
        self._request_ids = itertools.count(1)
        self._cache = {}
        self._decode_cfg = decode_config
        self.decode_engine = None       # built lazily on first translate
        self._engine_lock = threading.Lock()

    # -- engine plumbing ----------------------------------------------------
    def _engine(self):
        with self._engine_lock:
            return self._engine_locked()

    def _engine_locked(self):
        if self.decode_engine is None:
            from bigdl_tpu.serving.decode_engine import (DecodeConfig,
                                                         DecodeEngine,
                                                         Seq2SeqAdapter)

            cfg = self._decode_cfg
            if cfg is None:
                page = 8
                cap = self.max_len + 1
                cfg = DecodeConfig(
                    slots=8, page_size=page,
                    pages_per_slot=max(1, -(-cap // page)),
                    prompt_chunk=8, max_new_tokens=self.max_len,
                    eos_id=self.eos_id, base_seed=self._seed_int)
            adapter = Seq2SeqAdapter(self.model, self.params,
                                     cap=cfg.cap, bos_id=self.bos_id,
                                     src_buckets=self.src_buckets)
            self.decode_engine = DecodeEngine(adapter, cfg,
                                              name="seq2seq")
        return self.decode_engine

    def _requests(self, src: np.ndarray):
        from bigdl_tpu.serving.decode_engine import DecodeRequest

        temp = self.temperature if self.sample else 0.0
        return [DecodeRequest(
            tokens=row, max_new_tokens=self.max_len, temperature=temp,
            top_k=self.top_k, top_p=self.top_p,
            seed=next(self._request_ids)) for row in src]

    def _assemble(self, results) -> Tuple[np.ndarray, np.ndarray]:
        """Engine results -> the legacy (tokens incl. BOS, scores)
        surface: generated tokens padded with EOS to ``max_len`` (the
        one-scan decode freezes finished rows on EOS, so the padded
        forms agree byte-for-byte)."""
        n = len(results)
        tokens = np.full((n, self.max_len + 1), self.eos_id, np.int32)
        tokens[:, 0] = self.bos_id
        scores = np.zeros((n,), np.float32)
        for i, res in enumerate(results):
            gen = res.tokens[: self.max_len]
            tokens[i, 1:1 + len(gen)] = gen
            scores[i] = np.float32(res.logp)
        return tokens, scores

    # -- legacy beam path ---------------------------------------------------
    def _decode_fn(self, batch: int):
        fn = self._cache.get(batch)
        if fn is None:
            from bigdl_tpu.nn.attention import transformer_decode

            def run(params, src, rng):
                toks, scores = transformer_decode(
                    self.model, params, src, self.bos_id, self.eos_id,
                    max_len=self.max_len, beam_size=self.beam_size)
                return toks[:, 0], scores[:, 0]   # best beam

            fn = jax.jit(run)
            self._cache[batch] = fn
        return fn

    def _translate_beam(self, src) -> Tuple[np.ndarray, np.ndarray]:
        n = src.shape[0]
        bucket = next((b for b in self.buckets if b >= n), None)
        if bucket is None:  # larger than the biggest bucket: chunk it
            big = self.buckets[-1]
            outs = [self._translate_beam(src[i:i + big]) for i in
                    range(0, n, big)]
            return (np.concatenate([o[0] for o in outs]),
                    np.concatenate([o[1] for o in outs]))
        if bucket > n:
            src = np.concatenate(
                [src, np.repeat(src[-1:], bucket - n, axis=0)])
        rng = jax.random.fold_in(self._seed, next(self._request_ids))
        tokens, scores = self._decode_fn(bucket)(self.params, src, rng)
        return np.asarray(tokens)[:n], np.asarray(scores)[:n]

    # -- public surface -----------------------------------------------------
    def translate(self, src) -> Tuple[np.ndarray, np.ndarray]:
        """src: (n, t_src) int tokens → (tokens (n, max_len+1) incl.
        BOS, scores (n,)).  Greedy/sample requests run row-by-row
        through the continuous decode engine (or the one-scan static
        reference under ``continuous=False``); beam requests take the
        legacy bucketed whole-batch path."""
        src = np.asarray(src, np.int32)
        if self.beam_size and self.beam_size > 1:
            return self._translate_beam(src)
        engine = self._engine()
        reqs = self._requests(src)
        if self.continuous:
            for r in reqs:
                engine.submit(r)
            results = [r.wait(timeout=300.0) for r in reqs]
        else:
            results = engine.static_generate(reqs)
        return self._assemble(results)

    def warmup(self) -> "Seq2SeqService":
        """Pre-compile the engine's closed program set (and the encode
        buckets) under ``expected_compile`` — after this a mixed-length
        sweep triggers zero unexpected XLA recompiles."""
        if not (self.beam_size and self.beam_size > 1):
            self._engine().warmup()
        return self

    def stop(self) -> None:
        if self.decode_engine is not None:
            self.decode_engine.stop()
