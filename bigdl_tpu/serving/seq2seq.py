"""Seq2Seq decode service — serving for translation Transformers.

Reference analog: Cluster Serving's ``InferenceModel`` holds classification
models; its Seq2Seq story (``models/rnn`` + ``SequenceBeamSearch``) never
got a serving surface.  Here decode IS servable: requests are bucketed to a
few batch sizes (same discipline as ``ServingServer``/``RecallService``) so
arbitrary request counts reuse a handful of compiled programs, and each
bucket's program is the whole autoregressive loop (one ``lax.scan`` — KV
caches inside, nothing host-side per token).
"""

import itertools
from typing import Optional, Sequence, Tuple

import numpy as np

import jax


class Seq2SeqService:
    """Holds a translation-mode :class:`~bigdl_tpu.nn.Transformer` and
    serves ``translate(src_batch)``.

    ``beam_size=0`` → KV-cached greedy (the fast path); ``>0`` → beam
    search with GNMT length penalty (re-attends over the prefix);
    ``temperature>0`` with ``sample=True`` → KV-cached stochastic decode
    (temperature / top-k / nucleus top-p, fresh fold of ``seed`` per
    request so repeated requests differ)."""

    BATCH_BUCKETS: Tuple[int, ...] = (1, 4, 16, 64)

    def __init__(self, model, params, bos_id: int, eos_id: int,
                 max_len: int = 32, beam_size: int = 0,
                 batch_buckets: Optional[Sequence[int]] = None,
                 sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0):
        if sample and beam_size and beam_size > 1:
            raise ValueError("sample=True and beam_size>1 are exclusive")
        if model.mode != "translation":
            raise ValueError("Seq2SeqService needs a translation-mode "
                             "Transformer")
        self.model = model
        self.params = params
        self.bos_id, self.eos_id = bos_id, eos_id
        self.max_len = max_len
        self.beam_size = beam_size
        self.buckets = tuple(batch_buckets or self.BATCH_BUCKETS)
        self.sample = bool(sample)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self._seed = jax.random.PRNGKey(seed)
        # itertools.count.__next__ is atomic under the GIL: the threaded
        # serving frontends must never hand two requests the same fold
        self._request_ids = itertools.count(1)
        self._cache = {}

    def _decode_fn(self, batch: int):
        fn = self._cache.get(batch)
        if fn is None:
            from bigdl_tpu.nn.attention import (transformer_decode,
                                                transformer_decode_cached)

            if self.beam_size and self.beam_size > 1:
                def run(params, src, rng):
                    toks, scores = transformer_decode(
                        self.model, params, src, self.bos_id, self.eos_id,
                        max_len=self.max_len, beam_size=self.beam_size)
                    return toks[:, 0], scores[:, 0]   # best beam
            elif self.sample:
                def run(params, src, rng):
                    return transformer_decode_cached(
                        self.model, params, src, self.bos_id, self.eos_id,
                        max_len=self.max_len, rng=rng,
                        temperature=self.temperature, top_k=self.top_k,
                        top_p=self.top_p)
            else:
                def run(params, src, rng):
                    return transformer_decode_cached(
                        self.model, params, src, self.bos_id, self.eos_id,
                        max_len=self.max_len)

            fn = jax.jit(run)
            self._cache[batch] = fn
        return fn

    def translate(self, src) -> Tuple[np.ndarray, np.ndarray]:
        """src: (n, t_src) int tokens → (tokens (n, max_len+1) incl. BOS,
        scores (n,)).  n is padded up to a bucket; pad rows are dropped."""
        src = np.asarray(src, np.int32)
        n = src.shape[0]
        bucket = next((b for b in self.buckets if b >= n), None)
        if bucket is None:  # larger than the biggest bucket: chunk it
            big = self.buckets[-1]
            outs = [self.translate(src[i:i + big]) for i in
                    range(0, n, big)]
            return (np.concatenate([o[0] for o in outs]),
                    np.concatenate([o[1] for o in outs]))
        if bucket > n:
            src = np.concatenate(
                [src, np.repeat(src[-1:], bucket - n, axis=0)])
        rng = jax.random.fold_in(self._seed, next(self._request_ids))
        tokens, scores = self._decode_fn(bucket)(self.params, src, rng)
        return np.asarray(tokens)[:n], np.asarray(scores)[:n]
