"""Serving client — ``InputQueue``/``OutputQueue`` API.

Reference analog (unverified — mount empty): ``python/serving/src/bigdl/
serving/client.py`` — enqueue ndarrays into Redis, poll results.  Here the
transport is the in-process ``ServingServer`` (the Redis/Flink cluster
plumbing is out of scope for the TPU core; the client API surface and
semantics — ids, enqueue/query, timeout — match).
"""

from typing import Optional

import numpy as np

from bigdl_tpu.serving.server import ServingServer


class InputQueue:
    def __init__(self, server: ServingServer):
        self._server = server

    def enqueue(self, uri: Optional[str] = None, **kwargs) -> str:
        """``InputQueue.enqueue(uri, t=ndarray)`` — returns the request id."""
        if len(kwargs) != 1:
            raise ValueError("enqueue expects exactly one named tensor, "
                             "e.g. enqueue('req-1', t=arr)")
        (arr,) = kwargs.values()
        return self._server.enqueue(np.asarray(arr), request_id=uri)


class OutputQueue:
    def __init__(self, server: ServingServer):
        self._server = server

    def query(self, uri: str, timeout: float = 30.0) -> np.ndarray:
        return self._server.query(uri, timeout=timeout)
