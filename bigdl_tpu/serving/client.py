"""Serving client — ``InputQueue``/``OutputQueue`` API.

Reference analog (unverified — mount empty): ``python/serving/src/bigdl/
serving/client.py`` — enqueue ndarrays into Redis, poll results.  Here the
transport is the in-process ``ServingServer`` (the Redis/Flink cluster
plumbing is out of scope for the TPU core; the client API surface and
semantics — ids, enqueue/query, timeout — match).

Lifecycle semantics ride through unchanged: ``enqueue`` can shed
(:class:`~bigdl_tpu.serving.server.ServiceUnavailableError` on a full
queue or degraded server — it never blocks unboundedly) and accepts a
per-request ``deadline_s``; ``query`` raises the request's recorded
verdict (:class:`~bigdl_tpu.serving.server.DeadlineExceededError` when it
expired in the queue, :class:`~bigdl_tpu.serving.server.
RequestDroppedError` when the server stopped before processing it).
"""

from typing import Optional

import numpy as np

from bigdl_tpu.serving.server import ServingServer


class InputQueue:
    def __init__(self, server: ServingServer):
        self._server = server

    def enqueue(self, uri: Optional[str] = None,
                deadline_s: Optional[float] = None,
                model: Optional[str] = None, **kwargs) -> str:
        """``InputQueue.enqueue(uri, t=ndarray)`` — returns the request id.

        ``deadline_s`` (relative) bounds how long the request may wait in
        the queue before the engine drops it instead of predicting;
        ``model`` names the registered tenant (default tenant when
        None)."""
        if len(kwargs) != 1:
            raise ValueError("enqueue expects exactly one named tensor, "
                             "e.g. enqueue('req-1', t=arr)")
        (arr,) = kwargs.values()
        return self._server.enqueue(np.asarray(arr), request_id=uri,
                                    deadline_s=deadline_s, model=model)

    def enqueue_generate(self, uri: Optional[str] = None,
                         deadline_s: Optional[float] = None,
                         model: Optional[str] = None, *, tokens,
                         **gen_kwargs) -> str:
        """Queue-client surface of the decode path (docs/serving.md
        §Autoregressive decode): admit a generate request for
        ``model``'s continuous decode engine; ``OutputQueue.query``
        returns the generated token array.  ``gen_kwargs`` pass through
        to :meth:`~bigdl_tpu.serving.server.ServingServer.
        enqueue_generate` (max_new_tokens, temperature, top_k, top_p,
        seed, on_token — and ``handoff``, a prefill worker's unpacked
        KV handoff for the decode-fleet split of docs/serving.md
        §Decode fleet, in which case ``tokens`` may be the handoff's
        own token array)."""
        return self._server.enqueue_generate(
            np.asarray(tokens, np.int32), request_id=uri,
            deadline_s=deadline_s, model=model, **gen_kwargs)


class OutputQueue:
    def __init__(self, server: ServingServer):
        self._server = server

    def query(self, uri: str, timeout: float = 30.0) -> np.ndarray:
        return self._server.query(uri, timeout=timeout)
