"""Multi-worker serving scale-out — process-isolated engine replicas.

Reference analog (unverified — mount empty): Cluster Serving's Flink job
(``scala/serving/.../ClusterServing.scala``) bought three things beyond
the single engine loop: process isolation (a poisoned model copy cannot
take the frontend down), horizontal scale-out (N task managers), and
supervision (Flink restarts failed tasks).  The TPU-native equivalent is
this pool: N worker subprocesses — each running the dynamic-batch
``ServingServer`` + ``HttpFrontend`` on its own port, each able to own
its own device — behind one round-robin HTTP proxy that health-checks
and RESTARTS dead workers.

    pool = ServingPool("my_pkg.my_mod:make_model", workers=2).start()
    # pool.url -> proxy endpoint: POST /predict, GET /health
    pool.stop()

``loader`` is a ``module:function`` spec resolving to a zero-arg callable
returning an :class:`~bigdl_tpu.serving.inference_model.InferenceModel` —
workers import it in their own interpreter (the model never crosses the
process boundary, exactly the reference's model-per-task-manager
posture).
"""

import json
import os
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib import request as _urlreq

from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.serving.pool")


def _worker_main(loader: str, batch_size: int, queue_capacity: int) -> None:
    """Entry point inside a worker subprocess."""
    import importlib

    import jax

    if os.environ.get("BIGDL_TPU_POOL_CPU"):
        jax.config.update("jax_platforms", "cpu")
    mod_name, _, fn_name = loader.partition(":")
    fn = getattr(importlib.import_module(mod_name), fn_name)

    from bigdl_tpu.serving.http_frontend import HttpFrontend
    from bigdl_tpu.serving.server import ServingConfig, ServingServer

    srv = ServingServer(fn(), ServingConfig(
        batch_size=batch_size, queue_capacity=queue_capacity)).start()
    fe = HttpFrontend(srv, port=0).start()
    print(f"WORKER_URL={fe.url}", flush=True)
    sys.stdin.readline()           # parent closes stdin to stop us
    fe.stop()
    srv.stop()


class _Worker:
    def __init__(self, loader: str, batch_size: int, queue_capacity: int,
                 env: Optional[dict] = None):
        self.loader = loader
        self.batch_size = batch_size
        self.queue_capacity = queue_capacity
        self.env = env
        self.proc: Optional[subprocess.Popen] = None
        self.url: Optional[str] = None

    def spawn(self, timeout: float = 120.0) -> None:
        env = dict(os.environ, **(self.env or {}))
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "bigdl_tpu.serving.pool", "--worker",
             "--loader", self.loader, "--batch-size",
             str(self.batch_size), "--queue-capacity",
             str(self.queue_capacity)],
            env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True)
        # readline blocks with no deadline, so read on a helper thread: a
        # loader that hangs before printing must not stall spawn() (the
        # supervisor calls spawn inline — a hung respawn would freeze ALL
        # supervision)
        found: List[str] = []

        def read_url():
            while True:
                line = self.proc.stdout.readline()
                if not line:
                    return
                line = line.strip()
                if line.startswith("WORKER_URL="):
                    found.append(line[len("WORKER_URL="):])
                    return

        t = threading.Thread(target=read_url, daemon=True)
        t.start()
        t.join(timeout)
        if found:
            self.url = found[0]
            return
        if self.proc.poll() is None:
            self.proc.kill()
        raise RuntimeError(
            f"serving worker failed to start within {timeout}s")

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def stop(self) -> None:
        if self.proc is None:
            return
        if self.proc.poll() is None:
            try:
                self.proc.stdin.close()
                self.proc.wait(timeout=10)
            except Exception:
                self.proc.kill()


class _ProxyHandler(BaseHTTPRequestHandler):
    server_version = "bigdl-tpu-serving-pool/1"

    def log_message(self, fmt, *args):
        log.debug(fmt, *args)

    def _forward(self, method: str, url: str, body: Optional[bytes]):
        req = _urlreq.Request(url, data=body, method=method, headers={
            "Content-Type": "application/json"})
        with _urlreq.urlopen(req, timeout=self.server.predict_timeout) as r:
            return r.status, r.read()

    def _reply(self, code: int, body: bytes):
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        import urllib.error

        pool: "ServingPool" = self.server.pool
        length = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(length)
        # try each worker once, starting at the round-robin cursor: a DEAD
        # worker (connection-level failure) is skipped instead of failing
        # the request; the supervisor thread notices the corpse and
        # respawns it independently
        last_err = None
        for url in pool._next_urls():
            try:
                code, out = self._forward("POST", url + self.path, body)
                return self._reply(code, out)
            except urllib.error.HTTPError as e:
                # the worker is ALIVE and answered (400 bad payload / 500
                # model error): relay its verdict, do NOT retry elsewhere
                return self._reply(e.code, e.read())
            except Exception as e:  # noqa: BLE001 — worker down mid-request
                last_err = e
        self._reply(503, json.dumps(
            {"error": f"no serving worker available: {last_err}"}).encode())

    def do_GET(self):
        pool: "ServingPool" = self.server.pool
        if self.path != "/health":
            return self._reply(404, b'{"error": "unknown path"}')
        agg = {"status": "ok", "workers": []}
        for w in pool.workers:
            one = {"url": w.url, "alive": w.alive()}
            if w.alive():
                try:
                    _, out = self._forward("GET", w.url + "/health", None)
                    one.update(json.loads(out))
                except Exception as e:  # noqa: BLE001
                    one["error"] = str(e)
            agg["workers"].append(one)
        agg["requests"] = sum(int(w.get("requests", 0))
                              for w in agg["workers"])
        agg["batches"] = sum(int(w.get("batches", 0))
                             for w in agg["workers"])
        self._reply(200, json.dumps(agg).encode())


class ServingPool:
    """N process-isolated serving workers behind one round-robin proxy
    with liveness supervision (dead workers are respawned)."""

    def __init__(self, loader: str, workers: int = 2, batch_size: int = 32,
                 queue_capacity: int = 4096, host: str = "127.0.0.1",
                 port: int = 0, predict_timeout: float = 30.0,
                 worker_env: Optional[dict] = None,
                 supervise_interval_s: float = 1.0):
        self.loader = loader
        self.n = workers
        self.batch_size = batch_size
        self.queue_capacity = queue_capacity
        self.worker_env = worker_env
        self.workers: List[_Worker] = []
        self._rr = 0
        self._rr_lock = threading.Lock()
        self._stop = threading.Event()
        self._supervise_interval = supervise_interval_s
        self._httpd = ThreadingHTTPServer((host, port), _ProxyHandler)
        self._httpd.pool = self  # type: ignore[attr-defined]
        self._httpd.predict_timeout = predict_timeout  # type: ignore[attr-defined]
        self.host, self.port = self._httpd.server_address[:2]
        self._threads: List[threading.Thread] = []
        self.restarts = 0

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- routing ------------------------------------------------------------
    def _next_urls(self) -> List[str]:
        with self._rr_lock:
            self._rr += 1
            start = self._rr
        ordered = [self.workers[(start + i) % len(self.workers)]
                   for i in range(len(self.workers))]
        return [w.url for w in ordered if w.alive() and w.url]

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ServingPool":
        for _ in range(self.n):
            w = _Worker(self.loader, self.batch_size, self.queue_capacity,
                        self.worker_env)
            w.spawn()
            self.workers.append(w)
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        s = threading.Thread(target=self._supervise, daemon=True)
        s.start()
        self._threads = [t, s]
        log.info("serving pool: %d workers behind %s", self.n, self.url)
        return self

    def _supervise(self) -> None:
        """Flink-style task supervision: respawn dead workers."""
        while not self._stop.is_set():
            for w in self.workers:
                if not w.alive() and not self._stop.is_set():
                    log.warning("serving worker %s died; respawning", w.url)
                    try:
                        w.spawn()
                        self.restarts += 1
                    except Exception as e:  # noqa: BLE001 — retried next tick
                        log.error("respawn failed: %s", e)
            self._stop.wait(self._supervise_interval)

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        for w in self.workers:
            w.stop()


def _main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--loader", required=True)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--queue-capacity", type=int, default=4096)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--port", type=int, default=8000)
    args = ap.parse_args()
    if args.worker:
        _worker_main(args.loader, args.batch_size, args.queue_capacity)
        return
    pool = ServingPool(args.loader, workers=args.workers,
                       batch_size=args.batch_size,
                       queue_capacity=args.queue_capacity,
                       port=args.port).start()
    print(f"POOL_URL={pool.url}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pool.stop()


if __name__ == "__main__":
    _main()
