"""Multi-worker serving scale-out — process-isolated engine replicas.

Reference analog (unverified — mount empty): Cluster Serving's Flink job
(``scala/serving/.../ClusterServing.scala``) bought three things beyond
the single engine loop: process isolation (a poisoned model copy cannot
take the frontend down), horizontal scale-out (N task managers), and
supervision (Flink restarts failed tasks).  The TPU-native equivalent is
this pool: N worker subprocesses — each running the continuous-batching
``ServingServer`` + ``HttpFrontend`` on its own port, each able to own
its own device — behind one round-robin HTTP proxy that health-checks
and RESTARTS dead workers.

    pool = ServingPool("my_pkg.my_mod:make_model", workers=2).start()
    # pool.url -> proxy endpoint: POST /predict, GET /health
    pool.stop()

``loader`` is a ``module:function`` spec resolving to a zero-arg callable
returning an :class:`~bigdl_tpu.serving.inference_model.InferenceModel` —
or a ``{name: model}`` dict for multi-tenant workers — imported in each
worker's own interpreter (the model never crosses the process boundary,
exactly the reference's model-per-task-manager posture).

Routing hardening (docs/serving.md): each worker sits behind a per-worker
CIRCUIT BREAKER — consecutive connection-level failures open it, an open
breaker is skipped without burning a connect timeout per request, and
after a cooldown a single half-open probe decides whether it closes.
Worker-side backpressure (429/503) routes to the next worker instead of
bouncing the client.  ``hedge_after_s`` optionally duplicates an
idempotent predict onto a second worker when the first is slow (bounded:
one hedge, first answer wins).  ``stop()`` drains workers before killing
them — each worker finishes its queued requests within the drain budget.
Forwards ride per-worker KEEP-ALIVE connections (``conn_reuse`` counts
the hits) instead of paying a TCP handshake per request.

Autoscaling (docs/serving.md §Autoscaling): with ``max_workers`` above
``min_workers``, a metrics thread watches the signals the workers already
export on ``/health`` — queue depth and the latency histogram — and
grows/shrinks the pool between the bounds — asymmetric on purpose: one
over-threshold pressure tick spawns a worker (queued users are waiting;
the cooldown rate-limits repeats), while shrinking demands sustained
idle (never while a breaker is open, always drain-before-kill, never
below ``min_workers``).
"""

import http.client
import json
import os
import subprocess
import sys
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.obs import flight, trace
from bigdl_tpu.obs.export import CONTENT_TYPE, federate, render_prometheus
from bigdl_tpu.optim.metrics import global_metrics
from bigdl_tpu.resilience import faults
from bigdl_tpu.serving.http_frontend import REQUEST_ID_RE
from bigdl_tpu.serving.json_http import reply_json
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.serving.pool")

# pool stats that ALSO publish under the fleet's canonical metric names
# (docs/observability.md): the proxy is the only process that can count
# failovers/orphans — the dying worker can't — so its registry carries
# the serving.fleet.* series the chaos gate asserts on
_FLEET_GLOBAL = {"fleet_failovers": "serving.fleet.failovers",
                 "fleet_migrations": "serving.fleet.migrations",
                 "fleet_resumed_tokens": "serving.fleet.resumed_tokens",
                 "fleet_orphans": "serving.fleet.orphaned_requests"}


def _worker_main(loader: str, batch_size: int, queue_capacity: int,
                 drain_timeout_s: float = 5.0, role: str = "both") -> None:
    """Entry point inside a worker subprocess."""
    import importlib

    import jax

    if os.environ.get("BIGDL_TPU_POOL_CPU"):
        jax.config.update("jax_platforms", "cpu")
    # same rationale as the proxy (see ServingPool.start): the handler
    # threads stream per-token chunks and must not queue a GIL switch
    # interval behind the engine thread for every token they write
    sys.setswitchinterval(0.001)
    mod_name, _, fn_name = loader.partition(":")
    fn = getattr(importlib.import_module(mod_name), fn_name)

    from bigdl_tpu.serving.http_frontend import HttpFrontend
    from bigdl_tpu.serving.server import ServingConfig, ServingServer

    cfg = ServingConfig(batch_size=batch_size, queue_capacity=queue_capacity)
    loaded = fn()
    if isinstance(loaded, dict):
        # multi-tenant worker: every model in the registry shares this
        # process's engine under weighted admission
        srv = ServingServer(models=loaded, config=cfg).start()
    else:
        srv = ServingServer(loaded, cfg).start()
    srv.role = role  # fleet role, reported via /health for the router
    hedge = os.environ.get("BIGDL_TPU_PREFILL_HEDGE_S")
    fe = HttpFrontend(srv, port=0,
                      prefill_hedge_s=float(hedge) if hedge else None
                      ).start()
    print(f"WORKER_URL={fe.url}", flush=True)
    sys.stdin.readline()           # parent closes stdin to stop us
    # drain-before-kill: finish queued requests (new ones are shed with
    # 429 by the draining server) before the frontend socket goes away
    srv.stop(drain=True, timeout=drain_timeout_s)
    fe.stop()


class _Breaker:
    """Per-worker circuit breaker over CONNECTION-level failures.

    closed -> (fail_threshold consecutive failures) -> open ->
    (cooldown_s elapses) -> half-open: exactly one probe request is
    admitted; its success closes the breaker, its failure re-opens.
    Application-level errors (worker answered 4xx/5xx) count as success —
    the worker is alive and routable.

    ``try_acquire`` (mutating — reserves the half-open probe slot) is
    called only at the moment a request is actually about to be sent;
    candidate listing must stay side-effect-free, otherwise a worker
    listed-but-never-contacted would burn its probe and wedge half-open
    forever with nothing ever feeding record_success/failure."""

    def __init__(self, fail_threshold: int = 3, cooldown_s: float = 2.0,
                 name: str = "worker", on_open=None):
        self.fail_threshold = fail_threshold
        self.cooldown_s = cooldown_s
        self.name = name
        self.state = "closed"
        self.failures = 0
        self.trips = 0
        self._opened_t = 0.0
        self._lock = threading.Lock()
        # fired (outside the lock) each time the breaker TRIPS open —
        # the pool wires this to invalidate_fleet_snapshot so the router
        # stops placing onto a worker the breaker just condemned, without
        # waiting out the snapshot TTL
        self._on_open = on_open

    def _transition(self, new: str, **data) -> None:
        """State change + its flight-recorder event (postmortems must show
        the breaker's trip/probe/close sequence around a worker death)."""
        if new != self.state:
            flight.record("breaker_" + new.replace("-", "_"),
                          breaker=self.name, **data)
        self.state = new

    def try_acquire(self) -> bool:
        """Admission for one real attempt (mutating).  Open past the
        cooldown flips to half-open and admits THIS caller as the probe;
        half-open admits nobody else until the probe reports back."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if time.time() - self._opened_t >= self.cooldown_s:
                    self._transition("half-open")
                    return True
                return False
            return False  # half-open: a probe is already in flight

    def record_success(self) -> None:
        with self._lock:
            self.failures = 0
            self._transition("closed")

    def record_failure(self) -> None:
        opened = False
        with self._lock:
            self.failures += 1
            if (self.state == "half-open"
                    or self.failures >= self.fail_threshold):
                if self.state != "open":
                    self.trips += 1
                    opened = True
                self._transition("open", failures=self.failures,
                                 trips=self.trips)
                self._opened_t = time.time()
        if opened and self._on_open is not None:
            try:
                self._on_open()
            except Exception:  # noqa: BLE001 — a callback must not poison
                pass           # the breaker's own accounting

    def reset(self) -> None:
        with self._lock:
            self._transition("closed", via="respawn")
            self.failures = 0

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self.state, "failures": self.failures,
                    "trips": self.trips}


class _ConnPool:
    """Per-worker-url keep-alive HTTP connections (satellite of the
    continuous-batching PR: the proxy used to pay a fresh TCP handshake
    per forwarded request).  ``acquire`` hands back an idle connection
    when one exists (``reused=True`` — the caller counts the hit) or
    opens a fresh one; ``release`` parks it for the next forward, bounded
    per url so a burst cannot hoard sockets."""

    def __init__(self, timeout: float, depth: int = 16):
        self._timeout = timeout
        self._depth = depth
        self._idle: Dict[str, List[http.client.HTTPConnection]] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _host_port(url: str) -> Tuple[str, int]:
        host, _, port = url.split("//", 1)[1].partition(":")
        return host, int(port or 80)

    def acquire(self, url: str
                ) -> Tuple[http.client.HTTPConnection, bool]:
        with self._lock:
            stack = self._idle.get(url)
            if stack:
                return stack.pop(), True
        host, port = self._host_port(url)
        return http.client.HTTPConnection(host, port,
                                          timeout=self._timeout), False

    def release(self, url: str, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            stack = self._idle.setdefault(url, [])
            if len(stack) < self._depth:
                stack.append(conn)
                return
        conn.close()

    def request(self, url: str, method: str, path: str,
                body: Optional[bytes] = None,
                headers: Optional[dict] = None,
                on_reuse=None) -> Tuple[int, bytes, dict]:
        """One request over a pooled connection: acquire, send, read,
        park (or close when the peer said so).  A reused socket that
        turns out stale gets ONE fresh-connection retry.  ``on_reuse``
        fires when the answering attempt rode a parked socket (the
        proxy's ``conn_reuse`` stat).  The single implementation behind
        forwards and health probes — the retry/release protocol must not
        fork."""
        for attempt in (0, 1):
            conn, reused = self.acquire(url)
            try:
                conn.request(method, path, body=body, headers=headers or {})
                resp = conn.getresponse()
                data = resp.read()
            except Exception:
                conn.close()
                if reused and attempt == 0:
                    continue  # stale keep-alive socket: one fresh retry
                raise
            if resp.will_close:
                conn.close()
            else:
                self.release(url, conn)
            if reused and on_reuse is not None:
                on_reuse()
            return resp.status, data, dict(resp.headers)
        raise RuntimeError("unreachable")

    def clear(self, url: Optional[str] = None) -> None:
        """Drop idle connections (for one url, or all) — a respawned or
        removed worker's sockets must not linger."""
        with self._lock:
            if url is None:
                stacks = list(self._idle.values())
                self._idle.clear()
            else:
                stacks = [self._idle.pop(url, [])]
        for stack in stacks:
            for conn in stack:
                try:
                    conn.close()
                except Exception:  # noqa: BLE001 — already gone
                    pass


class _Worker:
    def __init__(self, loader: str, batch_size: int, queue_capacity: int,
                 env: Optional[dict] = None,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 2.0,
                 drain_timeout_s: float = 5.0,
                 name: str = "worker", role: str = "both",
                 on_breaker_open=None):
        self.loader = loader
        self.batch_size = batch_size
        self.queue_capacity = queue_capacity
        self.env = env
        self.drain_timeout_s = drain_timeout_s
        self.name = name
        # fleet role (docs/serving.md §Decode fleet): "both" | "prefill"
        # | "decode" — the proxy's FleetRouter places /generate by it
        self.role = role
        self.proc: Optional[subprocess.Popen] = None
        self.url: Optional[str] = None
        self.breaker = _Breaker(breaker_threshold, breaker_cooldown_s,
                                name=name, on_open=on_breaker_open)

    def spawn(self, timeout: float = 120.0) -> None:
        env = dict(os.environ, **(self.env or {}))
        self.url = None  # a corpse's url must never leak into routing/health
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "bigdl_tpu.serving.pool", "--worker",
             "--loader", self.loader, "--batch-size",
             str(self.batch_size), "--queue-capacity",
             str(self.queue_capacity), "--drain-timeout",
             str(self.drain_timeout_s), "--role", self.role],
            env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True)
        # readline blocks with no deadline, so read on a helper thread: a
        # loader that hangs before printing must not stall spawn() (the
        # supervisor calls spawn inline — a hung respawn would freeze ALL
        # supervision)
        found: List[str] = []

        def read_url():
            while True:
                line = self.proc.stdout.readline()
                if not line:
                    return
                line = line.strip()
                if line.startswith("WORKER_URL="):
                    found.append(line[len("WORKER_URL="):])
                    return

        t = threading.Thread(target=read_url, daemon=True)
        t.start()
        t.join(timeout)
        if found:
            self.url = found[0]
            self.breaker.reset()  # fresh process, fresh record
            return
        if self.proc.poll() is None:
            self.proc.kill()
        raise RuntimeError(
            f"serving worker failed to start within {timeout}s")

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def routable(self) -> bool:
        """Listing-time check — deliberately breaker-blind (and so
        side-effect-free): the breaker gates at attempt time via
        ``try_acquire``, where a skip costs nothing."""
        return self.alive() and self.url is not None

    def request_stop(self) -> None:
        """Begin drain-before-kill: closing stdin asks the worker to
        finish its queued requests (bounded by its drain budget) and
        exit."""
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.stdin.close()
            except Exception:  # noqa: BLE001 — already half-dead
                self.proc.kill()

    def join_stop(self) -> None:
        """Wait out the drain budget; only a worker that overruns it is
        killed."""
        if self.proc is None or self.proc.poll() is not None:
            return
        try:
            self.proc.wait(timeout=self.drain_timeout_s + 10)
        except Exception:
            self.proc.kill()

    def stop(self) -> None:
        self.request_stop()
        self.join_stop()


class _ProxyHandler(BaseHTTPRequestHandler):
    server_version = "bigdl-tpu-serving-pool/1"
    protocol_version = "HTTP/1.1"  # clients keep-alive into the proxy too
    # the streaming relay re-frames many tiny chunks toward the client;
    # Nagle would hold each one for the previous chunk's ACK
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):
        log.debug(fmt, *args)

    def _forward(self, method: str, base: str, path: str,
                 body: Optional[bytes]):
        """One upstream request over the per-worker keep-alive pool.  A
        reused connection that fails before any response (the worker
        idle-closed it) is retried ONCE on a fresh connection — safe even
        for POST because predict is idempotent (the hedging premise)."""
        pool: "ServingPool" = self.server.pool
        headers = {"Content-Type": "application/json"}
        rid = getattr(self, "_rid", None)
        if rid is not None:
            # one id names the request across proxy, worker frontend, and
            # engine spans — retries and hedges reuse it, so a trace shows
            # every worker that saw this request
            headers["X-Request-Id"] = rid
        deadline = getattr(self, "_deadline_hdr", None)
        if deadline is not None:
            # the client's header-form deadline must reach the worker or
            # its request outlives itself in a backed-up queue
            headers["X-Deadline-S"] = deadline
        model = getattr(self, "_model_hdr", None)
        if model is not None:
            # header-form tenant routing: dropping it would silently
            # serve the default tenant's answer with a 200
            headers["X-Model"] = model
        prefill = getattr(self, "_prefill_hdr", None)
        if prefill is not None:
            # physical prefill/decode split: tells the decode worker
            # which prefill worker to ship the prompt to
            headers["X-Prefill-Url"] = prefill
        return pool.conns.request(
            base, method, path, body=body, headers=headers,
            on_reuse=lambda: pool._count("conn_reuse"))

    def _reply(self, code: int, body: bytes,
               headers: Optional[dict] = None):
        reply_json(self, code, body, headers)

    def _attempt(self, worker: "_Worker", body: bytes
                 ) -> Tuple[str, int, bytes]:
        """One forward to one worker, with breaker accounting.  Returns
        ('relay', code, body) for an answer that must go to the client,
        ('busy', ...) for worker-side backpressure (try the next worker),
        ('skip', ...) when the breaker refuses admission (open, or a
        probe already in flight), or raises on a connection-level failure
        (breaker already fed)."""
        if not worker.breaker.try_acquire():
            return ("skip", 0, b"")
        pool: "ServingPool" = self.server.pool
        try:
            code, out, _ = self._forward("POST", worker.url, self.path,
                                         body)
        except Exception:
            worker.breaker.record_failure()
            # a connection-level failure is fleet-placement news even
            # below the breaker threshold: the cached health snapshot may
            # still list this worker as the best decode target
            pool.invalidate_fleet_snapshot()
            raise
        # the worker is ALIVE and answered: its breaker stays closed.
        # 429/503 are backpressure/draining — route around, the next
        # worker may have queue room; other codes (400 bad payload /
        # 500 model error) relay as the worker's verdict
        worker.breaker.record_success()
        if code in (429, 503):
            return ("busy", code, out)
        return ("relay", code, out)

    def do_POST(self):
        pool: "ServingPool" = self.server.pool
        try:
            length = int(self.headers.get("Content-Length", "0"))
            if length < 0:
                raise ValueError(length)  # read(-1) would buffer to EOF
        except ValueError:
            self.close_connection = True  # unread body poisons keep-alive
            return self._reply(400, b'{"error": "bad Content-Length"}')
        if length > pool.max_body_bytes:
            pool._count("rejected_oversize")
            self.close_connection = True
            return self._reply(413, json.dumps(
                {"error": f"request body {length} bytes exceeds limit "
                          f"{pool.max_body_bytes}"}).encode())
        body = self.rfile.read(length)
        # assign the correlation id AT THE EDGE (caller's wins — header,
        # else the documented "request_id" payload fallback): every
        # retry/hedge below forwards the same X-Request-Id, so the worker
        # spans of one request share one id end to end (and the worker's
        # header-wins precedence cannot discard a payload-supplied id)
        rid = self.headers.get("X-Request-Id")
        if rid is None and b'"request_id"' in body:
            # the substring probe keeps the common no-id case from paying
            # a full JSON decode of the instances array at the proxy
            try:
                payload = json.loads(body)
                if isinstance(payload, dict) \
                        and payload.get("request_id") is not None:
                    rid = str(payload["request_id"])
            except (ValueError, json.JSONDecodeError):
                pass  # malformed body: the worker's 400 is the verdict
        if rid is not None and not REQUEST_ID_RE.fullmatch(rid):
            # the id is echoed into a response header: same guard as the
            # worker frontend, enforced at the edge too
            return self._reply(400, json.dumps(
                {"error": "bad request id: must match "
                          "[A-Za-z0-9._:-]{1,128}"}).encode())
        self._rid = rid or uuid.uuid4().hex
        self._deadline_hdr = self.headers.get("X-Deadline-S")
        self._model_hdr = self.headers.get("X-Model")
        self._prefill_hdr = None
        rid_hdr = {"X-Request-Id": self._rid}
        if self.path == "/generate":
            # decode-fleet path (docs/serving.md §Decode fleet): KV-aware
            # placement instead of round-robin, prefill/decode split when
            # the topology has dedicated prefill workers, and streaming
            # relay — the rid was assigned above, so every retry below
            # shares one id end to end
            return self._generate_fleet(pool, body, rid_hdr)
        # breaker-aware routing, starting at the round-robin cursor: dead
        # or breaker-open workers are skipped without burning a connect
        # timeout; worker-side 429/503 routes to the next worker; the
        # supervisor respawns corpses independently
        with trace.span("serving/proxy_request", request_id=self._rid):
            last_err: Optional[BaseException] = None
            busy: Optional[Tuple[int, bytes]] = None
            candidates = pool._next_workers()
            tried = set()  # a hedge backup that actually saw this request
            #                must not get the same body again next iteration
            #                (duplicate predict work)
            for i, w in enumerate(candidates):
                if id(w) in tried:
                    continue
                tried.add(id(w))
                try:
                    if (pool.hedge_after_s is not None
                            and i + 1 < len(candidates)):
                        verdict, code, out = self._attempt_hedged(
                            w, candidates[i + 1], body, pool, tried)
                    else:
                        verdict, code, out = self._attempt(w, body)
                except Exception as e:  # noqa: BLE001 — worker down mid-request
                    last_err = e
                    continue
                if verdict == "skip":
                    continue
                if verdict == "busy":
                    busy = (code, out)
                    continue
                return self._reply(code, out, rid_hdr)
            if busy is not None:
                # every routable worker is shedding: relay the backpressure
                # verdict (with its Retry-After) instead of inventing a 503
                pool._count("proxy_busy")
                return self._reply(
                    busy[0], busy[1],
                    {"Retry-After": str(pool.retry_after_s), **rid_hdr})
            pool._count("proxy_unavailable")
            self._reply(503, json.dumps(
                {"error": f"no serving worker available: {last_err}"}
                ).encode(),
                {"Retry-After": str(pool.retry_after_s), **rid_hdr})

    def _attempt_hedged(self, primary: "_Worker", backup: "_Worker",
                        body: bytes, pool: "ServingPool", tried: set
                        ) -> Tuple[str, int, bytes]:
        """Bounded hedge for idempotent predicts: fire the primary, and if
        it has not answered within ``hedge_after_s`` also fire ONE backup;
        the first answer wins (the loser's response is discarded — predict
        is pure, so duplicated work is wasted chip time, not corruption).
        The backup joins ``tried`` only when the hedge actually fires — a
        fast primary verdict must leave it available to the routing
        loop."""
        import queue as _queue

        results: "_queue.Queue" = _queue.Queue()

        def run(worker):
            try:
                results.put(("ok", self._attempt(worker, body)))
            except Exception as e:  # noqa: BLE001 — breaker already fed
                results.put(("err", e))

        threading.Thread(target=run, args=(primary,), daemon=True).start()
        try:
            kind, payload = results.get(timeout=pool.hedge_after_s)
        except _queue.Empty:
            pool._count("hedged_requests")
            tried.add(id(backup))
            threading.Thread(target=run, args=(backup,), daemon=True).start()
            kind, payload = results.get()  # first of the two to answer
            if kind == "err" or payload[0] == "skip":
                # give the straggler a chance before giving up on the pair
                try:
                    kind2, payload2 = results.get(
                        timeout=self.server.predict_timeout)
                    if kind2 == "ok" and payload2[0] != "skip":
                        kind, payload = kind2, payload2
                except _queue.Empty:
                    pass
        if kind == "ok":
            return payload
        raise payload

    # -- decode fleet (docs/serving.md §Decode fleet) -----------------------
    def _generate_fleet(self, pool: "ServingPool", body: bytes,
                        rid_hdr: dict) -> None:
        """Route one ``POST /generate``: KV-aware placement from cached
        worker healths (falling back to round-robin order behind the
        router's pick), the prefill/decode split via ``X-Prefill-Url``
        when the topology has dedicated prefill workers, and chunked
        streaming relayed end to end.  Backpressure (429/503) before any
        stream byte retries the next decode worker under the SAME
        request id — the proxy assigned it, so the worker-side duplicate
        guard never fires across a retry ladder."""
        from bigdl_tpu.serving.fleet import FleetRouter

        stream = False
        prompt_len = None
        try:
            payload = json.loads(body)
            if isinstance(payload, dict):
                stream = bool(payload.get("stream", False))
                toks = payload.get("tokens")
                if isinstance(toks, list):
                    prompt_len = len(toks)
        except (ValueError, json.JSONDecodeError):
            pass  # malformed body: a worker's 400 is the verdict
        snap = pool.fleet_snapshot()
        entries = []
        for w, h in snap:
            e = dict(h) if isinstance(h, dict) else {}
            e.setdefault("role", w.role)
            e["alive"] = w.routable()
            entries.append(e)
        didx, pidx = FleetRouter().route(entries)
        workers = [w for w, _ in snap]
        # the split is an optimization, not a routing invariant: shipping
        # a SHORT prompt's pages costs more than recomputing them on the
        # decode worker, so only prompts past the threshold cross the
        # handoff channel (an unknown length — prompt-string bodies —
        # splits: it may be arbitrarily long once tokenized)
        worth_splitting = (prompt_len is None
                           or prompt_len >= pool.fleet_split_min_tokens)
        if pidx is not None and workers[pidx].routable() and worth_splitting:
            self._prefill_hdr = workers[pidx].url
            pool._count("fleet_split")
        # the router's decode pick leads; every other decode-capable
        # routable worker follows in round-robin order as the retry
        # ladder (a prefill-role worker never decodes)
        cands: List[_Worker] = []
        seen = set()
        if didx is not None and workers[didx].routable():
            cands.append(workers[didx])
            seen.add(id(workers[didx]))
            pool._count("fleet_routed")
        for w in pool._next_workers():
            if id(w) not in seen and getattr(w, "role", "both") != "prefill":
                cands.append(w)
                seen.add(id(w))
        with trace.span("serving/proxy_generate", request_id=self._rid,
                        stream=stream):
            if stream:
                return self._relay_stream(pool, cands, body, rid_hdr)
            last_err: Optional[BaseException] = None
            busy: Optional[Tuple[int, bytes]] = None
            for w in cands:
                try:
                    verdict, code, out = self._attempt(w, body)
                except Exception as e:  # noqa: BLE001 — worker down
                    last_err = e
                    continue
                if verdict == "skip":
                    continue
                if verdict == "busy":
                    busy = (code, out)
                    continue
                return self._reply(code, out, rid_hdr)
            self._reply_unrouted(pool, busy, last_err, rid_hdr)

    @staticmethod
    def _park(pool: "ServingPool", url: str, conn, resp) -> None:
        if resp.will_close:
            conn.close()
        else:
            pool.conns.release(url, conn)

    def _relay_stream(self, pool: "ServingPool", candidates: List["_Worker"],
                      body: bytes, rid_hdr: dict) -> None:
        """Relay a chunked NDJSON token stream through the proxy's
        keep-alive path: one upstream connection held for the stream's
        life, each worker LINE re-framed toward the client as it arrives
        (token latency is the product — no buffering).

        Mid-stream FAILOVER (docs/serving.md §Fleet fault tolerance):
        every token line is parsed and its token id recorded in
        ``delivered`` before it reaches the client, so when the worker
        dies mid-stream (read error, truncated chunk framing, injected
        ``fleet_stream_sever``) the proxy re-places the request on the
        next decode-capable worker with ``resume_from=delivered`` — the
        engine's position-keyed sampling makes the resumed continuation
        byte-identical — and relays only tokens past the resume point.
        A drain-migrated request prefers the peer that adopted its KV
        (``pool.take_migrated``).  Re-placement rounds retry (the
        supervisor may still be respawning the fleet) within the
        predict-timeout budget; only when that runs out is the stream
        ORPHANED: the client gets a terminal error line and a proper
        chunk terminator, never a silent truncation."""
        headers = {"Content-Type": "application/json",
                   "X-Request-Id": self._rid}
        if self._deadline_hdr is not None:
            headers["X-Deadline-S"] = self._deadline_hdr
        if self._model_hdr is not None:
            headers["X-Model"] = self._model_hdr
        if self._prefill_hdr is not None:
            headers["X-Prefill-Url"] = self._prefill_hdr
        last_err: Optional[BaseException] = None
        busy: Optional[Tuple[int, bytes]] = None
        delivered: List[int] = []   # token ids already relayed, in order
        started = False             # 200 + chunked headers already sent
        failing_since: Optional[float] = None  # first worker-loss instant
        cur_body = body
        budget_t = time.time() + float(self.server.predict_timeout)
        while True:
            for w in candidates:
                if not w.breaker.try_acquire():
                    continue
                resp = conn = None
                try:
                    for attempt in (0, 1):
                        conn, reused = pool.conns.acquire(w.url)
                        try:
                            conn.request("POST", "/generate", body=cur_body,
                                         headers=headers)
                            resp = conn.getresponse()
                            break
                        except Exception:
                            conn.close()
                            conn = None
                            if not (reused and attempt == 0):
                                raise
                            # stale keep-alive socket: one fresh retry
                except Exception as e:  # noqa: BLE001 — worker down
                    w.breaker.record_failure()
                    pool.invalidate_fleet_snapshot()
                    last_err = e
                    continue
                w.breaker.record_success()
                if resp.status in (429, 503):
                    # backpressure BEFORE any stream byte: the next
                    # decode worker retries under the same request id
                    # (a resume body re-prefills deterministically, so
                    # bouncing it between workers is safe)
                    busy = (resp.status, resp.read())
                    self._park(pool, w.url, conn, resp)
                    continue
                chunked = "chunked" in (resp.getheader("Transfer-Encoding")
                                        or "")
                if resp.status != 200 or not chunked:
                    # error verdicts (400/404/500...) come back framed
                    # with Content-Length; relay buffered like any
                    # forward — unless the client already holds half a
                    # stream, in which case this worker merely refused
                    # the resume and the ladder continues
                    data = resp.read()
                    self._park(pool, w.url, conn, resp)
                    if started:
                        last_err = RuntimeError(
                            f"resume refused: HTTP {resp.status} "
                            f"{data[:200]!r}")
                        continue
                    return self._reply(resp.status, data, rid_hdr)
                if failing_since is not None:
                    # the request survived its worker: count the
                    # failover and the recovery latency the client paid
                    pool._count("fleet_failovers")
                    if delivered:
                        pool._count("fleet_resumed_tokens",
                                    len(delivered))
                    global_metrics().observe(
                        "serving.fleet.recovery_s",
                        time.time() - failing_since)
                    flight.record("fleet_failover", request_id=self._rid,
                                  worker=w.name,
                                  resumed_tokens=len(delivered))
                    failing_since = None
                if not started:
                    pool._count("stream_relays")
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     resp.getheader("Content-Type")
                                     or "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.send_header("X-Request-Id", self._rid)
                    self.end_headers()
                    started = True
                outcome, err = self._pump_stream(pool, w, conn, resp,
                                                 delivered)
                if outcome in ("done", "client_gone"):
                    return
                # "severed": the WORKER side failed mid-stream
                w.breaker.record_failure()
                pool.invalidate_fleet_snapshot()
                if failing_since is None:
                    failing_since = time.time()
                last_err = err
                cur_body = self._resume_body(body, delivered)
                if cur_body is None:
                    return self._orphan(pool, started, last_err, rid_hdr)
                candidates = self._failover_candidates(pool, w)
                break  # restart the ladder against the rebuilt list
            else:
                # ladder exhausted without an answer
                if not started:
                    return self._reply_unrouted(pool, busy, last_err,
                                                rid_hdr)
                if time.time() >= budget_t:
                    return self._orphan(pool, started, last_err, rid_hdr)
                # the fleet may be mid-respawn: wait a beat, rebuild
                time.sleep(0.25)
                candidates = self._failover_candidates(pool, None)
            if started and time.time() >= budget_t:
                return self._orphan(pool, started, last_err, rid_hdr)

    def _pump_stream(self, pool: "ServingPool", w: "_Worker", conn, resp,
                     delivered: List[int]
                     ) -> Tuple[str, Optional[BaseException]]:
        """Pump one worker's un-chunked NDJSON stream to the client,
        line-buffered so every ``{"token":..,"index":..}`` event lands in
        ``delivered`` — the failover resume point — before the client
        sees it.  Lines whose index is already delivered (an adopting
        worker re-emits its import-boundary token) are dropped, not
        duplicated.  Returns ``('done', None)`` after a complete stream
        (the worker wrote its terminator — a severed socket raises
        ``IncompleteRead`` from ``read1`` instead), ``('client_gone',
        None)`` when the CLIENT hung up (write-side failure — never
        confused with a worker death), or ``('severed', err)`` when the
        WORKER side failed mid-stream."""
        buf = b""
        while True:
            try:
                faults.fire("fleet_stream_sever")
                data = resp.read1(65536)
            except Exception as e:  # noqa: BLE001 — worker died mid-stream
                conn.close()
                return ("severed", e)
            if not data:
                break
            buf += data
            out = bytearray()
            while b"\n" in buf:
                line, _, buf = buf.partition(b"\n")
                if self._track_line(line, delivered):
                    out += line + b"\n"
            if out:
                try:
                    self.wfile.write(f"{len(out):X}\r\n".encode()
                                     + bytes(out) + b"\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    conn.close()  # worker sees the reset and cancels
                    self.close_connection = True
                    return ("client_gone", None)
        try:
            if buf:
                # defensive: a final line without its newline
                self.wfile.write(f"{len(buf):X}\r\n".encode() + buf
                                 + b"\r\n")
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            conn.close()
            self.close_connection = True
            return ("client_gone", None)
        if resp.will_close:
            conn.close()
        else:
            pool.conns.release(w.url, conn)
        return ("done", None)

    @staticmethod
    def _track_line(line: bytes, delivered: List[int]) -> bool:
        """Failover bookkeeping for one NDJSON event: token events append
        to ``delivered``; an index the client already holds (the resume
        boundary re-emitted by an adopting worker) is dropped.  Anything
        else — final verdicts, unparseable bytes — passes through
        untouched."""
        if not line.strip():
            return False  # swallow keep-alive blanks, don't re-frame them
        try:
            ev = json.loads(line)
        except Exception:  # noqa: BLE001 — not ours to judge
            return True
        if not isinstance(ev, dict):
            return True
        idx, tok = ev.get("index"), ev.get("token")
        if not isinstance(idx, int) or not isinstance(tok, int):
            return True
        if idx < len(delivered):
            return False  # duplicate of a token the client already has
        delivered.append(tok)
        return True

    def _resume_body(self, body: bytes, delivered: List[int]
                     ) -> Optional[bytes]:
        """Rebuild the request body for a failover re-placement: the
        original payload plus ``resume_from`` = every token the client
        already holds (the worker frontend re-prefills prompt+resume, or
        adopts a parked migration handoff, and continues byte-
        identically).  None when the body cannot be rebuilt (non-JSON
        payload) — the caller orphans the stream."""
        try:
            payload = json.loads(body)
        except Exception:  # noqa: BLE001
            return None
        if not isinstance(payload, dict):
            return None
        if delivered:
            payload["resume_from"] = list(delivered)
        payload["stream"] = True
        return json.dumps(payload).encode()

    def _failover_candidates(self, pool: "ServingPool",
                             exclude: Optional["_Worker"]
                             ) -> List["_Worker"]:
        """Decode-capable routable workers for one failover round — the
        peer that adopted this request's migrated KV (when the pool
        drained the dying worker first) sorted to the front, so a
        migrated request resumes from imported pages instead of paying a
        full re-prefill."""
        cands = [w for w in pool._next_workers()
                 if getattr(w, "role", "both") != "prefill"
                 and w is not exclude]
        peer = pool.take_migrated(self._rid)
        if peer is not None:
            cands.sort(key=lambda w: 0 if w.url == peer else 1)
        return cands

    def _orphan(self, pool: "ServingPool", started: bool,
                err: Optional[BaseException], rid_hdr: dict) -> None:
        """Every re-placement failed inside the budget: the stream is
        ORPHANED.  The client gets a terminal error line plus a proper
        chunk terminator — a well-formed, explicitly failed stream the
        SDK surfaces as an error, never a silent truncation it could
        mistake for completion."""
        pool._count("fleet_orphans")
        flight.record("fleet_orphan", request_id=self._rid,
                      error=str(err))
        if not started:
            return self._reply_unrouted(pool, None, err, rid_hdr)
        line = json.dumps(
            {"done": True,
             "error": f"stream orphaned: worker lost mid-stream and no "
                      f"re-placement succeeded ({err})"}).encode() + b"\n"
        try:
            self.wfile.write(f"{len(line):X}\r\n".encode() + line
                             + b"\r\n" + b"0\r\n\r\n")
        except Exception:  # noqa: BLE001 — client gone too
            pass
        self.close_connection = True

    def _reply_unrouted(self, pool: "ServingPool",
                        busy: Optional[Tuple[int, bytes]],
                        last_err: Optional[BaseException],
                        rid_hdr: dict) -> None:
        if busy is not None:
            # every routable worker is shedding: relay the backpressure
            # verdict instead of inventing a 503
            pool._count("proxy_busy")
            return self._reply(
                busy[0], busy[1],
                {"Retry-After": str(pool.retry_after_s), **rid_hdr})
        pool._count("proxy_unavailable")
        self._reply(503, json.dumps(
            {"error": f"no serving worker available: {last_err}"}).encode(),
            {"Retry-After": str(pool.retry_after_s), **rid_hdr})

    def _reply_federated(self, pool: "ServingPool") -> None:
        """One federated ``GET /metrics``.  A worker that cannot answer
        (dead, respawning, or killed mid-scrape) degrades the scrape —
        its series are dropped and ``federation_stale`` counts the gap —
        it NEVER fails it: the operator's dashboard must stay up exactly
        when workers are dying."""
        parts = []
        for w in pool.worker_list():
            if not w.routable():
                pool._count("federation_stale")
                continue
            try:
                code, data, _ = pool.conns.request(w.url, "GET",
                                                   "/metrics")
                if code != 200:
                    raise RuntimeError(f"HTTP {code}")
                parts.append(({"worker": w.name}, data.decode()))
            except Exception:  # noqa: BLE001 — killed mid-scrape
                pool._count("federation_stale")
        # the proxy's own registry LAST: federation_stale increments from
        # THIS scrape's failures are already visible in its own body
        parts.append(({}, render_prometheus()))
        try:
            body = federate(parts).encode()
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper hung up; never kill the proxy handler thread

    def do_GET(self):
        pool: "ServingPool" = self.server.pool
        # handler instances persist per keep-alive CONNECTION: a prior
        # POST's correlation id/deadline/model must not ride along on
        # probes
        self._rid = None
        self._deadline_hdr = None
        self._model_hdr = None
        self._prefill_hdr = None
        if self.path == "/metrics":
            # FEDERATED scrape (docs/observability.md §Federation): the
            # proxy's own registry plus every live worker's exposition,
            # each worker's series labeled worker="<name>" — one scrape
            # covers the whole pool, every tenant on every worker
            return self._reply_federated(pool)
        if self.path == "/models":
            # the registry lives in the workers; relay the first answer
            for w in pool._next_workers():
                try:
                    code, out, _ = self._forward("GET", w.url, "/models",
                                                 None)
                    return self._reply(code, out)
                except Exception:  # noqa: BLE001 — try the next worker
                    continue
            return self._reply(503, b'{"error": "no worker available"}')
        if self.path != "/health":
            return self._reply(404, b'{"error": "unknown path"}')
        agg = {"status": "ok", "restarts": pool.restarts,
               "pool": dict(pool.stats),
               "autoscale": pool.autoscale_snapshot(), "workers": []}
        for w in pool.worker_list():
            # url reflects the CURRENT process: spawn() clears it before
            # launching, so a corpse's old endpoint never shows up here
            one = {"name": w.name, "url": w.url, "alive": w.alive(),
                   "role": w.role, "breaker": w.breaker.snapshot()}
            if w.alive() and w.url:
                try:
                    _, out, _ = self._forward("GET", w.url, "/health", None)
                    one.update(json.loads(out))
                except Exception as e:  # noqa: BLE001
                    one["error"] = str(e)
            agg["workers"].append(one)
        agg["requests"] = sum(int(w.get("requests", 0))
                              for w in agg["workers"])
        agg["batches"] = sum(int(w.get("batches", 0))
                             for w in agg["workers"])
        if not any(w["alive"] for w in agg["workers"]):
            agg["status"] = "unavailable"
        self._reply(200, json.dumps(agg).encode())


class ServingPool:
    """N process-isolated serving workers behind one round-robin proxy
    with liveness supervision (dead workers are respawned), per-worker
    circuit breakers, drain-before-kill shutdown, keep-alive forwarding,
    and optional metrics-driven autoscaling between ``min_workers`` and
    ``max_workers``."""

    def __init__(self, loader: str, workers: int = 2, batch_size: int = 32,
                 queue_capacity: int = 4096, host: str = "127.0.0.1",
                 port: int = 0, predict_timeout: float = 30.0,
                 worker_env: Optional[dict] = None,
                 supervise_interval_s: float = 1.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 2.0,
                 hedge_after_s: Optional[float] = None,
                 drain_timeout_s: float = 5.0,
                 max_body_bytes: int = 64 * 1024 * 1024,
                 retry_after_s: float = 1.0,
                 min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 autoscale_interval_s: float = 2.0,
                 scale_up_queue_depth: Optional[float] = None,
                 scale_down_after: int = 3,
                 scale_cooldown_s: float = 5.0,
                 scale_up_slo_health: float = 0.5,
                 roles: Optional[List[str]] = None,
                 fleet_health_max_age_s: float = 0.25,
                 fleet_split_min_tokens: int = 0):
        self.loader = loader
        self.n = workers
        # per-initial-worker fleet roles (docs/serving.md §Decode fleet),
        # e.g. ["prefill", "decode"]; unnamed (and autoscaled) workers
        # default to "both".  The router only splits prefill from decode
        # when at least one dedicated "prefill" worker exists
        if roles is not None:
            bad = [r for r in roles if r not in ("both", "prefill",
                                                 "decode")]
            if bad:
                raise ValueError(f"bad worker roles {bad}; expected "
                                 "'both', 'prefill' or 'decode'")
            if len(roles) > workers:
                raise ValueError(f"{len(roles)} roles for {workers} "
                                 "workers")
        self.roles = list(roles) if roles else []
        # prompts shorter than this prefill on the decode worker even
        # when a dedicated prefill worker exists: the handoff's fixed
        # cost (harvest, serialize, HTTP, import) only beats local
        # recompute past a prompt length.  0 = always split.
        self.fleet_split_min_tokens = int(fleet_split_min_tokens)
        # /health snapshots the generate router places by, TTL-cached so
        # a burst of concurrent /generate POSTs costs one probe sweep
        self._fleet_max_age_s = fleet_health_max_age_s
        self._fleet_lock = threading.Lock()
        self._fleet_cache: Optional[List[Tuple[_Worker,
                                               Optional[dict]]]] = None
        self._fleet_t = 0.0
        self.batch_size = batch_size
        self.queue_capacity = queue_capacity
        self.worker_env = worker_env
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown_s = breaker_cooldown_s
        self.hedge_after_s = hedge_after_s
        self.drain_timeout_s = drain_timeout_s
        self.max_body_bytes = max_body_bytes
        self.retry_after_s = retry_after_s
        # autoscaling bounds: [min_workers, max_workers] around the
        # initial size; equal bounds (the default) disable the scaler
        self.min_workers = min(workers, min_workers
                               if min_workers is not None else workers)
        self.max_workers = max(workers, max_workers
                               if max_workers is not None else workers)
        self.autoscale_interval_s = autoscale_interval_s
        # pressure threshold: average queued requests per routable worker
        # that triggers a scale-up; default half a batch — the queue is
        # persistently outrunning one assembly window
        self.scale_up_queue_depth = (scale_up_queue_depth
                                     if scale_up_queue_depth is not None
                                     else max(1.0, batch_size / 2))
        self.scale_down_after = scale_down_after
        self.scale_cooldown_s = scale_cooldown_s
        # SLO-burn scale-up (docs/observability.md §SLOs & burn rates):
        # a worker-reported health score below this adds a worker even
        # when queues look shallow — burn rates see tail-latency pain
        # queue depth alone cannot (0 disables the signal)
        self.scale_up_slo_health = scale_up_slo_health
        self._idle_ticks = 0
        self._last_scale_t = 0.0
        self.workers: List[_Worker] = []
        self._workers_lock = threading.Lock()
        self._worker_seq = 0
        self._rr = 0
        self._rr_lock = threading.Lock()
        self._stop = threading.Event()
        self._supervise_interval = supervise_interval_s
        self.conns = _ConnPool(predict_timeout)
        self._httpd = ThreadingHTTPServer((host, port), _ProxyHandler)
        self._httpd.pool = self  # type: ignore[attr-defined]
        self._httpd.predict_timeout = predict_timeout  # type: ignore[attr-defined]
        self.host, self.port = self._httpd.server_address[:2]
        self._threads: List[threading.Thread] = []
        self.restarts = 0
        self._stats_lock = threading.Lock()
        self.stats = {"hedged_requests": 0, "proxy_busy": 0,
                      "proxy_unavailable": 0, "rejected_oversize": 0,
                      "conn_reuse": 0, "scale_up": 0, "scale_down": 0,
                      "federation_stale": 0, "fleet_routed": 0,
                      "fleet_split": 0, "stream_relays": 0,
                      "fleet_failovers": 0, "fleet_migrations": 0,
                      "fleet_resumed_tokens": 0, "fleet_orphans": 0}
        # where each drain-migrated request's KV went: request id ->
        # adopting peer url, recorded in phase 1 of _drain_victim BEFORE
        # phase 2 severs the victim's streams, so the failover relay
        # always finds the peer already holding its pages
        self._migrated: Dict[str, str] = {}
        self._migrated_lock = threading.Lock()
        # visible at 0 from the first scrape: an alert on increase needs
        # the series to exist BEFORE the first worker dies
        global_metrics().inc("serving_pool.federation_stale", 0)
        for alias in _FLEET_GLOBAL.values():
            global_metrics().inc(alias, 0)

    def _count(self, name: str, n: int = 1) -> None:
        # proxy handler threads count concurrently; += is not atomic
        with self._stats_lock:
            self.stats[name] += n
        # namespaced into the process registry so the proxy's /metrics
        # scrape exposes them in Prometheus form
        global_metrics().inc(f"serving_pool.{name}", n)
        alias = _FLEET_GLOBAL.get(name)
        if alias is not None:
            global_metrics().inc(alias, n)

    def take_migrated(self, request_id: str) -> Optional[str]:
        """Pop (single failover consumer) the url of the peer that
        adopted this request's migrated KV, if a drain recorded one."""
        with self._migrated_lock:
            return self._migrated.pop(request_id, None)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def worker_list(self) -> List[_Worker]:
        """Point-in-time copy — the autoscaler mutates ``workers``."""
        with self._workers_lock:
            return list(self.workers)

    # -- routing ------------------------------------------------------------
    def _next_workers(self) -> List[_Worker]:
        """Routable workers (alive, registered url, breaker admits) in
        round-robin order starting at the cursor."""
        workers = self.worker_list()
        if not workers:
            return []
        with self._rr_lock:
            self._rr += 1
            start = self._rr
        ordered = [workers[(start + i) % len(workers)]
                   for i in range(len(workers))]
        return [w for w in ordered if w.routable()]

    def _next_urls(self) -> List[str]:
        return [w.url for w in self._next_workers()]

    # -- lifecycle ----------------------------------------------------------
    def _new_worker(self, role: str = "both") -> _Worker:
        with self._workers_lock:
            name = f"worker-{self._worker_seq}"
            self._worker_seq += 1
        return _Worker(self.loader, self.batch_size, self.queue_capacity,
                       self.worker_env, self.breaker_threshold,
                       self.breaker_cooldown_s, self.drain_timeout_s,
                       name=name, role=role,
                       on_breaker_open=self.invalidate_fleet_snapshot)

    def start(self) -> "ServingPool":
        # the proxy process is pure I/O relay — handler threads shuttle
        # small per-token chunks between sockets and never compute.  At
        # the default 5ms GIL switch interval a ready relay thread can
        # sit several intervals behind its peers, which lands directly
        # in every streaming client's TTFT and inter-token tail
        # (measured on the fleet bench: ~8x TTFT p99, ~30% tokens/s).
        sys.setswitchinterval(0.001)
        for i in range(self.n):
            # autoscaled workers (and unnamed slots) are "both": extra
            # capacity must be able to serve whatever the load needs
            w = self._new_worker(self.roles[i] if i < len(self.roles)
                                 else "both")
            w.spawn()
            with self._workers_lock:
                self.workers.append(w)
        self._gauge_workers()
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        s = threading.Thread(target=self._supervise, daemon=True)
        s.start()
        self._threads = [t, s]
        if self.max_workers > self.min_workers:
            a = threading.Thread(target=self._autoscale_run, daemon=True)
            a.start()
            self._threads.append(a)
        log.info("serving pool: %d workers behind %s (autoscale %d..%d)",
                 self.n, self.url, self.min_workers, self.max_workers)
        return self

    def _supervise(self) -> None:
        """Flink-style task supervision: respawn dead workers."""
        while not self._stop.is_set():
            for w in self.worker_list():
                if not w.alive() and not self._stop.is_set():
                    log.warning("serving worker %s died; respawning", w.url)
                    flight.record("worker_died", worker=w.name, url=w.url)
                    self.invalidate_fleet_snapshot()  # don't route to it
                    if w.url:
                        self.conns.clear(w.url)  # the corpse's sockets
                    w.url = None  # stale endpoint: not routable, not
                    #               reported by /health as the corpse's
                    try:
                        w.spawn()
                        self.restarts += 1
                    except Exception as e:  # noqa: BLE001 — retried next tick
                        log.error("respawn failed: %s", e)
            self._stop.wait(self._supervise_interval)

    # -- autoscaling --------------------------------------------------------
    def _worker_health(self, w: _Worker) -> Optional[dict]:
        """One /health probe over the keep-alive pool; None when the
        worker cannot answer (the supervisor's problem, not ours)."""
        if not w.routable():
            return None
        try:
            # chaos seam: fleet_health_stale makes this probe fail as an
            # injected fault — the router must degrade to role+liveness
            # scoring, exactly as it does for a genuinely dead worker
            faults.fire("fleet_health_stale")
            _, data, _ = self.conns.request(w.url, "GET", "/health")
            return json.loads(data)
        except Exception:  # noqa: BLE001 — dead socket or non-JSON body
            return None

    def invalidate_fleet_snapshot(self) -> None:
        """Drop the TTL-cached fleet snapshot NOW — wired as every worker
        breaker's ``on_open`` callback and called on connection-level
        forward failures, so the next /generate routes from fresh healths
        instead of a snapshot that still scores the dead worker as the
        best decode target."""
        with self._fleet_lock:
            self._fleet_cache = None
            self._fleet_t = 0.0

    def fleet_snapshot(self, max_age_s: Optional[float] = None
                       ) -> List[Tuple[_Worker, Optional[dict]]]:
        """Point-in-time ``(worker, health)`` pairs for the generate
        router, TTL-cached (``fleet_health_max_age_s``): placement wants
        fresh slot/page pressure, but a burst of concurrent /generate
        POSTs must not turn into a /health probe per request.  Health is
        None for a worker that cannot answer — the router scores it from
        its configured role and liveness alone."""
        max_age = self._fleet_max_age_s if max_age_s is None else max_age_s
        now = time.time()
        with self._fleet_lock:
            if (self._fleet_cache is not None
                    and now - self._fleet_t <= max_age):
                return self._fleet_cache
        snap = [(w, self._worker_health(w)) for w in self.worker_list()]
        with self._fleet_lock:
            self._fleet_cache = snap
            self._fleet_t = now
        return snap

    def pool_pressure(self) -> dict:
        """The autoscaler's input, from signals the workers already
        export: queue depth and latency percentiles via ``/health``
        (which reads the same gauges/histograms ``/metrics`` scrapes)."""
        depths, p99s, slo_healths = [], [], []
        breaker_open = False
        for w in self.worker_list():
            breaker_open |= w.breaker.snapshot()["state"] != "closed"
            h = self._worker_health(w)
            if h is None:
                continue
            # backlog (heaps + assembled-but-unpredicted) is the honest
            # pressure number — the continuous engine's handoff slot
            # absorbs a queue_depth's worth of waiting work
            depths.append(float(h.get("backlog", h.get("queue_depth", 0))))
            p99s.append(float(h.get("p99_ms", 0.0)))
            slo_healths.append(float(h.get("slo_health", 1.0)))
        return {
            "routable": len(depths),
            "avg_queue_depth": sum(depths) / len(depths) if depths else 0.0,
            "max_p99_ms": max(p99s) if p99s else 0.0,
            "breaker_open": breaker_open,
            # the sickest worker's SLO health score: burn-rate pressure
            # the queue-depth signal cannot see (tail latency, expiries)
            "slo_health": min(slo_healths) if slo_healths else 1.0,
        }

    @staticmethod
    def autoscale_decision(n_workers: int, min_workers: int,
                           max_workers: int, avg_queue_depth: float,
                           up_depth: float, idle_ticks: int,
                           down_after: int, breaker_open: bool,
                           since_last_scale_s: float,
                           cooldown_s: float,
                           slo_health: float = 1.0,
                           unhealthy_below: float = 0.0) -> str:
        """Pure scaling policy (unit-testable without subprocesses),
        asymmetric on purpose: 'up' on a single over-threshold pressure
        tick below the max bound (queued users are waiting NOW; the
        cooldown rate-limits repeats), 'down' only after ``down_after``
        consecutive idle ticks above the min bound — never while a
        breaker is open (a sick worker's load is about to redistribute;
        shrinking now would double the shock), never inside the cooldown
        window after the previous action.  ``slo_health`` below
        ``unhealthy_below`` also scales up — an SLO burning on tail
        latency is user pain the queue-depth signal can miss entirely —
        and an unhealthy pool never scales DOWN, idle-looking or not."""
        if since_last_scale_s < cooldown_s:
            return "hold"
        unhealthy = slo_health < unhealthy_below
        if (avg_queue_depth >= up_depth or unhealthy) \
                and n_workers < max_workers:
            return "up"
        if (avg_queue_depth < 0.5 and idle_ticks >= down_after
                and n_workers > min_workers and not breaker_open
                and not unhealthy):
            return "down"
        return "hold"

    def autoscale_snapshot(self) -> dict:
        return {"min": self.min_workers, "max": self.max_workers,
                "workers": len(self.worker_list()),
                "enabled": self.max_workers > self.min_workers,
                "up_depth": self.scale_up_queue_depth,
                "idle_ticks": self._idle_ticks}

    def _gauge_workers(self) -> None:
        global_metrics().gauge("serving_pool.workers",
                               len(self.worker_list()))

    def _autoscale_run(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(self.autoscale_interval_s)
            if self._stop.is_set():
                return
            try:
                self._autoscale_tick()
            except Exception as e:  # noqa: BLE001 — scaler must outlive a tick
                log.error("autoscale tick failed: %s", e)

    def _autoscale_tick(self) -> None:
        p = self.pool_pressure()
        if p["routable"] == 0:
            return  # nothing measurable; supervision owns dead workers
        self._idle_ticks = (self._idle_ticks + 1
                            if p["avg_queue_depth"] < 0.5 else 0)
        decision = self.autoscale_decision(
            len(self.worker_list()), self.min_workers, self.max_workers,
            p["avg_queue_depth"], self.scale_up_queue_depth,
            self._idle_ticks, self.scale_down_after, p["breaker_open"],
            time.time() - self._last_scale_t, self.scale_cooldown_s,
            slo_health=p["slo_health"],
            unhealthy_below=self.scale_up_slo_health)
        if decision == "up":
            self._scale_up(p)
        elif decision == "down":
            self._scale_down(p)

    def _scale_up(self, pressure: dict) -> None:
        w = self._new_worker()
        try:
            w.spawn()
        except Exception as e:  # noqa: BLE001 — retried next tick
            log.error("scale-up spawn failed: %s", e)
            return
        with self._workers_lock:
            self.workers.append(w)
        self._last_scale_t = time.time()
        self._count("scale_up")
        self._gauge_workers()
        flight.record("pool_scale_up", worker=w.name,
                      workers=len(self.worker_list()), **pressure)
        log.info("autoscale: +%s (avg queue depth %.1f >= %.1f) -> %d "
                 "workers", w.name, pressure["avg_queue_depth"],
                 self.scale_up_queue_depth, len(self.worker_list()))

    def _scale_down(self, pressure: dict) -> None:
        # newest healthy worker leaves; removal from the routing list
        # comes FIRST, then the drain (stdin close -> the worker finishes
        # its queued requests within its budget) — PR 2's drain semantics
        with self._workers_lock:
            victim = next((w for w in reversed(self.workers)
                           if w.alive()
                           and w.breaker.snapshot()["state"] == "closed"),
                          None)
            if victim is None or len(self.workers) <= self.min_workers:
                return
            self.workers.remove(victim)
        self._last_scale_t = time.time()
        self._idle_ticks = 0
        # the action is visible (victim out of the routing list) NOW —
        # count/gauge/flight before the drain, so no reader ever sees a
        # shrunken pool with a zero scale_down count
        self._count("scale_down")
        self._gauge_workers()
        flight.record("pool_scale_down", worker=victim.name,
                      workers=len(self.worker_list()), **pressure)
        log.info("autoscale: -%s (idle) -> %d workers", victim.name,
                 len(self.worker_list()))
        # live KV migration (docs/serving.md §Fleet fault tolerance):
        # before the drain, the victim exports its in-flight decode
        # slots to surviving decode-capable peers — a scale-down must
        # never cost a client its stream
        peers = [w.url for w in self.worker_list()
                 if w.routable() and getattr(w, "role", "both") != "prefill"]
        if peers and victim.url:
            self._drain_victim(victim, peers)
        victim.request_stop()
        victim.join_stop()
        if victim.url:
            self.conns.clear(victim.url)

    def _drain_victim(self, victim: _Worker, peers: List[str]) -> None:
        """Two-phase live migration of the victim's in-flight decode
        slots.  Phase 1 (``/fleet/drain`` with ``evict: false``): the
        victim freezes each live slot, exports its pages + sampling
        state as a handoff blob and ships it to a peer, which PARKS it
        keyed by request id — and reports who adopted what.  The
        migration map is recorded HERE, at the proxy, before anything is
        severed.  Phase 2 (``/fleet/evict``): the frozen slots are
        cancelled, which aborts their victim-side streams WITHOUT a
        chunk terminator — the relay sees the truncation, finds the
        adopting peer in ``_migrated`` and resumes from the imported
        pages.  Any phase failing degrades to plain failover-by-
        re-prefill; a drain never drops a request."""
        try:
            code, out, _ = self.conns.request(
                victim.url, "POST", "/fleet/drain",
                body=json.dumps({"peers": peers,
                                 "evict": False}).encode(),
                headers={"Content-Type": "application/json"})
            if code != 200:
                raise RuntimeError(f"HTTP {code}: {out[:200]!r}")
            res = json.loads(out)
        except Exception as e:  # noqa: BLE001 — degrade, never drop
            log.warning("fleet drain of %s failed (%s); its streams will "
                        "fail over by re-prefill", victim.name, e)
            return
        migrated = res.get("migrated") or {}
        frozen = res.get("frozen") or []
        if migrated:
            with self._migrated_lock:
                self._migrated.update(migrated)
            self._count("fleet_migrations", len(migrated))
        flight.record("fleet_drain", worker=victim.name,
                      migrated=len(migrated),
                      failed=len(res.get("failed") or []),
                      request_ids=sorted(migrated))
        if frozen:
            try:
                self.conns.request(
                    victim.url, "POST", "/fleet/evict",
                    body=json.dumps({"rids": frozen}).encode(),
                    headers={"Content-Type": "application/json"})
            except Exception as e:  # noqa: BLE001 — stop() severs anyway
                log.warning("fleet evict on %s failed: %s", victim.name, e)

    def stop(self) -> None:
        """Shut down: close the proxy to new requests, then drain each
        worker (stdin close -> worker finishes queued requests within its
        drain budget) before any kill."""
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        workers = self.worker_list()
        # start every worker's drain first, THEN wait: one shared drain
        # window instead of O(workers * budget) sequential shutdowns
        for w in workers:
            w.request_stop()
        for w in workers:
            w.join_stop()
        self.conns.clear()


def _main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--loader", required=True)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--queue-capacity", type=int, default=4096)
    ap.add_argument("--drain-timeout", type=float, default=5.0)
    ap.add_argument("--role", default="both",
                    choices=("both", "prefill", "decode"),
                    help="fleet role for --worker mode "
                         "(docs/serving.md §Decode fleet)")
    ap.add_argument("--roles", default=None,
                    help="comma-separated per-worker roles for pool mode, "
                         "e.g. prefill,decode")
    ap.add_argument("--fleet-split-min-tokens", type=int, default=0,
                    help="only split prefill for prompts at least this "
                         "long (0 = always split)")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--min-workers", type=int, default=None)
    ap.add_argument("--max-workers", type=int, default=None)
    ap.add_argument("--port", type=int, default=8000)
    args = ap.parse_args()
    if args.worker:
        _worker_main(args.loader, args.batch_size, args.queue_capacity,
                     args.drain_timeout, role=args.role)
        return
    pool = ServingPool(args.loader, workers=args.workers,
                       batch_size=args.batch_size,
                       queue_capacity=args.queue_capacity,
                       min_workers=args.min_workers,
                       max_workers=args.max_workers,
                       roles=(args.roles.split(",") if args.roles
                              else None),
                       fleet_split_min_tokens=args.fleet_split_min_tokens,
                       port=args.port).start()
    print(f"POOL_URL={pool.url}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pool.stop()


if __name__ == "__main__":
    _main()
