"""NNFrames — ML-pipeline Estimator/Transformer stages over DataFrames.

Reference analog (unverified — mount empty): ``dllib/nnframes/
{NNEstimator,NNModel,NNClassifier,NNImageReader}.scala`` (SURVEY.md §2
L7): Spark-ML ``Estimator``/``Transformer`` stages that assemble
feature/label columns into Sample RDDs, train with the internal
DistriOptimizer, and append a prediction column.

TPU-native redesign: the DataFrame surface is pandas (the in-process
analog of a Spark DF partition; the distributed twin is an XShards of
frames via ``bigdl_tpu.data.shards``), and training runs the
``optim.Optimizer`` sharded train step over the local mesh.
"""

from bigdl_tpu.nnframes.nn_classifier import (NNClassifier, NNClassifierModel,
                                              NNEstimator, NNImageReader,
                                              NNModel)

__all__ = ["NNEstimator", "NNModel", "NNClassifier", "NNClassifierModel",
           "NNImageReader"]
