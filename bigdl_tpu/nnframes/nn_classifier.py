"""NNEstimator / NNModel / NNClassifier — reference
``dllib/nnframes/NNEstimator.scala`` ff.  See package docstring."""

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import numpy as np

from bigdl_tpu.data.dataset import DataSet
from bigdl_tpu.optim.optimizer import Optimizer
from bigdl_tpu.optim.trigger import Trigger


def _as_local_frame(df):
    """Accept a pandas DataFrame, a FeatureTable, an ``XShards`` of
    frames, or a ``ShardedFeatureTable`` — reference nnframes sit on Spark
    DataFrames, so the distributed containers are first-class inputs.
    Returns (pandas_frame_of_local_rows, was_distributed)."""
    from bigdl_tpu.data.shards import XShards
    from bigdl_tpu.friesian.sharded import ShardedFeatureTable
    from bigdl_tpu.friesian.table import FeatureTable

    if isinstance(df, ShardedFeatureTable):
        df = df.shards
    if isinstance(df, XShards):
        import pandas as pd

        return pd.concat(list(df.owned()), ignore_index=True), True
    if isinstance(df, FeatureTable):
        return df.df, False
    return df, False


def _col_matrix(df, cols: Union[str, Sequence[str]]) -> np.ndarray:
    """Column(s) → (n, …) float32 array; cells may be scalars or vectors."""
    if isinstance(cols, str):
        cols = [cols]
    parts = []
    for c in cols:
        v = df[c].to_numpy()
        if len(v) and isinstance(v[0], (list, tuple, np.ndarray)):
            v = np.stack([np.asarray(e, np.float32) for e in v])
        else:
            v = v.astype(np.float32)[:, None]
        parts.append(v.reshape(len(v), -1))
    out = np.concatenate(parts, axis=1)
    return out


class NNEstimator:
    """Fit a module on feature/label columns of a DataFrame —
    reference ``NNEstimator.scala`` (a Spark-ML Estimator).

    ``fit(df)`` returns an ``NNModel`` transformer."""

    def __init__(self, model, criterion,
                 features_col: Union[str, Sequence[str]] = "features",
                 label_col: Union[str, Sequence[str]] = "label",
                 feature_preprocessing: Optional[Callable] = None,
                 label_preprocessing: Optional[Callable] = None):
        self.model = model
        self.criterion = criterion
        self.features_col = features_col
        self.label_col = label_col
        self.feature_preprocessing = feature_preprocessing
        self.label_preprocessing = label_preprocessing
        # builder-style knobs (reference: setMaxEpoch/setBatchSize/…)
        self._max_epoch = 1
        self._batch_size = 32
        self._optim_method = None
        self._end_trigger = None
        self._validation = None
        self._checkpoint = None

    # -- Spark-ML-style builder setters -------------------------------------
    def set_max_epoch(self, n: int) -> "NNEstimator":
        self._max_epoch = n
        return self

    def set_batch_size(self, n: int) -> "NNEstimator":
        self._batch_size = n
        return self

    def set_optim_method(self, method) -> "NNEstimator":
        self._optim_method = method
        return self

    def set_end_when(self, trigger: Trigger) -> "NNEstimator":
        self._end_trigger = trigger
        return self

    def set_validation(self, trigger, df, methods,
                       batch_size: int = 0) -> "NNEstimator":
        self._validation = (trigger, df, methods,
                            batch_size or self._batch_size)
        return self

    def set_checkpoint(self, path: str, trigger=None) -> "NNEstimator":
        self._checkpoint = (path, trigger or Trigger.every_epoch())
        return self

    def _xy(self, df):
        x = _col_matrix(df, self.features_col)
        if self.feature_preprocessing is not None:
            x = np.asarray(self.feature_preprocessing(x), np.float32)
        y = _col_matrix(df, self.label_col)
        if self.label_preprocessing is not None:
            y = np.asarray(self.label_preprocessing(y))
        return x, y

    def _dataset(self, df):
        frame, distributed = _as_local_frame(df)
        x, y = self._xy(frame)
        ds = DataSet.array(x, self._label_cast(y))
        if distributed and jax.process_count() > 1:
            # the frame already holds only this process's rows — wrap so
            # the driver's process sharding doesn't slice it again
            from bigdl_tpu.data.dataset import ProcessLocalDataSet

            ds = ProcessLocalDataSet(ds)
        return ds

    def fit(self, df) -> "NNModel":
        ds = self._dataset(df)
        opt = Optimizer(self.model, ds, self.criterion,
                        batch_size=self._batch_size)
        if self._optim_method is not None:
            opt.set_optim_method(self._optim_method)
        opt.set_end_when(self._end_trigger
                         or Trigger.max_epoch(self._max_epoch))
        if self._validation is not None:
            trig, vdf, methods, vbs = self._validation
            opt.set_validation(trig, self._dataset(vdf), list(methods))
        if self._checkpoint is not None:
            opt.set_checkpoint(*self._checkpoint)
        trained = opt.optimize()
        return self._make_model(trained)

    def _label_cast(self, y):
        # regression keeps (n, d) labels matching the module output shape
        return y.astype(np.float32)

    def _make_model(self, trained) -> "NNModel":
        return NNModel(self.model, trained, self.features_col,
                       self.feature_preprocessing)


class NNModel:
    """Transformer appending a ``prediction`` column — reference
    ``NNModel.scala``."""

    prediction_col = "prediction"

    def __init__(self, model, trained, features_col,
                 feature_preprocessing=None):
        self.model = model
        self.trained = trained
        self.features_col = features_col
        self.feature_preprocessing = feature_preprocessing

    def _features(self, df):
        x = _col_matrix(df, self.features_col)
        if self.feature_preprocessing is not None:
            x = np.asarray(self.feature_preprocessing(x), np.float32)
        return x

    def _raw_predict(self, df, batch_size: int = 0) -> np.ndarray:
        return np.asarray(self.trained.predict(self._features(df),
                                               batch_size))

    def transform(self, df, batch_size: int = 0):
        sharded = self._maybe_transform_shards(df, batch_size)
        if sharded is not None:
            return sharded
        out = df.copy()
        pred = self._raw_predict(df, batch_size)
        pred = pred.reshape(len(pred), -1)
        # single-output models get a flat numeric column (the common
        # regression case); multi-output keeps per-row vectors
        out[self.prediction_col] = (pred[:, 0].astype(np.float32)
                                    if pred.shape[1] == 1 else list(pred))
        return out

    def _maybe_transform_shards(self, df, batch_size):
        """XShards / ShardedFeatureTable input -> per-shard transform,
        shard structure preserved (the distributed scoring path)."""
        from bigdl_tpu.data.shards import XShards
        from bigdl_tpu.friesian.sharded import ShardedFeatureTable

        if isinstance(df, ShardedFeatureTable):
            return ShardedFeatureTable(
                self._maybe_transform_shards(df.shards, batch_size))
        if isinstance(df, XShards):
            return df.transform_shard(
                lambda s: self.transform(s, batch_size))
        return None


class NNClassifier(NNEstimator):
    """Classification specialisation — reference ``NNClassifier.scala``:
    labels are class indices, prediction is the argmax class."""

    def _label_cast(self, y):
        # class-index labels are flat (n,) ints
        return y.reshape(len(y), -1)[:, 0].astype(np.int32)

    def _make_model(self, trained) -> "NNClassifierModel":
        return NNClassifierModel(self.model, trained, self.features_col,
                                 self.feature_preprocessing)


class NNClassifierModel(NNModel):
    def transform(self, df, batch_size: int = 0):
        sharded = self._maybe_transform_shards(df, batch_size)
        if sharded is not None:
            return sharded
        out = df.copy()
        logits = self._raw_predict(df, batch_size)
        out[self.prediction_col] = np.argmax(logits, axis=-1).astype(np.int64)
        return out


class NNImageReader:
    """Read images into a DataFrame — reference ``NNImageReader.scala``
    (``readImages(path, sc)`` returning a DataFrame with an image-struct
    column).  Pandas twin: one row per file, with the decoded HWC uint8
    array in ``image_col`` plus origin/height/width/n_channels columns, so
    the frame drops straight into ``NNEstimator``/``NNModel`` via
    ``features_col=image_col``."""

    @staticmethod
    def read_images(paths, image_col: str = "image", resize=None):
        import pandas as pd

        from bigdl_tpu.data.vision import ImageFrame, Resize

        frame = ImageFrame.read(paths)
        if resize is not None:
            h, w = (resize, resize) if isinstance(resize, int) else resize
            frame = frame.transform(Resize(h, w))
        rows = {
            image_col: [f.image for f in frame],
            "origin": [f.get("uri") for f in frame],
            "height": [f.image.shape[0] for f in frame],
            "width": [f.image.shape[1] for f in frame],
            "n_channels": [f.image.shape[2] for f in frame],
        }
        return pd.DataFrame(rows)
