"""Keras-style API — reference ``dllib/keras`` (keras-1 style layer names).

Layers are the nn catalog re-exported under keras names; models are
``Sequential`` and functional ``Model(inputs, outputs)`` with
``compile/fit/evaluate/predict``.
"""

from bigdl_tpu.keras.engine import Input, Model, Node, Sequential

# keras-1 layer names (reference keras/layers/*.scala) -> nn catalog
from bigdl_tpu.nn import (
    Dense, Dropout, Flatten, Embedding, LayerNorm,
    LSTM, GRU, SimpleRNN, TimeDistributed,
    MultiHeadAttention, TransformerLayer,
)
from bigdl_tpu.nn.layers import (
    Conv2D as Convolution2D, Conv2D,
    Conv1D as Convolution1D, Conv1D,
    MaxPool2D as MaxPooling2D,
    AvgPool2D as AveragePooling2D,
    GlobalAvgPool2D as GlobalAveragePooling2D,
    BatchNorm as BatchNormalization,
    ZeroPadding2D, Reshape,
)
from bigdl_tpu.nn.layers import _act  # noqa: F401  (internal)
from bigdl_tpu.nn import (
    ReLU, Tanh, Sigmoid, SoftMax, LogSoftMax, GELU, ELU, LeakyReLU,
)


class Activation:
    """keras Activation('relu') factory — returns the matching nn module."""

    def __new__(cls, name: str):
        from bigdl_tpu import nn as _nn

        table = {
            "relu": _nn.ReLU, "tanh": _nn.Tanh, "sigmoid": _nn.Sigmoid,
            "softmax": _nn.SoftMax, "log_softmax": _nn.LogSoftMax,
            "gelu": _nn.GELU, "elu": _nn.ELU, "linear": _nn.Identity,
        }
        return table[name.lower()]()


__all__ = [
    "Input", "Model", "Node", "Sequential", "Activation",
    "Dense", "Dropout", "Flatten", "Embedding", "LayerNorm", "LSTM", "GRU",
    "SimpleRNN", "TimeDistributed", "MultiHeadAttention", "TransformerLayer",
    "Convolution2D", "Conv2D", "Convolution1D", "Conv1D", "MaxPooling2D",
    "AveragePooling2D", "GlobalAveragePooling2D", "BatchNormalization",
    "ZeroPadding2D", "Reshape", "ReLU", "Tanh", "Sigmoid", "SoftMax",
    "LogSoftMax", "GELU", "ELU", "LeakyReLU",
]
