"""Keras-style API — reference ``dllib/keras`` (keras-1 style layer names).

Layers are the nn catalog re-exported under keras names; models are
``Sequential`` and functional ``Model(inputs, outputs)`` with
``compile/fit/evaluate/predict``.
"""

from bigdl_tpu.keras.engine import Input, Model, Node, Sequential

# keras-1 layer names (reference keras/layers/*.scala) -> nn catalog
from bigdl_tpu.nn import (
    Dense, Dropout, Flatten, Embedding, LayerNorm,
    LSTM, GRU, SimpleRNN, TimeDistributed, ConvLSTM2D,
    MultiHeadAttention, TransformerLayer,
    Masking, RepeatVector, Permute, Highway,
    GaussianNoise, GaussianDropout,
    SpatialDropout1D, SpatialDropout2D,
    Cropping1D, Cropping2D, Cropping3D,
    ZeroPadding1D, ZeroPadding3D,
    UpSampling1D, UpSampling2D, UpSampling3D,
    LocallyConnected1D, LocallyConnected2D,
)
from bigdl_tpu.nn.layers import (
    Conv2D as Convolution2D, Conv2D,
    Conv1D as Convolution1D, Conv1D,
    MaxPool2D as MaxPooling2D, MaxPool2D,
    AvgPool2D as AveragePooling2D, AvgPool2D,
    GlobalAvgPool2D as GlobalAveragePooling2D,
    BatchNorm as BatchNormalization,
    ZeroPadding2D, Reshape,
)
from bigdl_tpu.nn.layers_extra import (
    Conv3D as Convolution3D,
    Conv2DTranspose as Deconvolution2D,
    SeparableConv2D as SeparableConvolution2D,
    MaxPool1D as MaxPooling1D,
    AvgPool1D as AveragePooling1D,
    MaxPool3D as MaxPooling3D,
    AvgPool3D as AveragePooling3D,
    GlobalMaxPool1D as GlobalMaxPooling1D,
    GlobalMaxPool2D as GlobalMaxPooling2D,
    GlobalAvgPool1D as GlobalAveragePooling1D,
)
from bigdl_tpu.nn.layers_more import (
    GlobalMaxPool3D as GlobalMaxPooling3D,
    GlobalAvgPool3D as GlobalAveragePooling3D,
)
from bigdl_tpu.keras.layers import (
    AtrousConvolution1D, AtrousConvolution2D, Bidirectional, MaxoutDense,
    Merge,
)
from bigdl_tpu.nn.layers_misc import (
    SpatialWithinChannelLRN as WithinChannelLRN2D,
)
from bigdl_tpu.nn.layers import _act  # noqa: F401  (internal)
from bigdl_tpu.nn import (
    ReLU, Tanh, Sigmoid, SoftMax, LogSoftMax, GELU, ELU, LeakyReLU, PReLU,
    SReLU, ThresholdedReLU, HardSigmoid, SoftPlus, SoftSign,
)

InputLayer = Input


class Activation:
    """keras Activation('relu') factory — returns the matching nn module."""

    def __new__(cls, name: str):
        from bigdl_tpu import nn as _nn

        table = {
            "relu": _nn.ReLU, "relu6": _nn.ReLU6, "tanh": _nn.Tanh,
            "sigmoid": _nn.Sigmoid, "hard_sigmoid": _nn.HardSigmoid,
            "softmax": _nn.SoftMax, "log_softmax": _nn.LogSoftMax,
            "softplus": _nn.SoftPlus, "softsign": _nn.SoftSign,
            "gelu": _nn.GELU, "elu": _nn.ELU, "silu": _nn.SiLU,
            "swish": _nn.Swish, "mish": _nn.Mish, "linear": _nn.Identity,
        }
        try:
            return table[name.lower()]()
        except KeyError:
            raise ValueError(
                f"unknown activation {name!r}; one of {sorted(table)}"
            ) from None


__all__ = [
    "Input", "InputLayer", "Model", "Node", "Sequential", "Activation",
    "Dense", "Dropout", "Flatten", "Embedding", "LayerNorm", "LSTM", "GRU",
    "SimpleRNN", "TimeDistributed", "ConvLSTM2D", "Bidirectional",
    "MultiHeadAttention", "TransformerLayer",
    "Convolution2D", "Conv2D", "Convolution1D", "Conv1D", "Convolution3D",
    "AtrousConvolution1D", "AtrousConvolution2D", "Deconvolution2D",
    "SeparableConvolution2D",
    "MaxPooling1D", "MaxPooling2D", "MaxPooling3D", "MaxPool2D",
    "AveragePooling1D", "AveragePooling2D", "AveragePooling3D", "AvgPool2D",
    "GlobalMaxPooling1D", "GlobalMaxPooling2D", "GlobalMaxPooling3D",
    "GlobalAveragePooling1D", "GlobalAveragePooling2D",
    "GlobalAveragePooling3D",
    "BatchNormalization", "WithinChannelLRN2D",
    "ZeroPadding1D", "ZeroPadding2D", "ZeroPadding3D",
    "Cropping1D", "Cropping2D", "Cropping3D",
    "UpSampling1D", "UpSampling2D", "UpSampling3D",
    "LocallyConnected1D", "LocallyConnected2D",
    "Masking", "RepeatVector", "Permute", "Highway", "Merge", "MaxoutDense",
    "GaussianNoise", "GaussianDropout", "SpatialDropout1D",
    "SpatialDropout2D", "Reshape",
    "ReLU", "Tanh", "Sigmoid", "SoftMax", "LogSoftMax", "GELU", "ELU",
    "LeakyReLU", "PReLU", "SReLU", "ThresholdedReLU", "HardSigmoid",
    "SoftPlus", "SoftSign",
]
