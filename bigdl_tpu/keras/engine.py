"""Keras-style model engine: Sequential + functional Model(inputs, outputs).

Reference analog (unverified — mount empty): ``dllib/keras/{Sequential,Model}.
scala`` + ``nn/Graph.scala``/``StaticGraph.scala`` — keras-1-style API with
shape inference, compiled onto the nn core; ``compile/fit/evaluate/predict``
plumb into ``InternalDistriOptimizer``.

Here a ``Model`` is itself an ``nn.Module`` (graph of nodes, topologically
executed), so the whole keras layer sits directly on the L4 sharded optimizer.
Symbolic graph building: calling any ``nn.Module`` on a ``Node`` returns a new
``Node`` (see ``Module.__call__`` overload hook).
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from bigdl_tpu.nn.module import EMPTY, Module

_node_counter = [0]


class Node:
    """Symbolic tensor in the layer graph."""

    _graph_node = True  # duck-type sentinel checked by nn.Module.__call__

    def __init__(self, layer: Optional[Module], parents: Sequence["Node"],
                 shape: Optional[Tuple[int, ...]] = None):
        _node_counter[0] += 1
        self.id = _node_counter[0]
        self.layer = layer
        self.parents = list(parents)
        self.shape = shape  # only set for Input nodes
        lname = layer.name if layer is not None else "input"
        self.name = f"{lname}_{self.id}"

    def __repr__(self):
        return f"Node({self.name})"


def Input(shape: Tuple[int, ...], dtype=np.float32) -> Node:
    """Symbolic input — reference ``keras/Input``. ``shape`` EXCLUDES the
    batch dim (keras convention)."""
    n = Node(None, [], shape=None if shape is None else tuple(shape))
    n.dtype = dtype
    return n


def _topo_order(outputs: List[Node]) -> List[Node]:
    order, seen = [], set()

    def visit(n: Node):
        if n.id in seen:
            return
        seen.add(n.id)
        for p in n.parents:
            visit(p)
        order.append(n)

    for o in outputs:
        visit(o)
    return order


class Model(Module):
    """Functional graph model — reference ``keras/Model.scala`` (and the nn
    ``Graph``)."""

    def __init__(self, inputs: Union[Node, Sequence[Node]],
                 outputs: Union[Node, Sequence[Node]], name=None):
        super().__init__(name or "Model")
        self.inputs = [inputs] if isinstance(inputs, Node) else list(inputs)
        self.outputs = [outputs] if isinstance(outputs, Node) else list(outputs)
        self.order = _topo_order(self.outputs)
        self._compiled: Optional[Dict[str, Any]] = None

    # ---- Module contract --------------------------------------------------
    def init(self, rng, *sample_inputs):
        values: Dict[int, Any] = {}
        for node, x in zip(self.inputs, sample_inputs):
            x = np.asarray(x)
            # canonicalize host dtypes (python lists arrive float64/int64;
            # x64 is disabled so downstream astype would warn + truncate)
            if x.dtype == np.float64:
                x = x.astype(np.float32)
            elif x.dtype == np.int64:
                x = x.astype(np.int32)
            values[node.id] = x
        params, state = {}, {}
        for i, node in enumerate(self.order):
            if node.layer is None:
                continue
            xs = [values[p.id] for p in node.parents]
            v = node.layer.init(jax.random.fold_in(rng, i), *xs)
            if v["params"]:
                params[node.name] = v["params"]
            if v["state"]:
                state[node.name] = v["state"]
            y, _ = node.layer.apply(v, *xs, training=False)
            values[node.id] = y
        return {"params": params, "state": state}

    def forward(self, params, state, *inputs, training=False, rng=None):
        values: Dict[int, Any] = {}
        for node, x in zip(self.inputs, inputs):
            values[node.id] = x
        new_state = dict(state)
        for i, node in enumerate(self.order):
            if node.layer is None:
                continue
            xs = [values[p.id] for p in node.parents]
            y, st = node.layer.forward(
                params.get(node.name, EMPTY), state.get(node.name, EMPTY),
                *xs, training=training,
                rng=None if rng is None else jax.random.fold_in(rng, i))
            if st:
                new_state[node.name] = st
            values[node.id] = y
        outs = [values[o.id] for o in self.outputs]
        return outs[0] if len(outs) == 1 else tuple(outs), new_state

    # ---- keras training API ----------------------------------------------
    def compile(self, optimizer, loss, metrics: Sequence = ()):
        """Reference ``keras Model.compile(optimizer, loss, metrics)``."""
        from bigdl_tpu.keras.training import resolve_compile

        self._compiled = resolve_compile(optimizer, loss, metrics)
        return self

    def fit(self, x, y=None, batch_size: int = 32, nb_epoch: int = 10,
            validation_data=None, checkpoint_path: Optional[str] = None,
            log_every: int = 10, **kw):
        """Keras-style fit.  Notable keywords forwarded to the trainer:
        ``seq_parallel=True`` (long-context sequence sharding on the
        classic driver) and ``parallelism="dp"|"fsdp"|"tp:8"|"dp:4,tp:2"``
        — the declarative GSPMD layout path (docs/parallelism.md
        §Declarative layouts): the combo string resolves against the live
        device set into a named (data, fsdp, tp, seq) mesh + per-model
        SpecLayout table, so fsdp x tp trains models too big for one chip
        with no model-code change."""
        from bigdl_tpu.keras.training import fit_module

        if self._compiled is None:
            raise RuntimeError("call compile(...) before fit(...)")
        if "epochs" in kw:  # accept the keras-2 spelling alongside nb_epoch
            nb_epoch = kw.pop("epochs")
        self._trained = fit_module(
            self, self._compiled, x, y, batch_size=batch_size,
            nb_epoch=nb_epoch, validation_data=validation_data,
            checkpoint_path=checkpoint_path, log_every=log_every, **kw)
        return self._trained

    def predict(self, x, batch_size: int = 0):
        self._require_trained()
        return self._trained.predict(self._pack_inputs(x),
                                     batch_size=batch_size)

    def _pack_inputs(self, x):
        """list/tuple becomes a multi-input pack ONLY for multi-input
        models; a plain list of samples on a single-input model keeps its
        keras meaning of one stacked array."""
        if isinstance(x, (list, tuple)) and len(self.inputs) > 1:
            return tuple(np.asarray(a) for a in x)
        return np.asarray(x)

    def evaluate(self, x, y=None, batch_size: int = 32):
        from bigdl_tpu.data import ArrayDataSet

        self._require_trained()
        px = self._pack_inputs(x)
        if isinstance(px, tuple) and y is None:
            # same guard as fit_module: without labels ArrayDataSet would
            # silently unpack a 2-tuple input pack as (data, labels)
            raise ValueError(
                f"multi-input model ({len(self.inputs)} inputs) requires "
                "labels y for evaluate")
        ds = ArrayDataSet(px, None if y is None else np.asarray(y))
        from bigdl_tpu.optim import Loss

        methods = (self._compiled or {}).get("metrics")
        if not methods:
            # default to the effective loss (compiled, else the criterion the
            # trained engine was built with — the set_weights path)
            loss = ((self._compiled or {}).get("loss")
                    or self._trained._engine.criterion)
            methods = [Loss(loss)]
        return self._trained.evaluate(ds, methods, batch_size=batch_size)

    def set_weights(self, variables):
        """Install externally-trained variables (predict/evaluate without
        fit)."""
        from bigdl_tpu.keras.training import make_trained

        self._trained = make_trained(self, variables, self._compiled)

    def _require_trained(self):
        if not hasattr(self, "_trained"):
            raise RuntimeError("model has no weights yet: fit() or "
                               "set_weights() first")

    def get_weights(self):
        self._require_trained()
        return self._trained.variables

    def summary(self, variables=None) -> str:
        lines = [f"Model '{self.name}':"]
        for node in self.order:
            if node.layer is None:
                lines.append(f"  Input {node.shape}")
            else:
                lines.append(f"  {node.name} <- "
                             f"{[p.name for p in node.parents]}")
        return "\n".join(lines)


class Sequential(Model):
    """Keras Sequential — reference ``keras/Sequential.scala``.  Built as a
    degenerate graph so fit/predict/evaluate are shared with Model."""

    def __init__(self, layers: Sequence[Module] = (), input_shape=None,
                 name=None):
        self._layers: List[Module] = []
        self._input_shape = input_shape
        self._head: Optional[Node] = None
        Module.__init__(self, name or "Sequential")
        self.inputs, self.outputs, self.order = [], [], []
        self._compiled = None
        for l in layers:
            self.add(l)

    def add(self, layer: Module) -> "Sequential":
        self._layers.append(layer)
        self._rebuild()
        return self

    def _rebuild(self):
        if self._input_shape is not None:
            inp = Input(self._input_shape)
        else:
            inp = Input(shape=None)
        node = inp
        for l in self._layers:
            node = Node(l, [node])
        self.inputs, self.outputs = [inp], [node]
        self.order = _topo_order(self.outputs)
