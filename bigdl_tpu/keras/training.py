"""compile/fit plumbing: keras API -> L4 Optimizer.

Reference analog (unverified — mount empty): ``keras/python/PythonZooKeras.
zooFit`` -> ``InternalDistriOptimizer`` (SURVEY.md §4.2) — here it is a direct
in-process call, no py4j boundary.
"""

from typing import Any, Dict, Optional, Sequence

import numpy as np

from bigdl_tpu.data import ArrayDataSet
from bigdl_tpu.nn import criterion as crit_mod
from bigdl_tpu.nn import criterion_extra as _ce
from bigdl_tpu.optim import optim_method as _om
from bigdl_tpu.optim import validation as _vm
from bigdl_tpu.optim import (
    Adam, Loss, MAE, Optimizer, SGD, Top1Accuracy, Top5Accuracy, Trigger,
)
from bigdl_tpu.optim.optimizer import TrainedModel
from bigdl_tpu.optim.train_step import ShardedParameterStep
from bigdl_tpu.runtime.engine import Engine

_OPTIMIZERS = {
    "sgd": lambda: SGD(learning_rate=1e-2),
    "adam": lambda: Adam(learning_rate=1e-3),
    "rmsprop": lambda: _om.RMSprop(learning_rate=1e-3),
    "adagrad": lambda: _om.Adagrad(learning_rate=1e-2),
    "adadelta": lambda: _om.Adadelta(),
    "adamax": lambda: _om.Adamax(learning_rate=2e-3),
}

_LOSSES = {
    "categorical_crossentropy": crit_mod.CrossEntropyCriterion,
    "sparse_categorical_crossentropy": crit_mod.CrossEntropyCriterion,
    "mse": crit_mod.MSECriterion,
    "mean_squared_error": crit_mod.MSECriterion,
    "mae": crit_mod.AbsCriterion,
    "mean_absolute_error": crit_mod.AbsCriterion,
    "binary_crossentropy": crit_mod.BCECriterion,
    "nll": crit_mod.ClassNLLCriterion,
    "kld": _ce.KullbackLeiblerDivergenceCriterion,
    "kullback_leibler_divergence": _ce.KullbackLeiblerDivergenceCriterion,
    "mape": _ce.MeanAbsolutePercentageCriterion,
    "mean_absolute_percentage_error": _ce.MeanAbsolutePercentageCriterion,
    "msle": _ce.MeanSquaredLogarithmicCriterion,
    "mean_squared_logarithmic_error": _ce.MeanSquaredLogarithmicCriterion,
    # keras hinge accepts 0/1 labels; MarginCriterion wants ±1 — convert
    "hinge": lambda: _ce.TransformerCriterion(
        _ce.MarginCriterion(),
        target_transform=lambda t: 2.0 * (t > 0) - 1.0),
    "squared_hinge": lambda: _ce.TransformerCriterion(
        _ce.MarginCriterion(squared=True),
        target_transform=lambda t: 2.0 * (t > 0) - 1.0),
    "poisson": _ce.PoissonCriterion,
    "cosine_proximity": _ce.CosineProximityCriterion,
}

_METRICS = {
    "accuracy": Top1Accuracy,
    "acc": Top1Accuracy,
    "top1": Top1Accuracy,
    "top5": Top5Accuracy,
    "mae": MAE,
    "loss": Loss,
    "auc": _vm.AUC,
    "hitratio": _vm.HitRatio,
    "ndcg": _vm.NDCG,
}


def resolve_compile(optimizer, loss, metrics: Sequence) -> Dict[str, Any]:
    if isinstance(optimizer, str):
        optimizer = _OPTIMIZERS[optimizer.lower()]()
    if isinstance(loss, str):
        loss = _LOSSES[loss.lower()]()
    resolved = []
    for m in metrics:
        if isinstance(m, str):
            if m.lower() == "loss":  # the compiled loss, not a default one
                resolved.append(Loss(loss))
            else:
                resolved.append(_METRICS[m.lower()]())
        else:
            resolved.append(m)
    return {"optimizer": optimizer, "loss": loss, "metrics": resolved}


def fit_module(model, compiled: Dict[str, Any], x, y=None, batch_size=32,
               nb_epoch=10, validation_data=None, checkpoint_path=None,
               log_every=10, end_trigger=None,
               seq_parallel=False, parallelism=None) -> TrainedModel:
    n_inputs = len(getattr(model, "inputs", ()) or ())
    # ONE packing rule for fit/predict/evaluate: Model._pack_inputs
    pack = getattr(model, "_pack_inputs", np.asarray)

    if isinstance(x, ArrayDataSet):
        ds = x
    else:
        px = pack(x)
        if isinstance(px, tuple) and y is None:
            # without labels a 2-tuple would be silently unpacked as (x, y)
            raise ValueError(
                f"multi-input model ({n_inputs} inputs) requires labels y")
        ds = ArrayDataSet(px, None if y is None else np.asarray(y))
    if parallelism is not None:
        # declarative GSPMD fit (docs/parallelism.md §Declarative
        # layouts): the combo string resolves into a (data, fsdp, tp,
        # seq) mesh + per-model layout table; fsdp x tp trains models
        # too big for one chip with the SAME keras code
        if seq_parallel:
            raise ValueError(
                "parallelism= and seq_parallel= are exclusive: express "
                "sequence sharding as a layout axis ('dp:2,seq:4')")
        # what the layout path does not carry yet fails LOUDLY, never
        # silently (a missing checkpoint discovered after a long run)
        unsupported = [n for n, v in (
            ("checkpoint_path", checkpoint_path),
            ("end_trigger", end_trigger)) if v]
        if unsupported:
            raise ValueError(
                f"parallelism={parallelism!r} (declarative GSPMD fit) "
                f"does not support {', '.join(unsupported)} yet — drop "
                "them or unset parallelism for the classic driver "
                "(docs/parallelism.md §Declarative layouts)")
        from bigdl_tpu.parallel.gspmd import fit_layout
        from bigdl_tpu.utils.log import get_logger

        trained, _ = fit_layout(
            model, compiled["loss"], compiled["optimizer"], ds,
            parallelism=str(parallelism), batch_size=batch_size,
            epochs=nb_epoch, log_every=log_every)
        if validation_data is not None:
            if isinstance(validation_data, ArrayDataSet):
                vds = validation_data
            else:
                vx, vy = validation_data
                vds = ArrayDataSet(pack(vx), np.asarray(vy))
            methods = compiled["metrics"] or [Loss(compiled["loss"])]
            res = trained.evaluate(vds, methods, batch_size=batch_size)
            get_logger("bigdl_tpu.keras").info(
                "[layout %s] validation: %s", parallelism,
                {r.name: r.result for r in res})
        return trained
    opt = Optimizer(model, ds, compiled["loss"], batch_size=batch_size)
    opt.set_optim_method(compiled["optimizer"])
    opt.set_end_when(end_trigger or Trigger.max_epoch(nb_epoch))
    opt.log_every = log_every
    # long-context: shard dim 1 over the mesh "seq" axis (the model's
    # attention must be seq_parallel-aware — see optim.train_step)
    opt.seq_parallel = bool(seq_parallel)
    if validation_data is not None:
        if isinstance(validation_data, ArrayDataSet):
            vds = validation_data
        else:
            vx, vy = validation_data
            vds = ArrayDataSet(pack(vx), np.asarray(vy))
        methods = compiled["metrics"] or [Loss(compiled["loss"])]
        opt.set_validation(Trigger.every_epoch(), vds, methods,
                           batch_size=batch_size)
    if checkpoint_path:
        opt.set_checkpoint(checkpoint_path, Trigger.every_epoch())
    return opt.optimize()


def make_trained(model, variables, compiled) -> TrainedModel:
    """Build a TrainedModel from externally-provided variables (loading)."""
    engine = Engine.get()
    optim_method = (compiled or {}).get("optimizer") or SGD()
    step = ShardedParameterStep(
        model, (compiled or {}).get("loss") or crit_mod.MSECriterion(),
        optim_method, engine.mesh, variables)
    return TrainedModel(model, variables, step)
