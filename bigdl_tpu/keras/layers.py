"""Keras-1 layer names that need adaptation beyond a re-export.

Reference analog (unverified — mount empty): ``dllib/keras/layers/*.scala``
(``Merge``, ``Bidirectional``, ``MaxoutDense``, ``AtrousConvolution``) — the
keras-1 API surface of the reference, bound here onto the nn catalog.
"""

from typing import Optional, Sequence


from bigdl_tpu.nn import layers_extra as LX
from bigdl_tpu.nn.layers import Conv1D, Conv2D
from bigdl_tpu.nn.module import EMPTY, Module
from bigdl_tpu.nn.rnn import BiRecurrent, _RNNBase


class Merge(Module):
    """keras-1 merge layer, used as ``Merge(mode)([node_a, node_b])`` — modes
    sum | mul | ave | max | concat | dot | cosine.  Each mode delegates to
    the catalog table op with the same semantics (CAddTable, CMulTable,
    CAveTable, CMaxTable, JoinTable, DotProduct, CosineDistance), so Merge
    never drifts from the nn layers."""

    MODES = ("sum", "mul", "ave", "max", "concat", "dot", "cosine")

    def __init__(self, mode: str = "sum", concat_axis: int = -1, name=None):
        super().__init__(name)
        if mode not in self.MODES:
            raise ValueError(f"mode {mode!r}: one of {self.MODES}")
        from bigdl_tpu.nn.module import CAddTable, CMulTable, JoinTable

        self.mode = mode
        self.concat_axis = concat_axis
        self._op = {
            "sum": CAddTable, "mul": CMulTable, "ave": LX.CAveTable,
            "max": LX.CMaxTable, "dot": LX.DotProduct,
            "cosine": LX.CosineDistance,
            "concat": lambda: JoinTable(concat_axis),
        }[mode]()

    def forward(self, params, state, *xs, training=False, rng=None):
        if len(xs) == 1 and isinstance(xs[0], (list, tuple)):
            xs = tuple(xs[0])
        y, _ = self._op.forward(EMPTY, EMPTY, *xs, training=training, rng=rng)
        if self.mode in ("dot", "cosine"):
            y = y[..., None]  # keras Merge keeps a trailing feature axis
        return y, EMPTY


def Bidirectional(layer: _RNNBase, merge_mode: str = "concat",
                  name: Optional[str] = None) -> BiRecurrent:
    """keras ``Bidirectional(LSTM(...))`` — wraps the nn ``BiRecurrent``."""
    return BiRecurrent(layer, merge=merge_mode, name=name)


def MaxoutDense(in_features: Optional[int], out_features: int,
                nb_feature: int = 4, name=None) -> LX.Maxout:
    """keras-1 ``MaxoutDense`` — the nn ``Maxout`` with keras arg names."""
    return LX.Maxout(in_features, out_features, pool_size=nb_feature,
                     name=name)


def AtrousConvolution2D(in_channels, out_channels, kernel_size,
                        atrous_rate=1, stride=1, padding="VALID",
                        with_bias=True, name=None) -> Conv2D:
    """keras-1 ``AtrousConvolution2D`` == dilated Conv2D."""
    return Conv2D(in_channels, out_channels, kernel_size, stride=stride,
                  padding=padding, dilation=atrous_rate, with_bias=with_bias,
                  name=name)


def AtrousConvolution1D(in_channels, out_channels, kernel_size,
                        atrous_rate=1, stride=1, padding="VALID",
                        with_bias=True, name=None) -> Conv1D:
    """keras-1 ``AtrousConvolution1D`` == dilated Conv1D."""
    return Conv1D(in_channels, out_channels, kernel_size, stride=stride,
                  padding=padding, dilation=atrous_rate, with_bias=with_bias,
                  name=name)
