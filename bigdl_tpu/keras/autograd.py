"""Keras autograd — custom layers and losses from ops.

Reference analog (unverified — mount empty): ``dllib/keras/autograd/
{Variable,AutoGrad,CustomLoss}.scala`` (SURVEY.md §3.1): a mini symbolic
op set over ``Variable`` nodes so users can define layers/losses without
writing a Scala ``backward`` — the reference needs this machinery because
its nn core has NO autodiff.

TPU-native: JAX *is* the autograd, so this module is thin sugar:
- the op set (``add/mul/square/exp/clip/mean/…``) builds keras graph
  ``Node``s via ``Lambda`` modules, usable directly in ``Model(in, out)``;
- ``CustomLoss`` wraps any jnp function ``(y_true, y_pred) -> scalar`` as
  a ``Criterion`` for ``compile(loss=…)``; the gradient comes from
  ``jax.grad`` over the whole train step.
"""

import jax.numpy as jnp

from bigdl_tpu.keras.engine import Node
from bigdl_tpu.nn.criterion import Criterion
from bigdl_tpu.nn.module import Lambda


def _wrap(fn, name):
    """Lift a jnp function over Nodes/constants into a graph Node (or apply
    eagerly when called with arrays)."""

    def op(*args, **kw):
        if any(isinstance(a, Node) for a in args):
            nodes = [a for a in args if isinstance(a, Node)]
            consts = [(i, a) for i, a in enumerate(args)
                      if not isinstance(a, Node)]

            def run(*xs):
                full = list(xs)
                for i, c in consts:
                    full.insert(i, c)
                return fn(*full, **kw)

            return Lambda(run, name=name)(nodes if len(nodes) > 1
                                          else nodes[0])
        return fn(*args, **kw)

    op.__name__ = name
    return op


# -- reference AutoGrad op set ------------------------------------------------
add = _wrap(lambda a, b: a + b, "add")
sub = _wrap(lambda a, b: a - b, "sub")
mul = _wrap(lambda a, b: a * b, "mul")
div = _wrap(lambda a, b: a / b, "div")
neg = _wrap(lambda a: -a, "neg")
abs = _wrap(jnp.abs, "abs")  # noqa: A001 — reference name
square = _wrap(jnp.square, "square")
sqrt = _wrap(jnp.sqrt, "sqrt")
exp = _wrap(jnp.exp, "exp")
log = _wrap(jnp.log, "log")
pow = _wrap(jnp.power, "pow")  # noqa: A001 — reference name
maximum = _wrap(jnp.maximum, "maximum")
minimum = _wrap(jnp.minimum, "minimum")
clip = _wrap(jnp.clip, "clip")
sum = _wrap(jnp.sum, "sum")  # noqa: A001 — reference name
mean = _wrap(jnp.mean, "mean")
softsign = _wrap(lambda a: a / (1 + jnp.abs(a)), "softsign")
softplus = _wrap(lambda a: jnp.logaddexp(a, 0.0), "softplus")
dot = _wrap(lambda a, b: jnp.matmul(a, b), "dot")
stack = _wrap(lambda *xs, axis=0: jnp.stack(xs, axis=axis), "stack")
concatenate = _wrap(lambda *xs, axis=-1: jnp.concatenate(xs, axis=axis),
                    "concatenate")
expand_dims = _wrap(jnp.expand_dims, "expand_dims")
squeeze = _wrap(jnp.squeeze, "squeeze")


class CustomLoss(Criterion):
    """Wrap ``fn(y_true, y_pred) -> scalar`` as a criterion — reference
    ``CustomLoss.scala`` (there it builds a Variable graph; here the
    function IS differentiable already)."""

    def __init__(self, loss_fn, name: str = "custom_loss"):
        self.loss_fn = loss_fn
        self.name = name

    def forward(self, input, target):
        return self.loss_fn(target, input)


def mean_absolute_error(y_true, y_pred):
    return jnp.mean(jnp.abs(y_pred - y_true))


def mean_squared_error(y_true, y_pred):
    return jnp.mean(jnp.square(y_pred - y_true))
