"""Analytic FLOPs/bytes cost model + device peak table — the MFU denominator.

Reference analog (unverified — mount empty): the reference reports only
records/s; BigDL 2.0 (arXiv 2204.01715) leaves utilization to offline
TensorBoard summaries.  Here the cost of a model is derived ONCE per run
from the model itself — a shape-capturing walk over the ``nn/`` module tree
under ``jax.eval_shape`` (no compute, no compile) with per-layer FLOP
formulas — so a *running* job can export a live ``train.mfu`` gauge instead
of waiting for an offline ``bench.py`` one-shot.

Conventions (must stay aligned with ``bench.py`` so live and bench MFU
agree):

- forward FLOPs are *model* flops (2 x MACs for matmul-family layers;
  elementwise layers count one pass over their output) — the
  ``analytic_3x_fwd`` convention, generalized from bench.py's hardcoded
  ResNet-50 constant to per-layer counts over arbitrary module trees.
- training FLOPs = ``TRAIN_FLOPS_MULTIPLIER`` (3) x forward (fwd +
  input-grad + weight-grad).
- MFU = achieved FLOP/s per chip / the chip's bf16 peak
  (``peak_flops``); unknown device kinds yield ``None`` unless
  ``BIGDL_TPU_PEAK_FLOPS`` / ``EngineConfig.peak_flops`` pins one.
"""

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.obs")

# fwd + input-grad + weight-grad — the standard training-FLOPs convention
# (bench.py's analytic_3x_fwd)
TRAIN_FLOPS_MULTIPLIER = 3.0

# bf16 matmul peak FLOP/s by TPU generation (public spec sheets), keyed by
# substrings of jax Device.device_kind.  THE process-wide source of truth:
# bench.py / bench_lm.py delegate here.
PEAK_BF16_FLOPS: List[Tuple[str, float]] = [
    ("v6", 918e12),          # Trillium / v6e
    ("v5p", 459e12),
    ("v5 lite", 197e12),     # v5e reports device_kind "TPU v5 lite"
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4 lite", 138e12),     # v4i
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def peak_flops(device_kind: Optional[str],
               override: Optional[float] = None) -> Optional[float]:
    """Peak bf16 FLOP/s for one chip.  Resolution order:
    ``BIGDL_TPU_PEAK_FLOPS`` env (operator pin for unknown hardware /
    CPU test meshes) > explicit ``override`` (``EngineConfig.peak_flops``)
    > the device-kind table > None."""
    env = os.environ.get("BIGDL_TPU_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            log.warning("BIGDL_TPU_PEAK_FLOPS=%r is not a float; ignored",
                        env)
    if override:
        return float(override)
    kind = (device_kind or "").lower()
    for key, peak in PEAK_BF16_FLOPS:
        if key in kind:
            return peak
    return None


# ---------------------------------------------------------------------------
# per-layer shape capture + FLOP formulas
# ---------------------------------------------------------------------------

@dataclass
class LayerCost:
    """One module's forward cost from its observed shapes."""

    name: str
    kind: str
    flops: float          # forward model-flops (2 x MACs for matmul family)
    param_bytes: int
    out_elems: int
    # effective (executed) flops — differs from ``flops`` only for sparse
    # layers, where ``flops`` stays the DENSE-EQUIVALENT count and this
    # counts only the nonzero-block work the chip actually does
    eff_flops: float = -1.0

    def __post_init__(self):
        if self.eff_flops < 0:
            self.eff_flops = self.flops


@dataclass
class CostReport:
    """Forward-pass cost of one model on one batch shape."""

    layers: List[LayerCost] = field(default_factory=list)
    batch: int = 0

    @property
    def flops(self) -> float:
        """Total forward model-flops for the traced batch
        (dense-equivalent: sparsity does NOT shrink this number)."""
        return float(sum(l.flops for l in self.layers))

    @property
    def eff_flops(self) -> float:
        """Executed forward flops: nonzero-block work only.  Equal to
        ``flops`` for dense models; under block sparsity this is the
        honest MFU numerator (``flops`` would inflate it)."""
        return float(sum(l.eff_flops for l in self.layers))

    @property
    def param_bytes(self) -> int:
        return int(sum(l.param_bytes for l in self.layers))

    def train_flops(self) -> float:
        return TRAIN_FLOPS_MULTIPLIER * self.flops

    def train_eff_flops(self) -> float:
        """Executed training flops: per layer, forward and the input
        gradient run at EFFECTIVE cost (the block-sparse kernel skips
        pruned blocks in both) but the weight gradient is a dense matmul
        masked on the way out (``ops.block_sparse._bsmm_bwd``) — so the
        honest count is ``2·eff + 1·dense`` per layer, which collapses to
        the standard 3x for dense layers (eff == flops)."""
        return float(sum(2.0 * l.eff_flops + l.flops for l in self.layers))

    def per_sample_flops(self) -> float:
        return self.flops / max(self.batch, 1)


def iter_modules(module, seen=None):
    """Walk a module tree (containers, attribute children, lists)."""
    from bigdl_tpu.nn.module import Module

    if seen is None:
        seen = set()
    if id(module) in seen:
        return
    seen.add(id(module))
    yield module
    for v in vars(module).values():
        children = v if isinstance(v, (list, tuple)) else [v]
        for c in children:
            if isinstance(c, Module):
                yield from iter_modules(c, seen)


def _shape(a) -> Optional[Tuple[int, ...]]:
    s = getattr(a, "shape", None)
    if s is None:
        return None
    try:
        return tuple(int(d) for d in s)
    except TypeError:
        return None


def _elems(shape: Optional[Tuple[int, ...]]) -> int:
    if not shape:
        return 0
    return int(np.prod(shape))


def _out_shapes(y) -> List[Tuple[int, ...]]:
    if isinstance(y, (tuple, list)):
        return [s for s in (_shape(a) for a in y) if s is not None]
    s = _shape(y)
    return [s] if s is not None else []


# layers whose cost is one cheap pass over the output (normalization,
# activations, pooling, padding/reshape/dropout); counted as 2 flops/elem
# so they appear in the table without pretending to be matmuls
_ELEMENTWISE_KINDS = frozenset({
    "BatchNorm", "_BN", "LayerNorm", "RMSNorm", "GroupNorm", "ReLU",
    "ReLU6", "GELU", "SiLU", "Sigmoid", "Tanh", "SoftMax", "LogSoftMax",
    "LeakyReLU", "ELU", "HardTanh", "PReLU", "SoftPlus", "SoftSign",
    "Dropout", "MaxPool2D", "AvgPool2D", "MaxPool1D", "AvgPool1D",
    "MaxPool3D", "AvgPool3D", "GlobalAvgPool2D", "GlobalMaxPool2D",
    "GlobalAvgPool1D", "GlobalMaxPool1D", "CAddTable", "CMulTable",
    "Scale", "Power", "Abs", "Clamp", "Sqrt", "Square",
})


def _attention_flops(mod, in_shapes, out_shapes, params) -> float:
    """MultiHeadAttention: q/k/v/out projections + the two attention
    matmuls (qk^T and att@v), 2 flops per MAC."""
    x = in_shapes[0]
    if x is None or len(x) < 3:
        return 0.0
    b, t = x[0], x[1]
    proj = 0.0
    for key in ("wq", "wk", "wv", "wo"):
        w = _shape(params.get(key)) if isinstance(params, dict) else None
        if w is not None:
            proj += 2.0 * b * t * _elems(w)
    h = getattr(mod, "hidden_size", None) or (x[-1] if x else 0)
    # qk^T: b*heads*t*t*head_dim MACs; att@v the same => 4*b*t^2*h flops
    attn = 4.0 * b * t * t * h
    return proj + attn


def _layer_flops(mod, in_shapes, out_shapes, params) -> float:
    kind = type(mod).__name__
    out_e = sum(_elems(s) for s in out_shapes)
    if kind == "MultiHeadAttention":
        return _attention_flops(mod, in_shapes, out_shapes, params)
    if kind == "Embedding":
        return 0.0  # gather, no MACs
    if kind == "DepthwiseConv2D":
        w = _shape(params.get("weight")) if isinstance(params, dict) \
            else None
        if w is not None and len(w) >= 2:
            return 2.0 * out_e * w[0] * w[1]
        return 0.0
    if kind in _ELEMENTWISE_KINDS:
        return 2.0 * out_e
    # matmul family (Linear, Conv1/2/3D, SeparableConv2D pointwise,
    # custom conv-like modules e.g. SpaceToDepthStem): every output
    # element is a dot product over the weight's non-output dims —
    # 2 * out_elems * prod(weight.shape[:-1]) covers (in, out) linears and
    # (kh, kw, cin/groups, cout) convs with one formula
    w = _shape(params.get("weight")) if isinstance(params, dict) else None
    if w is not None and len(w) >= 2 and out_shapes \
            and out_shapes[0] and out_shapes[0][-1] == w[-1]:
        return 2.0 * out_e * _elems(w[:-1])
    # containers / reshapes / unknown glue: children are recorded
    # separately, so counting 0 here avoids double counting
    return 0.0


def _param_bytes(params) -> int:
    if not isinstance(params, dict):
        return 0
    total = 0
    for v in params.values():
        s = _shape(v)
        if s is not None:
            itemsize = getattr(getattr(v, "dtype", None), "itemsize", 4)
            total += _elems(s) * itemsize
        elif isinstance(v, dict):
            # a nested dict is a CHILD module's params — skip just that
            # entry (the child reports its own); the module's direct
            # arrays still count
            continue
    return total


def forward_costs(model, variables: Dict[str, Any], *sample_inputs,
                  training: bool = False) -> CostReport:
    """Per-layer forward cost of ``model`` on ``sample_inputs`` shapes.

    The forward runs under ``jax.eval_shape`` — pure shape propagation, no
    FLOP is executed and nothing compiles — with every module instance's
    ``forward`` wrapped to record its input/output shapes.  Leaf formulas
    turn shapes into FLOPs; container/unknown modules count 0 (their
    children are recorded separately), so the sum never double counts."""
    import jax

    records: List[Tuple[Any, list, list, Any]] = []
    patched: List[Any] = []

    def _wrap(mod, orig):
        def fwd(params, state, *xs, **kw):
            y, st = orig(params, state, *xs, **kw)
            records.append((mod, [_shape(a) for a in xs], _out_shapes(y),
                            params))
            return y, st

        return fwd

    try:
        for m in iter_modules(model):
            _wrap_fn = _wrap(m, m.forward)
            m.forward = _wrap_fn  # instance attr shadows the class method
            patched.append(m)
        jax.eval_shape(
            lambda v, xs: model.apply(v, *xs, training=training),
            variables, tuple(sample_inputs))
    finally:
        for m in patched:
            try:
                del m.__dict__["forward"]
            except KeyError:
                pass

    report = CostReport()
    first = _shape(sample_inputs[0]) if sample_inputs else None
    report.batch = first[0] if first else 1
    for mod, ins, outs, params in records:
        flops = _layer_flops(mod, ins, outs, params)
        out_e = sum(_elems(s) for s in outs)
        # block-sparse layers: ``flops`` stays dense-equivalent (the
        # matmul-family formula above); the EFFECTIVE count scales by the
        # mask's nonzero-block density — so train.mfu vs
        # train.effective_mfu make sparsity's utilization cost visible
        # instead of silently inflating one number
        eff = flops
        if type(mod).__name__ == "BlockSparseLinear":
            try:
                eff = flops * float(mod.density())
            except Exception:  # pragma: no cover — unbuilt module
                pass
        report.layers.append(LayerCost(
            name=getattr(mod, "name", type(mod).__name__),
            kind=type(mod).__name__, flops=flops,
            param_bytes=_param_bytes(params), out_elems=out_e,
            eff_flops=eff))
    return report


def train_step_flops(model, variables: Dict[str, Any], sample_inputs,
                     batch_size: int) -> float:
    """Analytic training FLOPs of ONE global step: 3 x forward, scaled
    from the traced sample batch to ``batch_size`` rows (layer FLOPs are
    linear in the batch dim; sequence lengths come from the sample)."""
    rep = forward_costs(model, variables, *sample_inputs)
    return rep.train_flops() / max(rep.batch, 1) * batch_size


def train_step_flops_detail(model, variables: Dict[str, Any],
                            sample_inputs,
                            batch_size: int) -> Dict[str, float]:
    """Like :func:`train_step_flops` but reports BOTH conventions:
    ``dense`` (dense-equivalent, sparsity-blind — the legacy
    ``train.flops_per_step``/``train.mfu`` numerator) and ``effective``
    (nonzero-block work only — the ``train.effective_mfu`` numerator)."""
    rep = forward_costs(model, variables, *sample_inputs)
    scale = batch_size / max(rep.batch, 1)
    return {"dense": rep.train_flops() * scale,
            "effective": rep.train_eff_flops() * scale}


def mfu(flops_per_step: float, step_time_s: float, n_devices: int,
        peak: Optional[float]) -> Optional[float]:
    """Model-flop utilization: achieved FLOP/s per chip over the chip's
    peak.  None when the peak is unknown (no table entry, no override)."""
    if not peak or step_time_s <= 0 or n_devices <= 0:
        return None
    achieved = flops_per_step / step_time_s / n_devices
    return achieved / peak


def collective_bytes_for_specs(params, specs, mesh,
                               dtype_bytes: int = 4) -> Dict[str, Any]:
    """Per-step, per-AXIS collective bytes of a declarative layout — the
    obs-side reader of ``parallel.layout`` PartitionSpec trees (docs/
    parallelism.md §Declarative layouts).  Pure layout math, usable
    before anything compiles: ``data`` carries the gradient allreduce,
    ``fsdp`` the 2004.13336 param-gather/grad-scatter cycle, ``tp``
    moves activations (priced separately via
    ``parallel.layout.tp_activation_bytes``).  Also reports
    ``param_bytes_per_chip`` — the "fits on one chip?" number fsdp x tp
    layouts exist to shrink.  ``bench_scaling --layout`` and the
    MULTICHIP_LAYOUT sentinel family consume exactly this dict.

    NOTE: distinct from the LEGACY ``parallel.gspmd.
    collective_bytes_for_specs`` (a flat
    ``dp_allreduce_bytes_per_step``-keyed dict) — this one returns the
    per-axis ``{"per_axis_bytes_per_step": ..., "param_bytes_per_chip":
    ...}`` shape of ``parallel.layout.collective_bytes_by_axis``."""
    from bigdl_tpu.parallel.layout import collective_bytes_by_axis

    return collective_bytes_by_axis(params, specs, mesh,
                                    dtype_bytes=dtype_bytes)


def embedding_lookup_bytes(batch: int, dim: int, sizes,
                           n_tables: int = 1,
                           dtype_bytes: int = 4) -> Dict[str, Any]:
    """Per-axis collective bytes of sparse embedding lookups against a
    vocab-sharded (fsdp x tp) table — the obs-side reader of the
    serving-side lookup accounting (docs/recsys.md §Lookup-collective
    ledger).  The RECSYS sentinel family consumes exactly this dict."""
    from bigdl_tpu.parallel.layout import embedding_lookup_bytes as _impl

    return _impl(batch, dim, sizes, n_tables=n_tables,
                 dtype_bytes=dtype_bytes)


def collective_ledger(step_engine) -> Dict[str, Any]:
    """Per-step collective-bytes ledger of a
    :class:`~bigdl_tpu.optim.train_step.ShardedParameterStep` — what
    MULTICHIP_LARGE measures offline, derived from the parameter layout
    and sync strategy (ZeRO-1 reduce-scatter + all_gather; hierarchical
    DCN hop when the mesh is multislice).

    Bytes are counted in the ACTUAL wire dtype of the configured
    ``grad_comm`` / ``param_comm`` modes — bf16 payloads at 2 B/elem,
    int8 payloads at 1 B/elem PLUS the f32 per-block quantization scales
    and block padding (``parallel.collectives`` estimators) — so
    before/after compression comparisons are honest.  ``grad_ici`` /
    ``param_ici`` split the ICI total into the gradient scatter and the
    param gather (f32, or the int8 delta gather under
    ``param_comm="int8"``)."""
    mode = getattr(step_engine, "grad_comm",
                   "bf16" if getattr(step_engine, "bf16_grads", False)
                   else "fp32")
    param_mode = getattr(step_engine, "param_comm", "fp32")
    grad_ici = float(getattr(step_engine, "grad_sync_ici_bytes_per_step",
                             step_engine.collective_bytes_per_step))
    param_ici = float(getattr(step_engine, "param_sync_ici_bytes_per_step",
                              0))
    from bigdl_tpu.parallel.collectives import wire_itemsize

    return {
        "ici_bytes_per_step": float(step_engine.collective_bytes_per_step),
        "dcn_bytes_per_step": float(step_engine.dcn_bytes_per_step),
        "grad_ici_bytes_per_step": grad_ici,
        "param_ici_bytes_per_step": param_ici,
        "n_data_replicas": float(step_engine.n_data_replicas),
        "grad_comm": mode,
        "param_comm": param_mode,
        # legacy key: payload bytes per gradient element on the wire
        "grad_dtype_bytes": wire_itemsize(mode),
        "comm_buckets": float(getattr(step_engine, "comm_buckets", 1)),
        "n_params_padded": float(step_engine.n_pad),
    }
