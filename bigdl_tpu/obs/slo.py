"""Declarative SLOs — sliding-window error budgets and burn-rate alerts.

Reference analog (unverified — mount empty): the reference reports raw
metrics and leaves "is the service healthy?" to the operator's eyeballs.
At fleet scale that judgment must be mechanical: an operator (or the pool
autoscaler) acts on *SLO burn rates*, not on per-process gauges
(docs/observability.md §SLOs & burn rates).

An :class:`SLOSpec` declares per-tenant objectives::

    {"tenant": "ranker",
     "objectives": {"predict_p99_s": 0.2,      # p99 predict latency <= 200ms
                    "ttft_p99_s": 0.5,         # p99 time-to-first-token
                    "availability": 0.999},    # >= 99.9% answered OK
     "window_s": 30.0}

Latency objectives read the labeled per-tenant histograms
(``serving.tenant_latency_seconds{tenant=...}`` etc.) through the sliding
window ``obs.hist.LogHistogram`` keeps next to its cumulative buckets; a
``predict_p99_s <= X`` objective means "at most 1% of window requests may
exceed X" — the error budget.  The **burn rate** is the observed bad
fraction divided by that budget: 1.0 burns exactly the budget, 2.0
exhausts it in half the window.  Availability objectives count good/bad
from the per-tenant request/expired/failed counters, delta'd per
evaluation tick into the same window math.

Multi-window: every objective is evaluated over its short window AND a
``long_window_factor``× window (the classic fast-burn/sustained-burn
pair); both export as labeled gauges (``slo_burn_rate{tenant=,objective=}``
/ ``slo_burn_rate_long``).  Crossing ``alert_burn`` records an
``slo_burn`` flight-recorder event (cleared with ``slo_burn_cleared``),
and the evaluator folds everything into a **health score** in [0, 1]
(``1 - max_burn / alert_burn``, clamped) that the pool autoscaler and the
serving degradation surface consult (docs/serving.md §Autoscaling).

No recent data is NO burn: an empty window reads NaN from the histogram
(the obs.hist contract) and the objective reports burn 0 with
``samples=0`` — silence must not page anyone.

CLI — the ``SLO_r*.json`` artifact source (burn-rate alert latency under
an injected hard violation; gated lower-better by ``obs.sentinel``)::

    python -m bigdl_tpu.obs.slo --bench
"""

import json
import math
import os
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from bigdl_tpu.obs import flight
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.obs")

DEFAULT_WINDOW_S = 30.0
DEFAULT_LONG_FACTOR = 6.0
DEFAULT_ALERT_BURN = 1.0

# shorthand objective keys -> the labeled per-tenant histogram they read
# (docs/observability.md §SLOs & burn rates has the spec grammar)
_METRIC_SHORTHAND = {
    "predict": "serving.tenant_latency_seconds",
    "latency": "serving.tenant_latency_seconds",
    "ttft": "serving.tenant_ttft_seconds",
    "queue_wait": "serving.tenant_queue_wait_seconds",
}
_LATENCY_KEY_RE = re.compile(r"^(?P<metric>[a-z_]+)_p(?P<q>\d{1,2})_s$")


@dataclass
class Objective:
    """One normalized objective of one tenant."""

    name: str                 # the spec key ("predict_p99_s", ...)
    kind: str                 # "latency" | "availability"
    target: float             # good-event fraction target (p99 -> 0.99)
    threshold_s: float = 0.0  # latency bound (latency kind only)
    metric: str = ""          # histogram base name (latency kind only)

    @property
    def budget(self) -> float:
        """Allowed bad-event fraction — the error budget denominator."""
        return max(1.0 - self.target, 1e-9)


@dataclass
class SLOSpec:
    """Declarative per-tenant objectives over a sliding window."""

    tenant: str
    objectives: List[Objective]
    window_s: float = DEFAULT_WINDOW_S
    long_window_factor: float = DEFAULT_LONG_FACTOR

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "SLOSpec":
        tenant = str(d.get("tenant", "default"))
        window_s = float(d.get("window_s", DEFAULT_WINDOW_S))
        if window_s <= 0:
            # a zero window would busy-spin the background evaluator
            # (interval_s derives from the shortest window)
            raise ValueError(f"SLO spec for {tenant!r}: window_s must be "
                             f"> 0, got {window_s}")
        long_factor = float(d.get("long_window_factor",
                                  DEFAULT_LONG_FACTOR))
        if long_factor < 1.0:
            raise ValueError(f"SLO spec for {tenant!r}: "
                             f"long_window_factor must be >= 1, got "
                             f"{long_factor}")
        objectives: List[Objective] = []
        raw = d.get("objectives", {})
        if not isinstance(raw, dict) or not raw:
            raise ValueError(f"SLO spec for {tenant!r} needs a non-empty "
                             "'objectives' dict")
        for key, val in raw.items():
            objectives.append(_parse_objective(str(key), val))
        return SLOSpec(
            tenant=tenant, objectives=objectives, window_s=window_s,
            long_window_factor=long_factor)


def _parse_objective(key: str, val: Any) -> Objective:
    """One spec entry -> a normalized :class:`Objective`.

    Grammar: ``availability: Z`` (good-fraction target), or
    ``<metric>_p<NN>_s: X`` — p<NN> sets the target (p99 -> 0.99), X the
    latency bound, ``<metric>`` one of predict/latency/ttft/queue_wait
    (or a full dict ``{"metric": "serving.xyz_seconds", "p": 99,
    "threshold_s": X}`` for histograms outside the shorthand table)."""
    if isinstance(val, dict):
        q = float(val.get("p", 99))
        return Objective(
            name=key, kind=str(val.get("kind", "latency")),
            target=float(val.get("target", 1.0 - (100.0 - q) / 100.0)),
            threshold_s=float(val.get("threshold_s", 0.0)),
            metric=str(val.get("metric", "")))
    if key == "availability":
        z = float(val)
        if not 0.0 < z < 1.0:
            raise ValueError(f"availability target must be in (0, 1); "
                             f"got {z}")
        return Objective(name=key, kind="availability", target=z)
    m = _LATENCY_KEY_RE.match(key)
    if m is None or m.group("metric") not in _METRIC_SHORTHAND:
        raise ValueError(
            f"unknown SLO objective {key!r}: expected 'availability' or "
            f"'<metric>_p<NN>_s' with metric in "
            f"{sorted(_METRIC_SHORTHAND)}")
    q = int(m.group("q"))
    return Objective(name=key, kind="latency",
                     target=1.0 - (100 - q) / 100.0,
                     threshold_s=float(val),
                     metric=_METRIC_SHORTHAND[m.group("metric")])


def load_specs(obj: Any) -> List[SLOSpec]:
    """Coerce the knob surface onto specs: a list of dicts (the
    ``ServingConfig.slo`` / ``EngineConfig.slo_specs`` form), one dict, a
    JSON string, or a path to a JSON file (the ``BIGDL_TPU_SLO_SPECS``
    env form)."""
    if obj is None:
        return []
    if isinstance(obj, SLOSpec):
        return [obj]
    if isinstance(obj, str):
        text = obj
        if not obj.lstrip().startswith(("[", "{")):
            with open(obj) as f:
                text = f.read()
        obj = json.loads(text)
    if isinstance(obj, dict):
        obj = [obj]
    return [s if isinstance(s, SLOSpec) else SLOSpec.from_dict(s)
            for s in obj]


@dataclass
class SLOStatus:
    """One objective's verdict at one evaluation tick."""

    tenant: str
    objective: str
    burn: float               # short-window burn rate (0 = no burn)
    burn_long: float
    budget_remaining: float   # max(0, 1 - burn)
    samples: int              # window events backing the verdict
    burning: bool             # burn >= alert threshold

    def asdict(self) -> Dict[str, Any]:
        return dict(self.__dict__)


class SLOEvaluator:
    """Evaluates declared SLOs against a ``Metrics`` registry and exports
    the verdicts as labeled ``slo.*`` gauges.

    Thread model: ``evaluate()`` may be called from any single driver (a
    background thread via :meth:`start`, the serving engine's GC tick via
    :meth:`maybe_evaluate`, or a test directly); internal state is
    lock-guarded so readers (``health_score`` from the autoscaler path)
    never race an evaluation."""

    def __init__(self, specs: Any, metrics=None,
                 alert_burn: float = DEFAULT_ALERT_BURN,
                 interval_s: Optional[float] = None,
                 clock: Callable[[], float] = time.time):
        if metrics is None:
            from bigdl_tpu.optim.metrics import global_metrics

            metrics = global_metrics()
        self.metrics = metrics
        self.specs = load_specs(specs)
        if not self.specs:
            raise ValueError("SLOEvaluator needs at least one spec")
        self.alert_burn = float(alert_burn)
        # default cadence: 6 ticks per shortest window — enough samples
        # for the availability delta ring without busy-polling
        self.interval_s = interval_s if interval_s is not None else \
            min(s.window_s for s in self.specs) / 6.0
        self.clock = clock
        # pre-size the tenant histograms this evaluator will read: the
        # default 60s ring cannot answer a longer spec window (short OR
        # 6x long) — slices keep the SHORT window's resolution.  A
        # histogram that already exists with a smaller ring (traffic
        # preceded the evaluator) is left alone but flagged: its long
        # window is silently capped at what the ring holds
        for spec in self.specs:
            need = spec.window_s * spec.long_window_factor
            slices = min(240, max(6, int(math.ceil(
                need / (spec.window_s / 6.0)))))
            for obj in spec.objectives:
                if obj.kind != "latency" or not obj.metric:
                    continue
                got = self.metrics.ensure_hist(
                    obj.metric, labels={"tenant": spec.tenant},
                    window_s=need, window_slices=slices)
                if got < need:
                    log.warning(
                        "SLO %s/%s: histogram window %.0fs predates this "
                        "evaluator and is shorter than the spec's long "
                        "window %.0fs — burn rates evaluate over the "
                        "shorter ring", spec.tenant, obj.name, got, need)
        self._lock = threading.Lock()
        # availability ring per tenant: (t, good_delta, bad_delta)
        self._avail_ring: Dict[str, deque] = {}
        self._last_counts: Dict[str, Tuple[float, float]] = {}
        self._burning: set = set()          # (tenant, objective) over alert
        self._last_eval_t = float("-inf")
        self._last_statuses: List[SLOStatus] = []
        self._health = 1.0
        self._tenant_health: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- reads (autoscaler / degradation / health endpoints) ----------------
    def health_score(self) -> float:
        """Pool health in [0, 1]: ``1 - max_burn / alert_burn`` clamped —
        1.0 while every budget holds, 0.0 once any objective burns at or
        past the alert threshold.  1.0 before the first evaluation (no
        verdict is not a bad verdict)."""
        with self._lock:
            return self._health

    def tenant_health(self, tenant: str) -> float:
        with self._lock:
            return self._tenant_health.get(tenant, 1.0)

    def statuses(self) -> List[SLOStatus]:
        with self._lock:
            return list(self._last_statuses)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe verdict summary for ``/health``."""
        with self._lock:
            return {"health": self._health,
                    "tenants": dict(self._tenant_health),
                    "alert_burn": self.alert_burn,
                    "objectives": [s.asdict()
                                   for s in self._last_statuses]}

    # -- evaluation ---------------------------------------------------------
    def maybe_evaluate(self, now: Optional[float] = None
                       ) -> Optional[List[SLOStatus]]:
        """Rate-limited :meth:`evaluate` — safe to call from a hot-ish
        loop (the serving engine piggybacks it on the result-GC tick)."""
        now = self.clock() if now is None else now
        with self._lock:
            if now - self._last_eval_t < self.interval_s:
                return None
        return self.evaluate(now)

    def evaluate(self, now: Optional[float] = None) -> List[SLOStatus]:
        now = self.clock() if now is None else now
        statuses: List[SLOStatus] = []
        for spec in self.specs:
            self._tick_availability(spec, now)
            for obj in spec.objectives:
                statuses.append(self._evaluate_one(spec, obj, now))
        by_tenant: Dict[str, float] = {}
        for st in statuses:
            by_tenant[st.tenant] = max(by_tenant.get(st.tenant, 0.0),
                                       st.burn)
        max_burn = max(by_tenant.values(), default=0.0)
        health = max(0.0, 1.0 - max_burn / self.alert_burn)
        tenant_health = {t: max(0.0, 1.0 - b / self.alert_burn)
                         for t, b in by_tenant.items()}
        with self._lock:
            self._last_eval_t = now
            self._last_statuses = statuses
            self._health = health
            self._tenant_health = tenant_health
        self.metrics.gauge("slo.health", health)
        for t, h in tenant_health.items():
            self.metrics.gauge("slo.tenant_health", h,
                               labels={"tenant": t})
        return statuses

    def _tick_availability(self, spec: SLOSpec, now: float) -> None:
        """Sample the tenant's cumulative good/bad counters into the
        delta ring (counters only move forward; a window sum of deltas is
        the windowed event count the budget math needs)."""
        t = spec.tenant
        lb = {"tenant": t}
        from bigdl_tpu.optim.metrics import label_key

        good = self.metrics.counter(
            label_key("serving.tenant_requests_total", **lb))
        bad = (self.metrics.counter(
                   label_key("serving.tenant_expired_total", **lb))
               + self.metrics.counter(
                   label_key("serving.tenant_failed_total", **lb)))
        ring = self._avail_ring.setdefault(t, deque())
        last = self._last_counts.get(t)
        if last is not None:
            dg, db = good - last[0], bad - last[1]
            if dg or db:
                ring.append((now, max(dg, 0.0), max(db, 0.0)))
        self._last_counts[t] = (good, bad)
        horizon = now - spec.window_s * spec.long_window_factor
        while ring and ring[0][0] < horizon:
            ring.popleft()

    def _avail_fracs(self, spec: SLOSpec, now: float
                     ) -> Tuple[float, float, int]:
        """(short bad fraction, long bad fraction, short window events)
        from the delta ring; NaN fractions when the window saw nothing."""
        ring = self._avail_ring.get(spec.tenant, ())

        def frac(window: float) -> Tuple[float, int]:
            g = b = 0.0
            for t, dg, db in ring:
                if t >= now - window:
                    g += dg
                    b += db
            total = g + b
            return ((b / total) if total else float("nan"), int(total))

        short, n = frac(spec.window_s)
        long_, _ = frac(spec.window_s * spec.long_window_factor)
        return short, long_, n

    def _evaluate_one(self, spec: SLOSpec, obj: Objective,
                      now: float) -> SLOStatus:
        lb = {"tenant": spec.tenant}
        if obj.kind == "availability":
            bad_s, bad_l, n = self._avail_fracs(spec, now)
        else:
            bad_s = self.metrics.window_fraction_over(
                obj.metric, obj.threshold_s, labels=lb,
                window_s=spec.window_s, now=now)
            bad_l = self.metrics.window_fraction_over(
                obj.metric, obj.threshold_s, labels=lb,
                window_s=spec.window_s * spec.long_window_factor, now=now)
            n = self.metrics.window_count(obj.metric, labels=lb,
                                          window_s=spec.window_s, now=now)
        # NaN = empty window = no burn: silence must not page anyone
        burn = 0.0 if math.isnan(bad_s) else bad_s / obj.budget
        burn_long = 0.0 if math.isnan(bad_l) else bad_l / obj.budget
        labels = {"tenant": spec.tenant, "objective": obj.name}
        self.metrics.gauge("slo.burn_rate", burn, labels=labels)
        self.metrics.gauge("slo.burn_rate_long", burn_long, labels=labels)
        self.metrics.gauge("slo.budget_remaining",
                           max(0.0, 1.0 - burn), labels=labels)
        key = (spec.tenant, obj.name)
        burning = burn >= self.alert_burn
        if burning and key not in self._burning:
            self._burning.add(key)
            self.metrics.inc("slo.burn_events_total")
            flight.record("slo_burn", tenant=spec.tenant,
                          objective=obj.name, burn=round(burn, 4),
                          burn_long=round(burn_long, 4),
                          threshold_s=obj.threshold_s,
                          target=obj.target, window_s=spec.window_s,
                          samples=n)
            log.warning("SLO BURN: tenant %s objective %s burn=%.2f "
                        "(alert >= %.2f, window %.0fs, %d events)",
                        spec.tenant, obj.name, burn, self.alert_burn,
                        spec.window_s, n)
        elif not burning and key in self._burning:
            self._burning.discard(key)
            flight.record("slo_burn_cleared", tenant=spec.tenant,
                          objective=obj.name, burn=round(burn, 4))
            log.info("SLO recovered: tenant %s objective %s burn=%.2f",
                     spec.tenant, obj.name, burn)
        return SLOStatus(tenant=spec.tenant, objective=obj.name,
                         burn=burn, burn_long=burn_long,
                         budget_remaining=max(0.0, 1.0 - burn),
                         samples=n, burning=burning)

    # -- background loop ----------------------------------------------------
    def start(self, interval_s: Optional[float] = None) -> "SLOEvaluator":
        if interval_s is not None:
            self.interval_s = interval_s
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.evaluate()
                except Exception as e:  # noqa: BLE001 — an evaluator tick
                    # must never take the host process down with it
                    log.warning("SLO evaluation failed: %s", e)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="bigdl-tpu-slo")
        self._thread.start()
        return self


    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 1)
            self._thread = None


def evaluator_from_env(metrics=None,
                       alert_burn: float = DEFAULT_ALERT_BURN
                       ) -> Optional[SLOEvaluator]:
    """Build an evaluator from ``BIGDL_TPU_SLO_SPECS`` (inline JSON or a
    JSON file path); None when the env is unset or unparseable — a bad
    spec degrades observability, never serving."""
    raw = os.environ.get("BIGDL_TPU_SLO_SPECS")
    if not raw:
        return None
    try:
        return SLOEvaluator(load_specs(raw), metrics=metrics,
                            alert_burn=alert_burn)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        log.error("BIGDL_TPU_SLO_SPECS unusable (%s); SLO evaluation "
                  "disabled", e)
        return None


# ---------------------------------------------------------------------------
# the SLO_r*.json artifact source: burn-rate alert latency under load
# ---------------------------------------------------------------------------

def bench(window_s: float = 2.0, warm_s: float = 1.0,
          threshold_s: float = 0.05, rate_hz: float = 200.0,
          timeout_s: float = 10.0) -> Dict[str, Any]:
    """Measure how fast the burn-rate alert fires after a hard SLO
    violation starts — THE number that decides whether an operator pages
    in seconds or in minutes.  Real wall clock on a compressed geometry
    (2s windows): feed in-budget latencies for ``warm_s``, then switch
    every request to 4x the objective bound and count evaluation TICKS
    until ``burn >= alert`` — the reported latency is ``ticks *
    interval``, quantized to the evaluation cadence so the committed
    artifact is stable run-to-run (a sub-tick wall measurement would
    gate on scheduler phase noise, not detection quality).  Gated
    lower-better by the sentinel's SLO family; ``slo_burn_peak`` gates
    higher-better (the detector must keep SEEING a hard violation as a
    hard burn)."""
    from bigdl_tpu.optim.metrics import Metrics

    m = Metrics()
    spec = SLOSpec.from_dict({
        "tenant": "bench",
        "objectives": {"predict_p99_s": threshold_s},
        "window_s": window_s})
    interval = window_s / 20.0
    ev = SLOEvaluator([spec], metrics=m, interval_s=interval)
    lb = {"tenant": "bench"}
    period = 1.0 / rate_hz
    t0 = time.time()
    while time.time() - t0 < warm_s:
        m.observe("serving.tenant_latency_seconds", threshold_s / 5,
                  labels=lb)
        ev.maybe_evaluate()
        time.sleep(period)
    warm_burn = max((s.burn for s in ev.statuses()), default=0.0)
    inject_t = time.time()
    alert_latency = None
    burn_peak = 0.0
    ticks = 0
    while time.time() - inject_t < timeout_s:
        # one full evaluation tick: violating traffic, then the verdict
        tick_end = inject_t + (ticks + 1) * interval
        while time.time() < tick_end:
            m.observe("serving.tenant_latency_seconds", threshold_s * 4,
                      labels=lb)
            time.sleep(period)
        ticks += 1
        burn = max((s.burn for s in ev.evaluate()), default=0.0)
        burn_peak = max(burn_peak, burn)
        if alert_latency is None and burn >= ev.alert_burn:
            alert_latency = ticks * interval
        if alert_latency is not None \
                and ticks * interval >= alert_latency + 5 * interval:
            break  # peak sampled well past the crossing; done
    row: Dict[str, Any] = {
        "metric": "slo_alert",
        "slo_alert_latency_s": alert_latency,
        "slo_burn_peak": round(burn_peak, 3),
        "warm_burn": round(warm_burn, 4),
        "window_s": window_s,
        "eval_interval_s": interval,
        "threshold_s": threshold_s,
        "alert_burn": ev.alert_burn,
        "evals_after_injection": ticks,
        "geometry": "inject_hard_violation_w2",
    }
    if alert_latency is None:
        row["error"] = "burn rate never crossed the alert threshold"
    elif warm_burn >= ev.alert_burn:
        row["error"] = "alert was already firing before the injection"
    return row


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="bigdl_tpu.obs.slo",
        description="SLO burn-rate alert-latency bench (the SLO_r*.json "
                    "artifact source; docs/observability.md §SLOs & burn "
                    "rates)")
    ap.add_argument("--bench", action="store_true",
                    help="measure burn-rate alert latency under an "
                         "injected hard violation")
    ap.add_argument("--window", type=float, default=2.0)
    ap.add_argument("--out", default=None,
                    help="also write the JSON row to this path")
    args = ap.parse_args(argv)
    if not args.bench:
        ap.error("nothing to do (use --bench)")
    row = bench(window_s=args.window)
    print(json.dumps(row))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(row, f, indent=1)
    if "error" in row:
        return 1
    # the gate the CI step enforces: detection inside ONE window
    return 0 if row["slo_alert_latency_s"] <= args.window else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
