"""Prometheus text-format export over the ``Metrics`` registry.

Reference analog (unverified — mount empty): the reference visualizes
training via TrainSummary/TensorBoard; operational scraping (the thing a
fleet actually alerts on) has no analog there.  This module renders any
:class:`~bigdl_tpu.optim.metrics.Metrics` registry — by default the
process-wide one that training, resilience, and serving all feed — in the
Prometheus text exposition format (version 0.0.4):

- monotonic ``counters``        -> ``# TYPE n counter`` single lines
- timer ``sums``/``counts``     -> ``# TYPE n summary`` ``n_sum``/``n_count``
- log-bucketed histograms       -> ``# TYPE n histogram`` cumulative
                                   ``n_bucket{le="..."}`` lines + ``+Inf``
                                   + ``n_sum``/``n_count``

Metric names are sanitized to the Prometheus grammar
(``[a-zA-Z_:][a-zA-Z0-9_:]*``) — the registry's dotted names
(``serving.shed_requests``) become underscored
(``serving_shed_requests``).

Serving exposes this at ``GET /metrics`` on the ``HttpFrontend`` and the
pool proxy; training jobs (no HTTP surface of their own) start a
standalone :class:`MetricsServer`.
"""

import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.obs")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str) -> str:
    """Map an arbitrary registry key onto the Prometheus metric-name
    grammar: invalid characters become ``_``; a leading digit gets a ``_``
    prefix."""
    out = _INVALID.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def split_label_key(key: str) -> Tuple[str, str]:
    """Split a registry key into (base name, label body).  Keys built by
    :func:`bigdl_tpu.optim.metrics.label_key` look like
    ``name{k="v",...}``; the label body is returned WITHOUT braces (empty
    for plain keys) and rides verbatim into the sample line."""
    if key.endswith("}") and "{" in key:
        base, _, rest = key.partition("{")
        return base, rest[:-1]
    return key, ""


def _merge_label_bodies(*bodies: str) -> str:
    """Join label bodies (brace-less ``k="v"`` lists), dropping empties."""
    return ",".join(b for b in bodies if b)


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


# help strings for the framework's own metric families, emitted as
# ``# HELP`` lines when the registry carries no explicit describe();
# keyed by the registry's dotted names
DEFAULT_HELP = {
    "train.step_time_s": "step wall time (window mean at coarse log "
                         "cadence)",
    "train.data_wait_s": "host time blocked on the input pipeline per "
                         "fetch (input-bound signal)",
    "train.attr.data_s": "per-step attributed time: input-pipeline wait",
    "train.attr.dispatch_s": "per-step attributed time: host dispatch of "
                             "the jitted step",
    "train.attr.device_s": "per-step attributed time: device compute "
                           "(residual at the log-point sync)",
    "train.attr.overhead_s": "per-step attributed time: trigger work "
                             "(validation/checkpoint/callbacks)",
    "train.mfu": "live model-flop utilization (analytic cost model over "
                 "the device-kind bf16 peak); DENSE-EQUIVALENT under "
                 "block sparsity — see train.effective_mfu",
    "train.effective_mfu": "live MFU counting only executed "
                           "(nonzero-block) FLOPs — the honest chip "
                           "utilization under block-sparse layers; "
                           "equals train.mfu for dense models",
    "train.flops_per_step": "analytic training FLOPs of one global step "
                            "(3x forward)",
    "train.effective_flops_per_step": "analytic training FLOPs of one "
                                      "global step counting only "
                                      "nonzero-block (executed) work",
    "ops.autotune_trials": "kernel-autotuner timing trials executed in "
                           "this process",
    "ops.autotune_cache_hits": "kernel tile lookups answered from the "
                               "autotune cache",
    "ops.autotune_cache_misses": "kernel tile lookups that fell back to "
                                 "hand-picked defaults (no cache entry)",
    "parallel.layout.replicated_params": "parameters the declarative "
                                         "layout silently replicated "
                                         "(matched no table rule / rank-"
                                         "rejected); 0 for covered model "
                                         "families — the paths ride the "
                                         "flight recorder",
    "parallel.layout.data_bytes_per_step": "analytic per-step gradient-"
                                           "allreduce bytes over the "
                                           "layout's data axes",
    "parallel.layout.fsdp_bytes_per_step": "analytic per-step param-"
                                           "gather + grad-scatter bytes "
                                           "over the fsdp axis",
    "parallel.layout.tp_bytes_per_step": "analytic per-step tp param-"
                                         "side bytes (activations price "
                                         "separately)",
    "parallel.layout.seq_bytes_per_step": "analytic per-step seq-axis "
                                          "param-side bytes",
    "parallel.layout.param_bytes_per_chip": "per-chip parameter bytes "
                                            "under the layout (the fits-"
                                            "on-one-chip meter fsdp x tp "
                                            "shrinks)",
    "train.achieved_flops_per_chip": "achieved FLOP/s per chip over the "
                                     "last log window",
    "train.collective_ici_bytes_per_step": "per-step ICI collective bytes "
                                           "of the ZeRO-1 cycle in the "
                                           "actual wire dtype (grad_comm "
                                           "payload + quantization scales "
                                           "+ f32 param gather)",
    "train.collective_dcn_bytes_per_step": "per-step cross-slice (DCN) "
                                           "collective bytes in the "
                                           "actual wire dtype",
    "train.collective_grad_ici_bytes_per_step":
        "per-step ICI bytes of the GRADIENT reduce-scatter alone (the "
        "compressible half; int8 counts payload + per-block scales)",
    "train.collective_param_ici_bytes_per_step":
        "per-step ICI bytes of the f32 updated-param all_gather",
    "train.grad_comm_buckets": "gradient-sync buckets per step (1 = "
                               "monolithic transfer)",
    "train.comm_overlap_efficiency": "fraction of gradient-sync "
                                     "collective time hidden under "
                                     "compute (startup audit; 1.0 = "
                                     "fully overlapped)",
    "train.comm_exposed_collective_s": "per-step collective time NOT "
                                       "hidden under compute (startup "
                                       "audit)",
    "train.collective_ici_bytes_total": "run-lifetime ICI collective "
                                        "bytes moved by training steps",
    "train.collective_dcn_bytes_total": "run-lifetime DCN collective "
                                        "bytes moved by training steps",
    "train.xla_compiles_total": "XLA backend compiles observed in this "
                                "process",
    "train.compile_time_s": "XLA backend compile durations",
    "train.unexpected_recompiles_total": "compiles after the run went "
                                         "steady (mid-run cache misses)",
    "train.step_time_skew_s": "max-min step time across hosts (straggler "
                              "skew)",
    "train.step_time_max_s": "slowest host's window step time",
    "train.step_time_min_s": "fastest host's window step time",
    "serving.latency_s": "admission-to-publish latency per request",
    "serving.queue_wait_s": "admission-to-predict queue wait per request "
                            "(the wait half of the tail decomposition)",
    "serving.batch_occupancy": "cumulative avg batch fill / batch_size "
                               "(continuous batching health)",
    "serving.queue_depth": "requests queued across all model heaps",
    "serving.backlog": "admitted requests not yet in predict (heaps + "
                       "handoff slot) — the autoscaling pressure signal",
    # token-level decode serving (docs/serving.md §Autoregressive decode)
    "serving.decode.tokens_per_s": "generated tokens/s over the recent "
                                   "decode-step window",
    "serving.decode.ttft_s": "time to first token per generate request "
                             "(admission -> first token out)",
    "serving.decode.inter_token_s": "gap between consecutive streamed "
                                    "tokens of one sequence",
    "serving.decode.step_s": "one decode model step (all active slots, "
                             "one token each)",
    "serving.decode.prefill_s": "one prompt prefill chunk through the "
                                "prefill program",
    "serving.decode.slot_occupancy": "occupied decode slots / slot pool "
                                     "size",
    "serving.decode.page_utilization": "allocated KV-cache pages / page "
                                       "pool size",
    "serving.decode.queue_depth": "generate requests queued for a free "
                                  "slot (deadline-heap ordered)",
    "serving.decode.tokens_total": "generated tokens, engine lifetime",
    "serving.decode.requests": "generate requests admitted into slots",
    "serving.decode.completed": "generate requests finished (eos or "
                                "length)",
    "serving.decode.expired": "generate requests dropped by per-token "
                              "deadline enforcement (queued or "
                              "mid-decode)",
    "serving.decode.steps": "decode model steps executed",
    "serving.decode.prefill_chunks": "prompt prefill chunks executed",
    "serving.decode.spec_accept_rate": "speculative decode: accepted / "
                                       "adjudicated draft tokens over "
                                       "the recent window "
                                       "(docs/serving.md §Speculative "
                                       "decoding) — 1.0 means every "
                                       "draft the target scored agreed",
    "serving.decode.spec_drafted_tokens": "speculative decode: tokens "
                                          "drafted by the block-sparse "
                                          "twin, engine lifetime",
    "serving.decode.spec_accepted_tokens": "speculative decode: drafted "
                                           "tokens the target verify "
                                           "accepted",
    "serving.decode.spec_rejected_tokens": "speculative decode: drafted "
                                           "tokens rejected by a verify "
                                           "mismatch (drafts past an "
                                           "eos/length finish count as "
                                           "neither)",
    "serving.decode.spec_draft_step_s": "one draft-model k-token scan "
                                        "(all active slots, one "
                                        "program call)",
    "serving.decode.spec_verify_step_s": "one target-model verify call "
                                         "scoring the drafted chunk",
    "serving.decode.kv_bytes_per_page": "HBM bytes one KV page costs in "
                                        "its stored dtype (int8 pages "
                                        "include the per-page scale "
                                        "pair; docs/quantization.md "
                                        "§Serving memory hierarchy) — "
                                        "page_dtype itself rides "
                                        "/health decode_pressure as a "
                                        "string",
    # label-form per-tenant serving families (docs/observability.md
    # §Federation): one family, one series per tenant="..." label — the
    # name-embedded serving.tenant.<name>.* families stay as deprecated
    # aliases for one release
    "serving.tenant_latency_seconds": "admission-to-publish latency per "
                                      "request, by tenant= label "
                                      "(labeled alias of "
                                      "serving.tenant.<name>.latency_s)",
    "serving.tenant_queue_wait_seconds": "admission-to-predict queue wait "
                                         "per request, by tenant= label",
    "serving.tenant_ttft_seconds": "generate time-to-first-token per "
                                   "request, by tenant= label",
    "serving.tenant_queue_depth": "requests queued in the tenant's "
                                  "admission heap, by tenant= label",
    "serving.tenant_requests_total": "requests answered, by tenant= label",
    "serving.tenant_expired_total": "requests dropped on deadline, by "
                                    "tenant= label",
    "serving.tenant_failed_total": "requests failed by predict errors, by "
                                   "tenant= label",
    # declarative SLOs (docs/observability.md §SLOs & burn rates)
    "slo.burn_rate": "error-budget burn rate over the objective's short "
                     "window, by tenant=/objective= labels (1.0 = burning "
                     "exactly the budget; >1 exhausts it early)",
    "slo.burn_rate_long": "burn rate over the long (6x) window — the "
                          "sustained-burn half of multi-window alerting",
    "slo.budget_remaining": "fraction of the window's error budget left "
                            "(clamped at 0), by tenant=/objective=",
    "slo.health": "pool health score in [0,1]: 1 - max burn rate across "
                  "tenants/objectives, clamped — the autoscaler/"
                  "degradation input",
    "slo.tenant_health": "per-tenant health score in [0,1], by tenant=",
    "slo.burn_events_total": "slo_burn flight events recorded (burn rate "
                             "crossed the alert threshold)",
    "serving_pool.workers": "serving pool size (autoscaler-managed)",
    "serving_pool.federation_stale": "federated /metrics scrapes that "
                                     "dropped a worker's series (dead or "
                                     "unreachable mid-scrape)",
    "serving_pool.conn_reuse": "proxy forwards served over a reused "
                               "keep-alive worker connection",
    "serving_pool.scale_up": "autoscaler worker additions",
    "serving_pool.scale_down": "autoscaler worker removals (drained "
                               "before exit)",
    # decode fleet (docs/serving.md §Decode fleet)
    "serving_pool.fleet_routed": "generate requests placed by the "
                                 "KV-aware fleet router (vs round-robin "
                                 "fallback)",
    "serving_pool.fleet_split": "generate requests routed through a "
                                "dedicated prefill worker (KV handoff)",
    "serving_pool.stream_relays": "streaming /generate token streams "
                                  "relayed through the pool proxy",
    "serving.fleet.prefix_cache_hits": "generate admissions that attached "
                                       "to cached prefix KV pages",
    "serving.fleet.prefix_cache_misses": "generate admissions with no "
                                         "cached prefix to attach",
    "serving.fleet.prefix_cache_evicted_pages": "prefix-cache pages "
                                                "LRU-evicted back to the "
                                                "engine's free pool",
    "serving.fleet.prefix_cache_pages": "KV pages currently held by the "
                                        "prefix cache",
    "serving.fleet.prefix_cache_entries": "distinct token prefixes "
                                          "currently cached",
    "serving.fleet.kv_exports": "prefill KV handoffs exported for a "
                                "decode worker",
    "serving.fleet.kv_imports": "prefill KV handoffs imported from a "
                                "prefill worker",
    # fleet fault tolerance (docs/serving.md §Fleet fault tolerance)
    "serving.fleet.failovers": "streams re-placed on a surviving decode "
                               "worker after their worker died "
                               "mid-stream",
    "serving.fleet.migrations": "live decode slots migrated (KV exported "
                                "and adopted by a peer) during a drain",
    "serving.fleet.resumed_tokens": "tokens already delivered to clients "
                                    "at failover time (resumed, not "
                                    "regenerated client-side)",
    "serving.fleet.orphaned_requests": "streams terminated with an error "
                                       "after every re-placement attempt "
                                       "failed within the budget",
    "serving.fleet.recovery_s": "client-visible failover recovery "
                                "latency: worker loss detected to the "
                                "resumed stream's first byte",
    "serving.fleet.hedged_prefills": "remote prefills abandoned at the "
                                     "hedge deadline and recomputed "
                                     "locally",
    "serving.fleet.parked_handoffs": "migration handoffs parked on this "
                                     "worker awaiting their resumed "
                                     "request",
    "serving.fleet.resumes": "generate requests carrying resume_from "
                             "(failover re-placements)",
    "serving.fleet.resume_adopted": "resumed requests that attached to a "
                                    "parked migration handoff (no "
                                    "re-prefill)",
    "serving.fleet.resume_reprefill": "resumed requests that rebuilt KV "
                                      "by chunked re-prefill",
    "serving.decode.cancelled": "live decode requests cancelled "
                                "(client disconnect, migration eviction, "
                                "explicit cancel)",
    "serving.decode.client_disconnects": "streaming clients that hung up "
                                         "mid-generate (slot and pages "
                                         "freed immediately)",
    "serving_pool.fleet_failovers": "proxy-side count of mid-stream "
                                    "failovers (see "
                                    "serving.fleet.failovers)",
    "serving_pool.fleet_migrations": "proxy-side count of drain "
                                     "migrations recorded",
    "serving_pool.fleet_resumed_tokens": "proxy-side count of tokens "
                                         "carried across failovers",
    "serving_pool.fleet_orphans": "proxy-side count of orphaned streams",
    # cluster control plane (docs/resilience.md §Multi-host recovery)
    "cluster.view_epoch": "current membership view epoch",
    "cluster.members": "live members in the current view",
    "cluster.leader": "leader rank of the current view (lowest live)",
    "cluster.mttr_s": "gang recovery wall time, detection to resumed",
    "cluster.recoveries_total": "coordinated recoveries completed",
    "cluster.recovery_bytes_total": "bytes restored across recoveries",
    "cluster.publish_bytes_total": "peer-shard store bytes published",
    "cluster.aborts_total": "gang abort flags posted by this process",
    "cluster.preempt_notices_total": "cluster-wide preemption notices "
                                     "posted or propagated",
    # training-side metric federation (docs/observability.md §Federation):
    # the leader re-exports each host's snapshot under cluster.host.*
    # families with a host= label — one scrape shows the whole gang
    "cluster.hosts_reporting": "hosts whose metric snapshots the leader "
                               "merged in the last sweep (self included)",
    "cluster.host.age_s": "staleness of one host's merged metric "
                          "snapshot, by host= label — a straggler shows "
                          "up as a growing age, not a missing series",
    # streaming input pipeline (docs/data.md §Reading the data.* metrics
    # + §Multi-host ingest)
    "data.read_batches": "batches fetched by the pipeline's read stage",
    "data.decoded_images": "rows decoded into ring slots by the worker "
                           "pool",
    "data.ready_batches": "ring slots turned READY (all decode parts "
                          "reported)",
    "data.queue_depth.raw": "raw-queue occupancy in decode part-jobs "
                            "(full = decode is the bottleneck)",
    "data.queue_depth.ring": "buffer-ring slots not FREE (assigned, "
                             "ready, or lent to the consumer)",
    "data.backpressure.read": "fraction of pipeline wall the read stage "
                              "spent blocked on a free slot or queue "
                              "space — high means decode or the "
                              "consumer caps the pipeline",
    "data.backpressure.decode": "fraction of decode-pool wall spent "
                                "starved for read work WHILE ring slots "
                                "were free — high means the read stage "
                                "caps the pipeline (a full ring, i.e. a "
                                "slow consumer, does not count here)",
    "data.dispatch.in_flight": "host-to-device transfers still unsynced "
                               "in the dispatch double-buffer window",
    "data.dispatch_overlapped_total": "transfers issued while a previous "
                                      "one was still in flight — 0 "
                                      "means the dispatch double buffer "
                                      "never engaged",
    "data.rate.shard_img_per_s": "genuine (unpadded) rows THIS host's "
                                 "shard fed per wall second — the "
                                 "per-host multi-host ingest rate",
    "data.rate.read_batches_per_s": "read-stage batches per wall second "
                                    "over the measured window",
    "data.rate.decode_batches_per_s": "decoded batches per wall second "
                                      "over the measured window",
    "data.rate.read_capacity_batches_per_s":
        "read-stage capacity (count / stage-busy seconds) — what the "
        "stage could do if never blocked",
    "data.rate.decode_capacity_batches_per_s":
        "decode-pool capacity (count / busy seconds, scaled by pool "
        "width) — the worker-autosizing signal",
    # recsys serving pipeline (docs/recsys.md): per-stage latency of the
    # feature -> recall -> ranking path; the recall/ranking tenants'
    # queue/SLO series ride the generic serving.tenant.* families
    "serving.recsys.feature_s": "recommend feature-fetch stage latency "
                                "(user history lookup)",
    "serving.recsys.recall_s": "recommend recall stage latency (tenant "
                               "admission + MXU top-k)",
    "serving.recsys.rank_s": "recommend ranking stage latency (inline "
                             "candidate scoring, no re-admission)",
    "serving.recsys.recommend_s": "end-to-end recommend latency across "
                                  "all three stages",
    "serving.recsys.candidates": "recall candidates handed to ranking "
                                 "per recommend request",
    "serving.recsys.requests": "recommend requests completed by the "
                               "pipeline",
    # sharded friesian feature engineering (docs/recsys.md §Sharded
    # feature tables): pickled-stat bytes through the cross-process
    # merge allgather — the payload the merge cap bounds
    "friesian.sharded.merge_bytes_total": "pickled stat-merge payload "
                                          "bytes offered to the "
                                          "cross-process allgather "
                                          "(bounded per op by the "
                                          "merge-bytes cap)",
}


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def render_prometheus(metrics=None) -> str:
    """One scrape: the full registry in text exposition format.  With no
    argument, renders the process-wide registry — the union every
    subsystem's counters mirror into.

    ``# HELP`` rides next to ``# TYPE`` (registry ``describe()`` strings
    first, the framework catalog as fallback), and a family's header is
    emitted at most ONCE per scrape — two dotted names that sanitize to
    the same family must not re-declare it.  The colliding LATER name's
    samples are dropped too: duplicate name+labels series make the whole
    scrape unparseable to a real Prometheus, which is strictly worse than
    losing the shadowed series."""
    if metrics is None:
        from bigdl_tpu.optim.metrics import global_metrics

        metrics = global_metrics()
    snap = metrics.snapshot()
    helps = dict(DEFAULT_HELP)
    helps.update(snap.get("helps", {}))
    lines = []
    emitted = set()
    owner: Dict[str, str] = {}  # family -> raw BASE name that claimed it

    def header(raw_base: str, n: str, typ: str) -> bool:
        """Declare family ``n`` once; False when ``raw_base`` lost the
        family to an earlier colliding name (caller skips its samples).
        Labeled series of ONE base name share the family — only a
        DIFFERENT base colliding onto the same sanitized family is
        dropped."""
        if owner.setdefault(n, raw_base) != raw_base:
            return False
        if n in emitted:
            return True  # family already declared this scrape
        emitted.add(n)
        h = helps.get(raw_base) or helps.get(n)
        if h:
            lines.append(f"# HELP {n} {_escape_help(h)}")
        lines.append(f"# TYPE {n} {typ}")
        return True

    def series(key: str) -> Tuple[str, str, str]:
        """(raw base, family, rendered sample suffix) of one registry
        key — ``suffix`` is ``{labels}`` or empty."""
        base, labels = split_label_key(key)
        n = sanitize_metric_name(base)
        return base, n, (f"{{{labels}}}" if labels else "")

    for name in sorted(snap["counters"]):
        base, n, sfx = series(name)
        if not header(base, n, "counter"):
            continue
        lines.append(f"{n}{sfx} {_fmt(snap['counters'][name])}")
    # gauges: point-in-time levels (queue depths, ring occupancy);
    # .get() tolerates snapshots from pre-gauge Metrics objects
    for name in sorted(snap.get("gauges", {})):
        base, n, sfx = series(name)
        if not header(base, n, "gauge"):
            continue
        lines.append(f"{n}{sfx} {_fmt(snap['gauges'][name])}")
    for name in sorted(snap["sums"]):
        base, n, sfx = series(name)
        if not header(base, n, "summary"):
            continue
        lines.append(f"{n}_sum{sfx} {_fmt(snap['sums'][name])}")
        lines.append(f"{n}_count{sfx} {snap['counts'].get(name, 0)}")
    for name in sorted(snap["hists"]):
        h = snap["hists"][name]
        base, n, sfx = series(name)
        if not header(base, n, "histogram"):
            continue
        _, labels = split_label_key(name)
        acc = 0
        for bound, count in zip(h["bounds"], h["counts"]):
            acc += count
            lb = _merge_label_bodies(labels, f'le="{_fmt(bound)}"')
            lines.append(f'{n}_bucket{{{lb}}} {acc}')
        lb = _merge_label_bodies(labels, 'le="+Inf"')
        lines.append(f'{n}_bucket{{{lb}}} {h["n"]}')
        lines.append(f"{n}_sum{sfx} {_fmt(h['sum'])}")
        lines.append(f"{n}_count{sfx} {h['n']}")
    return "\n".join(lines) + "\n"


# -- metrics federation (docs/observability.md §Federation) -----------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)(?:\s+(\S+))?$')


def parse_exposition(text: str) -> List[Dict]:
    """Parse one Prometheus text exposition into ordered families:
    ``[{"name", "type", "help", "samples": [(metric, labels, value)]}]``
    with ``labels`` the brace-less label body (may carry ``le=``).
    Samples are grouped under the family whose ``# TYPE`` header they
    follow (the exposition-format contract); a sample with no preceding
    header opens an untyped family of its own name.  Tolerant by design
    — a malformed line is skipped, never fatal: this is the proxy's read
    path over worker scrapes."""
    families: List[Dict] = []
    by_name: Dict[str, Dict] = {}
    current: Optional[Dict] = None

    def family(name: str, typ: Optional[str], help_text: Optional[str]
               ) -> Dict:
        fam = by_name.get(name)
        if fam is None:
            fam = {"name": name, "type": typ, "help": help_text,
                   "samples": []}
            by_name[name] = fam
            families.append(fam)
        else:
            if typ is not None and fam["type"] is None:
                fam["type"] = typ
            if help_text is not None and fam["help"] is None:
                fam["help"] = help_text
        return fam

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            current = family(name, None, help_text)
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, typ = rest.partition(" ")
            current = family(name, typ.strip() or None, None)
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        metric, labels, value = m.group(1), m.group(2) or "", m.group(3)
        fam = current
        if fam is None or not metric.startswith(fam["name"]):
            fam = family(metric, None, None)
            current = fam
        fam["samples"].append((metric, labels, value))
    return families


def _render_extra_labels(extra: Dict[str, str]) -> str:
    # THE label-body renderer is optim.metrics.label_key (imported
    # lazily — metrics imports obs.hist, so a module-level import here
    # would re-enter the obs package mid-init); an empty name yields
    # just the braced body, which this strips
    from bigdl_tpu.optim.metrics import label_key

    return label_key("", **extra)[1:-1] if extra else ""


def federate(parts: List[Tuple[Dict[str, str], str]]) -> str:
    """Merge several expositions into ONE parse-clean scrape — the pool
    proxy's federated ``GET /metrics`` (docs/observability.md
    §Federation).  ``parts`` is ``[(extra_labels, exposition_text)]``;
    every sample of a part gets its extra labels (``worker="worker-0"``)
    appended, which is what keeps same-named series from two workers
    distinct.  Each family is DECLARED exactly once (first part wins the
    ``# HELP``/``# TYPE``); a later part whose declared type disagrees
    has that family's samples dropped — a type-flapping family would make
    the whole scrape unparseable, which is strictly worse."""
    merged: List[Dict] = []
    by_name: Dict[str, Dict] = {}
    for extra, text in parts:
        sfx = _render_extra_labels(extra) if extra else ""
        for fam in parse_exposition(text):
            out = by_name.get(fam["name"])
            if out is None:
                out = {"name": fam["name"], "type": fam["type"],
                       "help": fam["help"], "samples": []}
                by_name[fam["name"]] = out
                merged.append(out)
            elif (fam["type"] is not None and out["type"] is not None
                    and fam["type"] != out["type"]):
                continue  # type conflict: drop the later part's samples
            for metric, labels, value in fam["samples"]:
                lb = _merge_label_bodies(labels, sfx)
                out["samples"].append(
                    (f"{metric}{{{lb}}}" if lb else metric, value))
    lines = []
    for fam in merged:
        if fam["help"]:
            lines.append(f"# HELP {fam['name']} {fam['help']}")
        if fam["type"]:
            lines.append(f"# TYPE {fam['name']} {fam['type']}")
        for metric, value in fam["samples"]:
            lines.append(f"{metric} {value}")
    return "\n".join(lines) + "\n"


def reply_metrics(handler: BaseHTTPRequestHandler, metrics=None) -> None:
    """Write one ``/metrics`` response on a stdlib handler — shared by the
    serving frontend, the pool proxy, and :class:`MetricsServer` so the
    exposition surface cannot drift between them."""
    try:
        body = render_prometheus(metrics).encode()
        handler.send_response(200)
        handler.send_header("Content-Type", CONTENT_TYPE)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
    except (BrokenPipeError, ConnectionResetError):
        pass  # scraper hung up; never kill the serving handler thread


class MetricsServer:
    """Standalone ``GET /metrics`` endpoint for jobs with no HTTP surface
    of their own (training drivers).  ``port=0`` picks a free port —
    ``url`` is the scrape target."""

    def __init__(self, metrics=None, host: str = "127.0.0.1",
                 port: int = 0):
        self.metrics = metrics

        outer = self

        class Handler(BaseHTTPRequestHandler):
            server_version = "bigdl-tpu-metrics/1"

            def log_message(self, fmt, *args):
                log.debug(fmt, *args)

            def do_GET(self):
                if self.path != "/metrics":
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                reply_metrics(self, outer.metrics)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        log.info("metrics server listening on %s", self.url)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
