"""Step-time attribution — every training run becomes an explained run.

Reference analog (unverified — mount empty): ``dllib/optim/Metrics.scala``
logged per-iteration "computing time average / get weights average / put
gradient" splits; under XLA the iteration is one fused program, so the
meaningful decomposition is host-side, assembled from the driver's existing
``train/step|dispatch|data`` spans plus the bundle-edge device sync:

- **data**     — host time blocked on the input pipeline (device idle,
  input-bound; the ``train.data_wait_s`` samples)
- **dispatch** — host time issuing the jitted bundle (python + transfer
  argument plumbing)
- **overhead** — trigger work at bundle edges: validation, checkpoint
  writes, parameter histograms, callbacks
- **device**   — the residual: device compute the host waited out at the
  log-point sync (plus any untracked host time — kept honest by the
  residual construction, the four components sum to the window wall by
  definition)

Per-step values land in ``train.attr.*_s`` histograms on ``/metrics``; the
run total is the end-of-run "where did the time go" table
(:meth:`StepAttribution.table`).

This module also owns two run-health sentinels:

- :class:`RecompileSentinel` — counts XLA cache misses mid-run via
  ``jax.monitoring`` backend-compile events; a compile that fires after
  the run went steady and outside an :func:`expected_compile` region is
  an *unexpected recompile* (shape drift, cache invalidation) — counted
  and flight-recorded.
- :func:`host_step_time_stats` — cross-process aggregation for
  multi-process meshes: allgathers each host's window step time and
  yields max/min/skew (straggler detection).
"""

import threading
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

import numpy as np

from bigdl_tpu.obs import flight
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.obs")

COMPONENTS = ("data", "dispatch", "device", "overhead")


class StepAttribution:
    """Accumulates per-window wall-time decompositions and exports them as
    ``train.attr.*`` histograms plus an end-of-run table."""

    def __init__(self, metrics=None):
        if metrics is None:
            from bigdl_tpu.optim.metrics import global_metrics

            metrics = global_metrics()
        self.metrics = metrics
        self.steps = 0
        self.wall_s = 0.0
        self.totals: Dict[str, float] = {c: 0.0 for c in COMPONENTS}
        self.windows = 0

    def window(self, steps: int, wall_s: float, data_s: float,
               dispatch_s: float, overhead_s: float) -> Dict[str, float]:
        """Record one log window of ``steps`` steps.  ``device`` is the
        residual (wall minus the tracked host components), clamped at 0 —
        so the components always sum back to the window wall (to within
        the clamp, which only engages when host timers overlap)."""
        if steps <= 0 or wall_s <= 0:
            return {}
        comps = {
            "data": max(data_s, 0.0),
            "dispatch": max(dispatch_s, 0.0),
            "overhead": max(overhead_s, 0.0),
        }
        comps["device"] = max(wall_s - sum(comps.values()), 0.0)
        self.steps += steps
        self.wall_s += wall_s
        self.windows += 1
        for name, v in comps.items():
            self.totals[name] += v
            # per-step values: comparable across log cadences and bundle
            # sizes, like train.step_time_s
            self.metrics.observe(f"train.attr.{name}_s", v / steps)
        return comps

    def report(self) -> Dict[str, Any]:
        """Run totals + fractions — the machine-readable table."""
        out: Dict[str, Any] = {
            "steps": self.steps, "wall_s": self.wall_s,
            "windows": self.windows, "components": {},
        }
        for name in COMPONENTS:
            t = self.totals[name]
            out["components"][name] = {
                "total_s": t,
                "per_step_s": t / self.steps if self.steps else 0.0,
                "fraction": t / self.wall_s if self.wall_s else 0.0,
            }
        return out

    def table(self) -> str:
        """The end-of-run "where did the time go" table (logged by the
        driver; first window includes compile, which lands in device)."""
        rep = self.report()
        lines = [
            f"step-time attribution over {rep['steps']} steps "
            f"({rep['wall_s']:.3f}s wall):",
            f"  {'component':<10} {'total_s':>10} {'per_step_ms':>12} "
            f"{'fraction':>9}",
        ]
        for name in COMPONENTS:
            c = rep["components"][name]
            lines.append(
                f"  {name:<10} {c['total_s']:>10.3f} "
                f"{c['per_step_s'] * 1e3:>12.3f} {c['fraction']:>8.1%}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# recompilation sentinel
# ---------------------------------------------------------------------------

_expected = threading.local()


def _expected_depth() -> int:
    return getattr(_expected, "depth", 0)


@contextmanager
def expected_compile():
    """Mark the calling thread's region as an EXPECTED compile site (a new
    bundle size, a fresh eval program, a plateau LR rebake) so the
    recompile sentinel doesn't flag it."""
    _expected.depth = _expected_depth() + 1
    try:
        yield
    finally:
        _expected.depth = _expected_depth() - 1


class RecompileSentinel:
    """Counts XLA backend compiles via ``jax.monitoring`` events.

    Every compile increments ``train.xla_compiles_total`` and lands in the
    ``train.compile_time_s`` histogram.  After :meth:`mark_steady` (the
    driver calls it once warmup compiles are done), a compile outside an
    :func:`expected_compile` region additionally increments
    ``train.unexpected_recompiles_total`` and records an
    ``unexpected_recompile`` flight event — the mid-run cache-miss signal
    (shape drift, donation breakage, cache eviction) that silently
    multiplies step time."""

    EVENT = "/jax/core/compile/backend_compile_duration"

    def __init__(self):
        self._steady = False
        self._step: Optional[int] = None
        self._registered = False

    # listener plumbing -----------------------------------------------------
    def install(self) -> "RecompileSentinel":
        """Register the jax.monitoring listener once per process (jax has
        no unregister; the listener is a no-op-cheap counter)."""
        if self._registered:
            return self
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(self._on_event)
        self._registered = True
        return self

    def _on_event(self, name: str, duration_s: float, **kw) -> None:
        if name != self.EVENT:
            return
        try:
            from bigdl_tpu.optim.metrics import global_metrics

            m = global_metrics()
            m.inc("train.xla_compiles_total")
            m.observe("train.compile_time_s", float(duration_s))
            if self._steady and _expected_depth() == 0:
                m.inc("train.unexpected_recompiles_total")
                flight.record("unexpected_recompile",
                              duration_s=float(duration_s),
                              step=self._step)
                log.warning(
                    "unexpected XLA recompile mid-run (%.3fs, step %s): "
                    "input shapes drifted or the compile cache was "
                    "invalidated", duration_s, self._step)
        except Exception:  # a metrics bug must never sink a compile
            pass

    # driver hooks ----------------------------------------------------------
    def mark_steady(self, step: Optional[int] = None) -> None:
        """Warmup is over: from here every unannounced compile is a cache
        miss worth flagging."""
        self._steady = True
        self._step = step

    def note_step(self, step: int) -> None:
        self._step = step

    def mark_warmup(self) -> None:
        """Back to warmup (run ended / new run starting): compiles are
        expected again."""
        self._steady = False
        self._step = None

    @property
    def steady(self) -> bool:
        return self._steady


_sentinel: Optional[RecompileSentinel] = None
_sentinel_lock = threading.Lock()


def recompile_sentinel() -> RecompileSentinel:
    """The process-wide sentinel, listener installed on first use."""
    global _sentinel
    if _sentinel is None:
        with _sentinel_lock:
            if _sentinel is None:
                _sentinel = RecompileSentinel().install()
    return _sentinel


# ---------------------------------------------------------------------------
# cross-process aggregation (straggler skew)
# ---------------------------------------------------------------------------

def step_time_stats(values) -> Dict[str, float]:
    """max/min/skew/mean over per-host step times (pure; unit-testable
    without a multi-process mesh)."""
    vals = np.ravel(np.asarray(values, np.float64))
    if vals.size == 0:
        return {}
    return {"max": float(vals.max()), "min": float(vals.min()),
            "skew": float(vals.max() - vals.min()),
            "mean": float(vals.mean()), "n_hosts": int(vals.size)}


def host_step_time_stats(step_time_s: float) -> Optional[Dict[str, float]]:
    """Allgather this host's window step time and reduce to straggler
    stats.  Multi-process only (None on a single process); every process
    must call at the same cadence (the driver's deterministic log points
    guarantee it).  The caller exports the result as the
    ``train.step_time_{max,min,skew}_s`` gauges."""
    import jax

    if jax.process_count() <= 1:
        return None
    from jax.experimental import multihost_utils

    vals = multihost_utils.process_allgather(
        np.asarray([step_time_s], np.float64))
    return step_time_stats(vals)
