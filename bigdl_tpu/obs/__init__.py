"""Observability — spans, Prometheus export, latency histograms, flight
recorder (docs/observability.md).

The layer every other subsystem reports through:

- :mod:`.trace`  — span tracer (Chrome-trace/Perfetto JSON) correlating a
  serving request or training step across subsystems
- :mod:`.export` — Prometheus text-format exporter over ``Metrics``
  (``GET /metrics`` on serving; :class:`MetricsServer` for training jobs)
- :mod:`.hist`   — bounded log-bucketed histograms (p50/p95/p99)
- :mod:`.flight` — fixed-size ring of notable events, dumped as JSONL on
  crash or SIGTERM
"""

from bigdl_tpu.obs import flight, trace
from bigdl_tpu.obs.export import (MetricsServer, render_prometheus,
                                  sanitize_metric_name)
from bigdl_tpu.obs.flight import FlightRecorder
from bigdl_tpu.obs.hist import LogHistogram
from bigdl_tpu.obs.trace import Span, Tracer

__all__ = [
    "trace", "flight", "Tracer", "Span", "FlightRecorder", "LogHistogram",
    "MetricsServer", "render_prometheus", "sanitize_metric_name",
]
