"""Observability — spans, Prometheus export, latency histograms, flight
recorder (docs/observability.md).

The layer every other subsystem reports through:

- :mod:`.trace`  — span tracer (Chrome-trace/Perfetto JSON) correlating a
  serving request or training step across subsystems
- :mod:`.export` — Prometheus text-format exporter over ``Metrics``
  (``GET /metrics`` on serving; :class:`MetricsServer` for training jobs)
- :mod:`.hist`   — bounded log-bucketed histograms (p50/p95/p99 +
  sliding windows)
- :mod:`.slo`    — declarative per-tenant SLOs: sliding-window error
  budgets, multi-window burn-rate alerts, the fleet health score
- :mod:`.flight` — fixed-size ring of notable events, dumped as JSONL on
  crash or SIGTERM
- :mod:`.attr`   — per-step wall-time attribution, the recompilation
  sentinel, and cross-host straggler stats
- :mod:`.cost`   — analytic FLOPs/bytes cost model + device peak table
  (the live ``train.mfu`` gauge)
- :mod:`.sentinel` — read-only perf-regression gate over the committed
  bench trajectory (``python -m bigdl_tpu.obs.sentinel``)
"""

# NOTE: obs.sentinel is deliberately NOT imported here — it is the
# `python -m bigdl_tpu.obs.sentinel` CLI, and an eager package import
# would trip runpy's double-import warning on every invocation
from bigdl_tpu.obs import attr, cost, flight, slo, trace
from bigdl_tpu.obs.attr import (RecompileSentinel, StepAttribution,
                                expected_compile, recompile_sentinel)
from bigdl_tpu.obs.cost import CostReport, forward_costs, peak_flops
from bigdl_tpu.obs.export import (MetricsServer, federate,
                                  parse_exposition, render_prometheus,
                                  sanitize_metric_name)
from bigdl_tpu.obs.flight import FlightRecorder
from bigdl_tpu.obs.hist import LogHistogram
from bigdl_tpu.obs.slo import SLOEvaluator, SLOSpec
from bigdl_tpu.obs.trace import Span, Tracer

__all__ = [
    "trace", "flight", "attr", "cost", "slo", "Tracer", "Span",
    "FlightRecorder", "LogHistogram", "MetricsServer", "render_prometheus",
    "parse_exposition", "federate", "SLOEvaluator", "SLOSpec",
    "sanitize_metric_name", "StepAttribution", "RecompileSentinel",
    "recompile_sentinel", "expected_compile", "CostReport", "forward_costs",
    "peak_flops",
]
