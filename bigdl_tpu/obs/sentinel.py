"""Perf-regression sentinel — fresh bench JSON vs the committed trajectory.

The repo commits one perf artifact per round (``BENCH_r01..r05``,
``BENCH_loader_r06``, ``BENCH_dispatch_r07``, ``SERVING_r04/r05``); until
now nothing *compared* a fresh measurement against that trajectory — a 20%
throughput regression would land silently as next round's artifact.  This
module is the gate: it normalizes every committed artifact into
``(family, value, direction)`` rows, takes the best good committed value
per family as the baseline, and flags a fresh row that regresses more than
``threshold`` (default 10%).

READ-ONLY by design: the sentinel never writes bench artifacts or touches
``BENCH_attempts.jsonl`` — ``chipup.py`` remains the repo's single
evidence writer (the test_watcher_single invariant; this is why the
historical ``bench_watch.py`` entry point stays retired and the CLI lives
at ``python -m bigdl_tpu.obs.sentinel`` / ``make bench-watch`` instead).

CLI::

    python -m bigdl_tpu.obs.sentinel fresh.json [...]   # exit 1 on regression
    python -m bigdl_tpu.obs.sentinel --smoke            # prove the gate works
                                                        # on synthetic rows

``--smoke`` synthesizes a 20% regressed row and an unregressed row from
the committed history and exits non-zero unless the sentinel flags exactly
the regressed one — the CI step that proves the gate, machine-independent.
"""

import argparse
import glob
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

HIGHER = "higher"
LOWER = "lower"

DEFAULT_THRESHOLD = 0.10

# committed artifact families: (glob, extractor).  An extractor maps one
# artifact dict onto zero or more normalized rows.
_ARTIFACT_GLOBS = (
    "BENCH_r[0-9]*.json",
    "BENCH_dispatch_r[0-9]*.json",
    "BENCH_loader_r[0-9]*.json",
    "SERVING_r[0-9]*.json",
    # token-level decode serving rounds (bench_serving --decode):
    # aggregate tokens/s and the continuous-vs-static speedup gate
    # higher-better; TTFT and inter-token tails gate lower-better
    "DECODE_r[0-9]*.json",
    # cluster recovery drills (docs/resilience.md §Multi-host recovery):
    # MTTR and restore traffic gate like the latency families — a
    # recovery that got 10% slower or 10% heavier is a regression
    "CLUSTER_r[0-9]*.json",
    # per-kernel Pallas selfcheck rounds (kernels_selfcheck.py): each
    # kernel's speedup-vs-XLA gates higher-better so a kernel regression
    # fails `make bench-watch` like every other family; parity_ok rows
    # only — a broken kernel is caught by the selfcheck exit code, not
    # misread as a perf row
    "KERNELS_r[0-9]*.json",
    # the MULTICHIP family: per-step collective bytes of the ZeRO-1
    # cycle.  The ledger is analytic (pure layout math, machine-
    # independent), so bytes gate exactly — a change that silently
    # re-inflates the wire fails the sentinel.  MULTICHIP_LARGE rounds
    # carry the measured dp_resnet50_multislice cycle; the GRADCOMM
    # rounds (bench_scaling --grad-comm) additionally carry the
    # int8-vs-fp32 gradient-bytes reduction (higher-better — the
    # compression must keep paying)
    "MULTICHIP_LARGE_r[0-9]*.json",
    "MULTICHIP_GRADCOMM_r[0-9]*.json",
    # declarative-layout ledger rounds (bench_scaling --layout): per-axis
    # collective bytes and per-chip param bytes of the dp vs fsdp x tp
    # layouts on the bench geometry.  Analytic (machine-independent), so
    # bytes gate exactly lower-better; the headline per-chip param-bytes
    # reduction rides the generic "metric" row higher-better — a layout-
    # table change that silently re-replicates the big tensors fails
    # bench-watch
    "MULTICHIP_LAYOUT_r[0-9]*.json",
    # SLO burn-rate alert drills (python -m bigdl_tpu.obs.slo --bench):
    # alert latency under an injected hard violation gates lower-better —
    # a PR that silently slows burn detection fails bench-watch; the
    # burn peak gates higher-better (the detector must keep seeing a
    # hard violation as a hard burn)
    "SLO_r[0-9]*.json",
    # decode fleet (bench_serving --fleet): multi-worker pool serving with
    # KV-aware routing — throughput/TTFT/inter-token gate per geometry
    # exactly as the single-host decode rows do (the tokens_per_s
    # normalize branch keys families by the row's geometry)
    "DECODE_POOL_r[0-9]*.json",
    # decode-fleet chaos drills (bench_serving --fleet --chaos): a decode
    # worker is killed mid-run under streaming load; the bench itself
    # hard-gates zero failed requests + token parity, so the committed
    # row only exists for a passing run — the sentinel trends the
    # recovery tail (lower-better) and the under-chaos throughput
    "DECODE_CHAOS_r[0-9]*.json",
    # recsys serving rounds (bench_recsys.py): the feature->recall->
    # ranking pipeline under sustained mixed-tenant load — recommend QPS
    # and recall candidate throughput gate higher-better, the recommend
    # p99 tail lower-better, geometry-scoped like every serving family.
    # The zero-unexpected-recompiles and sharded-parity gates are
    # enforced by the bench before the row is written
    "RECSYS_r[0-9]*.json",
    # quantized decode serving rounds (bench_serving --decode --quant):
    # int8 KV pages vs the f32 pool at EQUAL HBM budget.  The bench
    # hard-gates token parity and zero unexpected recompiles before the
    # row is written; the sentinel trends the slots-per-chip capacity
    # ratio and the quantized engine's tokens/s (both higher-better —
    # the memory win must keep paying and must not cost throughput)
    "DECODE_QUANT_r[0-9]*.json",
    # speculative decode rounds (bench_serving --decode --spec): the
    # weight-shared block-sparse draft + single-call verify vs the same
    # engine spec-off.  Greedy byte parity and zero unexpected
    # recompiles are hard gates inside the bench; the sentinel trends
    # the per-user token rate and the acceptance rate (both higher-
    # better — speculation must keep paying, and a draft that stops
    # agreeing with the target is a silent regression)
    "DECODE_SPEC_r[0-9]*.json",
)

# lower-is-better families (latencies, recovery time/traffic, collective
# bytes); everything else is higher-better
_LOWER_BETTER = frozenset({"serving_p50_ms", "serving_p99_ms",
                           "decode_ttft_ms_p50", "decode_ttft_ms_p99",
                           "decode_inter_token_p99_ms",
                           "cluster_mttr_s", "cluster_recovery_bytes",
                           "chaos_recovery_ms_p99",
                           "recsys_recommend_p99_ms",
                           "slo_alert_latency_s",
                           "multichip_ici_bytes_per_step",
                           "multichip_dcn_bytes_per_step",
                           "multichip_grad_sync_ici_bytes_per_step",
                           "multichip_grad_sync_dcn_bytes_per_step"})


@dataclass
class Row:
    family: str
    value: float
    direction: str
    source: str


@dataclass
class Verdict:
    family: str
    fresh: float
    baseline: float
    baseline_source: str
    direction: str
    ratio: float            # fresh / baseline
    regressed: bool
    threshold: float

    def asdict(self) -> Dict[str, Any]:
        return dict(self.__dict__)


def _good(row: Dict[str, Any]) -> bool:
    """A trustworthy committed row: parsed, no error, not flagged
    suspect.  Replayed (live=False) rows still count — they are real
    measurements preserved across a flaky tunnel."""
    return (isinstance(row, dict) and "error" not in row
            and not row.get("suspect"))


def _unwrap(doc: Any) -> Optional[Dict[str, Any]]:
    """Round artifacts are re-wrapped as {n, cmd, rc, tail, parsed} by the
    round driver — unwrap to the measurement row."""
    if not isinstance(doc, dict):
        return None
    if "parsed" in doc and not doc.get("metric"):
        doc = doc["parsed"]
    return doc if isinstance(doc, dict) else None


def normalize(doc: Any, source: str) -> List[Row]:
    """One artifact dict -> normalized rows (empty when not trustworthy)."""
    row = _unwrap(doc)
    if row is None or not _good(row):
        return []
    out: List[Row] = []

    def add(family: str, value: Any, direction: str = HIGHER) -> None:
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        if v > 0:
            out.append(Row(family, v, direction, source))

    if "metric" in row:  # bench.py / bench-dispatch rows carry their name
        add(str(row["metric"]), row.get("value"))
    if "pipeline_img_per_sec" in row:
        add("loader_pipeline_img_per_sec", row["pipeline_img_per_sec"])
    if "loader_img_per_sec" in row:
        add("loader_img_per_sec", row["loader_img_per_sec"])
    if "throughput_rps" in row:
        # captures from different load geometries are not comparable: a
        # saturated 32-client p50 includes queue wait a light 8-client
        # probe never pays.  A "geometry" tag scopes the serving families
        # to same-geometry baselines (both directions); legacy untagged
        # rows (r04/r05) keep the plain names and gate each other.
        geo = re.sub(r"[^A-Za-z0-9]+", "_",
                     str(row.get("geometry") or "")).strip("_")
        sfx = f"_{geo}" if geo else ""
        add(f"serving_throughput_rps{sfx}", row["throughput_rps"])
        add(f"serving_p50_ms{sfx}", row.get("p50_ms"), LOWER)
        add(f"serving_p99_ms{sfx}", row.get("p99_ms"), LOWER)
        # batching health: continuous assembly must keep batches FULL —
        # occupancy sliding back toward per-request predicts is the
        # regression the r05->r08 rebuild exists to prevent
        add(f"serving_avg_batch_size{sfx}", row.get("avg_batch_size"))
    if "tokens_per_s" in row:
        # DECODE_r*.json (bench_serving --decode): sustained-generation
        # geometry.  Same geometry-scoping rule as the SERVING family —
        # a saturated decode p99 is not comparable across client counts
        geo = re.sub(r"[^A-Za-z0-9]+", "_",
                     str(row.get("geometry") or "")).strip("_")
        sfx = f"_{geo}" if geo else ""
        add(f"decode_tokens_per_s{sfx}", row["tokens_per_s"])
        add(f"decode_tokens_per_s_user{sfx}", row.get("tokens_per_s_user"))
        add(f"decode_ttft_ms_p50{sfx}", row.get("ttft_ms_p50"), LOWER)
        add(f"decode_ttft_ms_p99{sfx}", row.get("ttft_ms_p99"), LOWER)
        add(f"decode_inter_token_p99_ms{sfx}",
            row.get("inter_token_p99_ms"), LOWER)
        # the reason this engine exists: continuous decode must keep
        # beating the whole-batch-restart baseline
        add(f"decode_speedup_vs_static{sfx}",
            row.get("speedup_vs_static"))
    if row.get("bench") == "decode_quant":
        # DECODE_QUANT_r*.json (bench_serving --decode --quant): int8 KV
        # pages vs f32 at equal HBM budget.  Token parity and the zero-
        # recompile sweep are hard gates inside the bench (a failing run
        # writes no row); the sentinel trends the capacity ratio and the
        # quantized throughput, both higher-better and geometry-scoped
        geo = re.sub(r"[^A-Za-z0-9]+", "_",
                     str(row.get("geometry") or "")).strip("_")
        sfx = f"_{geo}" if geo else ""
        add(f"decode_quant_slots_per_chip{sfx}",
            row.get("slots_per_chip_ratio"))
        add(f"decode_quant_tokens_per_s{sfx}",
            row.get("quant_tokens_per_s"))
    if row.get("bench") == "decode_spec":
        # DECODE_SPEC_r*.json (bench_serving --decode --spec): the
        # block-sparse draft + single-call verify vs the same engine
        # spec-off.  Byte parity, the >=1.5x speedup floor, and the
        # zero-recompile sweep are hard gates inside the bench; the
        # sentinel trends the per-user rate and the acceptance rate —
        # acceptance decaying means the draft stopped earning its keep
        # long before the speedup gate trips.  Geometry-scoped.
        geo = re.sub(r"[^A-Za-z0-9]+", "_",
                     str(row.get("geometry") or "")).strip("_")
        sfx = f"_{geo}" if geo else ""
        add(f"decode_spec_tokens_per_s_user{sfx}",
            row.get("spec_tokens_per_s_user"))
        add(f"decode_spec_accept_rate{sfx}", row.get("accept_rate"))
    if row.get("bench") == "decode_chaos":
        # DECODE_CHAOS_r*.json (bench_serving --fleet --chaos): the
        # pass/fail gates (zero failed requests, byte parity across the
        # mid-run worker kill) are enforced by the bench before the row
        # is written; here we trend what CAN regress gradually — the
        # failover recovery tail and throughput under chaos.  Geometry-
        # scoped like every serving family.
        geo = re.sub(r"[^A-Za-z0-9]+", "_",
                     str(row.get("geometry") or "")).strip("_")
        sfx = f"_{geo}" if geo else ""
        add(f"chaos_recovery_ms_p99{sfx}", row.get("recovery_ms_p99"),
            LOWER)
        add(f"chaos_tokens_per_s{sfx}", row.get("chaos_tokens_per_s"))
    if row.get("bench") == "recsys":
        # RECSYS_r*.json (bench_recsys.py): sustained mixed-tenant load
        # through the feature->recall->ranking pipeline.  The binary
        # gates (zero unexpected recompiles, sharded-vs-unsharded parity,
        # per-chip embedding shrink factor) fail the bench itself; here
        # we trend what can regress gradually.  Geometry-scoped like the
        # SERVING/DECODE families
        geo = re.sub(r"[^A-Za-z0-9]+", "_",
                     str(row.get("geometry") or "")).strip("_")
        sfx = f"_{geo}" if geo else ""
        add(f"recsys_qps{sfx}", row.get("recsys_qps"))
        add(f"recsys_recommend_p99_ms{sfx}",
            row.get("recommend_p99_ms"), LOWER)
        add(f"recsys_recall_candidates_per_s{sfx}",
            row.get("recall_candidates_per_s"))
    if "slo_alert_latency_s" in row:
        # SLO_r*.json burn-rate drills: both values are quantized to the
        # evaluation cadence / a hard injected violation, so they are
        # stable run-to-run (the bench docstring has the reasoning)
        add("slo_alert_latency_s", row["slo_alert_latency_s"], LOWER)
        add("slo_burn_peak", row.get("slo_burn_peak"))
    if "mttr_s" in row:  # CLUSTER_r*.json recovery drills
        add("cluster_mttr_s", row["mttr_s"], LOWER)
        add("cluster_recovery_bytes", row.get("recovery_bytes"), LOWER)
    if "grad_bytes_reduction_vs_fp32" in row:
        # MULTICHIP_GRADCOMM rounds (bench_scaling --grad-comm): the
        # int8-vs-fp32 compression ratio rides the generic "metric" row
        # above (higher-better — the wire must stay shrunk); the shipped
        # mode's absolute gradient bytes gate lower-better here.  All
        # are analytic ledger values — machine-independent, so exact
        add("multichip_grad_sync_ici_bytes_per_step",
            row.get("grad_sync_ici_bytes_per_step"), LOWER)
        add("multichip_grad_sync_dcn_bytes_per_step",
            row.get("grad_sync_dcn_bytes_per_step"), LOWER)
    if isinstance(row.get("layout_modes"), dict):
        # MULTICHIP_LAYOUT rounds (bench_scaling --layout): one family
        # per (layout mode, axis) plus the per-chip param-bytes meter.
        # All analytic ledger values — machine-independent, exact
        for mode, rec in sorted(row["layout_modes"].items()):
            if not isinstance(rec, dict):
                continue
            add(f"multichip_layout_{mode}_param_bytes_per_chip",
                rec.get("param_bytes_per_chip"), LOWER)
            per = rec.get("per_axis_bytes_per_step")
            if isinstance(per, dict):
                for axis, v in sorted(per.items()):
                    add(f"multichip_layout_{mode}_{axis}_bytes_per_step",
                        v, LOWER)
            add(f"multichip_layout_{mode}_tp_activation_bytes_per_step",
                rec.get("tp_activation_bytes_per_step"), LOWER)
    if isinstance(row.get("modes"), dict):
        # MULTICHIP_LARGE rounds: the measured dp_resnet50_multislice
        # ZeRO-1 cycle's per-step collective bytes (fp32 baseline ~204 MB
        # ICI + 51 MB DCN in r05) — a fresh round whose bytes regress
        # >threshold above the best committed value fails the gate
        m = row["modes"].get("dp_resnet50_multislice")
        if isinstance(m, dict):
            add("multichip_ici_bytes_per_step",
                m.get("ici_collective_bytes_per_step"), LOWER)
            add("multichip_dcn_bytes_per_step",
                m.get("dcn_collective_bytes_per_step"), LOWER)
    if "kernels" in row and isinstance(row["kernels"], dict):
        # KERNELS_r*.json: one speedup family per kernel.  Only
        # parity-clean, non-probe rows gate (probe_ entries are tiling
        # experiments, never shipped configs); amortized speedup is
        # preferred when present (single-dispatch numbers are tunnel-
        # latency bound on this fleet)
        for name, rec in sorted(row["kernels"].items()):
            if name.startswith("probe_") or not isinstance(rec, dict):
                continue
            if not rec.get("parity_ok"):
                continue
            add(f"kernel_speedup_{name}",
                rec.get("speedup_amortized", rec.get("speedup")))
    return out


def load_history(root: Optional[str] = None) -> Dict[str, List[Row]]:
    """All committed artifact rows, grouped by family."""
    root = root or os.getcwd()
    history: Dict[str, List[Row]] = {}
    for pattern in _ARTIFACT_GLOBS:
        for path in sorted(glob.glob(os.path.join(root, pattern))):
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            for row in normalize(doc, os.path.basename(path)):
                history.setdefault(row.family, []).append(row)
    return history


def baseline_for(family: str, history: Dict[str, List[Row]]
                 ) -> Optional[Row]:
    """The committed value to beat: best good row of the family (max for
    higher-better, min for lower-better) — a fresh number must not
    regress >threshold from the trajectory's best."""
    rows = history.get(family)
    if not rows:
        return None
    best = (max if rows[0].direction == HIGHER else min)(
        rows, key=lambda r: r.value)
    return best


def check_row(row: Row, history: Dict[str, List[Row]],
              threshold: float = DEFAULT_THRESHOLD) -> Optional[Verdict]:
    """Compare one fresh row against the committed trajectory.  None when
    the family has no committed history (nothing to regress from)."""
    base = baseline_for(row.family, history)
    if base is None:
        return None
    ratio = row.value / base.value
    if row.direction == HIGHER:
        regressed = ratio < 1.0 - threshold
    else:
        regressed = ratio > 1.0 + threshold
    return Verdict(family=row.family, fresh=row.value, baseline=base.value,
                   baseline_source=base.source, direction=row.direction,
                   ratio=round(ratio, 4), regressed=regressed,
                   threshold=threshold)


def check(fresh: Any, history: Dict[str, List[Row]],
          threshold: float = DEFAULT_THRESHOLD,
          source: str = "fresh") -> List[Verdict]:
    """Normalize a fresh artifact dict and check every family it carries."""
    out = []
    for row in normalize(fresh, source):
        v = check_row(row, history, threshold)
        if v is not None:
            out.append(v)
    return out


def _load_fresh(path: str) -> Optional[Dict[str, Any]]:
    """A fresh artifact: a JSON file, or bench stdout whose LAST line is
    the JSON row (the bench.py contract)."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        pass
    for line in reversed([ln for ln in text.splitlines() if ln.strip()]):
        try:
            doc = json.loads(line)
            if isinstance(doc, dict):
                return doc
        except json.JSONDecodeError:
            continue
    return None


def _smoke(history: Dict[str, List[Row]], threshold: float) -> int:
    """Prove the gate on synthetic rows: a 20% regression must be flagged,
    an on-trajectory row must pass.  Exit 0 only when both hold."""
    if not history:
        print(json.dumps({"smoke": "fail",
                          "reason": "no committed artifacts found"}))
        return 1
    failures = []
    for family, rows in sorted(history.items()):
        base = baseline_for(family, history)
        drop = 0.8 if base.direction == HIGHER else 1.25
        regressed_row = Row(family, base.value * drop, base.direction,
                            "synthetic-regressed")
        ok_row = Row(family, base.value, base.direction, "synthetic-ok")
        v_bad = check_row(regressed_row, history, threshold)
        v_ok = check_row(ok_row, history, threshold)
        if not (v_bad and v_bad.regressed):
            failures.append(f"{family}: synthetic 20% regression NOT flagged")
        if v_ok and v_ok.regressed:
            failures.append(f"{family}: on-trajectory value falsely flagged")
    verdict = {"smoke": "ok" if not failures else "fail",
               "families": len(history), "threshold": threshold,
               "failures": failures}
    print(json.dumps(verdict))
    return 0 if not failures else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bigdl_tpu.obs.sentinel",
        description="read-only perf-regression sentinel over committed "
                    "bench artifacts (docs/performance.md §Regression "
                    "sentinel)")
    ap.add_argument("fresh", nargs="*",
                    help="fresh artifact JSON files (bench.py stdout ok)")
    ap.add_argument("--root", default=None,
                    help="repo root holding the committed artifacts "
                         "(default: cwd)")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="relative regression that fails (default 0.10)")
    ap.add_argument("--smoke", action="store_true",
                    help="prove the gate on synthetic regressed rows")
    args = ap.parse_args(argv)

    # default root: the repo checkout this package sits in, falling back
    # to cwd when the package is installed outside a checkout
    repo = args.root or _find_repo_root() or os.getcwd()
    history = load_history(repo)

    if args.smoke:
        return _smoke(history, args.threshold)
    if not args.fresh:
        ap.error("need fresh artifact files (or --smoke)")
    rc = 0
    for path in args.fresh:
        doc = _load_fresh(path)
        if doc is None:
            print(json.dumps({"file": path, "error": "unparseable"}))
            rc = 1
            continue
        verdicts = check(doc, history, args.threshold,
                         source=os.path.basename(path))
        if not verdicts:
            print(json.dumps({"file": path, "checked": 0,
                              "note": "no family overlaps the committed "
                                      "trajectory"}))
            continue
        for v in verdicts:
            print(json.dumps(dict(v.asdict(), file=path)))
            if v.regressed:
                rc = 1
    return rc


def _find_repo_root() -> Optional[str]:
    """Walk up from this file looking for committed BENCH artifacts."""
    d = os.path.dirname(os.path.abspath(__file__))
    for _ in range(6):
        if glob.glob(os.path.join(d, "BENCH_r[0-9]*.json")):
            return d
        d = os.path.dirname(d)
    return None


if __name__ == "__main__":
    sys.exit(main())
