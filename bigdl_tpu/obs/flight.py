"""Crash flight recorder — the last N notable events, dumped on death.

Reference analog (unverified — mount empty): when a reference run died, the
postmortem record was whatever the Spark driver log happened to retain.
Here every notable event — injected faults, in-run retries, supervisor
recoveries, serving degradation transitions, circuit-breaker trips,
deadline drops — lands in a fixed-size ring buffer (O(1) per event, bounded
memory, always on), and the buffer is dumped as JSONL:

- explicitly (``dump()`` — tests, operator tooling),
- on SIGTERM (the TPU-VM preemption signal) via ``install()``,
- on an unhandled exception crashing the process (``sys.excepthook``
  chain), also via ``install()``.

The dump is one JSON object per line (``{"t": wall, "kind": ..., **data}``)
so ``grep``/``jq`` postmortems need no custom reader.  Recording is
process-wide by default (``record(kind, **data)`` hits the global
recorder); subsystems call it unconditionally — a ring-buffer append is
cheap enough to leave on in production, which is the entire point of a
flight recorder.
"""

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.obs")

DEFAULT_CAPACITY = 512


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 path: Optional[str] = None):
        self._events: "deque[Dict[str, Any]]" = deque(maxlen=capacity)
        # REENTRANT: the SIGTERM/crash handlers run on the main thread and
        # call record()/dump(); a plain Lock would deadlock if the signal
        # landed while the main thread was inside record()
        self._lock = threading.RLock()
        self._dumped = False
        self.installed = False  # install() was called: crash dumps armed
        self.path = path or os.path.join(
            os.getcwd(), f"flight-{os.getpid()}.jsonl")
        self.events_total = 0

    def record(self, kind: str, **data) -> None:
        evt = {"t": time.time(), "kind": kind}
        evt.update(data)
        with self._lock:
            self._events.append(evt)
            self.events_total += 1

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def dump(self, path: Optional[str] = None, reason: str = "explicit"
             ) -> str:
        """Write the ring as JSONL; returns the path.  Never raises — a
        failing dump inside a signal/crash handler must not mask the
        original death."""
        path = path or self.path
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            with self._lock:
                events = list(self._events)
            with open(path, "w") as f:
                f.write(json.dumps(
                    {"t": time.time(), "kind": "flight_dump",
                     "reason": reason, "pid": os.getpid(),
                     "events": len(events),
                     "events_total": self.events_total}) + "\n")
                snap = self._metrics_snapshot(
                    blocking=not reason.startswith("signal"))
                if snap is not None:
                    f.write(json.dumps(snap, default=str) + "\n")
                for line in _dump_source_lines():
                    f.write(json.dumps(line, default=str) + "\n")
                for evt in events:
                    f.write(json.dumps(evt, default=str) + "\n")
            self._dumped = True
            log.info("flight recorder: %d events dumped to %s (%s)",
                     len(events), path, reason)
        except Exception as e:  # noqa: BLE001 — see docstring
            log.error("flight recorder dump failed: %s", e)
        return path

    @staticmethod
    def _metrics_snapshot(blocking: bool = True) -> Optional[Dict[str, Any]]:
        """Final metric state (process-wide counters + gauges) for the
        dump, so a post-mortem carries how far the job got — not just the
        event ring.  Never raises (dump runs in crash handlers); signal
        paths pass ``blocking=False`` because the handler may have
        interrupted the frame holding the registry's non-reentrant lock —
        a blocking acquire there would hang the dump forever."""
        try:
            from bigdl_tpu.optim.metrics import global_metrics

            snap = global_metrics().snapshot(blocking=blocking)
            if snap is None:  # lock held by the interrupted frame
                return None
            return {"t": time.time(), "kind": "metrics_snapshot",
                    "counters": snap.get("counters", {}),
                    "gauges": snap.get("gauges", {})}
        except Exception:  # noqa: BLE001 — see dump() docstring
            return None

    def install(self, path: Optional[str] = None, signals=None) -> None:
        """Arm the crash/preemption dump: chain a ``sys.excepthook`` that
        dumps before the previous hook runs, and a handler for each signal
        (default SIGTERM) that dumps and then re-delivers to the previous
        handler.  Idempotent enough for tests: re-installing just layers
        another chain link."""
        import signal as _signal

        if path:
            self.path = path
        self.installed = True
        prev_hook = sys.excepthook

        def _hook(exc_type, exc, tb):
            self.record("crash", error=f"{exc_type.__name__}: {exc}")
            self.dump(reason="crash")
            prev_hook(exc_type, exc, tb)

        sys.excepthook = _hook
        # non-main threads (the serving engine loop, supervisor sweeps,
        # proxy handlers) are the recorder's main event sources and report
        # through threading.excepthook, not sys.excepthook
        prev_thook = threading.excepthook

        def _thook(args):
            self.record("thread_crash", thread=args.thread.name
                        if args.thread else None,
                        error=f"{args.exc_type.__name__}: {args.exc_value}")
            self.dump(reason="thread crash")
            prev_thook(args)

        threading.excepthook = _thook
        for sig in (signals if signals is not None else (_signal.SIGTERM,)):
            prev = _signal.getsignal(sig)

            def _on_signal(signum, frame, _prev=prev):
                self.record("signal", signum=signum)
                self.dump(reason=f"signal {signum}")
                if callable(_prev):
                    _prev(signum, frame)
                elif _prev != _signal.SIG_IGN:
                    # SIG_DFL, or None (handler owned by non-Python code —
                    # getsignal can't represent it): restore + re-raise so
                    # the dump never turns a fatal signal into a no-op
                    _signal.signal(signum, _signal.SIG_DFL)
                    _signal.raise_signal(signum)

            _signal.signal(sig, _on_signal)


# -- auxiliary dump sources (subsystem state rings) -------------------------
#
# Subsystems with their OWN bounded event state (the decode engine's
# scheduling ring: slot admissions, expiries, prefill interleave) register
# a provider; every dump writes one JSON line per live source next to the
# metrics_snapshot line.  Providers are held via weakref.WeakMethod so a
# stopped engine's ring is pruned, never pinned alive by the recorder.

_dump_sources: "Dict[str, Any]" = {}
_sources_lock = threading.Lock()


def register_dump_source(name: str, method) -> None:
    """Register a bound method returning a JSON-able dict to include in
    every flight dump (keyed by ``name``; re-registering replaces)."""
    import weakref

    with _sources_lock:
        _dump_sources[name] = weakref.WeakMethod(method)


def unregister_dump_source(name: str) -> None:
    with _sources_lock:
        _dump_sources.pop(name, None)


def _dump_source_lines() -> List[Dict[str, Any]]:
    """Evaluate live sources (dead weakrefs pruned); never raises — a
    broken provider must not mask the death being dumped."""
    with _sources_lock:
        items = list(_dump_sources.items())
    out, dead = [], []
    for name, ref in items:
        fn = ref()
        if fn is None:
            dead.append(name)
            continue
        try:
            payload = fn()
        except Exception:  # noqa: BLE001 — see docstring
            continue
        if isinstance(payload, dict):
            out.append({"t": time.time(), "kind": "dump_source",
                        "source": name, **payload})
    if dead:
        with _sources_lock:
            for name in dead:
                _dump_sources.pop(name, None)
    return out


# -- process-wide recorder (what the instrumented sites hit) ----------------

_recorder: Optional[FlightRecorder] = None
_lock = threading.Lock()


def global_recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def record(kind: str, **data) -> None:
    """The instrumented-site entry: appends to the process recorder."""
    global_recorder().record(kind, **data)


def install(path: Optional[str] = None, signals=None) -> FlightRecorder:
    """Arm the process recorder's crash/SIGTERM dump (see
    :meth:`FlightRecorder.install`)."""
    rec = global_recorder()
    rec.install(path=path, signals=signals)
    return rec


def dump_if_installed(reason: str) -> None:
    """Dump the process recorder ONLY when crash dumps were armed via
    :func:`install` — for death paths that bypass excepthook/signals/atexit
    entirely (``os._exit`` in exit-action fault injection).  Never raises."""
    rec = _recorder
    if rec is not None and rec.installed:
        rec.dump(reason=reason)
