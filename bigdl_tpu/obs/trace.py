"""Lightweight span tracer — Chrome-trace/Perfetto JSON, no dependencies.

Reference analog (unverified — mount empty): the reference's per-iteration
``Metrics`` breakdown tells you WHERE an iteration's time went on average;
it cannot correlate one serving request (or one training step) across
subsystems.  Spans do: every span has a ``span_id``, a ``parent_id`` (the
context-local current span at creation), a ``trace_id`` shared by the whole
tree, wall-clock start/duration, and free-form attributes.  Serving spans
additionally carry ``request_id`` so the enqueue→batch→predict→publish path
of one request joins across the client thread / engine thread boundary,
where parent links cannot reach (the batch loop serves many requests at
once — correlation there is by attribute, by design).

Export is the Chrome trace-event format (``{"traceEvents": [...]}``, phase
``"X"`` complete events) which Perfetto and ``chrome://tracing`` load
directly; span ids/attributes ride in ``args``.

Cost when disabled: one module-global ``None`` check per ``span()`` call
(the same posture as ``resilience.faults.fire``).  Enable programmatically
(``obs.trace.enable()``) or via ``BIGDL_TPU_TRACE=/path/out.json`` which
also registers an atexit export.
"""

import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.obs")

_current: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "bigdl_tpu_current_span", default=None)


class Span:
    """One timed region.  Use as a context manager (via ``Tracer.span`` /
    module-level ``span``); ``set_attribute`` adds attributes mid-flight
    (e.g. a request id only known after admission)."""

    __slots__ = ("name", "span_id", "parent_id", "trace_id", "start_s",
                 "end_s", "attrs", "_tracer", "_token", "_tid")

    def __init__(self, tracer: "Tracer", name: str, span_id: str,
                 parent_id: Optional[str], trace_id: str,
                 attrs: Dict[str, Any]):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.trace_id = trace_id
        self.attrs = attrs
        self.start_s = 0.0
        self.end_s = 0.0
        self._tracer = tracer
        self._token = None
        self._tid = threading.get_ident()

    def set_attribute(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        self.start_s = time.time()
        return self

    def end(self) -> "Span":
        """Finish the span NOW (idempotent; the context exit becomes a
        no-op).  For handlers whose LAST wire write is what signals
        completion to the client: ending before that write guarantees a
        reader reacting to the completion event sees the span exported,
        instead of racing the handler thread to the context exit."""
        if self.end_s:
            return self
        self.end_s = time.time()
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        self._tracer._finish(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.attrs["error"] = f"{exc_type.__name__}: {exc}"
        self.end()
        return False


class _NullSpan:
    """The disabled-tracer stand-in: every operation is a no-op."""

    __slots__ = ()

    def set_attribute(self, key, value):
        return self

    def end(self):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL = _NullSpan()


class Tracer:
    """Collects finished spans in a bounded ring (oldest evicted first —
    a long-running server must not grow without bound) and exports them
    as Chrome-trace JSON."""

    def __init__(self, max_spans: int = 20000):
        self._spans: "deque[Span]" = deque(maxlen=max_spans)
        self._lock = threading.Lock()
        self._ids = itertools.count(1)

    def _next_id(self) -> str:
        with self._lock:
            return f"{next(self._ids):x}"

    def span(self, name: str, **attrs) -> Span:
        parent = _current.get()
        sid = self._next_id()
        if parent is not None:
            return Span(self, name, sid, parent.span_id, parent.trace_id,
                        attrs)
        return Span(self, name, sid, None, sid, attrs)

    def _finish(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    def add_event(self, name: str, start_s: float, end_s: float,
                  **attrs) -> Span:
        """Append an explicitly-timed span — for call sites that time a
        region themselves (the decode engine's per-token steps span a
        jitted call shared by many requests; each request's event carries
        the same wall window with its own ``request_id``).  No
        contextvars involvement: these events correlate by attribute, not
        by parent link (docs/observability.md §Decode timelines)."""
        sid = self._next_id()
        s = Span(self, name, sid, None, sid, attrs)
        s.start_s = float(start_s)
        s.end_s = float(max(end_s, start_s))
        self._finish(s)
        return s

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def chrome_trace(self) -> Dict[str, Any]:
        """The trace-event dict (phase-X complete events, microsecond
        timestamps) Perfetto/chrome://tracing load as-is."""
        events = []
        pid = os.getpid()
        for s in self.spans():
            args = {"span_id": s.span_id, "trace_id": s.trace_id}
            if s.parent_id is not None:
                args["parent_id"] = s.parent_id
            args.update(s.attrs)
            events.append({
                "name": s.name, "cat": s.name.split("/", 1)[0], "ph": "X",
                "ts": s.start_s * 1e6,
                "dur": max(s.end_s - s.start_s, 0.0) * 1e6,
                "pid": pid, "tid": s._tid, "args": args})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            # default=str: one exotic span attribute (np scalar, enum)
            # must not lose the whole trace at the atexit export
            json.dump(self.chrome_trace(), f, default=str)
        log.info("chrome trace (%d spans) written to %s",
                 len(self._spans), path)
        return path


# -- module-level tracer (what the instrumented sites consult) --------------

_tracer: Optional[Tracer] = None
_env_checked = False
_install_lock = threading.RLock()  # enable() may be re-entered via active()
_atexit_path: Optional[str] = None
_atexit_armed = False

NULL_SPAN = _NULL  # for call sites that build span attributes lazily


def _export_at_exit() -> None:
    # one registered hook reading the CURRENT tracer/path — re-enabling
    # must not stack exporters that overwrite each other's file
    t, p = _tracer, _atexit_path
    if t is not None and p:
        t.export_chrome_trace(p)


def enable(path: Optional[str] = None, max_spans: int = 20000) -> Tracer:
    """Install a process-wide tracer.  ``path`` additionally arms a single
    atexit export (of whatever tracer is current at exit) so a traced run
    needs no explicit teardown."""
    global _tracer, _env_checked, _atexit_path, _atexit_armed
    with _install_lock:
        _tracer = Tracer(max_spans=max_spans)
        _env_checked = True
        if path and not _atexit_armed:
            import atexit

            atexit.register(_export_at_exit)
            _atexit_armed = True
        # pathless enable() clears any leftover path: this tracer was not
        # asked for a file, so exit must not overwrite an earlier run's
        _atexit_path = path
        return _tracer


def disable() -> None:
    global _tracer, _env_checked, _atexit_path
    _tracer = None
    _atexit_path = None
    _env_checked = True  # explicit disable also suppresses the env plan


def get() -> Optional[Tracer]:
    return _tracer


def active() -> Optional[Tracer]:
    """The process tracer, or None when tracing is off — after the lazy
    ``BIGDL_TPU_TRACE`` probe (done once, under a lock: concurrent first
    spans from serving threads must not each install a tracer and split
    the trace between them).  Hot call sites use this to skip building
    span attributes entirely when disabled."""
    global _env_checked
    if _tracer is None:
        if _env_checked:
            return None
        with _install_lock:
            if _tracer is None and not _env_checked:
                path = os.environ.get("BIGDL_TPU_TRACE")
                _env_checked = True
                if path:
                    enable(path)
    return _tracer


def current_span():
    """The context-local active span (None outside any span) — lets call
    sites annotate whatever region they run under without threading a
    span object through every signature."""
    return _current.get()


def span(name: str, **attrs):
    """Instrumented-site entry: near-zero cost when tracing is off (one
    None check after the lazy env probe)."""
    t = active()
    return _NULL if t is None else t.span(name, **attrs)
