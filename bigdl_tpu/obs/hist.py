"""Bounded log-bucketed latency histogram.

Reference analog (unverified — mount empty): the reference's per-iteration
``Metrics`` breakdown reports only means; serving SLOs live in the tail, so
``/metrics`` must expose p50/p95/p99 without unbounded per-sample storage.

One histogram is a fixed array of counts over exponentially-growing buckets
(bucket ``i`` covers ``[base*growth^(i-1), base*growth^i)``): O(1) observe,
O(buckets) percentile, bounded memory regardless of request volume.  With
the defaults (0.1ms base, x2 growth, 40 buckets) the range spans 0.1ms to
~15 hours with <=2x relative error — the Prometheus-native trade, and the
exporter emits these buckets verbatim as ``_bucket{le=...}`` lines.

NOT internally locked: the owner (``optim.metrics.Metrics``) already
serializes access under its registry lock; locking twice per observe on the
serving hot path would be pure overhead.
"""

import math
from typing import Dict, List, Sequence

_DEFAULT_BASE = 1e-4
_DEFAULT_GROWTH = 2.0
_DEFAULT_BUCKETS = 40


class LogHistogram:
    """Fixed-size log-bucketed histogram of non-negative samples."""

    __slots__ = ("base", "growth", "counts", "n", "sum", "min", "max",
                 "_log_growth")

    def __init__(self, base: float = _DEFAULT_BASE,
                 growth: float = _DEFAULT_GROWTH,
                 n_buckets: int = _DEFAULT_BUCKETS):
        if base <= 0 or growth <= 1:
            raise ValueError(f"need base > 0, growth > 1; got {base}, {growth}")
        self.base = base
        self.growth = growth
        self._log_growth = math.log(growth)
        # counts[0] covers [0, base); counts[-1] is the overflow bucket
        self.counts: List[int] = [0] * (n_buckets + 2)
        self.n = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def _bucket(self, v: float) -> int:
        if v < self.base:
            return 0
        i = 1 + int(math.log(v / self.base) / self._log_growth)
        return min(i, len(self.counts) - 1)

    def observe(self, v: float) -> None:
        v = float(v)
        if v != v or v < 0:
            # a negative/NaN "latency" is a clock bug upstream; clamping to
            # the underflow bucket beats corrupting every percentile after
            v = 0.0
        if v == math.inf:
            # slower-than-measurable (timeout sentinel): the OVERFLOW
            # bucket — recording it as fastest would invert every
            # percentile.  sum stays finite so the mean survives
            self.counts[-1] += 1
            self.n += 1
            self.max = math.inf
            return
        self.counts[self._bucket(v)] += 1
        self.n += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)

    def upper_bounds(self) -> List[float]:
        """Inclusive upper bound of each bucket except the +Inf overflow."""
        return [self.base * self.growth ** i
                for i in range(len(self.counts) - 1)]

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile (q in [0, 100]): the upper bound of
        the bucket holding the q-th sample, clamped to the observed max so
        a single slow request doesn't report a bound 2x above reality.

        Empty histogram: NaN — "no data" must be distinguishable from "a
        0.0s latency" (0.0 once fed a dashboard a phantom perfect p99);
        a single observation reports that observation (its bucket bound
        clamped to the observed max == the sample itself)."""
        if self.n == 0:
            return float("nan")
        rank = max(1, math.ceil(self.n * q / 100.0))
        acc = 0
        bounds = self.upper_bounds()
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank:
                bound = bounds[i] if i < len(bounds) else self.max
                return min(bound, self.max)
        return self.max

    def quantiles(self, qs: Sequence[float] = (50, 95, 99)
                  ) -> Dict[str, float]:
        return {f"p{g:g}": self.percentile(g) for g in qs}

    def snapshot(self) -> Dict[str, object]:
        """Point-in-time copy for exporters (taken under the owner's lock)."""
        return {"counts": list(self.counts), "bounds": self.upper_bounds(),
                "n": self.n, "sum": self.sum,
                "min": self.min if self.n else 0.0,
                "max": self.max if self.n else 0.0}
