"""Bounded log-bucketed latency histogram.

Reference analog (unverified — mount empty): the reference's per-iteration
``Metrics`` breakdown reports only means; serving SLOs live in the tail, so
``/metrics`` must expose p50/p95/p99 without unbounded per-sample storage.

One histogram is a fixed array of counts over exponentially-growing buckets
(bucket ``i`` covers ``[base*growth^(i-1), base*growth^i)``): O(1) observe,
O(buckets) percentile, bounded memory regardless of request volume.  With
the defaults (0.1ms base, x2 growth, 40 buckets) the range spans 0.1ms to
~15 hours with <=2x relative error — the Prometheus-native trade, and the
exporter emits these buckets verbatim as ``_bucket{le=...}`` lines.

Sliding window (docs/observability.md §SLOs & burn rates): alongside the
cumulative counts, each histogram keeps a small ring of time-sliced
sub-histograms (``window_slices`` slices of ``window_s/window_slices``
seconds each, rotated lazily on observe/read).  ``window_percentile`` /
``window_fraction_over`` answer over the trailing window only — the view
SLO burn rates need, which the cumulative buckets cannot give (a week of
good latency drowns a bad minute).  An EMPTY window returns NaN exactly
like an empty histogram: "no recent data" must never read as a perfect
recent p99.

NOT internally locked: the owner (``optim.metrics.Metrics``) already
serializes access under its registry lock; locking twice per observe on the
serving hot path would be pure overhead.
"""

import math
import time
from typing import Dict, List, Optional, Sequence, Tuple

_DEFAULT_BASE = 1e-4
_DEFAULT_GROWTH = 2.0
_DEFAULT_BUCKETS = 40
_DEFAULT_WINDOW_S = 60.0
_DEFAULT_WINDOW_SLICES = 6


def percentile_from(counts: Sequence[int], bounds: Sequence[float],
                    n: int, mx: float, q: float) -> float:
    """THE bucket-upper-bound percentile rule, over raw fields — shared
    by live histograms and consumers of ``snapshot()`` dicts (the
    cluster leader's federated quantiles), so the rule cannot fork.
    NaN on empty; the answer is the holding bucket's upper bound clamped
    to the observed max."""
    if n == 0:
        return float("nan")
    rank = max(1, math.ceil(n * q / 100.0))
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= rank:
            bound = bounds[i] if i < len(bounds) else mx
            return min(float(bound), mx)
    return mx


class LogHistogram:
    """Fixed-size log-bucketed histogram of non-negative samples."""

    __slots__ = ("base", "growth", "counts", "n", "sum", "min", "max",
                 "_log_growth", "window_s", "_slice_s", "_slices",
                 "_clock")

    def __init__(self, base: float = _DEFAULT_BASE,
                 growth: float = _DEFAULT_GROWTH,
                 n_buckets: int = _DEFAULT_BUCKETS,
                 window_s: float = _DEFAULT_WINDOW_S,
                 window_slices: int = _DEFAULT_WINDOW_SLICES,
                 clock=time.time):
        if base <= 0 or growth <= 1:
            raise ValueError(f"need base > 0, growth > 1; got {base}, {growth}")
        if window_s <= 0 or window_slices < 1:
            raise ValueError(f"need window_s > 0, window_slices >= 1; got "
                             f"{window_s}, {window_slices}")
        self.base = base
        self.growth = growth
        self._log_growth = math.log(growth)
        # counts[0] covers [0, base); counts[-1] is the overflow bucket
        self.counts: List[int] = [0] * (n_buckets + 2)
        self.n = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        # sliding-window ring: (slice_start_t, counts, n, max) per slice,
        # newest last.  Rotated lazily — no timer thread; an idle
        # histogram simply has only stale slices, which window reads drop
        self.window_s = window_s
        self._slice_s = window_s / window_slices
        self._slices: List[Tuple[float, List[int], int, float]] = []
        self._clock = clock

    def _bucket(self, v: float) -> int:
        if v < self.base:
            return 0
        i = 1 + int(math.log(v / self.base) / self._log_growth)
        return min(i, len(self.counts) - 1)

    def _rotate(self, now: float) -> None:
        """Drop slices fully outside the window; open a fresh slice when
        the newest one's span has elapsed.  Called lazily from observe
        and window reads — rotation and observation are serialized by
        the owner's lock, so a slice is never mutated after it ages out
        (the concurrent-observe regression specs pin this)."""
        cutoff = now - self.window_s
        keep = 0
        for start, _, _, _ in self._slices:
            if start + self._slice_s > cutoff:
                break
            keep += 1
        if keep:
            del self._slices[:keep]
        if not self._slices or now >= self._slices[-1][0] + self._slice_s:
            # align slice starts to the slice grid so rotation cadence is
            # independent of observation timing
            start = math.floor(now / self._slice_s) * self._slice_s
            self._slices.append((start, [0] * len(self.counts), 0,
                                 -math.inf))

    def observe(self, v: float, now: Optional[float] = None) -> None:
        v = float(v)
        if v != v or v < 0:
            # a negative/NaN "latency" is a clock bug upstream; clamping to
            # the underflow bucket beats corrupting every percentile after
            v = 0.0
        now = self._clock() if now is None else now
        self._rotate(now)
        start, counts, n, mx = self._slices[-1]
        if v == math.inf:
            # slower-than-measurable (timeout sentinel): the OVERFLOW
            # bucket — recording it as fastest would invert every
            # percentile.  sum stays finite so the mean survives
            self.counts[-1] += 1
            self.n += 1
            self.max = math.inf
            counts[-1] += 1
            self._slices[-1] = (start, counts, n + 1, math.inf)
            return
        b = self._bucket(v)
        self.counts[b] += 1
        self.n += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        counts[b] += 1
        self._slices[-1] = (start, counts, n + 1, max(mx, v))

    def upper_bounds(self) -> List[float]:
        """Inclusive upper bound of each bucket except the +Inf overflow."""
        return [self.base * self.growth ** i
                for i in range(len(self.counts) - 1)]

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile (q in [0, 100]): the upper bound of
        the bucket holding the q-th sample, clamped to the observed max so
        a single slow request doesn't report a bound 2x above reality.

        Empty histogram: NaN — "no data" must be distinguishable from "a
        0.0s latency" (0.0 once fed a dashboard a phantom perfect p99);
        a single observation reports that observation (its bucket bound
        clamped to the observed max == the sample itself)."""
        return self._percentile_of(self.counts, self.n, self.max, q)

    def _percentile_of(self, counts: List[int], n: int, mx: float,
                       q: float) -> float:
        return percentile_from(counts, self.upper_bounds(), n, mx, q)

    def quantiles(self, qs: Sequence[float] = (50, 95, 99)
                  ) -> Dict[str, float]:
        return {f"p{g:g}": self.percentile(g) for g in qs}

    # -- sliding-window reads (the SLO burn-rate view) ----------------------
    def _window_merge(self, now: Optional[float],
                      window_s: Optional[float]
                      ) -> Tuple[List[int], int, float]:
        """Merged (counts, n, max) over slices inside the trailing
        ``window_s`` (capped at the histogram's own window).  Rotates
        first, so an idle histogram's stale slices never leak in."""
        now = self._clock() if now is None else now
        w = self.window_s if window_s is None \
            else min(window_s, self.window_s)
        self._rotate(now)
        counts = [0] * len(self.counts)
        n, mx = 0, -math.inf
        cutoff = now - w
        for start, c, sn, smx in self._slices:
            # a slice counts when any part of its span is in the window
            if start + self._slice_s <= cutoff or sn == 0:
                continue
            for i, v in enumerate(c):
                counts[i] += v
            n += sn
            mx = max(mx, smx)
        return counts, n, mx

    def window_count(self, now: Optional[float] = None,
                     window_s: Optional[float] = None) -> int:
        return self._window_merge(now, window_s)[1]

    def window_percentile(self, q: float, now: Optional[float] = None,
                          window_s: Optional[float] = None) -> float:
        """q-th percentile over the trailing window only.  An empty
        WINDOW returns NaN even when the cumulative histogram has data —
        same contract as an empty histogram (no recent data is not a
        0.0s recent latency)."""
        counts, n, mx = self._window_merge(now, window_s)
        return self._percentile_of(counts, n, mx, q)

    def window_fraction_over(self, threshold: float,
                             now: Optional[float] = None,
                             window_s: Optional[float] = None) -> float:
        """Fraction of window samples above ``threshold`` — the bad-event
        ratio SLO burn rates divide by the error budget.  Counted at
        bucket granularity: a sample is 'over' when its whole bucket lies
        above the threshold (lower bound >= threshold), so the answer is
        conservative by at most one bucket (<=2x at the default growth,
        exact when the threshold sits on a bucket boundary).  NaN on an
        empty window."""
        counts, n, mx = self._window_merge(now, window_s)
        if n == 0:
            return float("nan")
        bounds = self.upper_bounds()
        over = 0
        for i, c in enumerate(counts):
            lower = 0.0 if i == 0 else bounds[i - 1]
            if lower >= threshold:
                over += c
        return over / n

    def snapshot(self) -> Dict[str, object]:
        """Point-in-time copy for exporters (taken under the owner's lock)."""
        return {"counts": list(self.counts), "bounds": self.upper_bounds(),
                "n": self.n, "sum": self.sum,
                "min": self.min if self.n else 0.0,
                "max": self.max if self.n else 0.0}
