"""Block-sparse matmul + BlockSparseLinear + magnitude block pruning.

Reference analog: none — the reference has no sparse compute path at all.
This is the BLaST-style block-sparse FFN (PAPERS.md: arXiv 2507.03117)
for transformer pretraining and inference: weights are pruned in
``(block_k, block_n)`` tiles after a dense warmup, and the forward matmul
SKIPS pruned blocks entirely instead of multiplying by zeros.

Kernel: ``x (M,K) @ (W ⊙ mask) (K,N)`` with a host-side block mask of
shape ``(ceil(K/bk), ceil(N/bn))``.  The grid is ``(M/bm, N/bn,
max_nnz_per_column)`` and a scalar-prefetched per-column index map
(``pltpu.PrefetchScalarGridSpec``) walks ONLY the nonzero k-blocks of
each output column — compute and k/v HBM traffic scale with the nonzero
block count, not with K.  Columns with fewer nonzero blocks than the
widest column idle via ``pl.when`` on the prefetched per-column count.
``interpret=True`` runs the identical code path on CPU, so tier-1
exercises the real kernel.

The mask is STATIC per compiled program (a hashable host array): pruning
events between training segments retrace — the BLaST schedule prunes a
handful of times per run, and each new mask announces itself via
``obs.attr.expected_compile`` so the recompile sentinel stays quiet.

Backward: ``dx`` reuses the block-sparse kernel on the transposed
problem (same skipping, mask transposed); ``dw`` is a dense XLA matmul
masked on the way out (weight-grad sparsity is future work — it needs an
output-block-skipping variant).
"""

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.nn.layers import Linear
from bigdl_tpu.nn.module import EMPTY, Module
from bigdl_tpu.ops.common import cdiv, default_interpret, round_up
from bigdl_tpu.utils.log import get_logger

log = get_logger(__name__)


class StaticMask:
    """Hashable wrapper around a host bool block mask so it can ride as a
    ``custom_vjp`` nondiff / jit-static argument: two masks with equal
    bytes hash equal, so retraces happen exactly when the mask changes."""

    __slots__ = ("arr", "_hash")

    def __init__(self, arr):
        self.arr = np.ascontiguousarray(np.asarray(arr, bool))
        self._hash = hash((self.arr.shape, self.arr.tobytes()))

    @property
    def shape(self):
        return self.arr.shape

    def density(self) -> float:
        return float(self.arr.mean()) if self.arr.size else 1.0

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return (isinstance(other, StaticMask)
                and self.arr.shape == other.arr.shape
                and bool(np.array_equal(self.arr, other.arr)))

    def __repr__(self):
        return (f"StaticMask({self.arr.shape}, "
                f"density={self.density():.3f})")


def expand_mask(mask, k: int, n: int, block_k: int,
                block_n: int) -> np.ndarray:
    """Block mask -> elementwise (k, n) mask (the dense-reference view)."""
    arr = mask.arr if isinstance(mask, StaticMask) else np.asarray(mask,
                                                                   bool)
    full = np.repeat(np.repeat(arr, block_k, 0), block_n, 1)
    return full[:k, :n]


def _column_plan(arr: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per output-column-block: nonzero k-block count + padded index list.
    Padding indices point at block 0 but never execute (``pl.when`` on the
    count)."""
    nkb, nnb = arr.shape
    counts = arr.sum(0).astype(np.int32)
    maxc = max(1, int(counts.max()) if counts.size else 1)
    idx = np.zeros((nnb, maxc), np.int32)
    for j in range(nnb):
        nz = np.nonzero(arr[:, j])[0]
        idx[j, : len(nz)] = nz
    return counts, idx


def _bs_kernel(counts_ref, idx_ref, x_ref, w_ref, o_ref):
    j = pl.program_id(1)
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    @pl.when(t < counts_ref[j])
    def _step():
        o_ref[:] += jax.lax.dot_general(
            x_ref[:].astype(jnp.float32), w_ref[:].astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


def _bs_matmul_raw(x, w, smask: StaticMask, block_m: int, block_k: int,
                   block_n: int, interpret: bool):
    """The kernel proper: x (M,K) @ (w ⊙ mask) (K,N) -> f32 (M,N)."""
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    nkb, nnb = smask.shape
    if (nkb, nnb) != (cdiv(k, block_k), cdiv(n, block_n)):
        raise ValueError(
            f"mask {smask.shape} does not tile ({k}, {n}) in "
            f"({block_k}, {block_n}) blocks: want "
            f"({cdiv(k, block_k)}, {cdiv(n, block_n)})")
    bm = min(block_m, round_up(m, 8))
    mp = round_up(m, bm)
    kp, np_ = nkb * block_k, nnb * block_n
    xp = jnp.pad(x, ((0, mp - m), (0, kp - k)))
    wp = jnp.pad(w, ((0, kp - k), (0, np_ - n)))
    counts, idx = _column_plan(smask.arr)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(mp // bm, nnb, idx.shape[1]),
        in_specs=[
            pl.BlockSpec((bm, block_k),
                         lambda i, j, t, counts, idx: (i, idx[j, t])),
            pl.BlockSpec((block_k, block_n),
                         lambda i, j, t, counts, idx: (idx[j, t], j)),
        ],
        out_specs=pl.BlockSpec((bm, block_n),
                               lambda i, j, t, counts, idx: (i, j)),
    )
    out = pl.pallas_call(
        _bs_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        interpret=interpret,
    )(jnp.asarray(counts), jnp.asarray(idx), xp, wp)
    return out[:m, :n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _bsmm(x, w, smask, block_m, block_k, block_n, interpret):
    out = _bs_matmul_raw(x, w, smask, block_m, block_k, block_n, interpret)
    return out.astype(x.dtype)


def _bsmm_fwd(x, w, smask, block_m, block_k, block_n, interpret):
    out = _bs_matmul_raw(x, w, smask, block_m, block_k, block_n, interpret)
    return out.astype(x.dtype), (x, w)


def _bsmm_bwd(smask, block_m, block_k, block_n, interpret, res, g):
    x, w = res
    k, n = w.shape
    # dx = g @ (w ⊙ mask)ᵀ — the transposed problem keeps the SAME block
    # skipping (mask transposed, block shape swapped)
    tmask = StaticMask(smask.arr.T)
    dx = _bs_matmul_raw(g.astype(jnp.float32), w.T.astype(jnp.float32),
                        tmask, block_m, block_n, block_k, interpret)
    # dw = (xᵀ g) ⊙ mask — dense XLA matmul, masked on the way out
    dw = jnp.matmul(x.T.astype(jnp.float32), g.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    em = jnp.asarray(expand_mask(smask, k, n, block_k, block_n))
    dw = jnp.where(em, dw, 0.0)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_bsmm.defvjp(_bsmm_fwd, _bsmm_bwd)


def block_sparse_matmul(x, w, mask, *, block_k: int, block_n: int,
                        block_m: Optional[int] = None,
                        interpret: Optional[bool] = None):
    """``x (…, K) @ (w ⊙ mask) (K, N)`` skipping pruned weight blocks.

    ``mask`` is a HOST bool array ``(ceil(K/block_k), ceil(N/block_n))``
    (or a :class:`StaticMask`) — it must be concrete; a traced mask cannot
    drive the static index maps.  Differentiable (see module docstring for
    the backward split).  ``block_m=None`` consults the autotune cache
    (docs/performance.md §Kernel autotuning); explicit wins."""
    if isinstance(mask, jax.core.Tracer):
        raise TypeError(
            "block_sparse_matmul needs a concrete (host) block mask — the "
            "sparsity pattern is static per compiled program")
    smask = mask if isinstance(mask, StaticMask) else StaticMask(mask)
    lead = x.shape[:-1]
    k = x.shape[-1]
    x2 = x.reshape(-1, k)
    if block_m is None:
        from bigdl_tpu.ops import autotune

        shape_key = autotune.block_sparse_key(
            x2.shape[0], k, w.shape[1], block_k, block_n, x.dtype)
        online = ((int(x2.shape[0]), k, int(w.shape[1]), block_k,
                   block_n, x.dtype.name)
                  if autotune.is_concrete(x, w) else None)
        block_m = autotune.resolve("block_sparse_matmul", shape_key,
                                   online_shape=online)["block_m"]
    out = _bsmm(x2, w, smask, int(block_m), int(block_k), int(block_n),
                default_interpret(interpret))
    return out.reshape(*lead, w.shape[1])


# ---------------------------------------------------------------------------
# BlockSparseLinear module
# ---------------------------------------------------------------------------

class BlockSparseLinear(Linear):
    """Drop-in :class:`~bigdl_tpu.nn.layers.Linear` with a block-prunable
    weight (init/lazy-shape/bias semantics inherited).  Starts DENSE
    (all-ones mask = plain Linear forward, so the warmup phase pays
    nothing); after :meth:`set_mask` / :func:`prune_model_to_sparsity`
    the forward routes through the block-sparse Pallas kernel.

    The mask lives on the MODULE (host numpy), not in the params pytree —
    it is a static compile-time structure, not a trained tensor.  The
    Optimizer's checkpoint path persists masks automatically (driver
    state) and restores them on resume; for custom checkpointing use
    :func:`collect_masks` / :func:`apply_masks`."""

    def __init__(self, in_features: Optional[int] = None,
                 out_features: int = 0,
                 block_shape: Tuple[int, int] = (64, 64),
                 with_bias: bool = True, target_sparsity: float = 0.0,
                 use_kernel: bool = True, name=None, **linear_kwargs):
        super().__init__(in_features, out_features, with_bias=with_bias,
                         name=name, **linear_kwargs)
        self.block_shape = (int(block_shape[0]), int(block_shape[1]))
        # the pruning schedule's end state; the schedule/prune helpers
        # read it, the layer itself only ever applies self.mask
        self.target_sparsity = float(target_sparsity)
        # use_kernel=False routes a pruned mask through a masked DENSE
        # matmul instead of the Pallas kernel: identical math (the mask
        # zeroes the same blocks), no Pallas dispatch — the right trade
        # for the tiny hidden sizes of a speculative draft model on CPU,
        # where a grid launch per FFN costs more than the skipped FLOPs
        self.use_kernel = bool(use_kernel)
        self.mask: Optional[np.ndarray] = None

    def build(self, rng, x):
        params, state = super().build(rng, x)
        fan_in = int(params["weight"].shape[0])
        self.in_features = fan_in
        bk, bn = self.block_shape
        if self.mask is None:
            self.mask = np.ones((cdiv(fan_in, bk),
                                 cdiv(self.out_features, bn)), bool)
        return params, state

    # -- mask management ----------------------------------------------------
    def set_mask(self, mask) -> None:
        arr = np.asarray(mask, bool)
        bk, bn = self.block_shape
        want = (cdiv(self.in_features or arr.shape[0] * bk, bk),
                cdiv(self.out_features, bn))
        if self.in_features is not None and arr.shape != want:
            raise ValueError(f"mask {arr.shape} != expected {want}")
        self.mask = arr

    def density(self) -> float:
        return float(self.mask.mean()) if self.mask is not None else 1.0

    def sparsity(self) -> float:
        return 1.0 - self.density()

    def prune_to(self, params: Dict[str, Any], sparsity: float) -> float:
        """Magnitude block pruning: keep the highest-L1 weight blocks so
        that ``1 - sparsity`` of ALL blocks survive.  Monotone — only
        currently-kept blocks are candidates, so a pruned block never
        resurrects (the BLaST schedule's invariant).  Returns the achieved
        sparsity."""
        if self.mask is None:
            raise RuntimeError("prune_to before build/init")
        bk, bn = self.block_shape
        w = np.asarray(jax.device_get(params["weight"]), np.float32)
        k, n = w.shape
        nkb, nnb = self.mask.shape
        wp = np.zeros((nkb * bk, nnb * bn), np.float32)
        wp[:k, :n] = np.abs(w)
        scores = wp.reshape(nkb, bk, nnb, bn).sum(axis=(1, 3))
        total = self.mask.size
        n_keep = max(1, int(round((1.0 - float(sparsity)) * total)))
        kept = int(self.mask.sum())
        if n_keep >= kept:
            return self.sparsity()  # already at or past this level
        flat = np.where(self.mask.ravel(), scores.ravel(), -np.inf)
        order = np.argsort(flat)[::-1]
        new = np.zeros(total, bool)
        new[order[:n_keep]] = True
        self.mask = new.reshape(self.mask.shape)
        return self.sparsity()

    def forward(self, params, state, x, training=False, rng=None):
        if self.mask is None or bool(self.mask.all()):
            # dense warmup: exactly Linear (math AND speed)
            return super().forward(params, state, x, training=training,
                                   rng=rng)
        from bigdl_tpu.tensor.policy import cast_compute

        xc, wc = cast_compute(x, params["weight"])
        if self.use_kernel:
            y = block_sparse_matmul(
                xc, wc, self.mask, block_k=self.block_shape[0],
                block_n=self.block_shape[1]).astype(jnp.float32)
        else:
            k, n = int(wc.shape[0]), int(wc.shape[1])
            em = jnp.asarray(expand_mask(self.mask, k, n,
                                         self.block_shape[0],
                                         self.block_shape[1]))
            y = jnp.matmul(xc.astype(jnp.float32),
                           jnp.where(em, wc.astype(jnp.float32), 0.0),
                           preferred_element_type=jnp.float32)
        if self.with_bias:
            y = y + params["bias"]
        return y.astype(x.dtype), EMPTY


# ---------------------------------------------------------------------------
# model-level pruning helpers + schedule
# ---------------------------------------------------------------------------

def iter_sparse_modules(model):
    """Every :class:`BlockSparseLinear` in a module tree (depth-first,
    cycle-safe), as ``(path, module)`` pairs."""
    seen = set()

    def walk(mod, path):
        if id(mod) in seen or not isinstance(mod, Module):
            return
        seen.add(id(mod))
        if isinstance(mod, BlockSparseLinear):
            yield path, mod
        for k, v in vars(mod).items():
            children = v if isinstance(v, (list, tuple)) else [v]
            for i, c in enumerate(children):
                if isinstance(c, Module):
                    sub = f"{path}.{k}" if path else k
                    if isinstance(v, (list, tuple)):
                        sub = f"{sub}[{i}]"
                    yield from walk(c, sub)

    yield from walk(model, "")


def _capture_params(model, variables, sample_inputs) -> Dict[int, Any]:
    """EXACT module → params binding: every BlockSparseLinear's forward
    is wrapped to record the params dict it receives, then one real
    forward on the sample batch runs.  Container-layout agnostic (works
    for Sequential keys, keras graph nodes, Transformer's literal dict
    keys alike) — the captured dicts ARE the sub-dicts of ``variables``,
    passed down by reference."""
    captured: Dict[int, Any] = {}
    patched = []

    def _wrap(mod, orig):
        def fwd(params, state, *xs, **kw):
            captured[id(mod)] = params
            return orig(params, state, *xs, **kw)

        return fwd

    try:
        for _, m in iter_sparse_modules(model):
            m.forward = _wrap(m, m.forward)
            patched.append(m)
        model.apply(variables, *sample_inputs)
    finally:
        for m in patched:
            m.__dict__.pop("forward", None)
    return captured


def _params_by_tree_order(variables_params):
    """Fallback binding (no sample inputs): every {"weight": 2-D[,
    "bias"]} leaf dict in depth-first pytree order.  nn/ containers key
    params by child name so this order matches module iteration order for
    the stock layouts; a custom container interleaving a SAME-shaped
    dense Linear ahead of a sparse layer can fool it — pass
    ``sample_inputs`` for the exact capture-based binding instead."""
    found = []

    def walk(node):
        if isinstance(node, dict):
            if set(node) <= {"weight", "bias"} \
                    and getattr(node.get("weight"), "ndim", 0) == 2:
                found.append(node)
            else:
                for v in node.values():
                    walk(v)

    walk(variables_params)
    return found


def prune_model_to_sparsity(model, variables, sparsity: float,
                            sample_inputs: Optional[tuple] = None
                            ) -> Dict[str, float]:
    """One pruning EVENT: every :class:`BlockSparseLinear` whose
    ``target_sparsity`` allows it prunes to ``min(sparsity, target)`` by
    block magnitude.  Mutates module masks (host state); the caller is
    responsible for rebuilding/retracing its compiled step — wrap that
    rebuild in ``obs.attr.expected_compile()`` so the recompile sentinel
    stays quiet.  Returns ``{path: achieved_sparsity}``.

    ``sample_inputs`` (a tuple of sample batch arrays for
    ``model.apply``) enables the EXACT module→params binding via one
    forward pass; without it a tree-order shape-matching heuristic binds
    weights (correct for all stock nn/ layouts, see
    :func:`_params_by_tree_order`)."""
    out: Dict[str, float] = {}
    sparse = list(iter_sparse_modules(model))
    if not sparse:
        return out
    if sample_inputs is not None:
        captured = _capture_params(model, variables, tuple(sample_inputs))
        for path, mod in sparse:
            params = captured.get(id(mod))
            if params is None:
                log.warning("prune: %s never ran in the sample forward; "
                            "skipped", path)
                continue
            goal = min(float(sparsity),
                       mod.target_sparsity or float(sparsity))
            out[path] = mod.prune_to(params, goal)
        return out
    mats = _params_by_tree_order(variables.get("params", variables))
    used: set = set()
    for path, mod in sparse:
        want = ((mod.in_features, mod.out_features)
                if mod.in_features else None)
        params = None
        for i, cand in enumerate(mats):
            if i in used:
                continue
            shape = tuple(int(d) for d in cand["weight"].shape)
            if want is None or shape == want:
                params = cand
                used.add(i)
                break
        if params is None:
            log.warning("prune: no params found for %s; skipped (pass "
                        "sample_inputs for exact binding)", path)
            continue
        goal = min(float(sparsity), mod.target_sparsity or float(sparsity))
        out[path] = mod.prune_to(params, goal)
    return out


def derive_draft_masks(model, params, sparsity: float) -> Dict[str, float]:
    """Derive block masks for a SPECULATIVE DRAFT twin from a SERVED
    checkpoint (docs/serving.md §Speculative decoding): ``model`` is a
    freshly-constructed sparse twin of the target architecture (its
    :class:`BlockSparseLinear` layers carry ctor-known shapes but have
    never been built, so their masks are ``None``); ``params`` is the
    target's trained ``variables["params"]`` tree, which the twin
    consumes verbatim — weight sharing is the whole point.  Seeds every
    sparse layer with the all-ones mask its ``build`` would create, then
    runs one magnitude-pruning event to ``sparsity``.  Returns
    ``{path: achieved_sparsity}``."""
    for path, mod in iter_sparse_modules(model):
        if mod.mask is not None:
            continue
        if not mod.in_features or not mod.out_features:
            raise ValueError(
                f"derive_draft_masks: {path or 'layer'} has no ctor "
                "shapes — construct the draft twin with explicit "
                "in/out features (PositionwiseFFN does)")
        bk, bn = mod.block_shape
        mod.mask = np.ones((cdiv(mod.in_features, bk),
                            cdiv(mod.out_features, bn)), bool)
    return prune_model_to_sparsity(model, {"params": params},
                                   float(sparsity))


def collect_masks(model) -> Dict[str, Any]:
    """Serializable ``{path: mask-as-list}`` snapshot (checkpoint
    sidecar)."""
    return {path: mod.mask.tolist()
            for path, mod in iter_sparse_modules(model)
            if mod.mask is not None}


def apply_masks(model, masks: Dict[str, Any]) -> int:
    """Restore masks captured by :func:`collect_masks`.  Returns how many
    modules matched."""
    n = 0
    for path, mod in iter_sparse_modules(model):
        if path in masks:
            mod.set_mask(np.asarray(masks[path], bool))
            n += 1
    return n


class BlockPruningSchedule:
    """BLaST-style dense-warmup → gradual magnitude pruning.

    ``sparsity_at(step)`` is 0 through ``warmup_steps``, then ramps to
    ``target_sparsity`` over ``ramp_steps`` in ``n_events`` equal jumps
    (cubic ramp, the gradual-pruning standard: early events prune gently
    while the network can still heal).  Monotone non-decreasing by
    construction.  ``prune_steps()`` lists the exact steps where the mask
    changes — the driver/bench retraces only there."""

    def __init__(self, target_sparsity: float, warmup_steps: int,
                 ramp_steps: int, n_events: int = 4):
        if not 0.0 <= target_sparsity < 1.0:
            raise ValueError(f"target_sparsity {target_sparsity}: [0, 1)")
        if warmup_steps < 0 or ramp_steps < 0 or n_events < 1:
            raise ValueError("warmup/ramp steps >= 0, n_events >= 1")
        self.target_sparsity = float(target_sparsity)
        self.warmup_steps = int(warmup_steps)
        self.ramp_steps = int(ramp_steps)
        self.n_events = int(n_events)

    def _ramp(self, frac: float) -> float:
        # cubic: s(t) = target * (1 - (1 - t)^3)
        frac = min(max(frac, 0.0), 1.0)
        return self.target_sparsity * (1.0 - (1.0 - frac) ** 3)

    def sparsity_at(self, step: int) -> float:
        if step < self.warmup_steps or self.target_sparsity == 0.0:
            return 0.0
        if self.ramp_steps == 0:
            return self.target_sparsity
        # quantized to n_events jumps so masks change at a handful of
        # announced steps, not every step
        frac = (step - self.warmup_steps) / self.ramp_steps
        event = min(self.n_events, int(np.floor(frac * self.n_events)) + 1)
        return self._ramp(event / self.n_events)

    def prune_steps(self):
        """Exactly the steps where ``sparsity_at`` increases."""
        if self.target_sparsity == 0.0:
            return []
        if self.ramp_steps == 0:
            return [self.warmup_steps]
        steps, prev = [], 0.0
        for s in range(self.warmup_steps,
                       self.warmup_steps + self.ramp_steps + 1):
            cur = self.sparsity_at(s)
            if cur > prev:
                steps.append(s)
                prev = cur
        return steps
