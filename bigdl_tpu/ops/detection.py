"""Detection ops — TPU-first building blocks for the MaskRCNN family.

Reference analog (unverified — mount empty): ``dllib/models/maskrcnn/`` and
the vision heads under ``dllib/nn`` (Anchor, BboxUtil, Nms, Pooler/RoiAlign,
RegionProposal in the upstream 2.x layout).  The reference implements these
with dynamic-length JVM loops; here every op is **static-shape** so the whole
detector jits onto the MXU: NMS is a fixed-iteration ``fori_loop`` returning
padded indices + validity mask, RoIAlign samples a fixed grid per box, and
"select top-k then pad" replaces data-dependent filtering.

Boxes are ``(y1, x1, y2, x2)`` in image coordinates throughout (row-major,
NHWC-friendly).
"""

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# anchors
# ---------------------------------------------------------------------------


def generate_anchors(feat_sizes: Sequence[Tuple[int, int]],
                     strides: Sequence[int],
                     sizes: Sequence[float],
                     ratios: Sequence[float] = (0.5, 1.0, 2.0)) -> np.ndarray:
    """Multi-level anchor grid.  ``feat_sizes[i]`` is the (H, W) of pyramid
    level i with stride ``strides[i]`` and base anchor area ``sizes[i]**2``;
    each cell gets ``len(ratios)`` anchors.  Returns (sum_i H_i*W_i*R, 4)
    float32 (y1, x1, y2, x2) — a host-side constant baked into the jitted
    program (anchors depend only on static shapes)."""
    out = []
    for (fh, fw), stride, size in zip(feat_sizes, strides, sizes):
        ys = (np.arange(fh) + 0.5) * stride
        xs = (np.arange(fw) + 0.5) * stride
        cy, cx = np.meshgrid(ys, xs, indexing="ij")
        boxes = []
        for r in ratios:
            h = size * np.sqrt(r)
            w = size / np.sqrt(r)
            boxes.append(np.stack([cy - h / 2, cx - w / 2,
                                   cy + h / 2, cx + w / 2], axis=-1))
        # (fh, fw, R, 4) -> (fh*fw*R, 4)
        lv = np.stack(boxes, axis=2).reshape(-1, 4)
        out.append(lv)
    return np.concatenate(out, axis=0).astype(np.float32)


# ---------------------------------------------------------------------------
# box utilities
# ---------------------------------------------------------------------------


def box_area(boxes):
    return ((boxes[..., 2] - boxes[..., 0]).clip(0)
            * (boxes[..., 3] - boxes[..., 1]).clip(0))


def box_iou(a, b):
    """IoU matrix: a (Na,4), b (Nb,4) -> (Na,Nb)."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = (rb - lt).clip(0)
    inter = wh[..., 0] * wh[..., 1]
    union = box_area(a)[:, None] + box_area(b)[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


BBOX_XFORM_CLIP = float(np.log(1000.0 / 16))


def encode_boxes(boxes, anchors, weights=(1.0, 1.0, 1.0, 1.0)):
    """Faster-RCNN deltas (ty, tx, th, tw) of ``boxes`` w.r.t. ``anchors``."""
    ah = anchors[..., 2] - anchors[..., 0]
    aw = anchors[..., 3] - anchors[..., 1]
    acy = anchors[..., 0] + 0.5 * ah
    acx = anchors[..., 1] + 0.5 * aw
    bh = boxes[..., 2] - boxes[..., 0]
    bw = boxes[..., 3] - boxes[..., 1]
    bcy = boxes[..., 0] + 0.5 * bh
    bcx = boxes[..., 1] + 0.5 * bw
    wy, wx, wh, ww = weights
    return jnp.stack([
        wy * (bcy - acy) / jnp.maximum(ah, 1e-6),
        wx * (bcx - acx) / jnp.maximum(aw, 1e-6),
        wh * jnp.log(jnp.maximum(bh, 1e-6) / jnp.maximum(ah, 1e-6)),
        ww * jnp.log(jnp.maximum(bw, 1e-6) / jnp.maximum(aw, 1e-6)),
    ], axis=-1)


def decode_boxes(deltas, anchors, weights=(1.0, 1.0, 1.0, 1.0)):
    """Inverse of :func:`encode_boxes` with the standard exp clip."""
    ah = anchors[..., 2] - anchors[..., 0]
    aw = anchors[..., 3] - anchors[..., 1]
    acy = anchors[..., 0] + 0.5 * ah
    acx = anchors[..., 1] + 0.5 * aw
    wy, wx, wh, ww = weights
    ty = deltas[..., 0] / wy
    tx = deltas[..., 1] / wx
    th = jnp.minimum(deltas[..., 2] / wh, BBOX_XFORM_CLIP)
    tw = jnp.minimum(deltas[..., 3] / ww, BBOX_XFORM_CLIP)
    cy = ty * ah + acy
    cx = tx * aw + acx
    h = jnp.exp(th) * ah
    w = jnp.exp(tw) * aw
    return jnp.stack([cy - 0.5 * h, cx - 0.5 * w,
                      cy + 0.5 * h, cx + 0.5 * w], axis=-1)


def clip_boxes(boxes, height, width):
    y1 = boxes[..., 0].clip(0, height)
    x1 = boxes[..., 1].clip(0, width)
    y2 = boxes[..., 2].clip(0, height)
    x2 = boxes[..., 3].clip(0, width)
    return jnp.stack([y1, x1, y2, x2], axis=-1)


# ---------------------------------------------------------------------------
# NMS — static shape
# ---------------------------------------------------------------------------


def nms_padded(boxes, scores, iou_threshold: float, max_out: int):
    """Greedy NMS with static output size.

    Returns ``(indices (max_out,), valid (max_out,) bool)``: indices into
    ``boxes`` of the kept boxes in descending score order, padded with 0
    where invalid.  Implemented as ``max_out`` fixed iterations of
    select-best-then-suppress — O(max_out * N), fully jittable."""
    n = boxes.shape[0]
    iou = box_iou(boxes, boxes)

    def body(i, carry):
        alive, out_idx, out_valid = carry
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        ok = masked[best] > -jnp.inf
        out_idx = out_idx.at[i].set(jnp.where(ok, best, 0))
        out_valid = out_valid.at[i].set(ok)
        suppress = iou[best] > iou_threshold
        alive = alive & ~suppress & ~(jnp.arange(n) == best)
        alive = alive & ok  # once exhausted, stay exhausted
        return alive, out_idx, out_valid

    alive0 = jnp.ones((n,), bool)
    idx0 = jnp.zeros((max_out,), jnp.int32)
    val0 = jnp.zeros((max_out,), bool)
    _, idx, valid = jax.lax.fori_loop(0, max_out, body, (alive0, idx0, val0))
    return idx, valid


def class_aware_nms(boxes, scores, classes, iou_threshold: float,
                    max_out: int, coord_span: float = 1e4):
    """Per-class NMS in one call: shift each class's boxes to a disjoint
    coordinate island so cross-class pairs never overlap (the standard
    batched-NMS trick), then run :func:`nms_padded`."""
    offset = classes.astype(boxes.dtype)[:, None] * coord_span
    return nms_padded(boxes + offset, scores, iou_threshold, max_out)


# ---------------------------------------------------------------------------
# RoIAlign — static grid bilinear sampling
# ---------------------------------------------------------------------------


def _bilinear(feat, y, x):
    """Sample feat (H, W, C) at fractional (y, x) grids of shape (S, S).
    Coordinates in (-1, 0) are clamped to 0 before the weights are computed
    (torchvision ``roi_align`` border semantics); samples fully outside
    [-1, H]x[-1, W] contribute 0."""
    h, w, _ = feat.shape
    oob = (y < -1) | (y > h) | (x < -1) | (x > w)
    y = y.clip(0, None)
    x = x.clip(0, None)
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy1 = y - y0
    wx1 = x - x0
    y0i = y0.astype(jnp.int32).clip(0, h - 1)
    x0i = x0.astype(jnp.int32).clip(0, w - 1)
    y1i = (y0i + 1).clip(0, h - 1)
    x1i = (x0i + 1).clip(0, w - 1)
    v00 = feat[y0i, x0i]
    v01 = feat[y0i, x1i]
    v10 = feat[y1i, x0i]
    v11 = feat[y1i, x1i]
    wy1 = wy1[..., None]
    wx1 = wx1[..., None]
    val = (v00 * (1 - wy1) * (1 - wx1) + v01 * (1 - wy1) * wx1
           + v10 * wy1 * (1 - wx1) + v11 * wy1 * wx1)
    return jnp.where(oob[..., None], 0.0, val)


def roi_align(feat, boxes, output_size: int, spatial_scale: float,
              sampling_ratio: int = 2):
    """RoIAlign on one feature map.

    feat (H, W, C); boxes (N, 4) in IMAGE coordinates -> (N, S, S, C).
    Each output cell averages ``sampling_ratio**2`` bilinear samples; the
    half-pixel center shift matches torchvision ``roi_align(aligned=True)``
    (the Detectron2 convention)."""
    s = output_size
    sr = sampling_ratio

    def one(box):
        y1, x1, y2, x2 = box * spatial_scale
        bh = jnp.maximum(y2 - y1, 1e-6)
        bw = jnp.maximum(x2 - x1, 1e-6)
        cell_h = bh / s
        cell_w = bw / s
        # sample points: for output cell (i,j), sr x sr points
        iy = jnp.arange(s * sr) + 0.5
        ix = jnp.arange(s * sr) + 0.5
        ys = y1 + iy * (cell_h / sr)
        xs = x1 + ix * (cell_w / sr)
        yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
        vals = _bilinear(feat, yy - 0.5, xx - 0.5)  # center convention
        # average-pool sr x sr sample blocks -> (s, s, C)
        c = vals.shape[-1]
        vals = vals.reshape(s, sr, s, sr, c)
        return vals.mean(axis=(1, 3))

    return jax.vmap(one)(boxes)


def multilevel_roi_align(feats: List, boxes, output_size: int,
                         strides: Sequence[int], canonical_level: int = 2,
                         canonical_size: float = 224.0,
                         sampling_ratio: int = 2):
    """FPN-aware RoIAlign: each box is assigned a pyramid level by the FPN
    heuristic ``k = k0 + log2(sqrt(area)/224)``; TPU-friendly form computes
    the align on EVERY level (static shapes) and selects per box — the
    standard TPU detection trade (compute for shape stability)."""
    area = box_area(boxes)
    k = jnp.floor(canonical_level
                  + jnp.log2(jnp.sqrt(jnp.maximum(area, 1e-6))
                             / canonical_size + 1e-9))
    k = k.clip(0, len(feats) - 1).astype(jnp.int32)
    pooled = jnp.stack([
        roi_align(f, boxes, output_size, 1.0 / st, sampling_ratio)
        for f, st in zip(feats, strides)], axis=0)  # (L, N, S, S, C)
    return pooled[k, jnp.arange(boxes.shape[0])]


def paste_mask(mask, box, height: int, width: int):
    """Resize a (M, M) mask into its box within an (height, width) canvas —
    the inference-time inverse of the mask head's 28x28 crop."""
    m = mask.shape[0]
    y1, x1, y2, x2 = box
    bh = jnp.maximum(y2 - y1, 1.0)
    bw = jnp.maximum(x2 - x1, 1.0)
    yy = (jnp.arange(height) + 0.5 - y1) / bh * m - 0.5
    xx = (jnp.arange(width) + 0.5 - x1) / bw * m - 0.5
    gy, gx = jnp.meshgrid(yy, xx, indexing="ij")
    val = _bilinear(mask[..., None], gy, gx)[..., 0]
    inside = ((jnp.arange(height)[:, None] >= jnp.floor(y1))
              & (jnp.arange(height)[:, None] < jnp.ceil(y2))
              & (jnp.arange(width)[None, :] >= jnp.floor(x1))
              & (jnp.arange(width)[None, :] < jnp.ceil(x2)))
    return jnp.where(inside, val, 0.0)
