"""Kernel tile autotuner — searchable tile spaces over the ``automl``
Searchers (docs/performance.md §Kernel autotuning).

KERNELS_r04 showed the flagship Pallas kernels running at ~1.0x XLA: the
hand-picked ``block_q``/``block_k``/``block_rows`` tiles were guessed, not
searched.  TVM (PAPERS.md: arXiv 1802.04799) is the precedent — treat op
scheduling as a search problem.  Here each kernel declares a discrete tile
space; trials time the REAL kernel on synthetic inputs of the caller's
shape (median wall over ``block_until_ready`` repeats, compile excluded by
a warm call) driven by the existing :mod:`bigdl_tpu.automl.search`
machinery — :class:`GridSearcher` when the space is small enough to
enumerate, :class:`TPESearcher` above that — and the winner is cached on
disk keyed by ``(device_kind, kernel, shape-bucket, dtype)``.

Guarantees:

- **Never slower than the defaults**: the default tiles are always
  measured under the same protocol, and the tuner returns them unless a
  candidate beat them.  A config Mosaic rejects (bad tiling, VMEM OOM)
  scores ``inf`` via the Searcher's failure handling and cannot win.
- **Cache-hit determinism**: a second process with the same key loads the
  winner from disk and runs ZERO timing trials.
- **Explicit kwargs win**: ``flash_attention(..., block_q=256)`` bypasses
  the cache entirely for that axis.

Resolution order at kernel call time (``resolve``): explicit kwarg >
cached winner > registry default.  Online tuning (measure on first miss)
only ever happens on CONCRETE arrays — inside a ``jit`` trace the kernel
sees tracers and falls back to cache/defaults, so the offline CLI is how
the training path gets tuned tiles::

    python -m bigdl_tpu.ops.autotune                 # tune all kernels
    python -m bigdl_tpu.ops.autotune --kernel flash_attention_fwd \
        --small --trials 8

Knobs: ``BIGDL_TPU_AUTOTUNE`` = ``0``/``off`` (defaults only), ``cache``
(consult the cache, never measure — the default), ``1``/``online``
(measure-and-cache on miss, eager calls only).  The env var is read at
call time by this module (its single owner — mirrors the
``BIGDL_TPU_PEAK_FLOPS`` pattern); ``EngineConfig.kernel_autotune`` is the
in-process fallback when the env var is unset.
``BIGDL_TPU_AUTOTUNE_CACHE`` overrides the cache directory (default
``~/.cache/bigdl_tpu/autotune``).
"""

import argparse
import dataclasses
import functools
import json
import os
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu.automl import hp as hp_mod
from bigdl_tpu.automl.search import GridSearcher, TPESearcher
from bigdl_tpu.utils.log import get_logger

log = get_logger(__name__)

# grid spaces at or under this many points enumerate exhaustively; larger
# spaces sample with TPE under the trial budget
GRID_LIMIT = 16
DEFAULT_TRIALS = 12
DEFAULT_REPEATS = 10


def _metrics():
    from bigdl_tpu.optim.metrics import global_metrics

    return global_metrics()


# ---------------------------------------------------------------------------
# mode / cache-dir resolution
# ---------------------------------------------------------------------------

def autotune_mode() -> str:
    """``off`` | ``cache`` | ``online``.  Env var wins; the Engine's
    ``kernel_autotune`` config is the in-process fallback; default is
    ``cache`` (a populated cache is consulted, nothing is ever measured
    behind the caller's back)."""
    raw = os.environ.get("BIGDL_TPU_AUTOTUNE")
    if raw is None:
        try:
            from bigdl_tpu.runtime.engine import Engine

            if Engine._instance is not None:
                raw = Engine._instance.config.kernel_autotune
        except Exception:  # pragma: no cover — engine import cycles
            raw = None
    if raw is None:
        return "cache"
    raw = str(raw).strip().lower()
    if raw in ("0", "off", "false", "none"):
        return "off"
    if raw in ("1", "online", "tune", "true"):
        return "online"
    return "cache"


def cache_dir() -> str:
    return os.environ.get("BIGDL_TPU_AUTOTUNE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "bigdl_tpu", "autotune")


def device_kind() -> str:
    import jax

    try:
        return jax.devices()[0].device_kind
    except RuntimeError:  # pragma: no cover — no backend at all
        return "unknown"


def is_concrete(*arrays) -> bool:
    """True when no argument is a tracer — i.e. we are NOT inside a jit
    trace and may legally run timing trials right now."""
    import jax

    return not any(isinstance(a, jax.core.Tracer) for a in arrays)


# ---------------------------------------------------------------------------
# disk cache
# ---------------------------------------------------------------------------

class AutotuneCache:
    """One JSON file of ``key -> {tiles, best_ms, default_ms, trials}``.

    Reads are memoized; writes are read-merge-replace under a lock with an
    atomic rename, so concurrent tuners on one host lose at most their own
    last write, never the file."""

    def __init__(self, directory: Optional[str] = None):
        self.dir = directory or cache_dir()
        self.path = os.path.join(self.dir, "tiles.json")
        self._mem: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()

    def _load(self) -> Dict[str, Any]:
        if self._mem is None:
            try:
                with open(self.path) as f:
                    self._mem = json.load(f)
            except (OSError, json.JSONDecodeError):
                self._mem = {}
        return self._mem

    def get(self, key: str) -> Optional[Dict[str, Any]]:
        return self._load().get(key)

    def put(self, key: str, entry: Dict[str, Any]) -> None:
        with self._lock:
            # merge-on-write: pick up entries other processes landed since
            # our last read, then replace atomically
            try:
                with open(self.path) as f:
                    disk = json.load(f)
            except (OSError, json.JSONDecodeError):
                disk = {}
            disk[key] = entry
            os.makedirs(self.dir, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(disk, f, indent=1, sort_keys=True)
                os.replace(tmp, self.path)
            finally:
                if os.path.exists(tmp):  # pragma: no cover — replace raced
                    os.unlink(tmp)
            self._mem = disk


_cache: Optional[AutotuneCache] = None
_cache_lock = threading.Lock()


def get_cache() -> AutotuneCache:
    global _cache
    with _cache_lock:
        if _cache is None or _cache.dir != cache_dir():
            _cache = AutotuneCache()
        return _cache


def reset_cache() -> None:
    """Drop the in-memory cache handle (tests; env-var redirects)."""
    global _cache
    with _cache_lock:
        _cache = None


# ---------------------------------------------------------------------------
# kernel registry
# ---------------------------------------------------------------------------

def _pow2_bucket(n: int) -> int:
    """Round up to a power of two so nearby shapes share one cache entry
    (tile choice is driven by tiling granularity, not exact size)."""
    n = max(1, int(n))
    return 1 << (n - 1).bit_length()


@dataclasses.dataclass
class KernelSpec:
    """One tunable kernel: its tile space, defaults, and a builder that
    turns ``(shape_key, config)`` into a timable thunk on synthetic
    inputs."""

    name: str
    space: Dict[str, hp_mod.Sampler]
    defaults: Dict[str, int]
    # (shape_key) -> (config -> zero-arg jitted thunk)
    builder: Callable[[Tuple], Callable[[Dict[str, int]], Callable[[], Any]]]
    # shape_key tuple -> the SAME bucketed key string the kernel computes
    # at call time — tune()/the CLI key cache entries through this, so an
    # offline-tuned winner is exactly what flash_attention/fused_layernorm/
    # int8_matmul/block_sparse_matmul look up
    key_fn: Callable[[Tuple], str] = None
    # CLI bench shapes: {label: shape_key}; "small" labels run under --small
    bench_shapes: Dict[str, Tuple] = dataclasses.field(default_factory=dict)


def _flash_inputs(shape_key):
    import jax.numpy as jnp

    b, h, s, d, dtype = shape_key
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(b, h, s, d), dtype)
    k = jnp.asarray(rs.randn(b, h, s, d), dtype)
    v = jnp.asarray(rs.randn(b, h, s, d), dtype)
    return q, k, v


def _flash_fwd_builder(shape_key):
    import jax

    from bigdl_tpu.ops.flash_attention import flash_attention

    q, k, v = _flash_inputs(shape_key)

    def make(cfg):
        return jax.jit(lambda: flash_attention(
            q, k, v, causal=True, block_q=cfg["block_q"],
            block_k=cfg["block_k"]))

    return make


def _flash_bwd_builder(shape_key):
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.flash_attention import flash_attention

    q, k, v = _flash_inputs(shape_key)

    def make(cfg):
        def loss(qq):
            return flash_attention(
                qq, k, v, causal=True, block_q=cfg.get("block_q", 128),
                block_k=128, block_k_bwd=cfg["block_k"]).astype(
                    jnp.float32).sum()

        return jax.jit(lambda: jax.grad(loss)(q))

    return make


def _flash_decode_builder(shape_key):
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.flash_attention import paged_decode_attention

    S, h, page, d, nb, dtype = shape_key
    rs = np.random.RandomState(0)
    P = S * nb
    q = jnp.asarray(rs.randn(S, h, d), dtype)
    kp = jnp.asarray(rs.randn(P, h, page, d), dtype)
    vp = jnp.asarray(rs.randn(P, h, page, d), dtype)
    pt = jnp.asarray(rs.permutation(P)[: S * nb].reshape(S, nb), jnp.int32)
    lengths = jnp.asarray(rs.randint(0, nb * page, (S,)), jnp.int32)

    def make(cfg):
        if h % cfg["block_h"] != 0:
            raise ValueError(f"block_h {cfg['block_h']} does not divide "
                             f"heads {h}")
        return jax.jit(lambda: paged_decode_attention(
            q, kp, vp, pt, lengths, block_h=cfg["block_h"]))

    return make


def _ln_builder(shape_key):
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.fused import fused_layernorm

    rows, cols, dtype = shape_key
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(rows, cols), dtype)
    g = jnp.asarray(rs.randn(cols), jnp.float32)
    b = jnp.asarray(rs.randn(cols), jnp.float32)

    def make(cfg):
        return jax.jit(lambda: fused_layernorm(
            x, g, b, block_rows=cfg["block_rows"]))

    return make


def _int8_builder(shape_key):
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.quantized import int8_matmul

    m, k, n = shape_key
    rs = np.random.RandomState(0)
    a = jnp.asarray(rs.randint(-127, 128, (m, k)), jnp.int8)
    w = jnp.asarray(rs.randint(-127, 128, (k, n)), jnp.int8)

    def make(cfg):
        return jax.jit(lambda: int8_matmul(
            a, w, block_m=cfg["block_m"], block_n=cfg["block_n"],
            block_k=cfg["block_k"]))

    return make


def _bs_builder(shape_key):
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.block_sparse import block_sparse_matmul
    from bigdl_tpu.ops.common import cdiv

    m, k, n, bk, bn, dtype = shape_key
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(m, k), dtype)
    w = jnp.asarray(rs.randn(k, n), dtype)
    # half-density mask: the regime where block skipping starts to pay
    mask = rs.rand(cdiv(k, bk), cdiv(n, bn)) < 0.5
    mask[0, :] = True  # no empty columns in the bench mask

    def make(cfg):
        return jax.jit(lambda: block_sparse_matmul(
            x, w, mask, block_k=bk, block_n=bn, block_m=cfg["block_m"]))

    return make


_TILE_CHOICES = [64, 128, 256, 512]

REGISTRY: Dict[str, KernelSpec] = {
    "flash_attention_fwd": KernelSpec(
        name="flash_attention_fwd",
        space={"block_q": hp_mod.choice([64, 128, 256, 512]),
               "block_k": hp_mod.choice([128, 256, 512, 1024])},
        defaults={"block_q": 128, "block_k": 128},
        builder=_flash_fwd_builder,
        key_fn=lambda sk: attention_key(sk[:4], sk[2], sk[4]),
        bench_shapes={
            "small": (1, 2, 256, 64, "bfloat16"),
            "lm_2k": (4, 8, 2048, 128, "bfloat16"),
        }),
    "flash_attention_bwd": KernelSpec(
        name="flash_attention_bwd",
        space={"block_k": hp_mod.choice([64, 128, 256, 512])},
        defaults={"block_k": 128},
        builder=_flash_bwd_builder,
        key_fn=lambda sk: attention_key(sk[:4], sk[2], sk[4]),
        bench_shapes={
            "small": (1, 2, 256, 64, "bfloat16"),
            "lm_2k": (4, 8, 2048, 128, "bfloat16"),
        }),
    "flash_attention_decode": KernelSpec(
        name="flash_attention_decode",
        space={"block_h": hp_mod.choice([1, 2, 4, 8])},
        defaults={"block_h": 4},
        builder=_flash_decode_builder,
        key_fn=lambda sk: decode_attention_key(sk[0], sk[1], sk[2],
                                               sk[3], sk[4], sk[5]),
        bench_shapes={
            "small": (8, 4, 8, 32, 4, "float32"),
            "serve_8x8": (16, 8, 16, 64, 8, "bfloat16"),
        }),
    "fused_layernorm": KernelSpec(
        name="fused_layernorm",
        space={"block_rows": hp_mod.choice([64, 128, 256, 512, 1024])},
        defaults={"block_rows": 256},
        builder=_ln_builder,
        key_fn=lambda sk: rows_key(sk[0], sk[1], sk[2]),
        bench_shapes={
            "small": (512, 256, "float32"),
            "lm_act": (8192, 1024, "float32"),
        }),
    "int8_matmul": KernelSpec(
        name="int8_matmul",
        space={"block_m": hp_mod.choice(_TILE_CHOICES),
               "block_n": hp_mod.choice(_TILE_CHOICES),
               "block_k": hp_mod.choice([128, 256, 512, 1024])},
        defaults={"block_m": 256, "block_n": 256, "block_k": 512},
        builder=_int8_builder,
        key_fn=lambda sk: matmul_key(sk[0], sk[1], sk[2], "int8"),
        bench_shapes={
            "small": (256, 512, 256),
            "gemm_1k": (1024, 2048, 1024),
        }),
    "block_sparse_matmul": KernelSpec(
        name="block_sparse_matmul",
        space={"block_m": hp_mod.choice(_TILE_CHOICES)},
        defaults={"block_m": 128},
        builder=_bs_builder,
        key_fn=lambda sk: block_sparse_key(sk[0], sk[1], sk[2], sk[3],
                                           sk[4], sk[5]),
        bench_shapes={
            "small": (128, 128, 256, 32, 32, "float32"),
            "ffn_gpt2s": (4096, 768, 3072, 64, 64, "bfloat16"),
        }),
}


def canonical_key(kernel: str, shape_key: Tuple,
                  kind: Optional[str] = None) -> str:
    """THE cache key for one (kernel, concrete shape): the registry's
    ``key_fn`` bucketing under the device kind — identical to what the
    kernel computes at call time, so tune()/CLI winners are exactly what
    call-time resolution finds."""
    return full_key(kernel, REGISTRY[kernel].key_fn(tuple(shape_key)),
                    kind=kind)


# -- shape-bucket keys (one per kernel family) ------------------------------

def _dtype_name(dtype) -> str:
    return np.dtype(dtype).name if not hasattr(dtype, "name") else dtype.name


def attention_key(q_shape, kv_len: int, dtype) -> str:
    b, h, s, d = q_shape
    return (f"bh{_pow2_bucket(b * h)}_q{_pow2_bucket(s)}"
            f"_k{_pow2_bucket(kv_len)}_d{d}_{_dtype_name(dtype)}")


def decode_attention_key(slots: int, heads: int, page: int, hd: int,
                         n_blocks: int, dtype) -> str:
    return (f"s{_pow2_bucket(slots)}_h{heads}_p{page}_d{hd}"
            f"_nb{_pow2_bucket(n_blocks)}_{_dtype_name(dtype)}")


def rows_key(rows: int, cols: int, dtype) -> str:
    return f"r{_pow2_bucket(rows)}_c{cols}_{_dtype_name(dtype)}"


def matmul_key(m: int, k: int, n: int, dtype) -> str:
    return f"m{_pow2_bucket(m)}_k{k}_n{n}_{_dtype_name(dtype)}"


def block_sparse_key(m: int, k: int, n: int, bk: int, bn: int,
                     dtype) -> str:
    return (f"m{_pow2_bucket(m)}_k{k}_n{n}_bk{bk}_bn{bn}"
            f"_{_dtype_name(dtype)}")


def full_key(kernel: str, shape_key: str, kind: Optional[str] = None) -> str:
    return f"{kind or device_kind()}|{kernel}|{shape_key}"


# ---------------------------------------------------------------------------
# measurement + search
# ---------------------------------------------------------------------------

def _measure_ms(thunk: Callable[[], Any],
                repeats: int = DEFAULT_REPEATS) -> float:
    """Median wall time of ``thunk`` over ``repeats`` (compile excluded by
    one warm call).  Module-level on purpose: tests monkeypatch it to
    count trials and to make timing deterministic."""
    import jax

    jax.block_until_ready(thunk())  # warm (compile)
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(thunk())
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times))


def _space_size(space: Dict[str, hp_mod.Sampler]) -> int:
    total = 1
    for v in space.values():
        g = v.grid()
        total *= len(g) if g else GRID_LIMIT + 1
    return total


def tune(kernel: str, shape_key: Tuple, *, key: Optional[str] = None,
         n_trials: int = DEFAULT_TRIALS, repeats: int = DEFAULT_REPEATS,
         cache: Optional[AutotuneCache] = None,
         write_cache: bool = True) -> Dict[str, Any]:
    """Search ``kernel``'s tile space at ``shape_key`` and cache the
    winner.  Returns the cache entry ``{tiles, best_ms, default_ms,
    trials, winner}``.  The default config is timed under the SAME
    protocol and wins ties/regressions — the tuner may return the default,
    it may not regress from it."""
    spec = REGISTRY[kernel]
    make = spec.builder(tuple(shape_key))
    key = key or canonical_key(kernel, shape_key)
    trials = {"n": 0}

    def trial_fn(cfg):
        cfg = {k: v for k, v in cfg.items() if not k.startswith("_")}
        trials["n"] += 1
        _metrics().inc("ops.autotune_trials")
        return _measure_ms(make(cfg), repeats=repeats)

    default_ms = trial_fn(dict(spec.defaults))
    if _space_size(spec.space) <= max(GRID_LIMIT, n_trials):
        searcher = GridSearcher(mode="min")
        n = 0  # grid: exhaust the space
    else:
        searcher = TPESearcher(mode="min", seed=0)
        n = n_trials
    best = searcher.run(trial_fn, dict(spec.space), n_sampling=n)
    if best.error is None and best.metric < default_ms:
        tiles, best_ms, winner = dict(best.config), best.metric, "searched"
    else:  # the guarantee: never slower than the hand-picked defaults
        tiles, best_ms, winner = dict(spec.defaults), default_ms, "default"
    tiles = {k: v for k, v in tiles.items() if not k.startswith("_")}
    entry = {"tiles": tiles, "best_ms": round(best_ms, 4),
             "default_ms": round(default_ms, 4), "trials": trials["n"],
             "winner": winner}
    if write_cache:
        (cache or get_cache()).put(key, entry)
    log.info("autotune %s %s: %s %s (%.3f ms vs default %.3f ms, "
             "%d trials)", kernel, key, winner, tiles, best_ms, default_ms,
             trials["n"])
    return entry


def _shape_label(shape_key: Tuple) -> str:
    return "x".join(str(d) for d in shape_key)


# ---------------------------------------------------------------------------
# call-time resolution (the kernels' entry point)
# ---------------------------------------------------------------------------

def resolve(kernel: str, shape_key: str,
            explicit: Optional[Dict[str, Optional[int]]] = None,
            online_shape: Optional[Tuple] = None) -> Dict[str, int]:
    """Tiles for one kernel call.  Per axis: explicit kwarg (not None) >
    cached winner > registry default.  In ``online`` mode a cache miss
    with a concrete ``online_shape`` triggers a tuning run first (eager
    calls only — the kernels never pass ``online_shape`` from a trace)."""
    spec = REGISTRY[kernel]
    tiles = dict(spec.defaults)
    explicit = {k: v for k, v in (explicit or {}).items() if v is not None}
    mode = autotune_mode()
    if mode != "off" and len(explicit) < len(tiles):
        key = full_key(kernel, shape_key)
        entry = get_cache().get(key)
        if entry is None and mode == "online" and online_shape is not None:
            try:
                entry = tune(kernel, online_shape, key=key)
            except Exception as e:  # noqa: BLE001 — tuning must not break
                log.warning("online autotune of %s failed (%s); using "
                            "defaults", kernel, e)
                entry = None
        if entry is not None:
            _metrics().inc("ops.autotune_cache_hits")
            cached = entry.get("tiles", {})
            for k in tiles:
                v = cached.get(k)
                if isinstance(v, (int, float)) and v > 0:
                    tiles[k] = int(v)
        else:
            _metrics().inc("ops.autotune_cache_misses")
    tiles.update({k: int(v) for k, v in explicit.items()})
    return tiles


# ---------------------------------------------------------------------------
# offline CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bigdl_tpu.ops.autotune",
        description="offline kernel tile tuner (docs/performance.md "
                    "§Kernel autotuning); winners land in the shared "
                    "on-disk cache that flash_attention/fused_layernorm/"
                    "int8_matmul/block_sparse_matmul consult at call time")
    ap.add_argument("--kernel", action="append", default=None,
                    help="kernel(s) to tune (default: all registered)")
    ap.add_argument("--trials", type=int, default=DEFAULT_TRIALS,
                    help="trial budget for TPE spaces (grids enumerate)")
    ap.add_argument("--repeats", type=int, default=DEFAULT_REPEATS,
                    help="timing repeats per trial (median)")
    ap.add_argument("--small", action="store_true",
                    help="tiny shapes only (CPU/CI smoke)")
    ap.add_argument("--cache-dir", default=None,
                    help="override BIGDL_TPU_AUTOTUNE_CACHE")
    args = ap.parse_args(argv)

    if args.cache_dir:
        os.environ["BIGDL_TPU_AUTOTUNE_CACHE"] = args.cache_dir
        reset_cache()
    names = args.kernel or list(REGISTRY)
    rc = 0
    for name in names:
        if name not in REGISTRY:
            print(json.dumps({"kernel": name, "error": "unknown kernel",
                              "known": sorted(REGISTRY)}))
            rc = 1
            continue
        spec = REGISTRY[name]
        shapes = {k: v for k, v in spec.bench_shapes.items()
                  if (k == "small") == bool(args.small)} or spec.bench_shapes
        for label, shape_key in shapes.items():
            key = canonical_key(name, shape_key)
            try:
                entry = tune(name, shape_key, key=key,
                             n_trials=args.trials, repeats=args.repeats)
                print(json.dumps(dict(entry, kernel=name, shape=label,
                                      key=key)), flush=True)
            except Exception as e:  # noqa: BLE001 — keep tuning the rest
                print(json.dumps({"kernel": name, "shape": label,
                                  "error": f"{type(e).__name__}: "
                                           f"{str(e)[:300]}"}), flush=True)
                rc = 1
    print(json.dumps({"cache": get_cache().path, "mode": autotune_mode()}))
    return rc


if __name__ == "__main__":
    sys.exit(main())
