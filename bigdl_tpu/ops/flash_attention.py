"""Blockwise fused (flash) attention — Pallas TPU kernel.

Reference analog: the reference has NO fused attention — its
``nn/Attention.scala`` / Keras ``TransformerLayer`` materialise the full
O(S²) score matrix on one device (SURVEY.md §6.7).  This kernel is the
TPU-native upgrade: online-softmax blockwise attention that keeps exactly
one (block_q × d) query tile and one (block_k × d) key/value tile in VMEM
at a time, so peak on-chip memory is O(block·d) and the matmuls stay on
the MXU.

Forward is a Pallas kernel with grid (batch·heads, q-blocks, k-blocks);
the k dimension is innermost and iterates sequentially on-core, carrying
the online-softmax running (max, denom, accumulator) in VMEM scratch —
the k/v BlockSpecs stream one tile per step from HBM.  Backward is a
custom VJP: the standard flash-attention backward recurrence evaluated
blockwise with a ``lax.scan`` over k/v tiles using the saved logsumexp,
so the O(S²) score matrix is never materialised in either direction
(single-chip long context; cross-chip sequence parallelism lives in
``bigdl_tpu/parallel/ring_attention.py``).

Shapes: q, k, v are (batch, heads, seq, head_dim); output matches q.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigdl_tpu.ops.common import default_interpret, round_up

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, sm_scale, causal, block_q, block_k, kv_len):
    # q_ref: (1, block_q, d); k_ref/v_ref: (1, block_k, d) — one tile each.
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    num_kb = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: skip k-blocks strictly above this q-block's diagonal band
    needed = jnp.bool_(True)
    if causal:
        needed = kj * block_k < (qi + 1) * block_q

    @pl.when(needed)
    def _step():
        q = q_ref[0].astype(jnp.float32) * sm_scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)

        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < kv_len
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, 0]
        l_prev = l_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:, 0] = m_new
        l_scr[:, 0] = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32)

    @pl.when(kj == num_kb - 1)
    def _finish():
        m = m_scr[:, 0]
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, :, 0] = m + jnp.log(l_safe)


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    skv = k.shape[2]
    bq = min(block_q, round_up(sq, 8))
    bk = min(block_k, round_up(skv, 8))
    sq_p, skv_p = round_up(sq, bq), round_up(skv, bk)

    qp = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
    qp = qp.reshape(b * h, sq_p, d)
    kp = kp.reshape(b * h, skv_p, d)
    vp = vp.reshape(b * h, skv_p, d)

    grid = (b * h, sq_p // bq, skv_p // bk)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=bq,
        block_k=bk, kv_len=skv)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            # lse carries a trailing singleton lane dim: a 2-D (1, bq) block
            # would put bq in the lane slot and 1 in the sublane slot, which
            # TPU tiling rejects when batch·heads > 1.
            pl.BlockSpec((1, bq, 1), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),   # running max
            pltpu.VMEM((bq, 1), jnp.float32),   # running denom
            pltpu.VMEM((bq, d), jnp.float32),   # output accumulator
        ],
        interpret=default_interpret(interpret),
    )(qp, kp, vp)

    out = out.reshape(b, h, sq_p, d)[:, :, :sq]
    lse = lse.reshape(b, h, sq_p)[:, :, :sq]
    return out, lse  # lse: (b, h, sq)


def _blockwise_bwd(q, k, v, out, lse, g, sm_scale, causal, block_k=128):
    """Memory-efficient flash-attention backward: a ``lax.scan`` over k/v
    blocks reconstructs one (sq × block_k) score tile at a time from the
    saved logsumexp — peak memory O(S·block) instead of the O(S²) full
    score matrix.  Recurrence: p = exp(q·kᵀ·scale − lse);
    D = rowsum(g ⊙ out); dS = p ⊙ (g·vᵀ − D)·scale."""
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    b, h, sq, d = qf.shape
    skv = kf.shape[2]
    bk = min(block_k, round_up(skv, 8))
    skv_p = round_up(skv, bk)
    kp = jnp.pad(kf, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
    vp = jnp.pad(vf, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
    # (nblocks, b, h, bk, d) scan layout
    kb = kp.reshape(b, h, skv_p // bk, bk, d).transpose(2, 0, 1, 3, 4)
    vb = vp.reshape(b, h, skv_p // bk, bk, d).transpose(2, 0, 1, 3, 4)

    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)  # (b,h,sq)
    q_pos = jax.lax.broadcasted_iota(jnp.int32, (sq, bk), 0)
    k_off = jax.lax.broadcasted_iota(jnp.int32, (sq, bk), 1)

    def step(dq_acc, inp):
        j, k_j, v_j = inp
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_j) * sm_scale
        k_pos = j * bk + k_off
        mask = k_pos < skv
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        p = jnp.where(mask, jnp.exp(s - lse[..., None]), 0.0)
        dv_j = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, v_j)
        ds = p * (dp - delta[..., None]) * sm_scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, k_j)
        dk_j = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        return dq_acc, (dk_j, dv_j)

    nb = skv_p // bk
    dq, (dk_b, dv_b) = jax.lax.scan(
        step, jnp.zeros_like(qf), (jnp.arange(nb), kb, vb))
    dk = dk_b.transpose(1, 2, 0, 3, 4).reshape(b, h, skv_p, d)[:, :, :skv]
    dv = dv_b.transpose(1, 2, 0, 3, 4).reshape(b, h, skv_p, d)[:, :, :skv]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, block_k_bwd,
           interpret):
    out, _ = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                        interpret)
    return out


def _flash_vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                   block_k_bwd, interpret):
    out, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k,
                          interpret)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(sm_scale, causal, block_q, block_k, block_k_bwd,
                   interpret, res, g):
    q, k, v, out, lse = res
    return _blockwise_bwd(q, k, v, out, lse, g, sm_scale, causal,
                          block_k=block_k_bwd)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _decode_kernel(pt_ref, len_ref, *refs, sm_scale, page_size, quantized):
    """Single-query attention over one slot's paged KV cache.  Grid
    (slots, head-blocks, page-blocks); the page dimension is innermost
    and walks the slot's page table via the scalar-prefetched index map
    — only the slot's own pages are ever touched, so HBM traffic scales
    with the sequence's true length, not the pool size.

    ``quantized`` adds two scalar-prefetched per-page scale tables
    (k/v, one f32 per pool page — docs/quantization.md §Serving memory
    hierarchy): the int8 page block is dequantized IN-REGISTER right
    after the DMA, so HBM reads stay 1 byte/element and the softmax
    math is identical to the f32 kernel."""
    if quantized:
        (ks_ref, vs_ref, q_ref, k_ref, v_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
    s = pl.program_id(0)
    j = pl.program_id(2)
    num_pb = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # positions [j*page, (j+1)*page) attend when <= the slot's length
    @pl.when(j * page_size <= len_ref[s])
    def _step():
        q = q_ref[0].astype(jnp.float32) * sm_scale      # (bh, d)
        k = k_ref[0].astype(jnp.float32)                 # (bh, page, d)
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            pid = pt_ref[s, j]
            k = k * ks_ref[pid]
            v = v * vs_ref[pid]
        # VPU-friendly batched dot: broadcast-multiply-reduce keeps the
        # per-head contraction off the (batched-dot-averse) MXU path
        sc = jnp.sum(q[:, None, :] * k, axis=-1)         # (bh, page)
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, sc.shape, 1)
        sc = jnp.where(pos <= len_ref[s], sc, _NEG_INF)
        m_prev = m_scr[:, 0]
        l_prev = l_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1))
        p = jnp.exp(sc - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:, 0] = m_new
        l_scr[:, 0] = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + jnp.sum(
            p[:, :, None] * v, axis=1)

    @pl.when(j == num_pb - 1)
    def _finish():
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, page_table, lengths, *,
                           k_scales=None, v_scales=None,
                           sm_scale: Optional[float] = None,
                           block_h: Optional[int] = None,
                           interpret: Optional[bool] = None):
    """Query-length-1 decode-step attention over a paged KV cache — the
    serving-side sibling of :func:`flash_attention` (docs/serving.md
    §Autoregressive decode).

    ``q``: (slots, heads, head_dim) — one query per sequence slot.
    ``k_pages``/``v_pages``: (num_pages, heads, page_size, head_dim) —
    the page pool ONE layer's cache lives in.  ``page_table``: (slots,
    n_blocks) int32 — each slot's ordered page list (entries past the
    allocated count may be stale; they are masked by ``lengths``).
    ``lengths``: (slots,) int32 — the highest valid cache position per
    slot, INCLUSIVE (the current token's K/V must already be written).

    int8 page pools (docs/quantization.md §Serving memory hierarchy)
    pass ``k_scales``/``v_scales``: (num_pages,) float32 per-page
    abs-max scales, scalar-prefetched alongside the page table so each
    page block is dequantized in-register after its 1-byte/element DMA.

    ``block_h`` tiles the head dimension per program (must divide
    heads); ``None`` consults the autotune cache under the
    ``flash_attention_decode`` registry entry and falls back to the
    largest of {1,2,4,8} that divides ``heads``."""
    S, h, d = q.shape
    P, hk, page, dk = k_pages.shape
    assert (h, d) == (hk, dk), (q.shape, k_pages.shape)
    quantized = k_pages.dtype == jnp.int8
    if quantized and (k_scales is None or v_scales is None):
        raise ValueError("int8 k_pages/v_pages need k_scales/v_scales "
                         "(one f32 abs-max scale per pool page)")
    if not quantized and (k_scales is not None or v_scales is not None):
        raise ValueError("k_scales/v_scales only apply to int8 pages, "
                         f"got {k_pages.dtype} pages")
    nb = page_table.shape[1]
    if sm_scale is None:
        sm_scale = d ** -0.5
    from bigdl_tpu.ops import autotune

    if block_h is None:
        key = autotune.decode_attention_key(S, h, page, d, nb,
                                            q.dtype)
        shape = ((S, h, page, d, nb, q.dtype.name)
                 if autotune.is_concrete(q, k_pages, v_pages) else None)
        bh = int(autotune.resolve("flash_attention_decode", key,
                                  online_shape=shape)["block_h"])
        if h % bh != 0:  # cached winner from another head count
            bh = max(c for c in (1, 2, 4, 8) if h % c == 0)
    else:
        bh = int(block_h)
        if h % bh != 0:
            raise ValueError(f"block_h {bh} must divide heads {h}")

    kernel = functools.partial(_decode_kernel, sm_scale=float(sm_scale),
                               page_size=page, quantized=quantized)
    # scalar-prefetch operands: (page_table, lengths) always; the int8
    # pool adds the two per-page scale tables (index maps then take four
    # trailing scalar refs instead of two — hence the arity split below)
    if quantized:
        def q_map(s, hb, j, pt, ln, ks, vs):
            return (s, hb, 0)

        def kv_map(s, hb, j, pt, ln, ks, vs):
            return (pt[s, j], hb, 0, 0)
    else:
        def q_map(s, hb, j, pt, ln):
            return (s, hb, 0)

        def kv_map(s, hb, j, pt, ln):
            return (pt[s, j], hb, 0, 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4 if quantized else 2,
        grid=(S, h // bh, nb),
        in_specs=[
            pl.BlockSpec((1, bh, d), q_map),
            pl.BlockSpec((1, bh, page, d), kv_map),
            pl.BlockSpec((1, bh, page, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, bh, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((bh, 1), jnp.float32),    # running max
            pltpu.VMEM((bh, 1), jnp.float32),    # running denom
            pltpu.VMEM((bh, d), jnp.float32),    # output accumulator
        ],
    )
    scalars = [jnp.asarray(page_table, jnp.int32),
               jnp.asarray(lengths, jnp.int32)]
    if quantized:
        scalars += [jnp.asarray(k_scales, jnp.float32),
                    jnp.asarray(v_scales, jnp.float32)]
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, h, d), q.dtype),
        interpret=default_interpret(interpret),
    )(*scalars, q, k_pages, v_pages)


def _verify_kernel(pt_ref, pos_ref, *refs, sm_scale, page_size, chunk,
                   quantized):
    """Multi-query (speculative-verify) attention over one slot's paged
    KV cache (docs/serving.md §Speculative decoding).  Identical page
    walk to :func:`_decode_kernel`, but the query block carries the
    whole k+1-token verify chunk: query ``c`` sits at cache position
    ``pos_ref[s] + c`` and attends keys at positions ``<= pos_ref[s] +
    c`` — the per-query causal staircase that makes one program score
    every drafted token."""
    if quantized:
        (ks_ref, vs_ref, q_ref, k_ref, v_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
    s = pl.program_id(0)
    j = pl.program_id(2)
    num_pb = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # the LAST query (c = chunk-1) attends the furthest position, so a
    # page participates iff it starts at or below pos + chunk - 1
    @pl.when(j * page_size <= pos_ref[s] + chunk - 1)
    def _step():
        q = q_ref[0].astype(jnp.float32) * sm_scale      # (bh, C, d)
        k = k_ref[0].astype(jnp.float32)                 # (bh, page, d)
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            pid = pt_ref[s, j]
            k = k * ks_ref[pid]
            v = v * vs_ref[pid]
        # (bh, C, page) scores via broadcast-multiply-reduce (VPU path,
        # like the decode kernel — C and page are both small here)
        sc = jnp.sum(q[:, :, None, :] * k[:, None, :, :], axis=-1)
        key_pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, sc.shape, 2)
        q_lim = pos_ref[s] + jax.lax.broadcasted_iota(
            jnp.int32, sc.shape, 1)
        sc = jnp.where(key_pos <= q_lim, sc, _NEG_INF)
        m_prev = m_scr[:]                                # (bh, C)
        l_prev = l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1))
        p = jnp.exp(sc - m_new[..., None])
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_scr[:] = acc_scr[:] * alpha[..., None] + jnp.sum(
            p[..., None] * v[:, None], axis=2)

    @pl.when(j == num_pb - 1)
    def _finish():
        l = l_scr[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe[..., None]).astype(o_ref.dtype)


def paged_verify_attention(q, k_pages, v_pages, page_table, positions, *,
                           k_scales=None, v_scales=None,
                           sm_scale: Optional[float] = None,
                           block_h: Optional[int] = None,
                           interpret: Optional[bool] = None):
    """Query-length-``k+1`` speculative-VERIFY attention over a paged KV
    cache — the multi-query sibling of :func:`paged_decode_attention`
    (docs/serving.md §Speculative decoding): one call scores the whole
    drafted chunk against the target cache instead of k+1 single-query
    steps.

    ``q``: (slots, heads, chunk, head_dim) — the verify chunk's queries,
    query ``c`` of slot ``s`` sitting at cache position ``positions[s]
    + c``.  ``k_pages``/``v_pages``/``page_table`` exactly as
    :func:`paged_decode_attention`; the chunk's own K/V must already be
    scattered into the pages (positions ``[positions[s], positions[s] +
    chunk)``) before the call.  ``positions``: (slots,) int32 — the
    FIRST query's cache position per slot; the per-query causal
    staircase ``key_pos <= positions[s] + c`` makes each query attend
    its own prefix only, so the outputs match chunk single-query decode
    steps.

    int8 pools pass ``k_scales``/``v_scales`` per-page f32 abs-max
    scales, dequantized in-register like the decode kernel.  ``block_h``
    tiles heads (``None`` = the largest of {1, 2, 4, 8} dividing
    ``heads`` — the verify chunk is not autotuned separately)."""
    S, h, C, d = q.shape
    P, hk, page, dk = k_pages.shape
    assert (h, d) == (hk, dk), (q.shape, k_pages.shape)
    quantized = k_pages.dtype == jnp.int8
    if quantized and (k_scales is None or v_scales is None):
        raise ValueError("int8 k_pages/v_pages need k_scales/v_scales "
                         "(one f32 abs-max scale per pool page)")
    if not quantized and (k_scales is not None or v_scales is not None):
        raise ValueError("k_scales/v_scales only apply to int8 pages, "
                         f"got {k_pages.dtype} pages")
    nb = page_table.shape[1]
    if sm_scale is None:
        sm_scale = d ** -0.5
    if block_h is None:
        bh = max(c for c in (1, 2, 4, 8) if h % c == 0)
    else:
        bh = int(block_h)
        if h % bh != 0:
            raise ValueError(f"block_h {bh} must divide heads {h}")

    kernel = functools.partial(_verify_kernel, sm_scale=float(sm_scale),
                               page_size=page, chunk=C,
                               quantized=quantized)
    if quantized:
        def q_map(s, hb, j, pt, pos, ks, vs):
            return (s, hb, 0, 0)

        def kv_map(s, hb, j, pt, pos, ks, vs):
            return (pt[s, j], hb, 0, 0)
    else:
        def q_map(s, hb, j, pt, pos):
            return (s, hb, 0, 0)

        def kv_map(s, hb, j, pt, pos):
            return (pt[s, j], hb, 0, 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4 if quantized else 2,
        grid=(S, h // bh, nb),
        in_specs=[
            pl.BlockSpec((1, bh, C, d), q_map),
            pl.BlockSpec((1, bh, page, d), kv_map),
            pl.BlockSpec((1, bh, page, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, bh, C, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((bh, C), jnp.float32),    # running max per query
            pltpu.VMEM((bh, C), jnp.float32),    # running denom
            pltpu.VMEM((bh, C, d), jnp.float32),  # output accumulator
        ],
    )
    scalars = [jnp.asarray(page_table, jnp.int32),
               jnp.asarray(positions, jnp.int32)]
    if quantized:
        scalars += [jnp.asarray(k_scales, jnp.float32),
                    jnp.asarray(v_scales, jnp.float32)]
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, h, C, d), q.dtype),
        interpret=default_interpret(interpret),
    )(*scalars, q, k_pages, v_pages)


def flash_attention(q, k, v, *, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    block_k_bwd: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Fused blockwise attention.  q, k, v: (batch, heads, seq, head_dim).

    ``block_*=None`` consults the autotune cache for this device/shape
    bucket and falls back to the hand-picked 128 defaults
    (docs/performance.md §Kernel autotuning); explicit kwargs always win.
    ``block_k_bwd`` tiles the backward k/v scan independently of the
    forward."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    from bigdl_tpu.ops import autotune

    key = autotune.attention_key(q.shape, k.shape[2], q.dtype)
    # online mode tunes on a cache miss, but only on EAGER calls —
    # inside a jit trace the args are tracers and we must not run timing
    # trials mid-trace
    shape = (tuple(q.shape) + (q.dtype.name,)
             if autotune.is_concrete(q, k, v) else None)
    fwd = autotune.resolve("flash_attention_fwd", key,
                           explicit={"block_q": block_q,
                                     "block_k": block_k},
                           online_shape=shape)
    if block_k_bwd is None:
        if block_k is not None:
            # an explicit forward block_k also pins the backward (the
            # legacy single-knob contract) — no bwd lookup, no online
            # tuning run whose winner would be discarded
            block_k_bwd = block_k
        else:
            # cache/defaults only — no online_shape: a forward-only eager
            # call must not pay a jax.grad tuning sweep for a backward it
            # may never run (the offline CLI tunes flash_attention_bwd)
            block_k_bwd = autotune.resolve("flash_attention_bwd",
                                           key)["block_k"]
    return _flash(q, k, v, float(sm_scale), bool(causal),
                  int(fwd["block_q"]), int(fwd["block_k"]),
                  int(block_k_bwd), interpret)
