"""Fused elementwise kernels.

Reference analog: the MKL VML batch calls (``vsExp/vsAdd/...`` through
``com.intel.analytics.bigdl.mkl.MKL`` — SURVEY.md §3.2) that the reference
uses to avoid per-element JNI overhead.  On TPU, XLA already fuses most
elementwise chains into the surrounding matmuls; the kernel here covers the
remaining normalisation pattern where a hand-rolled single-pass kernel
keeps the row resident in VMEM across both reduction and scale steps.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from bigdl_tpu.ops.common import default_interpret, round_up


def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps, d):
    x = x_ref[:].astype(jnp.float32)
    mask = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1) < d
    xm = jnp.where(mask, x, 0.0)
    mean = jnp.sum(xm, axis=-1, keepdims=True) / d
    var = jnp.sum(jnp.where(mask, (x - mean) ** 2, 0.0), axis=-1,
                  keepdims=True) / d
    inv = jax.lax.rsqrt(var + eps)
    y = (x - mean) * inv * g_ref[0][None, :] + b_ref[0][None, :]
    o_ref[:] = y.astype(o_ref.dtype)


def fused_layernorm(x, gamma, beta, *, eps: float = 1e-5,
                    block_rows: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Single-pass LayerNorm over the last axis.  Differentiable: backward
    is the closed-form LayerNorm VJP evaluated with jnp (XLA fuses it).

    ``block_rows=None`` consults the autotune cache (default 256);
    an explicit value always wins."""
    if block_rows is None:
        from bigdl_tpu.ops import autotune

        rows = 1
        for d in x.shape[:-1]:
            rows *= int(d)
        key = autotune.rows_key(rows, x.shape[-1], x.dtype)
        shape = ((rows, int(x.shape[-1]), x.dtype.name)
                 if autotune.is_concrete(x) else None)
        block_rows = autotune.resolve("fused_layernorm", key,
                                      online_shape=shape)["block_rows"]
    return _fused_ln(x, gamma, beta, eps, int(block_rows), interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_ln(x, gamma, beta, eps, block_rows, interpret):
    return _ln_forward(x, gamma, beta, eps, block_rows, interpret)


def _ln_forward(x, gamma, beta, eps, block_rows, interpret):
    lead = x.shape[:-1]
    d = x.shape[-1]
    x2 = x.reshape(-1, d)
    m = x2.shape[0]
    br = min(block_rows, round_up(m, 8))
    mp = round_up(m, br)
    dp = round_up(d, 128)
    xp = jnp.pad(x2, ((0, mp - m), (0, dp - d)))
    gp = jnp.pad(gamma.astype(jnp.float32), (0, dp - d))[None, :]
    bp = jnp.pad(beta.astype(jnp.float32), (0, dp - d))[None, :]

    kernel = functools.partial(_ln_kernel, eps=eps, d=d)
    out = pl.pallas_call(
        kernel,
        grid=(mp // br,),
        in_specs=[
            pl.BlockSpec((br, dp), lambda i: (i, 0)),
            pl.BlockSpec((1, dp), lambda i: (0, 0)),
            pl.BlockSpec((1, dp), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, dp), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((mp, dp), x.dtype),
        interpret=default_interpret(interpret),
    )(xp, gp, bp)
    return out[:m, :d].reshape(*lead, d)


def _ln_vjp_fwd(x, gamma, beta, eps, block_rows, interpret):
    out = _ln_forward(x, gamma, beta, eps, block_rows, interpret)
    return out, (x, gamma, beta)


def _ln_vjp_bwd(eps, block_rows, interpret, res, g):
    x, gamma, beta = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    d = x.shape[-1]
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mean) ** 2, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = (xf - mean) * inv
    dgamma = jnp.sum(gf * xhat, axis=tuple(range(x.ndim - 1)))
    dbeta = jnp.sum(gf, axis=tuple(range(x.ndim - 1)))
    gy = gf * gamma.astype(jnp.float32)
    dx = inv * (gy - jnp.mean(gy, axis=-1, keepdims=True)
                - xhat * jnp.mean(gy * xhat, axis=-1, keepdims=True))
    # gradients match each primal's dtype (f32 master params keep f32 grads
    # even when activations are bf16)
    return (dx.astype(x.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(beta.dtype))


_fused_ln.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)
