"""Pallas (Mosaic) TPU kernels for the hot ops.

Reference analog: the native C/C++ kernel layer of BigDL —
``com.intel.analytics.bigdl.mkl.MKL`` (BLAS/VML JNI) and the
``bigdl-core`` int8 quantization kernels (SURVEY.md §3.2).  On TPU the
bulk of that role is played by XLA itself; this package holds the
hand-written kernels for what XLA does not fuse well:

- ``flash_attention`` — blockwise fused attention (online softmax, O(S)
  memory), the MXU-friendly replacement for materialised O(S²) attention.
- ``int8_matmul`` / ``quantize_int8`` — quantized inference gemm + abs-max
  calibration (reference: ``nn/quantized`` + bigdl-core int8 kernels).
- ``fused_layernorm`` — single-pass row-blocked LayerNorm.

Every kernel has an ``interpret`` escape hatch so the full test suite runs
on CPU (`interpret=True` under `--xla_force_host_platform_device_count`),
mirroring the reference's MKL-vs-pure-JVM fallback split.
"""

from bigdl_tpu.ops.common import on_tpu, default_interpret
from bigdl_tpu.ops.flash_attention import flash_attention
from bigdl_tpu.ops.quantized import (abs_max_scales, dequantize_int8,
                                     int8_matmul, quantize_int8,
                                     quantized_linear)
from bigdl_tpu.ops.fused import fused_layernorm
# block_sparse last: it reaches into nn/ (Module base), whose own
# quantized layer imports bigdl_tpu.ops.quantized — already in
# sys.modules by this point, so the cycle never bites
from bigdl_tpu.ops.block_sparse import (BlockPruningSchedule,
                                        BlockSparseLinear,
                                        block_sparse_matmul,
                                        prune_model_to_sparsity)

__all__ = [
    "on_tpu", "default_interpret", "flash_attention",
    "abs_max_scales", "quantize_int8", "dequantize_int8", "int8_matmul",
    "quantized_linear", "fused_layernorm",
    "block_sparse_matmul", "BlockSparseLinear", "BlockPruningSchedule",
    "prune_model_to_sparsity",
]
