"""Shared helpers for the Pallas kernel layer."""

import functools

import jax


@functools.lru_cache(maxsize=1)
def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


def default_interpret(interpret=None) -> bool:
    """Kernels compile with Mosaic on TPU, interpret everywhere else so the
    same code path is exercised by the CPU-simulated-mesh test suite."""
    if interpret is None:
        return not on_tpu()
    return bool(interpret)


def cdiv(a: int, b: int) -> int:
    return -(-a // b)


def round_up(a: int, b: int) -> int:
    return cdiv(a, b) * b
