"""Int8 quantized matmul + calibration — Pallas TPU kernels.

Reference analog: BigDL's post-training int8 inference path —
``nn/quantized/{Quantizer,Linear,SpatialConvolution}.scala`` backed by the
``bigdl-core`` native int8 gemm with abs-max calibration (SURVEY.md
§3.1/§3.2).  TPU-native redesign: symmetric per-channel weight
quantization + dynamic per-row activation quantization feeding an
int8×int8→int32 MXU matmul kernel, rescaled to float on the way out.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from bigdl_tpu.ops.common import default_interpret, round_up


def abs_max_scales(x, axis) -> jnp.ndarray:
    """Symmetric abs-max calibration: scale s.t. x/scale fits int8."""
    amax = jnp.max(jnp.abs(x), axis=axis, keepdims=False)
    return jnp.maximum(amax, 1e-8) / 127.0


def quantize_int8(w, axis: int = 0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-output-channel symmetric int8 quantization of a weight.

    ``axis`` is the reduction axis (the one contracted in the matmul); for a
    (in, out) Linear weight use axis=0 → per-out-channel scales (out,)."""
    scales = abs_max_scales(w, axis=axis)
    q = jnp.clip(jnp.round(w / jnp.expand_dims(scales, axis)), -127, 127)
    return q.astype(jnp.int8), scales.astype(jnp.float32)


def dequantize_int8(q, scales, axis: int = 0) -> jnp.ndarray:
    return q.astype(jnp.float32) * jnp.expand_dims(scales, axis)


def quantize_blockwise(x, block: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Blockwise symmetric int8 quantization along the LAST dimension.

    ``x`` is ``(..., L)`` with ``L % block == 0``; every length-``block``
    run gets its own abs-max scale, so one outlier only costs its own
    block's mantissa (the EQuARX-style gradient-compression granularity —
    PAPERS.md arXiv 2506.17615).  Returns ``(q int8 (..., L), scales f32
    (..., L // block))``.  Pure jnp — safe inside jit/shard_map."""
    lead, L = x.shape[:-1], x.shape[-1]
    if L % block != 0:
        raise ValueError(f"last dim {L} not a multiple of block {block}")
    xb = x.reshape(*lead, L // block, block)
    scales = abs_max_scales(xb, axis=-1)
    q = jnp.clip(jnp.round(xb / scales[..., None]), -127, 127)
    return q.astype(jnp.int8).reshape(*lead, L), scales.astype(jnp.float32)


def dequantize_blockwise(q, scales) -> jnp.ndarray:
    """Inverse of :func:`quantize_blockwise`: ``q (..., L)`` int8 +
    ``scales (..., L // block)`` → f32 ``(..., L)``.  The block size is
    implied by the shapes."""
    lead, L = q.shape[:-1], q.shape[-1]
    nb = scales.shape[-1]
    block = L // nb
    xb = q.astype(jnp.float32).reshape(*lead, nb, block)
    return (xb * scales[..., None]).reshape(*lead, L)


def quantize_pages(pages, floor_scales=None
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-page symmetric int8 quantization of KV page images.

    ``pages`` is ``(..., heads, page_size, head_dim)`` float32 — every
    trailing-3-dim page image gets ONE abs-max scale (the page-table
    granularity of docs/quantization.md §Serving memory hierarchy), so
    the scales ride the page table as a flat ``(..., )`` float32 array.
    Without ``floor_scales`` this is :func:`quantize_blockwise` with one
    block per page.

    ``floor_scales`` (shape = the returned scales) makes the scale
    MONOTONE within a page's occupancy: ``new = max(floor, amax/127)``.
    A page whose contents did not change since the last quantization
    requantizes EXACTLY under a monotone scale (``round(q·s / s) == q``),
    which is what makes the decode engine's whole-row write-back safe.
    A floor of 0.0 marks a freshly allocated page: until something is
    written, dequantize yields zeros regardless of the stale int8
    payload left by the page's previous owner."""
    lead, elems = pages.shape[:-3], int(
        pages.shape[-3] * pages.shape[-2] * pages.shape[-1])
    flat = pages.reshape(*lead, elems)
    if floor_scales is None:
        q, scales = quantize_blockwise(flat, elems)
        return q.reshape(pages.shape), scales[..., 0]
    amax = jnp.max(jnp.abs(flat), axis=-1)
    scales = jnp.maximum(amax / 127.0,
                         jnp.asarray(floor_scales, jnp.float32))
    safe = jnp.maximum(scales, 1e-12)[..., None]
    q = jnp.clip(jnp.round(flat / safe), -127, 127).astype(jnp.int8)
    return q.reshape(pages.shape), scales.astype(jnp.float32)


def dequantize_pages(q, scales) -> jnp.ndarray:
    """Inverse of :func:`quantize_pages`: int8 pages ``(..., h, p, hd)``
    + per-page scales ``(...,)`` → float32 pages."""
    return q.astype(jnp.float32) * scales[..., None, None, None]


def _int8_mm_kernel(x_ref, w_ref, o_ref):
    # x: (bm, bk) int8, w: (bk, bn) int8 → o: (bm, bn) int32; the K grid
    # dimension is innermost (sequential on-core), so the output block stays
    # resident and accumulates across K tiles.
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)

    # precision pinned to DEFAULT: a global jax_default_matmul_precision of
    # "highest" would stamp an fp32 contract precision onto this integer
    # matmul, which Mosaic rejects ("Bad lhs type").
    o_ref[:] += jax.lax.dot_general(
        x_ref[:], w_ref[:], dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
        precision=jax.lax.Precision.DEFAULT)


def int8_matmul(x_q, w_q, *, block_m: Optional[int] = None,
                block_n: Optional[int] = None,
                block_k: Optional[int] = None,
                interpret: Optional[bool] = None):
    """int8 (M,K) × int8 (K,N) → int32 (M,N) on the MXU, tiled on all
    three dimensions (one (bm,bk) + (bk,bn) tile pair in VMEM per step).

    ``block_*=None`` consults the autotune cache (defaults 256/256/512);
    explicit kwargs always win."""
    m, k = x_q.shape
    k2, n = w_q.shape
    assert k == k2, (x_q.shape, w_q.shape)
    if block_m is None or block_n is None or block_k is None:
        from bigdl_tpu.ops import autotune

        tiles = autotune.resolve(
            "int8_matmul", autotune.matmul_key(m, k, n, x_q.dtype),
            explicit={"block_m": block_m, "block_n": block_n,
                      "block_k": block_k},
            online_shape=((m, k, n) if autotune.is_concrete(x_q, w_q)
                          else None))
        block_m, block_n, block_k = (tiles["block_m"], tiles["block_n"],
                                     tiles["block_k"])
    bm = min(block_m, round_up(m, 32))
    bn = min(block_n, round_up(n, 128))
    bk = min(block_k, round_up(k, 128))
    mp, np_, kp = round_up(m, bm), round_up(n, bn), round_up(k, bk)
    xp = jnp.pad(x_q, ((0, mp - m), (0, kp - k)))
    wp = jnp.pad(w_q, ((0, kp - k), (0, np_ - n)))

    out = pl.pallas_call(
        _int8_mm_kernel,
        grid=(mp // bm, np_ // bn, kp // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.int32),
        interpret=default_interpret(interpret),
    )(xp, wp)
    return out[:m, :n]


def quantized_linear(x, w_q, w_scales, bias=None, act_scale=None,
                     interpret: Optional[bool] = None):
    """Dense layer with a pre-quantized (in, out) int8 weight.

    Activation quantization is **dynamic** per-row abs-max
    (``act_scale=None``), **static per-tensor** with a calibrated scalar
    scale (the reference's min/max-calibration path, SURVEY.md §3.2 —
    values beyond ±127·scale saturate), or **static per-channel** with a
    calibrated (K,) scale vector.  In the per-channel case the caller must
    have FOLDED the activation scales into the weight before quantizing it
    (``w'[k,n] = w[k,n]·s[k]``): then ``x_q·w'_q ≈ Σₖ (x/s)·(w·s)/sw`` and
    the output rescale is the weight scale alone.  The matmul always runs
    int8×int8→int32."""
    lead = x.shape[:-1]
    k = x.shape[-1]
    x2 = x.reshape(-1, k)
    per_channel_act = (act_scale is not None
                      and jnp.ndim(act_scale) == 1)
    if act_scale is None:
        sx = abs_max_scales(x2, axis=1)[:, None]  # (M, 1) dynamic
    elif per_channel_act:
        sx = jnp.asarray(act_scale, jnp.float32)[None, :]   # (1, K)
    else:
        sx = jnp.asarray(act_scale, jnp.float32)  # scalar, calibrated
    x_q = jnp.clip(jnp.round(x2 / sx), -127, 127).astype(jnp.int8)
    acc = int8_matmul(x_q, w_q, interpret=interpret)
    if per_channel_act:   # act scales already folded into w_q's rows
        y = acc.astype(jnp.float32) * w_scales[None, :]
    else:
        y = acc.astype(jnp.float32) * sx * w_scales[None, :]
    if bias is not None:
        y = y + bias
    return y.reshape(*lead, w_q.shape[1]).astype(x.dtype)
