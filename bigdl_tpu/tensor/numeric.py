"""Dtype policy — the TensorNumeric analog.

Reference analog (unverified — mount empty): BigDL's
``tensor/TensorNumeric.scala`` is a typeclass routing Float/Double math to MKL
JNI calls (``vsExp``/``sgemm``/...).  On TPU there is no JNI layer: every op
lowers to XLA.  What remains of TensorNumeric is the *policy*: which dtype
tensors default to, and which dtype matmuls/convs accumulate in.  bfloat16 is
the native MXU input type; float32 accumulation is XLA's default
(preferred_element_type) and what we use.
"""

import jax.numpy as jnp

_DEFAULT_DTYPE = jnp.float32


def set_default_dtype(dtype) -> None:
    global _DEFAULT_DTYPE
    _DEFAULT_DTYPE = jnp.dtype(dtype)


def get_default_dtype():
    return _DEFAULT_DTYPE


class TensorNumeric:
    """Named dtype bundles mirroring TensorNumeric.NumericFloat etc."""

    NumericFloat = jnp.float32
    NumericDouble = jnp.float64  # requires jax_enable_x64; kept for API parity
    NumericBFloat16 = jnp.bfloat16
    NumericInt = jnp.int32
    NumericBool = jnp.bool_
