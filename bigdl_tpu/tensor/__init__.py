from bigdl_tpu.tensor.tensor import Tensor
from bigdl_tpu.tensor.numeric import TensorNumeric, get_default_dtype, set_default_dtype

__all__ = ["Tensor", "TensorNumeric", "get_default_dtype", "set_default_dtype"]
