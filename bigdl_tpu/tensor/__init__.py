from bigdl_tpu.tensor.tensor import Tensor
from bigdl_tpu.tensor.numeric import TensorNumeric, get_default_dtype, set_default_dtype
from bigdl_tpu.tensor.sparse import SparseTensor, sparse_join

__all__ = ["Tensor", "TensorNumeric", "get_default_dtype",
           "set_default_dtype", "SparseTensor", "sparse_join"]
