"""Tensor — the user-facing n-d tensor facade over ``jax.Array``.

Reference analog (unverified — mount empty): ``dllib/tensor/Tensor.scala`` (a
~250-op trait), ``DenseTensor*.scala`` with strided views and MKL-backed BLAS.
TPU-native re-design decisions:

- **Functional, not mutating.**  The reference mutates storage in place
  (``addmm`` writes into ``this``); under XLA, in-place turns into
  copy-on-write anyway and blocks fusion.  Every op here returns a new Tensor;
  the in-place-named reference methods (``add_``-style) exist but return the
  new value.  Buffer reuse is delegated to XLA via donation at jit boundaries.
- **No strided-view machinery.**  ``narrow``/``select``/``transpose`` are
  lazy-view tricks in the reference to avoid copies on CPU; XLA fuses slices
  and transposes into consumers, so these are plain ops.
- **BLAS dispatch disappears.**  ``DenseTensorBLAS.gemm`` picking MKL kernels
  becomes ``jnp.matmul`` with ``preferred_element_type=float32`` — XLA tiles it
  onto the MXU.

The class exists for API parity and interactive use; the nn/optim hot path
works on raw ``jax.Array`` pytrees (a Tensor in a jitted function would only
add wrapper overhead at trace time — it unwraps transparently).
"""

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.tensor.numeric import get_default_dtype

ArrayLike = Union[jnp.ndarray, np.ndarray, float, int, Sequence]


def _unwrap(x: Any):
    return x.data if isinstance(x, Tensor) else x


@jax.tree_util.register_pytree_node_class
class Tensor:
    """Immutable n-d tensor. Thin wrapper over jax.Array with the reference's
    op names. Registered as a pytree so it can cross jit boundaries."""

    __slots__ = ("data",)
    __array_priority__ = 100  # win over numpy in mixed arithmetic

    def __init__(self, data: ArrayLike = None, dtype=None):
        if data is None:
            data = jnp.zeros((), dtype or get_default_dtype())
        self.data = jnp.asarray(_unwrap(data), dtype=dtype)

    # -- pytree -------------------------------------------------------------
    def tree_flatten(self):
        return (self.data,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        t = object.__new__(cls)
        t.data = children[0]
        return t

    # -- constructors -------------------------------------------------------
    @staticmethod
    def zeros(*size, dtype=None) -> "Tensor":
        return Tensor(jnp.zeros(_size(size), dtype or get_default_dtype()))

    @staticmethod
    def ones(*size, dtype=None) -> "Tensor":
        return Tensor(jnp.ones(_size(size), dtype or get_default_dtype()))

    @staticmethod
    def full(size, value, dtype=None) -> "Tensor":
        return Tensor(jnp.full(size, value, dtype or get_default_dtype()))

    @staticmethod
    def arange(start, stop=None, step=1, dtype=None) -> "Tensor":
        return Tensor(jnp.arange(start, stop, step, dtype))

    @staticmethod
    def eye(n, dtype=None) -> "Tensor":
        return Tensor(jnp.eye(n, dtype=dtype or get_default_dtype()))

    @staticmethod
    def rand(*size, key=None, dtype=None) -> "Tensor":
        key = _key(key)
        return Tensor(jax.random.uniform(key, _size(size), dtype or get_default_dtype()))

    @staticmethod
    def randn(*size, key=None, dtype=None) -> "Tensor":
        key = _key(key)
        return Tensor(jax.random.normal(key, _size(size), dtype or get_default_dtype()))

    # -- properties ---------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self.data.shape)

    @property
    def dtype(self):
        return self.data.dtype

    def size(self, dim: Optional[int] = None):
        return self.shape if dim is None else self.shape[dim]

    def dim(self) -> int:
        return self.data.ndim

    @property
    def ndim(self) -> int:
        return self.data.ndim

    def nelement(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    def numpy(self) -> np.ndarray:
        return np.asarray(self.data)

    def item(self) -> float:
        return self.data.item()

    def astype(self, dtype) -> "Tensor":
        return Tensor(self.data.astype(dtype))

    cast = astype

    # -- elementwise math ---------------------------------------------------
    def __add__(self, o):
        return Tensor(self.data + _unwrap(o))

    __radd__ = __add__

    def __sub__(self, o):
        return Tensor(self.data - _unwrap(o))

    def __rsub__(self, o):
        return Tensor(_unwrap(o) - self.data)

    def __mul__(self, o):
        return Tensor(self.data * _unwrap(o))

    __rmul__ = __mul__

    def __truediv__(self, o):
        return Tensor(self.data / _unwrap(o))

    def __rtruediv__(self, o):
        return Tensor(_unwrap(o) / self.data)

    def __pow__(self, o):
        return Tensor(self.data ** _unwrap(o))

    def __neg__(self):
        return Tensor(-self.data)

    def __matmul__(self, o):
        return self.matmul(o)

    def __getitem__(self, idx):
        idx = jax.tree_util.tree_map(_unwrap, idx)
        return Tensor(self.data[idx])

    # comparison (return bool tensors)
    def __lt__(self, o):
        return Tensor(self.data < _unwrap(o))

    def __le__(self, o):
        return Tensor(self.data <= _unwrap(o))

    def __gt__(self, o):
        return Tensor(self.data > _unwrap(o))

    def __ge__(self, o):
        return Tensor(self.data >= _unwrap(o))

    def eq(self, o):
        return Tensor(self.data == _unwrap(o))

    def add(self, o, alpha=1):
        return Tensor(self.data + alpha * _unwrap(o))

    def sub(self, o, alpha=1):
        return Tensor(self.data - alpha * _unwrap(o))

    def mul(self, o):
        return self * o

    def div(self, o):
        return self / o

    def cmul(self, o):  # reference name for elementwise multiply
        return self * o

    def cdiv(self, o):
        return self / o

    def pow(self, o):
        return self ** o

    def abs(self):
        return Tensor(jnp.abs(self.data))

    def sign(self):
        return Tensor(jnp.sign(self.data))

    def sqrt(self):
        return Tensor(jnp.sqrt(self.data))

    def rsqrt(self):
        return Tensor(jax.lax.rsqrt(self.data))

    def square(self):
        return Tensor(jnp.square(self.data))

    def exp(self):
        return Tensor(jnp.exp(self.data))

    def log(self):
        return Tensor(jnp.log(self.data))

    def log1p(self):
        return Tensor(jnp.log1p(self.data))

    def floor(self):
        return Tensor(jnp.floor(self.data))

    def ceil(self):
        return Tensor(jnp.ceil(self.data))

    def round(self):
        return Tensor(jnp.round(self.data))

    def tanh(self):
        return Tensor(jnp.tanh(self.data))

    def sigmoid(self):
        return Tensor(jax.nn.sigmoid(self.data))

    def erf(self):
        return Tensor(jax.lax.erf(self.data))

    def sin(self):
        return Tensor(jnp.sin(self.data))

    def cos(self):
        return Tensor(jnp.cos(self.data))

    def clamp(self, min_v, max_v):
        return Tensor(jnp.clip(self.data, min_v, max_v))

    clip = clamp

    def maximum(self, o):
        return Tensor(jnp.maximum(self.data, _unwrap(o)))

    cmax = maximum

    def minimum(self, o):
        return Tensor(jnp.minimum(self.data, _unwrap(o)))

    cmin = minimum

    # -- BLAS ---------------------------------------------------------------
    def matmul(self, o) -> "Tensor":
        return Tensor(
            jnp.matmul(self.data, _unwrap(o), preferred_element_type=jnp.float32).astype(
                jnp.result_type(self.dtype, _unwrap(o).dtype)
            )
        )

    def mm(self, o) -> "Tensor":
        return self.matmul(o)

    def mv(self, v) -> "Tensor":
        return self.matmul(v)

    def dot(self, o) -> "Tensor":
        return Tensor(jnp.vdot(self.data, _unwrap(o)))

    def bmm(self, o) -> "Tensor":
        return self.matmul(o)

    def addmm(self, mat1, mat2, beta=1.0, alpha=1.0) -> "Tensor":
        """beta*self + alpha*(mat1 @ mat2) — reference Tensor.addmm semantics,
        returned (not mutated)."""
        return Tensor(beta * self.data + alpha * _unwrap(Tensor(_unwrap(mat1)).matmul(mat2)))

    def addmv(self, mat, vec, beta=1.0, alpha=1.0) -> "Tensor":
        return self.addmm(mat, vec, beta=beta, alpha=alpha)

    def addcmul(self, t1, t2, value=1.0) -> "Tensor":
        return Tensor(self.data + value * _unwrap(t1) * _unwrap(t2))

    def addcdiv(self, t1, t2, value=1.0) -> "Tensor":
        return Tensor(self.data + value * _unwrap(t1) / _unwrap(t2))

    def outer(self, o) -> "Tensor":
        return Tensor(jnp.outer(self.data, _unwrap(o)))

    addr = outer

    # -- reductions ---------------------------------------------------------
    def sum(self, dim=None, keepdim=False) -> "Tensor":
        return Tensor(jnp.sum(self.data, axis=dim, keepdims=keepdim))

    def mean(self, dim=None, keepdim=False) -> "Tensor":
        return Tensor(jnp.mean(self.data, axis=dim, keepdims=keepdim))

    def max(self, dim=None, keepdim=False):
        if dim is None:
            return Tensor(jnp.max(self.data))
        return (
            Tensor(jnp.max(self.data, axis=dim, keepdims=keepdim)),
            Tensor(jnp.argmax(self.data, axis=dim, keepdims=keepdim)),
        )

    def min(self, dim=None, keepdim=False):
        if dim is None:
            return Tensor(jnp.min(self.data))
        return (
            Tensor(jnp.min(self.data, axis=dim, keepdims=keepdim)),
            Tensor(jnp.argmin(self.data, axis=dim, keepdims=keepdim)),
        )

    def argmax(self, dim=None) -> "Tensor":
        return Tensor(jnp.argmax(self.data, axis=dim))

    def argmin(self, dim=None) -> "Tensor":
        return Tensor(jnp.argmin(self.data, axis=dim))

    def prod(self, dim=None) -> "Tensor":
        return Tensor(jnp.prod(self.data, axis=dim))

    def cumsum(self, dim=0) -> "Tensor":
        return Tensor(jnp.cumsum(self.data, axis=dim))

    def norm(self, p=2) -> "Tensor":
        return Tensor(jnp.linalg.norm(self.data.ravel(), ord=p))

    def std(self, dim=None) -> "Tensor":
        return Tensor(jnp.std(self.data, axis=dim))

    def var(self, dim=None) -> "Tensor":
        return Tensor(jnp.var(self.data, axis=dim))

    def topk(self, k, dim=-1, largest=True):
        d = self.data if largest else -self.data
        vals, idx = jax.lax.top_k(jnp.moveaxis(d, dim, -1), k)
        if not largest:
            vals = -vals
        return Tensor(jnp.moveaxis(vals, -1, dim)), Tensor(jnp.moveaxis(idx, -1, dim))

    # -- shape ops ----------------------------------------------------------
    def view(self, *size) -> "Tensor":
        return Tensor(jnp.reshape(self.data, _size(size)))

    reshape = view

    def resize(self, *size) -> "Tensor":
        return self.view(*size)

    def transpose(self, d0: int, d1: int) -> "Tensor":
        return Tensor(jnp.swapaxes(self.data, d0, d1))

    def t(self) -> "Tensor":
        return Tensor(self.data.T)

    def permute(self, *dims) -> "Tensor":
        return Tensor(jnp.transpose(self.data, _size(dims)))

    def squeeze(self, dim=None) -> "Tensor":
        return Tensor(jnp.squeeze(self.data, axis=dim))

    def unsqueeze(self, dim: int) -> "Tensor":
        return Tensor(jnp.expand_dims(self.data, dim))

    def narrow(self, dim: int, start: int, length: int) -> "Tensor":
        idx = [slice(None)] * self.data.ndim
        idx[dim] = slice(start, start + length)
        return Tensor(self.data[tuple(idx)])

    def select(self, dim: int, index: int) -> "Tensor":
        return Tensor(jnp.take(self.data, index, axis=dim))

    def index_select(self, dim: int, index) -> "Tensor":
        return Tensor(jnp.take(self.data, _unwrap(index), axis=dim))

    def gather(self, dim: int, index) -> "Tensor":
        return Tensor(jnp.take_along_axis(self.data, _unwrap(index), axis=dim))

    def masked_fill(self, mask, value) -> "Tensor":
        return Tensor(jnp.where(_unwrap(mask), value, self.data))

    def masked_select(self, mask) -> "Tensor":
        return Tensor(self.data[_unwrap(mask)])

    def expand(self, *size) -> "Tensor":
        return Tensor(jnp.broadcast_to(self.data, _size(size)))

    def repeat(self, *reps) -> "Tensor":
        return Tensor(jnp.tile(self.data, _size(reps)))

    def flatten(self) -> "Tensor":
        return Tensor(self.data.ravel())

    def contiguous(self) -> "Tensor":
        return self  # XLA arrays are always logically contiguous

    def clone(self) -> "Tensor":
        return Tensor(self.data)

    def split(self, size_or_sections, dim=0):
        """split(k) -> chunks of size k (torch.split semantics)."""
        n = self.shape[dim]
        if isinstance(size_or_sections, int):
            points = list(range(size_or_sections, n, size_or_sections))
        else:
            points = list(np.cumsum(size_or_sections))[:-1]
        return [Tensor(a) for a in jnp.split(self.data, points, axis=dim)]

    def chunk(self, n_chunks: int, dim=0):
        """chunk(n) -> n chunks (torch/BigDL chunk semantics)."""
        n = self.shape[dim]
        size = -(-n // n_chunks)
        return self.split(size, dim)

    @staticmethod
    def cat(tensors, dim=0) -> "Tensor":
        return Tensor(jnp.concatenate([_unwrap(t) for t in tensors], axis=dim))

    concat = cat

    @staticmethod
    def stack(tensors, dim=0) -> "Tensor":
        return Tensor(jnp.stack([_unwrap(t) for t in tensors], axis=dim))

    # -- "mutating"-named ops (functional: return the new tensor) -----------
    def fill(self, value) -> "Tensor":
        return Tensor(jnp.full_like(self.data, value))

    def zero(self) -> "Tensor":
        return Tensor(jnp.zeros_like(self.data))

    def copy(self, src) -> "Tensor":
        return Tensor(jnp.broadcast_to(_unwrap(src), self.shape).astype(self.dtype))

    def set_index(self, idx, value) -> "Tensor":
        return Tensor(self.data.at[idx].set(_unwrap(value)))

    def add_index(self, idx, value) -> "Tensor":
        return Tensor(self.data.at[idx].add(_unwrap(value)))

    def scatter(self, dim: int, index, src) -> "Tensor":
        """Functional scatter along dim (take_along_axis inverse)."""
        idx = _unwrap(index)
        src_a = jnp.broadcast_to(_unwrap(src), idx.shape)
        # build open meshgrid of indices, replace `dim`
        grids = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
        grids[dim] = idx
        return Tensor(self.data.at[tuple(grids)].set(src_a))

    # -- misc ---------------------------------------------------------------
    def isnan(self) -> "Tensor":
        return Tensor(jnp.isnan(self.data))

    def almost_equal(self, o, tol=1e-5) -> bool:
        return bool(jnp.allclose(self.data, _unwrap(o), atol=tol, rtol=tol))

    def __repr__(self):
        return f"Tensor({self.data!r})"

    def __len__(self):
        return self.shape[0]


def _size(size) -> Tuple[int, ...]:
    if len(size) == 1 and isinstance(size[0], (tuple, list)):
        return tuple(size[0])
    return tuple(size)


_seed_counter = [0]


def _key(key):
    if key is not None:
        return key
    _seed_counter[0] += 1
    return jax.random.PRNGKey(_seed_counter[0])
