"""Tensor — the user-facing n-d tensor facade over ``jax.Array``.

Reference analog (unverified — mount empty): ``dllib/tensor/Tensor.scala`` (a
~250-op trait), ``DenseTensor*.scala`` with strided views and MKL-backed BLAS.
TPU-native re-design decisions:

- **Functional, not mutating.**  The reference mutates storage in place
  (``addmm`` writes into ``this``); under XLA, in-place turns into
  copy-on-write anyway and blocks fusion.  Every op here returns a new Tensor;
  the in-place-named reference methods (``add_``-style) exist but return the
  new value.  Buffer reuse is delegated to XLA via donation at jit boundaries.
- **No strided-view machinery.**  ``narrow``/``select``/``transpose`` are
  lazy-view tricks in the reference to avoid copies on CPU; XLA fuses slices
  and transposes into consumers, so these are plain ops.
- **BLAS dispatch disappears.**  ``DenseTensorBLAS.gemm`` picking MKL kernels
  becomes ``jnp.matmul`` with ``preferred_element_type=float32`` — XLA tiles it
  onto the MXU.

The class exists for API parity and interactive use; the nn/optim hot path
works on raw ``jax.Array`` pytrees (a Tensor in a jitted function would only
add wrapper overhead at trace time — it unwraps transparently).
"""

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.tensor.numeric import get_default_dtype

ArrayLike = Union[jnp.ndarray, np.ndarray, float, int, Sequence]


def _unwrap(x: Any):
    return x.data if isinstance(x, Tensor) else x


@jax.tree_util.register_pytree_node_class
class Tensor:
    """Immutable n-d tensor. Thin wrapper over jax.Array with the reference's
    op names. Registered as a pytree so it can cross jit boundaries."""

    __slots__ = ("data",)
    __array_priority__ = 100  # win over numpy in mixed arithmetic

    def __init__(self, data: ArrayLike = None, dtype=None):
        if data is None:
            data = jnp.zeros((), dtype or get_default_dtype())
        self.data = jnp.asarray(_unwrap(data), dtype=dtype)

    # -- pytree -------------------------------------------------------------
    def tree_flatten(self):
        return (self.data,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        t = object.__new__(cls)
        t.data = children[0]
        return t

    # -- constructors -------------------------------------------------------
    @staticmethod
    def zeros(*size, dtype=None) -> "Tensor":
        return Tensor(jnp.zeros(_size(size), dtype or get_default_dtype()))

    @staticmethod
    def ones(*size, dtype=None) -> "Tensor":
        return Tensor(jnp.ones(_size(size), dtype or get_default_dtype()))

    @staticmethod
    def full(size, value, dtype=None) -> "Tensor":
        return Tensor(jnp.full(size, value, dtype or get_default_dtype()))

    @staticmethod
    def arange(start, stop=None, step=1, dtype=None) -> "Tensor":
        return Tensor(jnp.arange(start, stop, step, dtype))

    @staticmethod
    def eye(n, dtype=None) -> "Tensor":
        return Tensor(jnp.eye(n, dtype=dtype or get_default_dtype()))

    @staticmethod
    def linspace(start, stop, steps, dtype=None) -> "Tensor":
        return Tensor(jnp.linspace(start, stop, steps,
                                   dtype=dtype or get_default_dtype()))

    @staticmethod
    def logspace(start, stop, steps, base=10.0, dtype=None) -> "Tensor":
        return Tensor(jnp.logspace(start, stop, steps, base=base,
                                   dtype=dtype or get_default_dtype()))

    @staticmethod
    def rand(*size, key=None, dtype=None) -> "Tensor":
        key = _key(key)
        return Tensor(jax.random.uniform(key, _size(size), dtype or get_default_dtype()))

    @staticmethod
    def randn(*size, key=None, dtype=None) -> "Tensor":
        key = _key(key)
        return Tensor(jax.random.normal(key, _size(size), dtype or get_default_dtype()))

    # -- properties ---------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self.data.shape)

    @property
    def dtype(self):
        return self.data.dtype

    def size(self, dim: Optional[int] = None):
        return self.shape if dim is None else self.shape[dim]

    def dim(self) -> int:
        return self.data.ndim

    @property
    def ndim(self) -> int:
        return self.data.ndim

    def nelement(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    def numpy(self) -> np.ndarray:
        return np.asarray(self.data)

    def item(self) -> float:
        return self.data.item()

    def astype(self, dtype) -> "Tensor":
        return Tensor(self.data.astype(dtype))

    cast = astype

    # -- elementwise math ---------------------------------------------------
    def __add__(self, o):
        return Tensor(self.data + _unwrap(o))

    __radd__ = __add__

    def __sub__(self, o):
        return Tensor(self.data - _unwrap(o))

    def __rsub__(self, o):
        return Tensor(_unwrap(o) - self.data)

    def __mul__(self, o):
        return Tensor(self.data * _unwrap(o))

    __rmul__ = __mul__

    def __truediv__(self, o):
        return Tensor(self.data / _unwrap(o))

    def __rtruediv__(self, o):
        return Tensor(_unwrap(o) / self.data)

    def __pow__(self, o):
        return Tensor(self.data ** _unwrap(o))

    def __neg__(self):
        return Tensor(-self.data)

    def __matmul__(self, o):
        return self.matmul(o)

    def __getitem__(self, idx):
        idx = jax.tree_util.tree_map(_unwrap, idx)
        return Tensor(self.data[idx])

    # comparison (return bool tensors)
    def __lt__(self, o):
        return Tensor(self.data < _unwrap(o))

    def __le__(self, o):
        return Tensor(self.data <= _unwrap(o))

    def __gt__(self, o):
        return Tensor(self.data > _unwrap(o))

    def __ge__(self, o):
        return Tensor(self.data >= _unwrap(o))

    def eq(self, o):
        return Tensor(self.data == _unwrap(o))

    def add(self, o, alpha=1):
        return Tensor(self.data + alpha * _unwrap(o))

    def sub(self, o, alpha=1):
        return Tensor(self.data - alpha * _unwrap(o))

    def mul(self, o):
        return self * o

    def div(self, o):
        return self / o

    def cmul(self, o):  # reference name for elementwise multiply
        return self * o

    def cdiv(self, o):
        return self / o

    def pow(self, o):
        return self ** o

    def abs(self):
        return Tensor(jnp.abs(self.data))

    def sign(self):
        return Tensor(jnp.sign(self.data))

    def sqrt(self):
        return Tensor(jnp.sqrt(self.data))

    def rsqrt(self):
        return Tensor(jax.lax.rsqrt(self.data))

    def square(self):
        return Tensor(jnp.square(self.data))

    def exp(self):
        return Tensor(jnp.exp(self.data))

    def log(self):
        return Tensor(jnp.log(self.data))

    def log1p(self):
        return Tensor(jnp.log1p(self.data))

    def floor(self):
        return Tensor(jnp.floor(self.data))

    def ceil(self):
        return Tensor(jnp.ceil(self.data))

    def round(self):
        return Tensor(jnp.round(self.data))

    def tanh(self):
        return Tensor(jnp.tanh(self.data))

    def sigmoid(self):
        return Tensor(jax.nn.sigmoid(self.data))

    def erf(self):
        return Tensor(jax.lax.erf(self.data))

    def sin(self):
        return Tensor(jnp.sin(self.data))

    def cos(self):
        return Tensor(jnp.cos(self.data))

    def tan(self):
        return Tensor(jnp.tan(self.data))

    def sinh(self):
        return Tensor(jnp.sinh(self.data))

    def cosh(self):
        return Tensor(jnp.cosh(self.data))

    def asin(self):
        return Tensor(jnp.arcsin(self.data))

    def acos(self):
        return Tensor(jnp.arccos(self.data))

    def atan(self):
        return Tensor(jnp.arctan(self.data))

    def atan2(self, o):
        return Tensor(jnp.arctan2(self.data, _unwrap(o)))

    def asinh(self):
        return Tensor(jnp.arcsinh(self.data))

    def acosh(self):
        return Tensor(jnp.arccosh(self.data))

    def atanh(self):
        return Tensor(jnp.arctanh(self.data))

    def log2(self):
        return Tensor(jnp.log2(self.data))

    def log10(self):
        return Tensor(jnp.log10(self.data))

    def expm1(self):
        return Tensor(jnp.expm1(self.data))

    def erfc(self):
        return Tensor(jax.lax.erfc(self.data))

    def lgamma(self):
        return Tensor(jax.lax.lgamma(self.data))

    def digamma(self):
        return Tensor(jax.lax.digamma(self.data))

    def frac(self):
        """Fractional part with the sign of the input (torch ``frac``)."""
        return Tensor(self.data - jnp.trunc(self.data))

    def trunc(self):
        return Tensor(jnp.trunc(self.data))

    def reciprocal(self):
        return Tensor(1.0 / self.data)

    inv = reciprocal

    def neg(self):
        return Tensor(-self.data)

    def remainder(self, o):
        """Python/torch ``remainder``: result has the divisor's sign."""
        return Tensor(jnp.remainder(self.data, _unwrap(o)))

    def fmod(self, o):
        """C ``fmod``: result has the dividend's sign."""
        return Tensor(jnp.fmod(self.data, _unwrap(o)))

    def lerp(self, end, weight):
        return Tensor(self.data + weight * (_unwrap(end) - self.data))

    def clamp(self, min_v, max_v):
        return Tensor(jnp.clip(self.data, min_v, max_v))

    clip = clamp

    def clamp_min(self, v):
        return Tensor(jnp.maximum(self.data, v))

    def clamp_max(self, v):
        return Tensor(jnp.minimum(self.data, v))

    def maximum(self, o):
        return Tensor(jnp.maximum(self.data, _unwrap(o)))

    cmax = maximum

    def minimum(self, o):
        return Tensor(jnp.minimum(self.data, _unwrap(o)))

    cmin = minimum

    # -- BLAS ---------------------------------------------------------------
    def matmul(self, o) -> "Tensor":
        return Tensor(
            jnp.matmul(self.data, _unwrap(o), preferred_element_type=jnp.float32).astype(
                jnp.result_type(self.dtype, _unwrap(o).dtype)
            )
        )

    def mm(self, o) -> "Tensor":
        return self.matmul(o)

    def mv(self, v) -> "Tensor":
        return self.matmul(v)

    def dot(self, o) -> "Tensor":
        return Tensor(jnp.vdot(self.data, _unwrap(o)))

    def bmm(self, o) -> "Tensor":
        return self.matmul(o)

    def addmm(self, mat1, mat2, beta=1.0, alpha=1.0) -> "Tensor":
        """beta*self + alpha*(mat1 @ mat2) — reference Tensor.addmm semantics,
        returned (not mutated)."""
        return Tensor(beta * self.data + alpha * _unwrap(Tensor(_unwrap(mat1)).matmul(mat2)))

    def addmv(self, mat, vec, beta=1.0, alpha=1.0) -> "Tensor":
        return self.addmm(mat, vec, beta=beta, alpha=alpha)

    def addcmul(self, t1, t2, value=1.0) -> "Tensor":
        return Tensor(self.data + value * _unwrap(t1) * _unwrap(t2))

    def addcdiv(self, t1, t2, value=1.0) -> "Tensor":
        return Tensor(self.data + value * _unwrap(t1) / _unwrap(t2))

    def outer(self, o) -> "Tensor":
        return Tensor(jnp.outer(self.data, _unwrap(o)))

    addr = outer

    # -- reductions ---------------------------------------------------------
    def sum(self, dim=None, keepdim=False) -> "Tensor":
        return Tensor(jnp.sum(self.data, axis=dim, keepdims=keepdim))

    def mean(self, dim=None, keepdim=False) -> "Tensor":
        return Tensor(jnp.mean(self.data, axis=dim, keepdims=keepdim))

    def max(self, dim=None, keepdim=False):
        if dim is None:
            return Tensor(jnp.max(self.data))
        return (
            Tensor(jnp.max(self.data, axis=dim, keepdims=keepdim)),
            Tensor(jnp.argmax(self.data, axis=dim, keepdims=keepdim)),
        )

    def min(self, dim=None, keepdim=False):
        if dim is None:
            return Tensor(jnp.min(self.data))
        return (
            Tensor(jnp.min(self.data, axis=dim, keepdims=keepdim)),
            Tensor(jnp.argmin(self.data, axis=dim, keepdims=keepdim)),
        )

    def argmax(self, dim=None) -> "Tensor":
        return Tensor(jnp.argmax(self.data, axis=dim))

    def argmin(self, dim=None) -> "Tensor":
        return Tensor(jnp.argmin(self.data, axis=dim))

    def prod(self, dim=None) -> "Tensor":
        return Tensor(jnp.prod(self.data, axis=dim))

    def cumsum(self, dim=0) -> "Tensor":
        return Tensor(jnp.cumsum(self.data, axis=dim))

    def norm(self, p=2) -> "Tensor":
        return Tensor(jnp.linalg.norm(self.data.ravel(), ord=p))

    def std(self, dim=None) -> "Tensor":
        return Tensor(jnp.std(self.data, axis=dim))

    def var(self, dim=None) -> "Tensor":
        return Tensor(jnp.var(self.data, axis=dim))

    def topk(self, k, dim=-1, largest=True):
        d = self.data if largest else -self.data
        vals, idx = jax.lax.top_k(jnp.moveaxis(d, dim, -1), k)
        if not largest:
            vals = -vals
        return Tensor(jnp.moveaxis(vals, -1, dim)), Tensor(jnp.moveaxis(idx, -1, dim))

    def cumprod(self, dim=0) -> "Tensor":
        return Tensor(jnp.cumprod(self.data, axis=dim))

    def median(self, dim=None) -> "Tensor":
        return Tensor(jnp.median(self.data, axis=dim))

    def kthvalue(self, k: int, dim=-1):
        """(values, indices) of the k-th SMALLEST along dim (1-indexed,
        torch semantics)."""
        order = jnp.argsort(self.data, axis=dim)
        idx = jnp.take(order, k - 1, axis=dim)
        vals = jnp.take_along_axis(
            self.data, jnp.expand_dims(idx, dim), axis=dim).squeeze(dim)
        return Tensor(vals), Tensor(idx)

    def sort(self, dim=-1, descending=False):
        d = -self.data if descending else self.data
        idx = jnp.argsort(d, axis=dim)
        vals = jnp.take_along_axis(self.data, idx, axis=dim)
        return Tensor(vals), Tensor(idx)

    def argsort(self, dim=-1, descending=False) -> "Tensor":
        d = -self.data if descending else self.data
        return Tensor(jnp.argsort(d, axis=dim))

    def all(self, dim=None) -> "Tensor":
        return Tensor(jnp.all(self.data, axis=dim))

    def any(self, dim=None) -> "Tensor":
        return Tensor(jnp.any(self.data, axis=dim))

    def count_nonzero(self, dim=None) -> "Tensor":
        return Tensor(jnp.count_nonzero(self.data, axis=dim))

    def nansum(self, dim=None) -> "Tensor":
        return Tensor(jnp.nansum(self.data, axis=dim))

    def nanmean(self, dim=None) -> "Tensor":
        return Tensor(jnp.nanmean(self.data, axis=dim))

    def dist(self, o, p=2) -> "Tensor":
        return Tensor(jnp.linalg.norm(
            (self.data - _unwrap(o)).ravel(), ord=p))

    def renorm(self, p: float, dim: int, max_norm: float) -> "Tensor":
        """Reference ``renorm``: scale sub-tensors along ``dim`` whose
        p-norm exceeds ``max_norm`` down to it."""
        moved = jnp.moveaxis(self.data, dim, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.linalg.norm(flat, ord=p, axis=1)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale[:, None]
        return Tensor(jnp.moveaxis(out.reshape(moved.shape), 0, dim))

    # -- shape ops ----------------------------------------------------------
    def view(self, *size) -> "Tensor":
        return Tensor(jnp.reshape(self.data, _size(size)))

    reshape = view

    def resize(self, *size) -> "Tensor":
        return self.view(*size)

    def transpose(self, d0: int, d1: int) -> "Tensor":
        return Tensor(jnp.swapaxes(self.data, d0, d1))

    def t(self) -> "Tensor":
        return Tensor(self.data.T)

    def permute(self, *dims) -> "Tensor":
        return Tensor(jnp.transpose(self.data, _size(dims)))

    def squeeze(self, dim=None) -> "Tensor":
        return Tensor(jnp.squeeze(self.data, axis=dim))

    def unsqueeze(self, dim: int) -> "Tensor":
        return Tensor(jnp.expand_dims(self.data, dim))

    def narrow(self, dim: int, start: int, length: int) -> "Tensor":
        idx = [slice(None)] * self.data.ndim
        idx[dim] = slice(start, start + length)
        return Tensor(self.data[tuple(idx)])

    def select(self, dim: int, index: int) -> "Tensor":
        return Tensor(jnp.take(self.data, index, axis=dim))

    def index_select(self, dim: int, index) -> "Tensor":
        return Tensor(jnp.take(self.data, _unwrap(index), axis=dim))

    def gather(self, dim: int, index) -> "Tensor":
        return Tensor(jnp.take_along_axis(self.data, _unwrap(index), axis=dim))

    def masked_fill(self, mask, value) -> "Tensor":
        return Tensor(jnp.where(_unwrap(mask), value, self.data))

    def masked_select(self, mask) -> "Tensor":
        return Tensor(self.data[_unwrap(mask)])

    def expand(self, *size) -> "Tensor":
        return Tensor(jnp.broadcast_to(self.data, _size(size)))

    def repeat(self, *reps) -> "Tensor":
        return Tensor(jnp.tile(self.data, _size(reps)))

    def flatten(self) -> "Tensor":
        return Tensor(self.data.ravel())

    def contiguous(self) -> "Tensor":
        return self  # XLA arrays are always logically contiguous

    def clone(self) -> "Tensor":
        return Tensor(self.data)

    def split(self, size_or_sections, dim=0):
        """split(k) -> chunks of size k (torch.split semantics)."""
        n = self.shape[dim]
        if isinstance(size_or_sections, int):
            points = list(range(size_or_sections, n, size_or_sections))
        else:
            points = list(np.cumsum(size_or_sections))[:-1]
        return [Tensor(a) for a in jnp.split(self.data, points, axis=dim)]

    def chunk(self, n_chunks: int, dim=0):
        """chunk(n) -> n chunks (torch/BigDL chunk semantics)."""
        n = self.shape[dim]
        size = -(-n // n_chunks)
        return self.split(size, dim)

    @staticmethod
    def cat(tensors, dim=0) -> "Tensor":
        return Tensor(jnp.concatenate([_unwrap(t) for t in tensors], axis=dim))

    concat = cat

    @staticmethod
    def stack(tensors, dim=0) -> "Tensor":
        return Tensor(jnp.stack([_unwrap(t) for t in tensors], axis=dim))

    # -- "mutating"-named ops (functional: return the new tensor) -----------
    def fill(self, value) -> "Tensor":
        return Tensor(jnp.full_like(self.data, value))

    def zero(self) -> "Tensor":
        return Tensor(jnp.zeros_like(self.data))

    def copy(self, src) -> "Tensor":
        return Tensor(jnp.broadcast_to(_unwrap(src), self.shape).astype(self.dtype))

    def set_index(self, idx, value) -> "Tensor":
        return Tensor(self.data.at[idx].set(_unwrap(value)))

    def add_index(self, idx, value) -> "Tensor":
        return Tensor(self.data.at[idx].add(_unwrap(value)))

    def scatter(self, dim: int, index, src) -> "Tensor":
        """Functional scatter along dim (take_along_axis inverse)."""
        idx = _unwrap(index)
        src_a = jnp.broadcast_to(_unwrap(src), idx.shape)
        # build open meshgrid of indices, replace `dim`
        grids = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
        grids[dim] = idx
        return Tensor(self.data.at[tuple(grids)].set(src_a))

    def scatter_add(self, dim: int, index, src) -> "Tensor":
        idx = _unwrap(index)
        src_a = jnp.broadcast_to(_unwrap(src), idx.shape)
        grids = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape],
                             indexing="ij")
        grids[dim] = idx
        return Tensor(self.data.at[tuple(grids)].add(src_a))

    def index_fill(self, dim: int, index, value) -> "Tensor":
        idx = [slice(None)] * self.data.ndim
        idx[dim] = _unwrap(index)
        return Tensor(self.data.at[tuple(idx)].set(value))

    def index_copy(self, dim: int, index, src) -> "Tensor":
        idx = [slice(None)] * self.data.ndim
        idx[dim] = _unwrap(index)
        return Tensor(self.data.at[tuple(idx)].set(_unwrap(src)))

    def index_add(self, dim: int, index, src) -> "Tensor":
        idx = [slice(None)] * self.data.ndim
        idx[dim] = _unwrap(index)
        return Tensor(self.data.at[tuple(idx)].add(_unwrap(src)))

    def take(self, index) -> "Tensor":
        return Tensor(jnp.take(self.data.ravel(), _unwrap(index)))

    # -- structure / linalg --------------------------------------------------
    def diag(self, k: int = 0) -> "Tensor":
        return Tensor(jnp.diag(self.data, k=k))

    def triu(self, k: int = 0) -> "Tensor":
        return Tensor(jnp.triu(self.data, k=k))

    def tril(self, k: int = 0) -> "Tensor":
        return Tensor(jnp.tril(self.data, k=k))

    def trace(self) -> "Tensor":
        return Tensor(jnp.trace(self.data))

    def cross(self, o, dim=-1) -> "Tensor":
        return Tensor(jnp.cross(self.data, _unwrap(o), axis=dim))

    def kron(self, o) -> "Tensor":
        return Tensor(jnp.kron(self.data, _unwrap(o)))

    def flip(self, dim) -> "Tensor":
        return Tensor(jnp.flip(self.data, axis=dim))

    def roll(self, shifts, dim=None) -> "Tensor":
        return Tensor(jnp.roll(self.data, shifts, axis=dim))

    def rot90(self, k: int = 1, dims=(0, 1)) -> "Tensor":
        return Tensor(jnp.rot90(self.data, k=k, axes=dims))

    def tile(self, reps) -> "Tensor":
        return Tensor(jnp.tile(self.data, reps))

    def repeat_interleave(self, repeats: int, dim: Optional[int] = None
                          ) -> "Tensor":
        return Tensor(jnp.repeat(self.data, repeats, axis=dim))

    def unfold(self, dim: int, size: int, step: int) -> "Tensor":
        """Sliding windows along ``dim`` (torch ``unfold``): the window
        axis lands last."""
        n = (self.data.shape[dim] - size) // step + 1
        starts = jnp.arange(n) * step
        moved = jnp.moveaxis(self.data, dim, 0)
        win = jax.vmap(
            lambda s: jax.lax.dynamic_slice_in_dim(moved, s, size, 0))(starts)
        # win: (n, size, *rest) -> (n, *rest, size), then restore dim
        win = jnp.moveaxis(win, 1, -1)
        return Tensor(jnp.moveaxis(win, 0, dim))

    def baddbmm(self, b1, b2, beta: float = 1.0, alpha: float = 1.0
                ) -> "Tensor":
        prod = jnp.matmul(_unwrap(b1), _unwrap(b2),
                          preferred_element_type=jnp.float32)
        return Tensor((beta * self.data.astype(jnp.float32)
                       + alpha * prod).astype(self.dtype))

    def inverse(self) -> "Tensor":
        return Tensor(jnp.linalg.inv(self.data))

    def det(self) -> "Tensor":
        return Tensor(jnp.linalg.det(self.data))

    def svd(self):
        u, s, vt = jnp.linalg.svd(self.data, full_matrices=False)
        return Tensor(u), Tensor(s), Tensor(vt)

    def qr(self):
        q, r = jnp.linalg.qr(self.data)
        return Tensor(q), Tensor(r)

    def cholesky(self) -> "Tensor":
        return Tensor(jnp.linalg.cholesky(self.data))

    def solve(self, b) -> "Tensor":
        return Tensor(jnp.linalg.solve(self.data, _unwrap(b)))

    def matrix_power(self, n: int) -> "Tensor":
        return Tensor(jnp.linalg.matrix_power(self.data, n))

    # -- random (explicit keys: the TPU PRNG discipline) ---------------------
    def bernoulli(self, p: float = 0.5, key=None) -> "Tensor":
        return Tensor(jax.random.bernoulli(
            _key(key), p, self.shape).astype(self.dtype))

    def uniform(self, low: float = 0.0, high: float = 1.0, key=None
                ) -> "Tensor":
        return Tensor(jax.random.uniform(
            _key(key), self.shape, self.dtype if jnp.issubdtype(
                self.dtype, jnp.floating) else jnp.float32,
            minval=low, maxval=high))

    def normal(self, mean: float = 0.0, std: float = 1.0, key=None
               ) -> "Tensor":
        return Tensor(mean + std * jax.random.normal(
            _key(key), self.shape,
            self.dtype if jnp.issubdtype(self.dtype, jnp.floating)
            else jnp.float32))

    def multinomial(self, num_samples: int, replacement: bool = False,
                    key=None) -> "Tensor":
        """Sample category indices from unnormalized row weights:
        (C,) → (num_samples,); (B, C) → (B, num_samples).  Default is
        WITHOUT replacement, matching ``torch.multinomial`` (Gumbel top-k:
        argtop of log-weights + Gumbel noise is a weighted sample without
        replacement)."""
        logits = jnp.log(jnp.maximum(self.data, 1e-30))
        if replacement:
            if logits.ndim == 1:
                return Tensor(jax.random.categorical(
                    _key(key), logits, shape=(num_samples,)))
            s = jax.random.categorical(
                _key(key), logits, shape=(num_samples,) + logits.shape[:-1])
            return Tensor(jnp.moveaxis(s, 0, -1))
        if num_samples > logits.shape[-1]:
            raise ValueError(
                f"multinomial without replacement: num_samples "
                f"{num_samples} > categories {logits.shape[-1]}")
        # torch raises when a row lacks enough NONZERO weights to fill the
        # draw; zero weights are masked to -inf so they can never win top_k
        logits = jnp.where(self.data > 0, logits, -jnp.inf)
        try:
            nz = int(jnp.min(jnp.sum(self.data > 0, axis=-1)))
            if num_samples > nz:
                raise ValueError(
                    f"multinomial without replacement: num_samples "
                    f"{num_samples} > nonzero categories {nz}")
        except jax.errors.ConcretizationTypeError:
            pass  # traced: the -inf mask still keeps zeros last in top_k
        g = jax.random.gumbel(_key(key), logits.shape, jnp.float32)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return Tensor(idx)

    # -- misc ---------------------------------------------------------------
    def isnan(self) -> "Tensor":
        return Tensor(jnp.isnan(self.data))

    def isinf(self) -> "Tensor":
        return Tensor(jnp.isinf(self.data))

    def isfinite(self) -> "Tensor":
        return Tensor(jnp.isfinite(self.data))

    def ne(self, o) -> "Tensor":
        return Tensor(self.data != _unwrap(o))

    def equal(self, o) -> bool:
        """Exact whole-tensor equality (reference ``equal``)."""
        o = _unwrap(o)
        return bool(self.data.shape == o.shape
                    and jnp.all(self.data == o))

    def almost_equal(self, o, tol=1e-5) -> bool:
        return bool(jnp.allclose(self.data, _unwrap(o), atol=tol, rtol=tol))

    def __repr__(self):
        return f"Tensor({self.data!r})"

    def __len__(self):
        return self.shape[0]


def _size(size) -> Tuple[int, ...]:
    if len(size) == 1 and isinstance(size[0], (tuple, list)):
        return tuple(size[0])
    return tuple(size)


_seed_counter = [0]


def _key(key):
    if key is not None:
        return key
    _seed_counter[0] += 1
    return jax.random.PRNGKey(_seed_counter[0])


def _tensor_tail_ops():
    """Late-bound tranche (keeps the class body above readable)."""

    def bincount(self, minlength: int = 0, weights=None) -> "Tensor":
        # static length: jnp.bincount needs a bound; use max+1 eagerly like
        # torch (data-dependent — not for use under jit)
        n = int(jnp.max(self.data)) + 1 if self.data.size else 0
        length = max(n, minlength)
        return Tensor(jnp.bincount(
            self.data.astype(jnp.int32).ravel(),
            weights=None if weights is None else _unwrap(weights).ravel(),
            length=length))

    def histc(self, bins: int = 100, min: float = 0.0, max: float = 0.0
              ) -> "Tensor":
        lo, hi = float(min), float(max)
        if lo == 0.0 and hi == 0.0:
            lo = float(jnp.min(self.data))
            hi = float(jnp.max(self.data))
        hist, _ = jnp.histogram(self.data.ravel(), bins=bins,
                                range=(lo, hi))
        return Tensor(hist.astype(jnp.float32))

    def where(self, condition, other) -> "Tensor":
        return Tensor(jnp.where(_unwrap(condition), self.data,
                                _unwrap(other)))

    def logsumexp(self, dim: int, keepdim: bool = False) -> "Tensor":
        return Tensor(jax.nn.logsumexp(self.data, axis=dim,
                                       keepdims=keepdim))

    def softmax(self, dim: int = -1) -> "Tensor":
        return Tensor(jax.nn.softmax(self.data, axis=dim))

    def diagonal(self, offset: int = 0, dim1: int = 0, dim2: int = 1
                 ) -> "Tensor":
        return Tensor(jnp.diagonal(self.data, offset=offset, axis1=dim1,
                                   axis2=dim2))

    for fn in (bincount, histc, where, logsumexp, softmax, diagonal):
        setattr(Tensor, fn.__name__, fn)


_tensor_tail_ops()
