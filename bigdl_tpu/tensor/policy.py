"""Mixed-precision policy.

TPU-first stance: params live in float32, matmuls/convs run with bfloat16
inputs and float32 accumulation (native MXU mode) — so the DEFAULT compute
dtype is bfloat16 on TPU and float32 elsewhere (CPU test meshes keep full
precision for golden comparisons).  The reference has no such policy (MKL
float32 everywhere); this replaces the engineType ``mklblas|mkldnn`` switch
(dllib/utils/Engine.scala, unverified) as the "which compute path" knob.
"""

from contextlib import contextmanager

import jax.numpy as jnp

# None = resolve lazily from the platform on first use (importing jax.devices
# at module import time would initialize the backend too early).
_COMPUTE_DTYPE = [None]


def _platform_default():
    try:
        import jax

        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover — backend init failure
        on_tpu = False
    return jnp.dtype(jnp.bfloat16) if on_tpu else jnp.dtype(jnp.float32)


def set_compute_dtype(dtype) -> None:
    _COMPUTE_DTYPE[0] = None if dtype is None else jnp.dtype(dtype)


def get_compute_dtype():
    if _COMPUTE_DTYPE[0] is None:
        _COMPUTE_DTYPE[0] = _platform_default()
    return _COMPUTE_DTYPE[0]


@contextmanager
def compute_dtype(dtype):
    old = _COMPUTE_DTYPE[0]
    set_compute_dtype(dtype)
    try:
        yield
    finally:
        _COMPUTE_DTYPE[0] = old


def cast_compute(*arrays):
    """Cast op inputs to the compute dtype (no-op when already matching)."""
    dt = get_compute_dtype()
    out = tuple(a.astype(dt) if a.dtype != dt else a for a in arrays)
    return out if len(out) > 1 else out[0]
