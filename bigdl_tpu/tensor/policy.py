"""Mixed-precision policy.

TPU-first stance: params live in float32, matmuls/convs run with bfloat16
inputs and float32 accumulation (native MXU mode).  The reference has no such
policy (MKL float32 everywhere); this replaces the engineType
``mklblas|mkldnn`` switch (dllib/utils/Engine.scala, unverified) as the
"which compute path" knob.
"""

from contextlib import contextmanager

import jax.numpy as jnp

_COMPUTE_DTYPE = [jnp.float32]


def set_compute_dtype(dtype) -> None:
    _COMPUTE_DTYPE[0] = jnp.dtype(dtype)


def get_compute_dtype():
    return _COMPUTE_DTYPE[0]


@contextmanager
def compute_dtype(dtype):
    old = _COMPUTE_DTYPE[0]
    set_compute_dtype(dtype)
    try:
        yield
    finally:
        _COMPUTE_DTYPE[0] = old


def cast_compute(*arrays):
    """Cast op inputs to the compute dtype (no-op when already matching)."""
    dt = _COMPUTE_DTYPE[0]
    out = tuple(a.astype(dt) if a.dtype != dt else a for a in arrays)
    return out if len(out) > 1 else out[0]
