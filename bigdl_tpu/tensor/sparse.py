"""Sparse tensor (COO) — TPU-native re-design of the reference SparseTensor.

Reference analog (unverified — mount empty): ``dllib/tensor/SparseTensor.
scala`` — CSR-ish 2-D sparse tensor used by ``nn/SparseLinear`` and
``nn/SparseJoinTable`` for wide (recsys) models.

TPU-first constraints drive the design:

- **Static nnz.** XLA wants static shapes, so a ``SparseTensor`` carries a
  fixed-capacity ``(nnz,)`` values array + ``(nnz, 2)`` indices array; unused
  slots are padded with ``value 0`` at row 0 (a zero value contributes
  nothing to any contraction, so padding is mathematically inert).
- **Contractions become gather + segment-sum**, the idiomatic TPU lowering
  for embedding-style sparse work: ``y[r] += v * W[c]`` is
  ``segment_sum(values[:, None] * W[cols], rows)`` — one dense gather feeding
  one dense scatter-add, both HBM-bandwidth-bound and jit-compatible (no
  dynamic shapes, no host loops like the reference's per-element JVM walk).
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class SparseTensor:
    """2-D COO sparse matrix (rows = batch) with fixed nnz capacity."""

    def __init__(self, indices, values, shape: Tuple[int, int]):
        self.indices = jnp.asarray(indices, jnp.int32)   # (nnz, 2) [row, col]
        self.values = jnp.asarray(values)                # (nnz,)
        self.shape = tuple(shape)
        if self.indices.ndim != 2 or self.indices.shape[-1] != 2:
            raise ValueError(f"indices must be (nnz, 2), got {self.indices.shape}")
        if self.values.shape[0] != self.indices.shape[0]:
            raise ValueError("values/indices nnz mismatch")

    @property
    def nnz(self) -> int:
        return self.values.shape[0]

    @property
    def dtype(self):
        return self.values.dtype

    # ---- constructors -----------------------------------------------------
    @staticmethod
    def from_dense(dense, nnz: Optional[int] = None) -> "SparseTensor":
        """Host-side conversion (data-pipeline use, not for inside jit)."""
        d = np.asarray(dense)
        rows, cols = np.nonzero(d)
        vals = d[rows, cols]
        cap = nnz if nnz is not None else len(vals)
        if len(vals) > cap:
            raise ValueError(f"dense has {len(vals)} nonzeros > capacity {cap}")
        pad = cap - len(vals)
        idx = np.concatenate(
            [np.stack([rows, cols], -1),
             np.zeros((pad, 2), np.int64)]).astype(np.int32)
        v = np.concatenate([vals, np.zeros((pad,), d.dtype)])
        return SparseTensor(idx, v, d.shape)

    def to_dense(self):
        out = jnp.zeros(self.shape, self.values.dtype)
        return out.at[self.indices[:, 0], self.indices[:, 1]].add(self.values)

    # ---- ops ---------------------------------------------------------------
    def matmul(self, dense):
        """(N, D)·(D, O) → (N, O) via gather + segment-sum."""
        if dense.shape[0] != self.shape[1]:
            # without this, XLA gather clamps OOB cols → silent garbage
            raise ValueError(
                f"matmul shape mismatch: sparse (N, {self.shape[1]}) @ "
                f"dense {tuple(dense.shape)}")
        rows = self.indices[:, 0]
        cols = self.indices[:, 1]
        gathered = dense[cols] * self.values[:, None]          # (nnz, O)
        if gathered.dtype in (jnp.bfloat16, jnp.float16):
            # accumulate in f32 like the dense layers' preferred_element_type
            # — bf16 segment-sum over wide rows loses digits
            gathered = gathered.astype(jnp.float32)
        return jax.ops.segment_sum(gathered, rows,
                                   num_segments=self.shape[0])

    def __matmul__(self, dense):
        return self.matmul(dense)

    def row_sum(self):
        return jax.ops.segment_sum(self.values, self.indices[:, 0],
                                   num_segments=self.shape[0])

    def scale(self, s) -> "SparseTensor":
        return SparseTensor(self.indices, self.values * s, self.shape)

    def __repr__(self):
        return (f"SparseTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def sparse_join(tensors, total_cols: Optional[int] = None) -> SparseTensor:
    """Concatenate sparse tensors along the feature (col) axis — reference
    ``nn/SparseJoinTable.scala``."""
    n = tensors[0].shape[0]
    for t in tensors:
        if t.shape[0] != n:
            raise ValueError("row-count mismatch in sparse_join")
    offset = 0
    idx_parts, val_parts = [], []
    for t in tensors:
        shifted = t.indices.at[:, 1].add(offset)
        # keep padding slots inert: col offset on a zero-value slot is fine
        idx_parts.append(shifted)
        val_parts.append(t.values)
        offset += t.shape[1]
    cols = total_cols if total_cols is not None else offset
    if cols < offset:
        raise ValueError(
            f"total_cols={cols} < combined column width {offset}")
    return SparseTensor(jnp.concatenate(idx_parts),
                        jnp.concatenate(val_parts), (n, cols))


# register as a pytree so SparseTensor can cross jit boundaries
def _flatten(t: SparseTensor):
    return (t.indices, t.values), t.shape


def _unflatten(shape, children):
    # trusted fast path: transforms may unflatten with non-array leaves
    # (ShapeDtypeStruct under eval_shape, tracers under jit) — skip the
    # validating constructor entirely
    idx, vals = children
    t = object.__new__(SparseTensor)
    t.indices = idx
    t.values = vals
    t.shape = tuple(shape)
    return t


jax.tree_util.register_pytree_node(SparseTensor, _flatten, _unflatten)
