"""Sequence model zoo: char-RNN, Seq2Seq.

Reference analog (unverified — mount empty): ``dllib/models/rnn/`` (PTB
char/word LM: LookupTable -> Recurrent(LSTM) -> TimeDistributed(Linear) ->
LogSoftMax) and the Seq2Seq Recurrent+RecurrentDecoder path named in
BASELINE.json config 3; ``dllib/models/autoencoder/``."""

from typing import Optional

from bigdl_tpu import nn


def char_rnn(vocab_size: int, embed_dim: int = 64, hidden: int = 128,
             layers: int = 1) -> nn.Sequential:
    """Character/word LM — logits per timestep."""
    mods = [nn.Embedding(vocab_size, embed_dim)]
    d = embed_dim
    for _ in range(layers):
        mods.append(nn.LSTM(d, hidden))
        d = hidden
    mods += [nn.TimeDistributed(nn.Linear(hidden, vocab_size)),
             nn.LogSoftMax()]
    return nn.Sequential(mods)


class Seq2Seq(nn.Module):
    """Encoder LSTM -> autoregressive decoder — the reference's
    Recurrent + RecurrentDecoder composition."""

    def __init__(self, input_dim: int, hidden: int, output_len: int,
                 output_dim: Optional[int] = None, name=None):
        super().__init__(name)
        self.encoder = nn.LSTM(input_dim, hidden, return_sequences=False)
        self.decoder = nn.RecurrentDecoder(
            nn.LSTM(hidden, hidden), seq_length=output_len)
        self.head = nn.TimeDistributed(nn.Linear(hidden, output_dim or
                                                 input_dim))

    def init(self, rng, x):
        import jax

        k1, k2, k3 = jax.random.split(rng, 3)
        ve = self.encoder.init(k1, x)
        h, _ = self.encoder.apply(ve, x)
        vd = self.decoder.init(k2, h)
        y, _ = self.decoder.apply(vd, h)
        vh = self.head.init(k3, y)
        return {"params": {"enc": ve["params"], "dec": vd["params"],
                           "head": vh["params"]}, "state": {}}

    def forward(self, params, state, x, training=False, rng=None):
        h, _ = self.encoder.forward(params["enc"], {}, x, training=training,
                                    rng=rng)
        y, _ = self.decoder.forward(params["dec"], {}, h, training=training,
                                    rng=rng)
        out, _ = self.head.forward(params["head"], {}, y, training=training,
                                   rng=rng)
        return out, {}


