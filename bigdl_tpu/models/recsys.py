"""Recsys model zoo: NCF (NeuMF) and Wide&Deep.

Reference analog (unverified — mount empty): the BigDL model zoo's
``NeuralCF`` (python ``models/recommendation/neuralcf.py``, the SoCC'19 BigDL
paper's headline NCF workload) and ``WideAndDeep``
(``models/recommendation/wide_n_deep.py``), both Keras-style models in the
reference.

TPU-native: embeddings are plain gathers; the GMF ⊙ and MLP towers fuse into
the surrounding matmuls under XLA.  The wide half of Wide&Deep consumes a
:class:`SparseTensor` through :class:`SparseLinear` (gather + segment-sum)."""

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn.module import EMPTY, Module
from bigdl_tpu.nn.sparse_layers import SparseLinear


class NeuralCF(Module):
    """NeuMF: GMF (elementwise product of user/item embeddings) + MLP tower,
    concatenated into a prediction head.

    Inputs: ``(user_ids, item_ids)`` int arrays of shape (N,).
    Output: (N, 1) score in (0,1) when ``include_sigmoid`` (rating/CTR) or raw
    logits otherwise (for CrossEntropy ranking losses use ``class_num``)."""

    def __init__(self, user_count: int, item_count: int,
                 embed_dim: int = 16, mlp_dims: Sequence[int] = (64, 32, 16),
                 class_num: int = 1, include_sigmoid: bool = True, name=None):
        super().__init__(name)
        self.user_count = user_count
        self.item_count = item_count
        self.embed_dim = embed_dim
        self.mlp_dims = tuple(mlp_dims)
        self.class_num = class_num
        self.include_sigmoid = include_sigmoid and class_num == 1

        self.user_embed_gmf = nn.Embedding(user_count, embed_dim)
        self.item_embed_gmf = nn.Embedding(item_count, embed_dim)
        self.user_embed_mlp = nn.Embedding(user_count, embed_dim)
        self.item_embed_mlp = nn.Embedding(item_count, embed_dim)
        mlp = []
        for d in self.mlp_dims:
            mlp += [nn.Linear(None, d), nn.ReLU()]
        self.mlp = nn.Sequential(mlp)
        self.head = nn.Linear(None, class_num)

    def build(self, rng, users, items):
        ks = jax.random.split(rng, 6)
        p = {
            "ue_gmf": self.user_embed_gmf.build(ks[0], users)[0],
            "ie_gmf": self.item_embed_gmf.build(ks[1], items)[0],
            "ue_mlp": self.user_embed_mlp.build(ks[2], users)[0],
            "ie_mlp": self.item_embed_mlp.build(ks[3], items)[0],
        }
        u_mlp = p["ue_mlp"]["weight"][users.astype(jnp.int32)]
        i_mlp = p["ie_mlp"]["weight"][items.astype(jnp.int32)]
        mlp_in = jnp.concatenate([u_mlp, i_mlp], -1)
        v_mlp = self.mlp.init(ks[4], mlp_in)
        p["mlp"] = v_mlp["params"]
        mlp_out, _ = self.mlp.apply(v_mlp, mlp_in)
        gmf = u_mlp[..., :self.embed_dim] * i_mlp[..., :self.embed_dim]
        head_in = jnp.concatenate([gmf, mlp_out], -1)
        p["head"] = self.head.build(ks[5], head_in)[0]
        return p, EMPTY

    def forward(self, params, state, users, items, training=False, rng=None):
        u = users.astype(jnp.int32)
        i = items.astype(jnp.int32)
        gmf = (params["ue_gmf"]["weight"][u]
               * params["ie_gmf"]["weight"][i])
        mlp_in = jnp.concatenate([params["ue_mlp"]["weight"][u],
                                  params["ie_mlp"]["weight"][i]], -1)
        mlp_out, _ = self.mlp.forward(params["mlp"], EMPTY, mlp_in,
                                      training=training, rng=rng)
        y, _ = self.head.forward(params["head"], EMPTY,
                                 jnp.concatenate([gmf, mlp_out], -1))
        if self.include_sigmoid:
            y = jax.nn.sigmoid(y)
        return y, EMPTY


class WideAndDeep(Module):
    """Wide (sparse cross features through SparseLinear) & Deep (categorical
    embeddings + dense features through an MLP), summed into logits.

    Inputs: ``(wide_sparse, deep_cat, deep_dense)`` where ``wide_sparse`` is a
    SparseTensor (N, wide_dim), ``deep_cat`` int (N, n_cat_fields) of
    categorical ids, ``deep_dense`` float (N, dense_dim)."""

    def __init__(self, wide_dim: int, cat_cardinalities: Sequence[int],
                 dense_dim: int, embed_dim: int = 8,
                 hidden: Sequence[int] = (64, 32), class_num: int = 1,
                 include_sigmoid: bool = True, name=None):
        super().__init__(name)
        self.wide = SparseLinear(wide_dim, class_num)
        self.cat_cardinalities = tuple(cat_cardinalities)
        self.embeds = [nn.Embedding(c, embed_dim)
                       for c in self.cat_cardinalities]
        self.dense_dim = dense_dim
        deep = []
        for h in hidden:
            deep += [nn.Linear(None, h), nn.ReLU()]
        deep.append(nn.Linear(None, class_num))
        self.deep = nn.Sequential(deep)
        self.include_sigmoid = include_sigmoid and class_num == 1

    def build(self, rng, wide_sp, deep_cat, deep_dense):
        ks = jax.random.split(rng, 3 + len(self.embeds))
        p = {"wide": self.wide.build(ks[0], wide_sp)[0]}
        emb_ps = []
        parts = []
        for f, emb in enumerate(self.embeds):
            ep = emb.build(ks[1 + f], deep_cat[:, f])[0]
            emb_ps.append(ep)
            parts.append(ep["weight"][deep_cat[:, f].astype(jnp.int32)])
        p["embeds"] = emb_ps
        deep_in = jnp.concatenate(parts + [deep_dense], -1)
        p["deep"] = self.deep.init(ks[-1], deep_in)["params"]
        return p, EMPTY

    def forward(self, params, state, wide_sp, deep_cat, deep_dense,
                training=False, rng=None):
        wide_y, _ = self.wide.forward(params["wide"], EMPTY, wide_sp)
        parts = [ep["weight"][deep_cat[:, f].astype(jnp.int32)]
                 for f, ep in enumerate(params["embeds"])]
        deep_in = jnp.concatenate(parts + [deep_dense], -1)
        deep_y, _ = self.deep.forward(params["deep"], EMPTY, deep_in,
                                      training=training, rng=rng)
        y = wide_y + deep_y
        if self.include_sigmoid:
            y = jax.nn.sigmoid(y)
        return y, EMPTY


class TwoTower(Module):
    """Two-tower retrieval model — the reference Friesian stack's recall
    model (its FeatureTable builds user histories for exactly this; the
    serving side's recall service does MIPS over the item tower's
    embeddings, `friesian/serving.py`).

    User tower: user-id embedding + mean-pooled history-item embeddings →
    MLP.  Item tower: item-id embedding (+ optional category) → MLP.
    Towers produce L2-normalized d-dim vectors; training score is their
    dot product (in-batch softmax or BCE on sampled pairs).

    Inputs: ``(user_ids (N,), hist_item_ids (N, H), item_ids (N,))`` —
    history padded with 0 (id 0 reserved for padding, masked out of the
    mean).  ``encode_users``/``encode_items`` expose the towers for
    offline embedding export into the recall service."""

    def __init__(self, n_users: int, n_items: int, dim: int = 32,
                 hidden: Sequence[int] = (64,), name=None):
        super().__init__(name)
        self.n_users = n_users
        self.n_items = n_items
        self.dim = dim
        self.hidden = tuple(hidden)

    def build(self, rng, user_ids, hist, item_ids):
        ks = jax.random.split(rng, 4 + 2 * len(self.hidden))
        d = self.dim
        params = {
            "user_emb": jax.random.normal(ks[0], (self.n_users, d)) * 0.05,
            "item_emb": jax.random.normal(ks[1], (self.n_items, d)) * 0.05,
        }
        ki = 2
        for tower in ("u", "i"):
            din = 2 * d if tower == "u" else d
            for li, h in enumerate(self.hidden):
                params[f"{tower}w{li}"] = jax.random.normal(
                    ks[ki], (din, h)) * jnp.sqrt(2.0 / din)
                params[f"{tower}b{li}"] = jnp.zeros((h,))
                din = h
                ki += 1
            params[f"{tower}w_out"] = jax.random.normal(
                ks[ki], (din, d)) * jnp.sqrt(1.0 / din)
            ki += 1
        return params, EMPTY

    def _tower(self, params, x, tower):
        for li in range(len(self.hidden)):
            x = jax.nn.relu(
                jnp.matmul(x, params[f"{tower}w{li}"])
                + params[f"{tower}b{li}"])
        v = jnp.matmul(x, params[f"{tower}w_out"])
        return v / (jnp.linalg.norm(v, axis=-1, keepdims=True) + 1e-8)

    def encode_users(self, params, user_ids, hist):
        ue = jnp.take(params["user_emb"], user_ids.astype(jnp.int32), axis=0)
        he = jnp.take(params["item_emb"], hist.astype(jnp.int32), axis=0)
        mask = (hist > 0).astype(he.dtype)[..., None]
        pooled = (he * mask).sum(1) / (mask.sum(1) + 1e-8)
        return self._tower(params, jnp.concatenate([ue, pooled], -1), "u")

    def encode_items(self, params, item_ids):
        ie = jnp.take(params["item_emb"], item_ids.astype(jnp.int32), axis=0)
        return self._tower(params, ie, "i")

    def forward(self, params, state, user_ids, hist, item_ids,
                training=False, rng=None):
        u = self.encode_users(params, user_ids, hist)
        v = self.encode_items(params, item_ids)
        # in-batch sampled-softmax logits: (N, N) of u_i . v_j — the
        # standard two-tower training objective (targets = arange(N))
        return jnp.matmul(u, v.T) * 10.0, EMPTY


class DIEN(Module):
    """Deep Interest Evolution Network (DIN/DIEN family) — the ranking
    model the reference Friesian FeatureTable's ``add_hist_seq`` exists to
    feed.  TPU-native shape: interest extraction is ONE scan-GRU over the
    padded history, interest evolution is attention between the target
    item and the GRU states (AUGRU simplified to attention-weighted
    pooling of evolution states — compiler-friendly, no per-step host
    control flow), head is an MLP over [user, target, evolved interest].

    Inputs: ``(user_ids (N,), hist_item_ids (N, H), target_item_ids (N,))``
    with 0-padded history.  Output: (N, 1) CTR logit.
    """

    def __init__(self, n_users: int, n_items: int, dim: int = 24,
                 gru_hidden: int = 24, hidden: Sequence[int] = (64, 32),
                 name=None):
        super().__init__(name)
        self.n_users = n_users
        self.n_items = n_items
        self.dim = dim
        self.gru = nn.GRU(dim, gru_hidden, return_sequences=True)
        self.hidden = tuple(hidden)
        self.gru_hidden = gru_hidden

    def init(self, rng, user_ids, hist, target_ids):
        ks = jax.random.split(rng, 6 + len(self.hidden))
        d, gh = self.dim, self.gru_hidden
        he = jnp.zeros((hist.shape[0], hist.shape[1], d))
        params = {
            "user_emb": jax.random.normal(ks[0], (self.n_users, d)) * 0.05,
            "item_emb": jax.random.normal(ks[1], (self.n_items, d)) * 0.05,
            "gru": self.gru.init(ks[2], he)["params"],
            # attention: score = v . tanh(W [state; target; state*target])
            "att_w": jax.random.normal(ks[3], (2 * gh + d, gh)) * 0.1,
            "att_b": jnp.zeros((gh,)),
            "att_v": jax.random.normal(ks[4], (gh,)) * 0.1,
        }
        din = d + d + gh
        for li, h in enumerate(self.hidden):
            params[f"w{li}"] = jax.random.normal(
                ks[5 + li], (din, h)) * jnp.sqrt(2.0 / din)
            params[f"b{li}"] = jnp.zeros((h,))
            din = h
        params["w_out"] = jax.random.normal(ks[-1], (din, 1)) * 0.1
        params["b_out"] = jnp.zeros((1,))
        return {"params": params, "state": EMPTY}

    def forward(self, params, state, user_ids, hist, target_ids,
                training=False, rng=None):
        ue = jnp.take(params["user_emb"], user_ids.astype(jnp.int32), axis=0)
        te = jnp.take(params["item_emb"], target_ids.astype(jnp.int32),
                      axis=0)
        he = jnp.take(params["item_emb"], hist.astype(jnp.int32), axis=0)
        mask = (hist > 0).astype(he.dtype)                     # (N, H)
        # interest extraction (masked scan-GRU; padded steps freeze state)
        states, _ = self.gru.forward(params["gru"], EMPTY, he, mask=mask)
        # interest evolution: target-conditioned attention over GRU states
        gh = states.shape[-1]
        tb = jnp.broadcast_to(te[:, None, :], he.shape)
        # align target to state width for the product term
        t_pad = jnp.pad(tb, ((0, 0), (0, 0), (0, max(0, gh - tb.shape[-1])))
                        )[..., :gh]
        feats = jnp.concatenate([states, tb, states * t_pad], axis=-1)
        scores = jnp.einsum(
            "nhk,k->nh",
            jnp.tanh(jnp.einsum("nhf,fk->nhk", feats, params["att_w"])
                     + params["att_b"]),
            params["att_v"])
        scores = jnp.where(mask > 0, scores, -1e30)
        att = jax.nn.softmax(scores, axis=-1)
        # fully-masked rows (no history) contribute a zero interest vector
        att = att * mask
        interest = jnp.einsum("nh,nhk->nk", att, states)
        x = jnp.concatenate([ue, te, interest], axis=-1)
        for li in range(len(self.hidden)):
            x = jax.nn.relu(jnp.matmul(x, params[f"w{li}"])
                            + params[f"b{li}"])
        return jnp.matmul(x, params["w_out"]) + params["b_out"], EMPTY
