"""MaskRCNN family — ResNet-FPN backbone, RPN, box head, mask head.

Reference analog (unverified — mount empty): ``dllib/models/maskrcnn/
MaskRCNN.scala`` + supporting layers (RegionProposal, Pooler, BoxHead,
MaskHead in the upstream 2.x layout).  The reference runs dynamic-length
JVM loops per image; this build is **fully static-shape** so the whole
detector compiles to one XLA program: fixed proposal count (top-K + padded
NMS), all-levels RoIAlign with per-box level select, fixed ``max_detections``
outputs with a validity mask.

Layout: images NHWC; boxes (y1, x1, y2, x2) in image coordinates.

Inference:

    model = maskrcnn_resnet50(num_classes=81)
    variables = model.init(rng, images)         # images (1, H, W, 3)
    det, _ = model.apply(variables, images)
    det["boxes"/"scores"/"classes"/"masks"/"valid"]

Training uses the functional losses (``rpn_loss``, ``detection_loss``) over
head outputs — see tests/test_maskrcnn.py.
"""

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import EMPTY, Module
from bigdl_tpu.models.resnet import Bottleneck, _conv_bn
from bigdl_tpu.ops import detection as D


# ---------------------------------------------------------------------------
# backbone with multi-scale taps
# ---------------------------------------------------------------------------


class ResNetC2345(Module):
    """ResNet-50 trunk returning (C2, C3, C4, C5) feature maps
    (strides 4/8/16/32)."""

    def __init__(self, depth_blocks=(3, 4, 6, 3), name=None):
        super().__init__(name)
        self.stem = nn.Sequential(_conv_bn(3, 64, 7, stride=2)
                                  + [nn.MaxPool2D(3, 2, padding=1)])
        self.stages = []
        cin = 64
        for stage, (width, blocks) in enumerate(
                zip([64, 128, 256, 512], depth_blocks)):
            mods = []
            for b in range(blocks):
                stride = 2 if (stage > 0 and b == 0) else 1
                mods.append(Bottleneck(cin, width, stride))
                cin = width * Bottleneck.expansion
            self.stages.append(nn.Sequential(mods))

    def init(self, rng, x):
        ks = jax.random.split(rng, 5)
        v = {"stem": self.stem.init(ks[0], x)}
        y, _ = self.stem.apply(v["stem"], x)
        for i, st in enumerate(self.stages):
            v[f"c{i + 2}"] = st.init(ks[i + 1], y)
            y, _ = st.apply(v[f"c{i + 2}"], y)
        return {"params": {k: vv["params"] for k, vv in v.items()},
                "state": {k: vv["state"] for k, vv in v.items()}}

    def forward(self, params, state, x, training=False, rng=None):
        new_state = {}
        y, st = self.stem.forward(params["stem"], state["stem"], x,
                                  training=training)
        new_state["stem"] = st or state["stem"]
        outs = []
        for i, stg in enumerate(self.stages):
            k = f"c{i + 2}"
            y, st = stg.forward(params[k], state[k], y, training=training)
            new_state[k] = st or state[k]
            outs.append(y)
        return tuple(outs), new_state


class FPN(Module):
    """Feature Pyramid Network: 1x1 laterals + top-down nearest upsample +
    3x3 smoothing, producing P2..P5 at ``channels`` each."""

    def __init__(self, in_channels: Sequence[int] = (256, 512, 1024, 2048),
                 channels: int = 256, name=None):
        super().__init__(name)
        self.channels = channels
        self.lat = [nn.Conv2D(c, channels, 1) for c in in_channels]
        self.out = [nn.Conv2D(channels, channels, 3, padding="SAME")
                    for _ in in_channels]

    def init(self, rng, feats):
        ks = jax.random.split(rng, 2 * len(self.lat))
        params = {}
        for i, (l, o, f) in enumerate(zip(self.lat, self.out, feats)):
            params[f"lat{i}"] = l.init(ks[2 * i], f)["params"]
            params[f"out{i}"] = o.init(
                ks[2 * i + 1], jnp.zeros(f.shape[:-1] + (self.channels,),
                                         f.dtype))["params"]
        return {"params": params, "state": EMPTY}

    def forward(self, params, state, feats, training=False, rng=None):
        lats = [l.forward(params[f"lat{i}"], EMPTY, f)[0]
                for i, (l, f) in enumerate(zip(self.lat, feats))]
        # top-down pathway
        ps = [None] * len(lats)
        ps[-1] = lats[-1]
        for i in range(len(lats) - 2, -1, -1):
            up = jnp.repeat(jnp.repeat(ps[i + 1], 2, axis=1), 2, axis=2)
            up = up[:, : lats[i].shape[1], : lats[i].shape[2], :]
            ps[i] = lats[i] + up
        outs = tuple(
            o.forward(params[f"out{i}"], EMPTY, p)[0]
            for i, (o, p) in enumerate(zip(self.out, ps)))
        return outs, EMPTY


# ---------------------------------------------------------------------------
# heads
# ---------------------------------------------------------------------------


class RPNHead(Module):
    """Shared conv + per-anchor objectness / box deltas, applied to every
    pyramid level."""

    def __init__(self, channels: int = 256, num_anchors: int = 3, name=None):
        super().__init__(name)
        self.conv = nn.Conv2D(channels, channels, 3, padding="SAME")
        self.cls = nn.Conv2D(channels, num_anchors, 1,
                             weight_init=init_mod.random_normal(0.0, 0.01))
        self.reg = nn.Conv2D(channels, num_anchors * 4, 1,
                             weight_init=init_mod.random_normal(0.0, 0.01))

    def init(self, rng, feats):
        k1, k2, k3 = jax.random.split(rng, 3)
        f = feats[0]
        return {"params": {
            "conv": self.conv.init(k1, f)["params"],
            "cls": self.cls.init(k2, f)["params"],
            "reg": self.reg.init(k3, f)["params"],
        }, "state": EMPTY}

    def forward(self, params, state, feats, training=False, rng=None):
        logits, deltas = [], []
        for f in feats:
            h = jax.nn.relu(self.conv.forward(params["conv"], EMPTY, f)[0])
            lg = self.cls.forward(params["cls"], EMPTY, h)[0]
            dl = self.reg.forward(params["reg"], EMPTY, h)[0]
            n = f.shape[0]
            logits.append(lg.reshape(n, -1))
            deltas.append(dl.reshape(n, -1, 4))
        return (jnp.concatenate(logits, axis=1),
                jnp.concatenate(deltas, axis=1)), EMPTY


class BoxHead(Module):
    """RoI features (P, 7, 7, C) -> 2xFC -> class logits + per-class box
    deltas."""

    def __init__(self, num_classes: int, channels: int = 256,
                 fc_dim: int = 1024, pool: int = 7, name=None):
        super().__init__(name)
        self.num_classes = num_classes
        self.fc1 = nn.Linear(pool * pool * channels, fc_dim)
        self.fc2 = nn.Linear(fc_dim, fc_dim)
        self.cls = nn.Linear(fc_dim, num_classes,
                             weight_init=init_mod.random_normal(0.0, 0.01))
        self.reg = nn.Linear(fc_dim, num_classes * 4,
                             weight_init=init_mod.random_normal(0.0, 0.001))

    def init(self, rng, rois):
        ks = jax.random.split(rng, 4)
        flat = rois.reshape(rois.shape[0], -1)
        v1 = self.fc1.init(ks[0], flat)
        h = jnp.zeros((rois.shape[0], self.fc1.out_features))
        return {"params": {
            "fc1": v1["params"],
            "fc2": self.fc2.init(ks[1], h)["params"],
            "cls": self.cls.init(ks[2], h)["params"],
            "reg": self.reg.init(ks[3], h)["params"],
        }, "state": EMPTY}

    def forward(self, params, state, rois, training=False, rng=None):
        h = rois.reshape(rois.shape[0], -1)
        h = jax.nn.relu(self.fc1.forward(params["fc1"], EMPTY, h)[0])
        h = jax.nn.relu(self.fc2.forward(params["fc2"], EMPTY, h)[0])
        logits = self.cls.forward(params["cls"], EMPTY, h)[0]
        deltas = self.reg.forward(params["reg"], EMPTY, h)[0]
        return (logits, deltas.reshape(-1, self.num_classes, 4)), EMPTY


class MaskHead(Module):
    """RoI features (P, 14, 14, C) -> 4x conv -> deconv x2 -> per-class
    28x28 mask logits."""

    def __init__(self, num_classes: int, channels: int = 256, name=None):
        super().__init__(name)
        self.convs = [nn.Conv2D(channels, channels, 3, padding="SAME")
                      for _ in range(4)]
        self.deconv = nn.Conv2DTranspose(channels, channels, 2, stride=2,
                                         padding="SAME")
        self.out = nn.Conv2D(channels, num_classes, 1,
                             weight_init=init_mod.random_normal(0.0, 0.01))

    def init(self, rng, rois):
        ks = jax.random.split(rng, 6)
        params = {}
        h = rois
        for i, c in enumerate(self.convs):
            params[f"conv{i}"] = c.init(ks[i], h)["params"]
        params["deconv"] = self.deconv.init(ks[4], h)["params"]
        h2 = jnp.zeros((h.shape[0], h.shape[1] * 2, h.shape[2] * 2,
                        h.shape[3]))
        params["out"] = self.out.init(ks[5], h2)["params"]
        return {"params": params, "state": EMPTY}

    def forward(self, params, state, rois, training=False, rng=None):
        h = rois
        for i, c in enumerate(self.convs):
            h = jax.nn.relu(c.forward(params[f"conv{i}"], EMPTY, h)[0])
        h = jax.nn.relu(self.deconv.forward(params["deconv"], EMPTY, h)[0])
        return self.out.forward(params["out"], EMPTY, h)[0], EMPTY


# ---------------------------------------------------------------------------
# full model
# ---------------------------------------------------------------------------


class MaskRCNN(Module):
    """Two-stage detector with mask branch, end-to-end static shapes.

    Single-image batch (B=1) inference path; the training losses below work
    on the head outputs directly (the reference trains per-image too)."""

    STRIDES = (4, 8, 16, 32)
    SIZES = (32.0, 64.0, 128.0, 256.0)

    def __init__(self, num_classes: int, image_size: Sequence[int] = (512, 512),
                 pre_nms_topk: int = 512, num_proposals: int = 128,
                 max_detections: int = 32, with_mask: bool = True,
                 score_threshold: float = 0.05, nms_iou: float = 0.5,
                 name=None):
        super().__init__(name)
        self.num_classes = num_classes
        self.image_size = tuple(image_size)
        if any(s % self.STRIDES[-1] for s in self.image_size):
            # anchor grids use exact H//stride; SAME-padded convs round up,
            # so non-multiple sizes would silently misalign anchors with RPN
            # outputs
            raise ValueError(
                f"image_size {self.image_size} must be a multiple of "
                f"{self.STRIDES[-1]} (pad the input)")
        self.pre_nms_topk = pre_nms_topk
        self.num_proposals = num_proposals
        self.max_detections = max_detections
        self.with_mask = with_mask
        self.score_threshold = score_threshold
        self.nms_iou = nms_iou

        self.backbone = ResNetC2345()
        self.fpn = FPN()
        self.rpn = RPNHead()
        self.box_head = BoxHead(num_classes)
        self.mask_head = MaskHead(num_classes) if with_mask else None

        h, w = self.image_size
        feat_sizes = [(h // s, w // s) for s in self.STRIDES]
        self.anchors = D.generate_anchors(feat_sizes, self.STRIDES,
                                          self.SIZES)

    # -- init ---------------------------------------------------------------
    def init(self, rng, x):
        ks = jax.random.split(rng, 5)
        v = {"backbone": self.backbone.init(ks[0], x)}
        feats, _ = self.backbone.apply(v["backbone"], x)
        v["fpn"] = self.fpn.init(ks[1], feats)
        ps, _ = self.fpn.apply(v["fpn"], feats)
        v["rpn"] = self.rpn.init(ks[2], ps)
        c = ps[0].shape[-1]
        v["box_head"] = self.box_head.init(
            ks[3], jnp.zeros((self.num_proposals, 7, 7, c)))
        if self.mask_head is not None:
            v["mask_head"] = self.mask_head.init(
                ks[4], jnp.zeros((self.max_detections, 14, 14, c)))
        return {"params": {k: vv["params"] for k, vv in v.items()},
                "state": {k: vv.get("state") or {} for k, vv in v.items()}}

    # -- pieces (used by both inference and the training losses) -----------
    def features(self, params, state, x, training=False):
        feats, bb_state = self.backbone.forward(
            params["backbone"], state["backbone"], x, training=training)
        ps, _ = self.fpn.forward(params["fpn"], EMPTY, feats)
        return ps, bb_state

    def rpn_outputs(self, params, ps):
        (logits, deltas), _ = self.rpn.forward(params["rpn"], EMPTY, ps)
        return logits[0], deltas[0]  # B=1

    def proposals(self, logits, deltas):
        """Top-K anchors by objectness -> decode -> clip -> NMS -> fixed
        ``num_proposals`` boxes (padded; validity via scores)."""
        h, w = self.image_size
        k = min(self.pre_nms_topk, logits.shape[0])
        top_scores, top_idx = jax.lax.top_k(logits, k)
        top_boxes = D.decode_boxes(deltas[top_idx],
                                   jnp.asarray(self.anchors)[top_idx])
        top_boxes = D.clip_boxes(top_boxes, h, w)
        keep, valid = D.nms_padded(top_boxes, top_scores, 0.7,
                                   self.num_proposals)
        boxes = top_boxes[keep] * valid[:, None]
        return jax.lax.stop_gradient(boxes), valid

    def detections(self, params, ps, prop_boxes, prop_valid):
        rois = D.multilevel_roi_align(
            [p[0] for p in ps], prop_boxes, 7, self.STRIDES)
        (cls_logits, box_deltas), _ = self.box_head.forward(
            params["box_head"], EMPTY, rois)
        probs = jax.nn.softmax(cls_logits, axis=-1)
        # best non-background class per proposal (class 0 = background)
        fg = probs[:, 1:]
        best_cls = jnp.argmax(fg, axis=-1) + 1
        best_score = jnp.max(fg, axis=-1) * prop_valid
        pick = jnp.take_along_axis(
            box_deltas, best_cls[:, None, None].repeat(4, -1),
            axis=1)[:, 0]
        boxes = D.decode_boxes(pick, prop_boxes, weights=(10., 10., 5., 5.))
        boxes = D.clip_boxes(boxes, *self.image_size)
        score_ok = best_score > self.score_threshold
        keep, valid = D.class_aware_nms(
            boxes, jnp.where(score_ok, best_score, -jnp.inf), best_cls,
            self.nms_iou, self.max_detections)
        det_boxes = boxes[keep]
        det_scores = jnp.where(valid, best_score[keep], 0.0)
        det_classes = jnp.where(valid, best_cls[keep], 0)
        return det_boxes, det_scores, det_classes, valid

    # -- inference forward --------------------------------------------------
    def forward(self, params, state, x, training=False, rng=None):
        ps, bb_state = self.features(params, state, x, training=training)
        logits, deltas = self.rpn_outputs(params, ps)
        prop_boxes, prop_valid = self.proposals(logits, deltas)
        det_boxes, det_scores, det_classes, valid = self.detections(
            params, ps, prop_boxes, prop_valid.astype(logits.dtype))
        out = {"boxes": det_boxes, "scores": det_scores,
               "classes": det_classes, "valid": valid}
        if self.mask_head is not None:
            rois = D.multilevel_roi_align(
                [p[0] for p in ps], det_boxes, 14, self.STRIDES)
            mask_logits, _ = self.mask_head.forward(
                params["mask_head"], EMPTY, rois)  # (D, 28, 28, K)
            sel = det_classes[:, None, None, None]
            masks = jnp.take_along_axis(
                mask_logits, sel.repeat(28, 1).repeat(28, 2), axis=-1)[..., 0]
            out["masks"] = jax.nn.sigmoid(masks)
        new_state = dict(state)
        new_state["backbone"] = bb_state
        return out, new_state


# ---------------------------------------------------------------------------
# training losses (functional)
# ---------------------------------------------------------------------------


def rpn_loss(logits, deltas, anchors, gt_boxes, gt_valid,
             pos_iou: float = 0.7, neg_iou: float = 0.3):
    """RPN objectness (BCE) + box regression (smooth-L1 on positives).

    gt_boxes (G, 4) padded, gt_valid (G,) bool."""
    n_anchors = logits.shape[0]
    iou = D.box_iou(jnp.asarray(anchors), gt_boxes)
    iou = jnp.where(gt_valid[None, :], iou, 0.0)
    best_iou = jnp.max(iou, axis=1)
    best_gt = jnp.argmax(iou, axis=1)
    pos = best_iou >= pos_iou
    # anchors that are the argmax for some VALID gt are positive too
    # (out-of-bounds scatter indices are dropped, masking invalid columns)
    col_best = jnp.where(gt_valid, jnp.argmax(iou, axis=0), n_anchors)
    is_best = jnp.zeros_like(pos).at[col_best].set(True, mode="drop")
    pos = pos | (is_best & (best_iou > 1e-3))
    neg = (best_iou < neg_iou) & ~pos

    labels = pos.astype(logits.dtype)
    weights = (pos | neg).astype(logits.dtype)
    cls = jnp.sum(weights * (jax.nn.softplus(logits) - labels * logits))
    cls = cls / jnp.maximum(jnp.sum(weights), 1.0)

    target = D.encode_boxes(gt_boxes[best_gt], jnp.asarray(anchors))
    diff = jnp.abs(deltas - target)
    sl1 = jnp.where(diff < 1.0, 0.5 * diff ** 2, diff - 0.5).sum(-1)
    reg = jnp.sum(pos * sl1) / jnp.maximum(jnp.sum(pos), 1.0)
    return cls + reg


def detection_loss(cls_logits, box_deltas, prop_boxes, prop_valid,
                   gt_boxes, gt_classes, gt_valid, fg_iou: float = 0.5):
    """Box-head loss: softmax CE over classes (bg=0) + smooth-L1 on the
    matched class's deltas for foreground proposals."""
    iou = D.box_iou(prop_boxes, gt_boxes)
    iou = jnp.where(gt_valid[None, :], iou, 0.0)
    best_iou = jnp.max(iou, axis=1)
    best_gt = jnp.argmax(iou, axis=1)
    fg = (best_iou >= fg_iou) & (prop_valid > 0)
    labels = jnp.where(fg, gt_classes[best_gt], 0)

    logp = jax.nn.log_softmax(cls_logits, axis=-1)
    ce = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    ce = jnp.sum(ce * prop_valid) / jnp.maximum(jnp.sum(prop_valid), 1.0)

    target = D.encode_boxes(gt_boxes[best_gt], prop_boxes,
                            weights=(10., 10., 5., 5.))
    pick = jnp.take_along_axis(
        box_deltas, labels[:, None, None].repeat(4, -1), axis=1)[:, 0]
    diff = jnp.abs(pick - target)
    sl1 = jnp.where(diff < 1.0, 0.5 * diff ** 2, diff - 0.5).sum(-1)
    reg = jnp.sum(fg * sl1) / jnp.maximum(jnp.sum(fg), 1.0)
    return ce + reg


def maskrcnn_resnet50(num_classes: int = 81, image_size=(512, 512),
                      **kw) -> MaskRCNN:
    """COCO-shaped MaskRCNN — reference model-zoo entry point."""
    return MaskRCNN(num_classes, image_size=image_size, **kw)
