"""Autoencoder zoo model.

Reference analog (unverified — mount empty): ``dllib/models/autoencoder/``
(SURVEY.md §3.1 model-zoo row) — the MNIST fully-connected autoencoder
example (784 → hidden → 784 with sigmoid output trained against the
input).

TPU note: widths are kept at MXU-friendly multiples of 128 by default.
"""

from typing import Sequence, Union

from bigdl_tpu.nn.layers import Flatten, Linear, ReLU, Sigmoid
from bigdl_tpu.nn.module import Module, Sequential


def autoencoder(input_dim: int = 784,
                hidden: Union[int, Sequence[int]] = (128, 32),
                final_activation: str = "sigmoid") -> Sequential:
    """Symmetric MLP autoencoder — reference ``models/autoencoder/
    Autoencoder.scala`` shape (encoder mirrored into decoder)."""
    if isinstance(hidden, int):
        hidden = (hidden,)
    layers = [Flatten()]
    dims = [input_dim] + list(hidden)
    for i in range(1, len(dims)):
        layers += [Linear(dims[i - 1], dims[i]), ReLU()]
    rev = list(reversed(dims))
    for i in range(1, len(rev)):
        layers += [Linear(rev[i - 1], rev[i])]
        if i < len(rev) - 1:
            layers.append(ReLU())
    if final_activation == "sigmoid":
        layers.append(Sigmoid())
    return Sequential(layers)


class Encoder(Module):
    """Encoder half of a trained autoencoder: reuse the trained params to
    embed inputs (the common downstream use)."""

    def __init__(self, auto: Sequential, n_hidden_layers: int, name=None):
        super().__init__(name)
        # Flatten + (Linear, ReLU) * n_hidden_layers; trunk indices (and so
        # param keys "i_name") line up with the autoencoder's own
        self.trunk = Sequential(auto.layers[: 1 + 2 * n_hidden_layers])

    def forward(self, params, state, x, training=False, rng=None):
        return self.trunk.forward(params, state, x, training=training,
                                  rng=rng)

    def encoder_variables(self, auto_variables):
        """Slice the autoencoder's variables down to the encoder trunk."""
        keep = {self.trunk._key(i) for i in range(len(self.trunk.layers))}
        params = {k: v for k, v in auto_variables.get("params", {}).items()
                  if k in keep}
        st = {k: v for k, v in auto_variables.get("state", {}).items()
              if k in keep}
        return {"params": params, "state": st}
