"""ResNet — reference ``dllib/models/resnet/ResNet.scala`` (unverified —
mount empty): v1 basic blocks for CIFAR-10 (depth 6n+2) and bottleneck
ResNet-50 for ImageNet, MSRA init, BN-gamma-zero on the last block BN
(reference ``optnet``/zero-init-residual trick).

NHWC, bf16-friendly; identity shortcuts use stride-slicing + channel pad,
projection shortcuts a 1x1 conv (shortcutType B for ImageNet like the
reference default)."""

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import EMPTY, Module


def _conv_bn(cin, cout, k, stride=1, pad="SAME", act=True, gamma_zero=False):
    layers = [nn.Conv2D(cin, cout, k, stride=stride, padding=pad,
                        with_bias=False, weight_init=init_mod.msra),
              _BN(cout, gamma_zero)]
    if act:
        layers.append(nn.ReLU())
    return layers


class _BN(nn.BatchNorm):
    def __init__(self, c, gamma_zero=False):
        super().__init__(c)
        self.gamma_zero = gamma_zero

    def build(self, rng, x):
        params, state = super().build(rng, x)
        if self.gamma_zero:
            params["weight"] = jnp.zeros_like(params["weight"])
        return params, state


class BasicBlock(Module):
    """3x3+3x3 residual block (CIFAR / resnet-18/34)."""

    def __init__(self, cin, cout, stride=1, name=None):
        super().__init__(name)
        self.body = nn.Sequential(
            _conv_bn(cin, cout, 3, stride) +
            _conv_bn(cout, cout, 3, act=False, gamma_zero=True))
        self.proj = (nn.Sequential(_conv_bn(cin, cout, 1, stride, act=False))
                     if stride != 1 or cin != cout else None)

    def init(self, rng, x):
        import jax

        k1, k2 = jax.random.split(rng)
        v = {"body": self.body.init(k1, x)}
        if self.proj is not None:
            v["proj"] = self.proj.init(k2, x)
        return {"params": {k: vv["params"] for k, vv in v.items()},
                "state": {k: vv["state"] for k, vv in v.items()}}

    def forward(self, params, state, x, training=False, rng=None):
        y, st_b = self.body.forward(params["body"], state.get("body", EMPTY),
                                    x, training=training, rng=rng)
        if self.proj is not None:
            sc, st_p = self.proj.forward(params["proj"],
                                         state.get("proj", EMPTY), x,
                                         training=training, rng=rng)
        else:
            sc, st_p = x, EMPTY
        out = jnp.maximum(y + sc, 0.0)
        new_state = {}
        if st_b:
            new_state["body"] = st_b
        if st_p:
            new_state["proj"] = st_p
        return out, new_state


class Bottleneck(Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (ResNet-50/101/152)."""

    expansion = 4

    def __init__(self, cin, width, stride=1, name=None):
        super().__init__(name)
        cout = width * self.expansion
        self.body = nn.Sequential(
            _conv_bn(cin, width, 1) +
            _conv_bn(width, width, 3, stride) +
            _conv_bn(width, cout, 1, act=False, gamma_zero=True))
        self.proj = (nn.Sequential(_conv_bn(cin, cout, 1, stride, act=False))
                     if stride != 1 or cin != cout else None)

    init = BasicBlock.init
    forward = BasicBlock.forward


def resnet_cifar(depth: int = 20, classes: int = 10) -> nn.Sequential:
    """CIFAR-10 ResNet (depth = 6n+2) — reference TrainCIFAR10 path."""
    assert (depth - 2) % 6 == 0, "depth must be 6n+2"
    n = (depth - 2) // 6
    layers = _conv_bn(3, 16, 3)
    cin = 16
    for stage, width in enumerate([16, 32, 64]):
        for b in range(n):
            stride = 2 if (stage > 0 and b == 0) else 1
            layers.append(BasicBlock(cin, width, stride))
            cin = width
    layers += [nn.GlobalAvgPool2D(), nn.Linear(64, classes), nn.LogSoftMax()]
    return nn.Sequential(layers)


def resnet50(classes: int = 1000, include_top: bool = True) -> nn.Sequential:
    """ImageNet ResNet-50 — reference TrainImageNet path.  Input NHWC
    224x224x3."""
    layers = _conv_bn(3, 64, 7, stride=2)
    layers.append(nn.MaxPool2D(3, 2, padding=1))
    cin = 64
    for stage, (width, blocks) in enumerate([(64, 3), (128, 4), (256, 6),
                                             (512, 3)]):
        for b in range(blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            layers.append(Bottleneck(cin, width, stride))
            cin = width * Bottleneck.expansion
    layers.append(nn.GlobalAvgPool2D())
    if include_top:
        layers += [nn.Linear(2048, classes), nn.LogSoftMax()]
    return nn.Sequential(layers)
