"""ResNet — reference ``dllib/models/resnet/ResNet.scala`` (unverified —
mount empty): v1 basic blocks for CIFAR-10 (depth 6n+2) and bottleneck
ResNet-50 for ImageNet, MSRA init, BN-gamma-zero on the last block BN
(reference ``optnet``/zero-init-residual trick).

NHWC, bf16-friendly; identity shortcuts use stride-slicing + channel pad,
projection shortcuts a 1x1 conv (shortcutType B for ImageNet like the
reference default)."""

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import EMPTY, Module


def _conv_bn(cin, cout, k, stride=1, pad="SAME", act=True, gamma_zero=False):
    layers = [nn.Conv2D(cin, cout, k, stride=stride, padding=pad,
                        with_bias=False, weight_init=init_mod.msra),
              _BN(cout, gamma_zero)]
    if act:
        layers.append(nn.ReLU())
    return layers


class _BN(nn.BatchNorm):
    def __init__(self, c, gamma_zero=False):
        super().__init__(c)
        self.gamma_zero = gamma_zero

    def build(self, rng, x):
        params, state = super().build(rng, x)
        if self.gamma_zero:
            params["weight"] = jnp.zeros_like(params["weight"])
        return params, state


class SpaceToDepthStem(Module):
    """MXU-friendly ImageNet stem: 2x2 space-to-depth, then a 4x4 stride-1
    conv over 12 channels — mathematically EQUIVALENT to the standard
    7x7/stride-2 conv over 3 channels (``pack_stem_kernel`` maps a 7x7
    kernel onto the packed one exactly; asserted in
    ``tests/test_nn_layers.py``), but far better laid out for the TPU: 3
    input channels waste 125 of the MXU's 128 lanes, 12 waste 4x fewer,
    and the stride-1 window tiles cleanly.  The packed kernel's (di==7)
    positions are extra degrees of freedom when trained from scratch.

    Reference analog: the ImageNet stem of ``models/resnet/ResNet.scala``
    (⚠ unverified — mount empty), re-laid-out for the systolic array."""

    def __init__(self, out_channels: int = 64, name=None):
        super().__init__(name)
        self.out_channels = out_channels

    def build(self, rng, x):
        if x.shape[1] % 2 or x.shape[2] % 2:
            raise ValueError(f"H/W must be even for 2x2 space-to-depth, "
                             f"got {x.shape}")
        cin = x.shape[-1]
        # init with the EFFECTIVE receptive field's fan-in (7*7*cin), not
        # the packed shape's, so variance matches the standard stem
        fan_in, fan_out = 7 * 7 * cin, 7 * 7 * self.out_channels
        w = init_mod.msra(rng, (4, 4, 4 * cin, self.out_channels),
                          fan_in, fan_out)
        return {"weight": w}, EMPTY

    def forward(self, params, state, x, training=False, rng=None):
        from bigdl_tpu.nn.layers import _conv_accum
        from bigdl_tpu.tensor.policy import cast_compute

        n, h, w, c = x.shape
        x2 = x.reshape(n, h // 2, 2, w // 2, 2, c) \
              .transpose(0, 1, 3, 2, 4, 5) \
              .reshape(n, h // 2, w // 2, 4 * c)
        xc, wc = cast_compute(x2, params["weight"])
        y = jax.lax.conv_general_dilated(
            xc, wc, window_strides=(1, 1),
            # window offsets -1..+2 in s2d coords == the 7x7/s2 SAME pad
            padding=((1, 2), (1, 2)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            **_conv_accum(xc))
        return y.astype(x.dtype), EMPTY


def pack_stem_kernel(k7):
    """Map a (7, 7, C, out) stride-2 stem kernel onto the (4, 4, 4C, out)
    space-to-depth kernel such that SpaceToDepthStem(x) ==
    Conv2D(k=7, s=2, SAME)(x) exactly.  Used by the parity test and for
    importing pretrained standard-stem weights."""
    k7 = jnp.asarray(k7)
    kh, kw, c, cout = k7.shape
    assert kh == 7 and kw == 7, k7.shape
    k2 = jnp.zeros((4, 4, 4 * c, cout), k7.dtype)
    for r in range(4):
        for p in range(2):
            di = 2 * r + p
            if di > 6:
                continue
            for s in range(4):
                for q in range(2):
                    dj = 2 * s + q
                    if dj > 6:
                        continue
                    ch = (p * 2 + q) * c
                    k2 = k2.at[r, s, ch:ch + c, :].set(k7[di, dj])
    return k2


class BasicBlock(Module):
    """3x3+3x3 residual block (CIFAR / resnet-18/34)."""

    def __init__(self, cin, cout, stride=1, name=None):
        super().__init__(name)
        self.body = nn.Sequential(
            _conv_bn(cin, cout, 3, stride) +
            _conv_bn(cout, cout, 3, act=False, gamma_zero=True))
        self.proj = (nn.Sequential(_conv_bn(cin, cout, 1, stride, act=False))
                     if stride != 1 or cin != cout else None)

    def init(self, rng, x):
        import jax

        k1, k2 = jax.random.split(rng)
        v = {"body": self.body.init(k1, x)}
        if self.proj is not None:
            v["proj"] = self.proj.init(k2, x)
        return {"params": {k: vv["params"] for k, vv in v.items()},
                "state": {k: vv["state"] for k, vv in v.items()}}

    def forward(self, params, state, x, training=False, rng=None):
        y, st_b = self.body.forward(params["body"], state.get("body", EMPTY),
                                    x, training=training, rng=rng)
        if self.proj is not None:
            sc, st_p = self.proj.forward(params["proj"],
                                         state.get("proj", EMPTY), x,
                                         training=training, rng=rng)
        else:
            sc, st_p = x, EMPTY
        out = jnp.maximum(y + sc, 0.0)
        new_state = {}
        if st_b:
            new_state["body"] = st_b
        if st_p:
            new_state["proj"] = st_p
        return out, new_state


class Bottleneck(Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (ResNet-50/101/152)."""

    expansion = 4

    def __init__(self, cin, width, stride=1, name=None):
        super().__init__(name)
        cout = width * self.expansion
        self.body = nn.Sequential(
            _conv_bn(cin, width, 1) +
            _conv_bn(width, width, 3, stride) +
            _conv_bn(width, cout, 1, act=False, gamma_zero=True))
        self.proj = (nn.Sequential(_conv_bn(cin, cout, 1, stride, act=False))
                     if stride != 1 or cin != cout else None)

    init = BasicBlock.init
    forward = BasicBlock.forward


def resnet_cifar(depth: int = 20, classes: int = 10) -> nn.Sequential:
    """CIFAR-10 ResNet (depth = 6n+2) — reference TrainCIFAR10 path."""
    assert (depth - 2) % 6 == 0, "depth must be 6n+2"
    n = (depth - 2) // 6
    layers = _conv_bn(3, 16, 3)
    cin = 16
    for stage, width in enumerate([16, 32, 64]):
        for b in range(n):
            stride = 2 if (stage > 0 and b == 0) else 1
            layers.append(BasicBlock(cin, width, stride))
            cin = width
    layers += [nn.GlobalAvgPool2D(), nn.Linear(64, classes), nn.LogSoftMax()]
    return nn.Sequential(layers)


def resnet50(classes: int = 1000, include_top: bool = True,
             stem: str = "conv") -> nn.Sequential:
    """ImageNet ResNet-50 — reference TrainImageNet path.  Input NHWC
    224x224x3.  ``stem="s2d"`` swaps the 7x7/s2 conv for the equivalent
    MXU-friendly space-to-depth stem (SpaceToDepthStem)."""
    if stem == "s2d":
        layers = [SpaceToDepthStem(64), _BN(64), nn.ReLU()]
    elif stem == "conv":
        layers = _conv_bn(3, 64, 7, stride=2)
    else:
        raise ValueError(f"stem {stem!r}: conv | s2d")
    layers.append(nn.MaxPool2D(3, 2, padding=1))
    cin = 64
    for stage, (width, blocks) in enumerate([(64, 3), (128, 4), (256, 6),
                                             (512, 3)]):
        for b in range(blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            layers.append(Bottleneck(cin, width, stride))
            cin = width * Bottleneck.expansion
    layers.append(nn.GlobalAvgPool2D())
    if include_top:
        layers += [nn.Linear(2048, classes), nn.LogSoftMax()]
    return nn.Sequential(layers)
