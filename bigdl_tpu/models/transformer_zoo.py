"""Transformer / BERT zoo models.

Reference analog (unverified — mount empty): ``dllib/nn/Transformer.scala``
(encoder-decoder WMT config in BASELINE.json) and keras-side ``BERT.scala``
(Analytics-Zoo lineage).  TPU-native: pre-LN blocks, bf16 matmuls, and the
mesh-aware sharded variants in ``bigdl_tpu.parallel`` for tp/sp.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn.attention import positional_encoding
from bigdl_tpu.nn.module import EMPTY, Module


class TransformerEncoder(Module):
    """Token LM / classifier trunk: embed + sinusoidal pos + N blocks."""

    def __init__(self, vocab_size: int, hidden: int = 256, layers: int = 4,
                 heads: int = 4, max_len: int = 512, dropout: float = 0.1,
                 causal: bool = False, num_classes: Optional[int] = None,
                 name=None):
        super().__init__(name)
        self.embed = nn.Embedding(vocab_size, hidden)
        self.blocks = [nn.TransformerLayer(hidden, heads, dropout=dropout,
                                           causal=causal)
                       for _ in range(layers)]
        self.ln = nn.LayerNorm(hidden)
        self.max_len = max_len
        self.hidden = hidden
        self.head = (nn.Linear(hidden, num_classes)
                     if num_classes is not None else None)

    def init(self, rng, tokens):
        ks = jax.random.split(rng, len(self.blocks) + 3)
        ve = self.embed.init(ks[0], tokens)
        x, _ = self.embed.apply(ve, tokens)
        x = x + positional_encoding(x.shape[1], x.shape[2])
        params = {"embed": ve["params"]}
        for i, blk in enumerate(self.blocks):
            vb = blk.init(ks[i + 1], x)
            params[f"block_{i}"] = vb["params"]
            x, _ = blk.apply(vb, x)
        vl = self.ln.init(ks[-2], x)
        params["ln"] = vl["params"]
        if self.head is not None:
            vh = self.head.init(ks[-1], x[:, 0])
            params["head"] = vh["params"]
        return {"params": params, "state": EMPTY}

    def forward(self, params, state, tokens, training=False, rng=None,
                mask=None):
        if tokens.shape[1] > self.max_len:
            raise ValueError(
                f"sequence length {tokens.shape[1]} exceeds max_len "
                f"{self.max_len}")
        x, _ = self.embed.forward(params["embed"], EMPTY, tokens)
        x = x + positional_encoding(x.shape[1], x.shape[2]).astype(x.dtype)
        for i, blk in enumerate(self.blocks):
            x, _ = blk.forward(
                params[f"block_{i}"], EMPTY, x, training=training,
                rng=None if rng is None else jax.random.fold_in(rng, i),
                mask=mask)
        x, _ = self.ln.forward(params["ln"], EMPTY, x)
        if self.head is not None:
            cls, _ = self.head.forward(params["head"], EMPTY, x[:, 0])
            return cls, EMPTY
        return x, EMPTY


class BERT(Module):
    """BERT-style encoder: token+position+segment embeddings, post-embedding
    LN+dropout, N transformer blocks, tanh pooler on [CLS] — reference
    keras ``BERT.scala`` surface (``initializer_range`` init etc. simplified
    to xavier)."""

    def __init__(self, vocab_size: int, hidden: int = 256, layers: int = 4,
                 heads: int = 4, max_position: int = 512, type_vocab: int = 2,
                 dropout: float = 0.1, name=None):
        super().__init__(name)
        self.tok = nn.Embedding(vocab_size, hidden)
        self.pos = nn.Embedding(max_position, hidden)
        self.seg = nn.Embedding(type_vocab, hidden)
        self.ln = nn.LayerNorm(hidden)
        self.dropout = nn.Dropout(dropout)
        self.blocks = [nn.TransformerLayer(hidden, heads, dropout=dropout)
                       for _ in range(layers)]
        self.pooler = nn.Linear(hidden, hidden)
        self.hidden = hidden

    def init(self, rng, tokens, segments=None):
        if segments is None:
            segments = jnp.zeros_like(tokens)
        ks = jax.random.split(rng, len(self.blocks) + 5)
        vt = self.tok.init(ks[0], tokens)
        vp = self.pos.init(ks[1], tokens)
        vs = self.seg.init(ks[2], segments)
        x = (self.tok.apply(vt, tokens)[0]
             + self.pos.apply(vp, jnp.arange(tokens.shape[1])[None])[0]
             + self.seg.apply(vs, segments)[0])
        vl = self.ln.init(ks[3], x)
        x, _ = self.ln.apply(vl, x)
        params = {"tok": vt["params"], "pos": vp["params"],
                  "seg": vs["params"], "ln": vl["params"]}
        for i, blk in enumerate(self.blocks):
            vb = blk.init(ks[i + 4], x)
            params[f"block_{i}"] = vb["params"]
            x, _ = blk.apply(vb, x)
        vpool = self.pooler.init(ks[-1], x[:, 0])
        params["pooler"] = vpool["params"]
        return {"params": params, "state": EMPTY}

    def forward(self, params, state, tokens, segments=None, training=False,
                rng=None, mask=None):
        if segments is None:
            segments = jnp.zeros_like(tokens)
        pos_ids = jnp.arange(tokens.shape[1])[None]
        x = (self.tok.forward(params["tok"], EMPTY, tokens)[0]
             + self.pos.forward(params["pos"], EMPTY, pos_ids)[0]
             + self.seg.forward(params["seg"], EMPTY, segments)[0])
        x, _ = self.ln.forward(params["ln"], EMPTY, x)
        if rng is not None:
            x, _ = self.dropout.forward(EMPTY, EMPTY, x, training=training,
                                        rng=rng)
        att_mask = None
        if mask is not None:  # (b, L) 1=real token
            att_mask = mask[:, None, None, :].astype(bool)
        for i, blk in enumerate(self.blocks):
            x, _ = blk.forward(
                params[f"block_{i}"], EMPTY, x, training=training,
                rng=None if rng is None else jax.random.fold_in(rng, i),
                mask=att_mask)
        pooled, _ = self.pooler.forward(params["pooler"], EMPTY, x[:, 0])
        return (x, jnp.tanh(pooled)), EMPTY


class BERTClassifier(Module):
    """BERT + classification head (the Orca BERT fine-tune config)."""

    def __init__(self, bert: BERT, num_classes: int, name=None):
        super().__init__(name)
        self.bert = bert
        self.head = nn.Linear(bert.hidden, num_classes)

    def init(self, rng, tokens, segments=None):
        k1, k2 = jax.random.split(rng)
        vb = self.bert.init(k1, tokens, segments)
        (seq, pooled), _ = self.bert.apply(vb, tokens, segments)
        vh = self.head.init(k2, pooled)
        return {"params": {"bert": vb["params"], "head": vh["params"]},
                "state": EMPTY}

    def forward(self, params, state, tokens, segments=None, training=False,
                rng=None, mask=None):
        (seq, pooled), _ = self.bert.forward(
            params["bert"], EMPTY, tokens, segments, training=training,
            rng=rng, mask=mask)
        logits, _ = self.head.forward(params["head"], EMPTY, pooled)
        return logits, EMPTY
