"""LeNet-5 — reference ``dllib/models/lenet/LeNet5.scala`` (unverified —
mount empty): conv6@5x5 -> tanh -> pool -> conv12@5x5 -> tanh -> pool ->
fc100 -> tanh -> fc(classes) -> logsoftmax.  NHWC here."""

from bigdl_tpu import nn


def LeNet5(class_num: int = 10) -> nn.Sequential:
    return nn.Sequential([
        nn.Conv2D(1, 6, 5, padding="SAME"), nn.Tanh(),
        nn.MaxPool2D(2, 2),
        nn.Conv2D(6, 12, 5), nn.Tanh(),
        nn.MaxPool2D(2, 2),
        nn.Flatten(),
        nn.Linear(12 * 5 * 5, 100), nn.Tanh(),
        nn.Linear(100, class_num),
        nn.LogSoftMax(),
    ])
