"""Inception-v1 (GoogLeNet) — reference ``dllib/models/inception/
Inception_v1.scala`` (unverified — mount empty).  Inception modules built with
the ``Concat`` container exactly like the reference (four parallel towers
concatenated on channels); NHWC."""

from bigdl_tpu import nn


def _tower(*layers):
    return nn.Sequential(list(layers))


def inception_module(cin, c1, c3r, c3, c5r, c5, pool_proj):
    """4-tower module: 1x1 / 3x3(reduced) / 5x5(reduced) / pool-proj."""
    return nn.Concat([
        _tower(nn.Conv2D(cin, c1, 1), nn.ReLU()),
        _tower(nn.Conv2D(cin, c3r, 1), nn.ReLU(),
               nn.Conv2D(c3r, c3, 3, padding="SAME"), nn.ReLU()),
        _tower(nn.Conv2D(cin, c5r, 1), nn.ReLU(),
               nn.Conv2D(c5r, c5, 5, padding="SAME"), nn.ReLU()),
        _tower(nn.MaxPool2D(3, 1, padding=1),
               nn.Conv2D(cin, pool_proj, 1), nn.ReLU()),
    ], dim=-1)


def inception_v1(classes: int = 1000, dropout: float = 0.4) -> nn.Sequential:
    """Main tower (the reference also has two aux classifiers used only for
    training-loss shaping; provided via ``inception_v1_aux``)."""
    return nn.Sequential([
        nn.Conv2D(3, 64, 7, stride=2, padding="SAME"), nn.ReLU(),
        nn.MaxPool2D(3, 2, padding=1),
        nn.Conv2D(64, 64, 1), nn.ReLU(),
        nn.Conv2D(64, 192, 3, padding="SAME"), nn.ReLU(),
        nn.MaxPool2D(3, 2, padding=1),
        inception_module(192, 64, 96, 128, 16, 32, 32),    # 3a -> 256
        inception_module(256, 128, 128, 192, 32, 96, 64),  # 3b -> 480
        nn.MaxPool2D(3, 2, padding=1),
        inception_module(480, 192, 96, 208, 16, 48, 64),   # 4a -> 512
        inception_module(512, 160, 112, 224, 24, 64, 64),  # 4b
        inception_module(512, 128, 128, 256, 24, 64, 64),  # 4c
        inception_module(512, 112, 144, 288, 32, 64, 64),  # 4d -> 528
        inception_module(528, 256, 160, 320, 32, 128, 128),  # 4e -> 832
        nn.MaxPool2D(3, 2, padding=1),
        inception_module(832, 256, 160, 320, 32, 128, 128),  # 5a
        inception_module(832, 384, 192, 384, 48, 128, 128),  # 5b -> 1024
        nn.GlobalAvgPool2D(),
        nn.Dropout(dropout),
        nn.Linear(1024, classes),
        nn.LogSoftMax(),
    ])


# ---------------------------------------------------------------------------
# Inception-v2 (BN-Inception) — reference dllib/models/inception/
# Inception_v2.scala: every conv is conv+BN+ReLU, the 5x5 tower becomes a
# double-3x3 tower, and grid reduction uses stride-2 modules with a
# pass-through pool tower.
# ---------------------------------------------------------------------------


def _cbr(cin, cout, k, stride=1):
    return [nn.Conv2D(cin, cout, k, stride=stride, padding="SAME",
                      with_bias=False),
            nn.BatchNorm(cout), nn.ReLU()]


def inception_v2_module(cin, c1, c3r, c3, d3r, d3, pool_proj,
                        pool: str = "avg", stride: int = 1):
    """BN-Inception module.  ``stride=2`` is the grid-reduction form: the
    1x1 tower is dropped and the pool tower passes through un-projected."""
    towers = []
    if stride == 1 and c1 > 0:
        towers.append(_tower(*_cbr(cin, c1, 1)))
    towers.append(_tower(*(_cbr(cin, c3r, 1) + _cbr(c3r, c3, 3, stride))))
    towers.append(_tower(*(_cbr(cin, d3r, 1) + _cbr(d3r, d3, 3)
                           + _cbr(d3, d3, 3, stride))))
    if stride == 1:
        pool_l = (nn.AvgPool2D(3, 1, padding=1) if pool == "avg"
                  else nn.MaxPool2D(3, 1, padding=1))
        towers.append(_tower(pool_l, *_cbr(cin, pool_proj, 1)))
    else:
        towers.append(_tower(nn.MaxPool2D(3, 2, padding=1)))
    return nn.Concat(towers, dim=-1)


def inception_v2(classes: int = 1000) -> nn.Sequential:
    return nn.Sequential(
        _cbr(3, 64, 7, 2) + [nn.MaxPool2D(3, 2, padding=1)]
        + _cbr(64, 64, 1) + _cbr(64, 192, 3)
        + [nn.MaxPool2D(3, 2, padding=1)]
        + [
            inception_v2_module(192, 64, 64, 64, 64, 96, 32),        # 3a->256
            inception_v2_module(256, 64, 64, 96, 64, 96, 64),        # 3b->320
            inception_v2_module(320, 0, 128, 160, 64, 96, 0,
                                stride=2),                            # 3c->576
            inception_v2_module(576, 224, 64, 96, 96, 128, 128),     # 4a->576
            inception_v2_module(576, 192, 96, 128, 96, 128, 128),    # 4b->576
            inception_v2_module(576, 160, 128, 160, 128, 160, 96),   # 4c->576
            inception_v2_module(576, 96, 128, 192, 160, 192, 96),    # 4d->576
            inception_v2_module(576, 0, 128, 192, 192, 256, 0,
                                stride=2),                            # 4e->1024
            inception_v2_module(1024, 352, 192, 320, 160, 224, 128),  # 5a
            inception_v2_module(1024, 352, 192, 320, 192, 224, 128,
                                pool="max"),                          # 5b
            nn.GlobalAvgPool2D(),
            nn.Linear(1024, classes),
            nn.LogSoftMax(),
        ])
