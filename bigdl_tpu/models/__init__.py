from bigdl_tpu.models.lenet import LeNet5
from bigdl_tpu.models.resnet import resnet_cifar, resnet50, BasicBlock, Bottleneck
from bigdl_tpu.models.inception import (inception_v1, inception_v2,
                                         inception_module,
                                         inception_v2_module)
from bigdl_tpu.models.vgg import vgg16, vgg_cifar10
from bigdl_tpu.models.rnn_zoo import char_rnn, Seq2Seq
from bigdl_tpu.models.autoencoder import Encoder, autoencoder
from bigdl_tpu.models.transformer_zoo import (
    TransformerEncoder, BERT, BERTClassifier,
)
from bigdl_tpu.models.recsys import NeuralCF, WideAndDeep
from bigdl_tpu.models.maskrcnn import MaskRCNN, maskrcnn_resnet50

__all__ = [
    "LeNet5", "resnet_cifar", "resnet50", "BasicBlock", "Bottleneck",
    "inception_v1", "inception_v2", "inception_module", "inception_v2_module",
    "vgg16", "vgg_cifar10", "char_rnn",
    "Seq2Seq", "autoencoder", "Encoder", "TransformerEncoder", "BERT",
    "BERTClassifier", "NeuralCF", "WideAndDeep", "MaskRCNN",
    "maskrcnn_resnet50",
]
