"""VGG — reference ``dllib/models/vgg/`` (unverified — mount empty).  VGG-16
(ImageNet) and the CIFAR VggForCifar10 variant with BN."""

from bigdl_tpu import nn

_CFG16 = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
          512, 512, 512, "M", 512, 512, 512, "M"]


def vgg16(classes: int = 1000, dropout: float = 0.5) -> nn.Sequential:
    layers = []
    cin = 3
    for v in _CFG16:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers += [nn.Conv2D(cin, v, 3, padding="SAME"), nn.ReLU()]
            cin = v
    layers += [
        nn.Flatten(),
        nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(dropout),
        nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(dropout),
        nn.Linear(4096, classes), nn.LogSoftMax(),
    ]
    return nn.Sequential(layers)


def vgg_cifar10(classes: int = 10) -> nn.Sequential:
    """VggForCifar10 — conv towers with BN, two fc512 heads."""
    layers = []
    cin = 3
    for v in _CFG16:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers += [nn.Conv2D(cin, v, 3, padding="SAME"),
                       nn.BatchNorm(v), nn.ReLU()]
            cin = v
    layers += [
        nn.Flatten(),
        nn.Linear(512, 512), nn.BatchNorm(512), nn.ReLU(), nn.Dropout(0.5),
        nn.Linear(512, classes), nn.LogSoftMax(),
    ]
    return nn.Sequential(layers)
