"""Heterogeneous activity container.

Reference analog: BigDL's ``utils/Table.scala`` ``T()`` (unverified — mount
empty): a lua-style 1-indexed table used to pass multi-input/multi-output
activations between layers. TPU-native version: a thin dict that is a JAX
pytree, so it can flow through ``jit``/``grad`` unchanged.
"""

from typing import Any, Dict

import jax


@jax.tree_util.register_pytree_node_class
class Table(dict):
    """Dict registered as a pytree; integer keys mimic the 1-indexed T()."""

    def tree_flatten(self):
        keys = sorted(self.keys(), key=repr)
        return [self[k] for k in keys], tuple(keys)

    @classmethod
    def tree_unflatten(cls, keys, values):
        return cls(zip(keys, values))

    def __getattr__(self, item: str) -> Any:
        try:
            return self[item]
        except KeyError as e:  # pragma: no cover
            raise AttributeError(item) from e


def T(*args: Any, **kwargs: Any) -> Table:
    """``T(a, b)`` -> Table {1: a, 2: b} (1-indexed, like the reference)."""
    t = Table()
    for i, v in enumerate(args):
        t[i + 1] = v
    t.update(kwargs)
    return t
