"""Logging setup.

Reference analog: BigDL routes chatty Spark loggers away and emits per-iteration
INFO lines from the driver (dllib/utils/LoggerFilter.scala, unverified — mount
empty). Here: plain ``logging`` with a single concise formatter; in a
multi-process (multi-host) job only process 0 logs at INFO by default.
"""

import logging
import os
import sys

_CONFIGURED = False


def _is_primary() -> bool:
    # Must NOT trigger JAX backend initialization (get_logger runs at import
    # time, before jax.distributed.initialize). Read already-known process id
    # only from env / distributed global state.
    pid = os.environ.get("BIGDL_TPU_PROCESS_ID")
    if pid is not None:
        return int(pid) == 0
    try:
        from jax._src import distributed

        return (distributed.global_state.process_id or 0) == 0
    except Exception:
        return True


def get_logger(name: str = "bigdl_tpu") -> logging.Logger:
    global _CONFIGURED
    logger = logging.getLogger(name)
    if not _CONFIGURED:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname)s %(name)s: %(message)s", "%H:%M:%S"
            )
        )
        root = logging.getLogger("bigdl_tpu")
        root.addHandler(handler)
        root.propagate = False
        level = os.environ.get("BIGDL_TPU_LOG_LEVEL", "INFO").upper()
        root.setLevel(level if _is_primary() else "WARNING")
        _CONFIGURED = True
    return logger
