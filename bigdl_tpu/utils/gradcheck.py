"""Finite-difference gradient checker — reference ``nn/GradientChecker
.scala`` (⚠ unverified — mount empty): central-difference validation of a
layer's backward against its forward.

In a jax.grad world autodiff is correct by construction for composite
ops; what still needs this check is every op with a HAND-WRITTEN
backward — the ``jax.custom_vjp`` Pallas kernels (flash attention, fused
layernorm) whose bwd rules are code, not derivation.
"""

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["numeric_grad", "check_grad"]


def numeric_grad(fn: Callable, x: np.ndarray, eps: float = 1e-3,
                 samples: int = 0, seed: int = 0) -> np.ndarray:
    """Central-difference gradient of scalar-valued ``fn`` at ``x``.

    ``samples > 0``: only that many randomly chosen coordinates are
    probed (the rest of the returned array is NaN) — full probing is
    O(2·size) forwards and pointless for large inputs.
    """
    x = np.asarray(x, np.float64)
    flat = x.reshape(-1).copy()
    g = np.full(flat.shape, np.nan)
    idx = np.arange(flat.size)
    if samples and samples < flat.size:
        idx = np.random.RandomState(seed).choice(flat.size, samples,
                                                 replace=False)
    for i in idx:
        orig = flat[i]
        flat[i] = orig + eps
        fp = float(fn(jnp.asarray(flat.reshape(x.shape), jnp.float32)))
        flat[i] = orig - eps
        fm = float(fn(jnp.asarray(flat.reshape(x.shape), jnp.float32)))
        flat[i] = orig
        g[i] = (fp - fm) / (2 * eps)
    return g.reshape(x.shape)


def check_grad(fn: Callable, x: np.ndarray, eps: float = 1e-3,
               rtol: float = 5e-2, atol: float = 1e-3,
               samples: int = 64, seed: int = 0) -> float:
    """Assert ``jax.grad(fn)(x)`` matches central differences on a random
    coordinate sample; returns the max abs deviation over the sample.

    Tolerances are loose by design: finite differences in f32 forwards
    carry O(eps^2 + ulp/eps) noise — this catches *wrong formulas*
    (missing terms, transposed operands), not last-ulp drift.
    """
    auto = np.asarray(jax.grad(fn)(jnp.asarray(x, jnp.float32)), np.float64)
    num = numeric_grad(fn, x, eps=eps, samples=samples, seed=seed)
    mask = ~np.isnan(num)
    dev = np.abs(auto[mask] - num[mask])
    bound = atol + rtol * np.abs(num[mask])
    if not (dev <= bound).all():
        worst = int(np.argmax(dev - bound))
        raise AssertionError(
            f"gradient mismatch: autodiff {auto[mask][worst]:.6f} vs "
            f"numeric {num[mask][worst]:.6f} (|Δ|={dev[worst]:.2e}, "
            f"bound {bound[worst]:.2e}) at sampled coord {worst}")
    return float(dev.max()) if dev.size else 0.0
