"""TensorFlow GraphDef import/export — the ``nn/tf`` + ``utils/tf`` analog.

Reference analog (unverified — mount empty):
``utils/tf/TensorflowLoader.scala`` pattern-matches frozen-TF ``GraphDef``
subgraphs (MatMul+BiasAdd → Linear, Conv2D+BiasAdd → SpatialConvolution, …)
into BigDL modules; ``utils/tf/TensorflowSaver.scala`` emits a BigDL graph
back out as a ``GraphDef``; the ~100 small wrappers in ``nn/ops/*.scala``
cover the remaining TF ops (those live here in ``nn/ops_layers.py``).

TPU-native re-design: no tensorflow (or protobuf) dependency — the wire
format is read/written directly via ``utils/proto``; imported graphs become
a keras-engine functional :class:`~bigdl_tpu.keras.engine.Model` whose
layers are catalog ``nn`` modules, so an imported model drops straight onto
the sharded ``pjit`` training/inference path like any native model.

Import:  ``model, variables = load_tf_graph(path_or_bytes)``
Export:  ``graph_bytes = save_tf_graph(model, variables, sample, path=...)``
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.utils import proto
from bigdl_tpu.utils.proto import Msg

# TF DataType enum (tensorflow/core/framework/types.proto)
DT_FLOAT, DT_DOUBLE, DT_INT32, DT_UINT8 = 1, 2, 3, 4
DT_INT16, DT_INT8, DT_STRING, DT_INT64, DT_BOOL = 5, 6, 7, 9, 10
DT_BFLOAT16, DT_HALF = 14, 19

_NP_OF_DT = {
    DT_FLOAT: np.float32, DT_DOUBLE: np.float64, DT_INT32: np.int32,
    DT_UINT8: np.uint8, DT_INT16: np.int16, DT_INT8: np.int8,
    DT_INT64: np.int64, DT_BOOL: np.bool_, DT_HALF: np.float16,
}
_DT_OF_NP = {np.dtype(v): k for k, v in _NP_OF_DT.items()}


class UnsupportedTFOp(ValueError):
    pass


# ---------------------------------------------------------------------------
# TensorProto / TensorShapeProto / AttrValue codec
# ---------------------------------------------------------------------------


def _decode_shape(data: bytes) -> Optional[Tuple[int, ...]]:
    f = proto.parse(data)
    if proto.get_bool(f, 3):  # unknown_rank
        return None
    dims = []
    for raw in proto.repeated(f, 2):
        dims.append(proto.get_int(proto.parse(raw), 1))
    return tuple(dims)


def _encode_shape(shape: Sequence[int]) -> Msg:
    m = Msg()
    for d in shape:
        m.msg(2, Msg().varint(1, int(d)))
    return m


def decode_tensor(data: bytes) -> np.ndarray:
    """TensorProto → numpy (tensorflow/core/framework/tensor.proto)."""
    f = proto.parse(data)
    dtype = proto.get_int(f, 1, DT_FLOAT)
    shape = _decode_shape(proto.get_bytes(f, 2)) or ()
    np_dtype = _NP_OF_DT.get(dtype)
    if np_dtype is None:
        raise UnsupportedTFOp(f"tensor dtype {dtype} not supported")
    content = proto.get_bytes(f, 4)
    if content:
        arr = np.frombuffer(content, dtype=np_dtype)
    else:
        if dtype == DT_FLOAT:
            vals = proto.repeated_f32(f, 5)
        elif dtype == DT_DOUBLE:
            vals = proto.repeated_f64(f, 6)
        elif dtype in (DT_INT32, DT_UINT8, DT_INT16, DT_INT8):
            vals = proto.repeated_ints(f, 7)
        elif dtype == DT_INT64:
            vals = proto.repeated_ints(f, 10)
        elif dtype == DT_BOOL:
            vals = proto.repeated_ints(f, 11)
        else:
            raise UnsupportedTFOp(f"tensor value field for dtype {dtype}")
        arr = np.asarray(vals, dtype=np_dtype)
        n = int(np.prod(shape)) if shape else max(len(vals), 1)
        if arr.size == 1 and n > 1:  # proto scalar-splat convention
            arr = np.full((n,), arr.reshape(-1)[0], dtype=np_dtype)
    return arr.reshape(shape)


def encode_tensor(arr: np.ndarray) -> Msg:
    arr = np.asarray(arr, order="C")  # NOT ascontiguousarray: keeps 0-d shape
    dt = _DT_OF_NP.get(arr.dtype)
    if dt is None:
        raise UnsupportedTFOp(f"cannot export dtype {arr.dtype}")
    m = Msg().varint(1, dt).msg(2, _encode_shape(arr.shape))
    return m.blob(4, arr.tobytes())


class Attr:
    """Decoded AttrValue (tensorflow/core/framework/attr_value.proto)."""

    def __init__(self, data: bytes):
        self.f = proto.parse(data)

    @property
    def s(self) -> bytes:
        return proto.get_bytes(self.f, 2)

    @property
    def i(self) -> int:
        return proto.get_int(self.f, 3)

    @property
    def fval(self) -> float:
        return proto.get_f32(self.f, 4)

    @property
    def b(self) -> bool:
        return proto.get_bool(self.f, 5)

    @property
    def type(self) -> int:
        return proto.get_int(self.f, 6)

    @property
    def shape(self) -> Optional[Tuple[int, ...]]:
        return _decode_shape(proto.get_bytes(self.f, 7))

    @property
    def tensor(self) -> np.ndarray:
        return decode_tensor(proto.get_bytes(self.f, 8))

    @property
    def ints(self) -> List[int]:
        lst = proto.get_bytes(self.f, 1)
        return proto.repeated_ints(proto.parse(lst), 3) if lst else []


def _attr_i(v: int) -> Msg:
    return Msg().varint(3, v)


def _attr_f(v: float) -> Msg:
    return Msg().f32(4, v)


def _attr_b(v: bool) -> Msg:
    return Msg().boolean(5, v)


def _attr_s(v: bytes) -> Msg:
    return Msg().blob(2, v)


def _attr_type(dt: int) -> Msg:
    return Msg().varint(6, dt)


def _attr_shape(shape: Sequence[int]) -> Msg:
    return Msg().msg(7, _encode_shape(shape))


def _attr_tensor(arr: np.ndarray) -> Msg:
    return Msg().msg(8, encode_tensor(arr))


def _attr_int_list(vals: Sequence[int]) -> Msg:
    return Msg().msg(1, Msg().packed_ints(3, vals))


class TFNode:
    def __init__(self, name: str, op: str, inputs: List[str],
                 attrs: Dict[str, Attr]):
        self.name, self.op, self.inputs, self.attrs = name, op, inputs, attrs

    def __repr__(self):
        return f"TFNode({self.op}:{self.name})"


def parse_graphdef(data: bytes) -> List[TFNode]:
    nodes = []
    for raw in proto.repeated(proto.parse(data), 1):
        f = proto.parse(raw)
        name = proto.get_str(f, 1)
        op = proto.get_str(f, 2)
        inputs = [b.decode("utf-8") for b in proto.repeated(f, 3)]
        attrs: Dict[str, Attr] = {}
        for entry in proto.repeated(f, 5):
            ef = proto.parse(entry)
            attrs[proto.get_str(ef, 1)] = Attr(proto.get_bytes(ef, 2))
        nodes.append(TFNode(name, op, inputs, attrs))
    return nodes


class GraphDefBuilder:
    """Emit a GraphDef; used by the exporter and by tests to fabricate
    "foreign" TF graphs."""

    def __init__(self):
        self.g = Msg()
        self._names: set = set()

    def node(self, name: str, op: str, inputs: Sequence[str] = (),
             **attrs: Msg) -> str:
        if name in self._names:
            raise ValueError(f"duplicate node name {name}")
        self._names.add(name)
        n = Msg().string(1, name).string(2, op)
        for i in inputs:
            n.string(3, i)
        for k, v in attrs.items():
            n.msg(5, Msg().string(1, k).msg(2, v))
        self.g.msg(1, n)
        return name

    def const(self, name: str, arr: np.ndarray) -> str:
        arr = np.asarray(arr)
        return self.node(name, "Const", dtype=_attr_type(_DT_OF_NP[arr.dtype]),
                         value=_attr_tensor(arr))

    def bytes(self) -> bytes:
        return self.g.bytes()


# ---------------------------------------------------------------------------
# Import: GraphDef → keras Model + variables
# ---------------------------------------------------------------------------


def _canon(inp: str) -> Optional[str]:
    """Canonical producer name of an input ref; None for control deps."""
    if inp.startswith("^"):
        return None
    return inp.split(":")[0]


def _pyname(tf_name: str) -> str:
    return tf_name.replace("/", "_").replace(":", "_")


def _toposort(nodes: List["TFNode"], by_name: Dict[str, "TFNode"]):
    """Iterative DFS (frozen graphs can chain 1000s of nodes deep)."""
    order: List[TFNode] = []
    mark: Dict[str, int] = {}  # 1 = on stack, 2 = done

    for root in nodes:
        if mark.get(root.name) == 2:
            continue
        stack: List[Tuple[TFNode, int]] = [(root, 0)]
        while stack:
            n, idx = stack.pop()
            if idx == 0:
                if mark.get(n.name) == 2:
                    continue
                if mark.get(n.name) == 1:
                    raise UnsupportedTFOp(f"cycle at node '{n.name}'")
                mark[n.name] = 1
            deps = [by_name[c] for c in
                    (_canon(i) for i in n.inputs) if c and c in by_name]
            while idx < len(deps) and mark.get(deps[idx].name) == 2:
                idx += 1
            if idx < len(deps):
                dep = deps[idx]
                if mark.get(dep.name) == 1:
                    raise UnsupportedTFOp(f"cycle at node '{dep.name}'")
                stack.append((n, idx + 1))
                stack.append((dep, 0))
            else:
                mark[n.name] = 2
                order.append(n)
    return order


def _act_import_table():
    from bigdl_tpu import nn
    return {
        "Relu": nn.ReLU, "Relu6": nn.ReLU6, "Elu": nn.ELU,
        "Sigmoid": nn.Sigmoid, "Tanh": nn.Tanh, "Softmax": nn.SoftMax,
        "LogSoftmax": nn.LogSoftMax, "Softplus": nn.SoftPlus,
        "Softsign": nn.SoftSign, "Rsqrt": nn.Rsqrt, "Sqrt": nn.Sqrt,
        "Square": nn.Square, "Exp": nn.Exp, "Log": nn.Log, "Abs": nn.Abs,
        "Neg": nn.Negative, "Floor": nn.Floor, "Ceil": nn.Ceil,
        "Sign": nn.Sign, "Sin": nn.Sin, "Cos": nn.Cos,
    }


def load_tf_graph(source, input_shapes: Optional[Dict[str, Sequence[int]]] = None,
                  outputs: Optional[Sequence[str]] = None):
    """Import a frozen-inference GraphDef.

    ``source``: bytes or a path to a ``.pb`` file.  ``input_shapes`` maps
    placeholder name → full shape (batch dim included) when the graph doesn't
    carry one.  Returns ``(model, variables)`` ready for
    ``model.apply(variables, x)``.
    """
    from bigdl_tpu import nn
    from bigdl_tpu.keras.engine import Input, Model, Node

    if isinstance(source, str):
        with open(source, "rb") as fh:
            source = fh.read()
    nodes = parse_graphdef(source)
    by_name = {n.name: n for n in nodes}
    acts = _act_import_table()

    consumers: Dict[str, List[TFNode]] = {}
    for n in nodes:
        for i in n.inputs:
            c = _canon(i)
            if c is not None:
                consumers.setdefault(c, []).append(n)

    consts: Dict[str, np.ndarray] = {}
    sym: Dict[str, Node] = {}
    inputs: List[Node] = []
    imported: List[Tuple[Any, Dict, Dict]] = []  # (layer, params, state)
    folded: set = set()  # names of bias nodes folded into a producing layer

    def const_of(name: Optional[str]) -> Optional[np.ndarray]:
        n = by_name.get(name) if name else None
        while n is not None and n.op in ("Identity", "StopGradient"):
            nxt = _canon(n.inputs[0])
            n = by_name.get(nxt) if nxt else None
        if n is None:
            return None
        if n.name not in consts and n.op == "Const":
            # decode on demand: bias-fold peeks at consts the topo walk has
            # not reached yet
            consts[n.name] = n.attrs["value"].tensor
        return consts.get(n.name)

    def add_layer(layer, params: Dict, state: Dict, parents: List[Node],
                  out_name: str):
        node = layer(parents[0] if len(parents) == 1 else parents)
        imported.append((layer, params, state))
        sym[out_name] = node

    def bias_fold_target(n: TFNode) -> Optional[Tuple[TFNode, np.ndarray]]:
        """If n's sole consumer is BiasAdd/Add(x, const-1d), return it."""
        cs = consumers.get(n.name, [])
        if len(cs) != 1 or cs[0].op not in ("BiasAdd", "Add", "AddV2"):
            return None
        ba = cs[0]
        ins = [_canon(i) for i in ba.inputs if _canon(i)]
        other = [i for i in ins if i != n.name]
        if len(other) != 1:
            return None
        b = const_of(other[0])
        if b is None or b.ndim != 1:
            return None
        return ba, b

    def sym_in(n: TFNode, idx: int = 0) -> Node:
        name = _canon(n.inputs[idx])
        if name not in sym:
            raise UnsupportedTFOp(
                f"{n.op} '{n.name}': input '{name}' is not a tensor value")
        return sym[name]

    for n in _toposort(nodes, by_name):
        op = n.op
        if op == "NoOp" or n.name in folded:
            continue
        if op == "Const":
            consts[n.name] = n.attrs["value"].tensor
        elif op in ("Placeholder", "PlaceholderV2"):
            shape = None
            if input_shapes and n.name in input_shapes:
                shape = tuple(input_shapes[n.name])[1:]
            elif "shape" in n.attrs:
                s = n.attrs["shape"].shape
                if s:
                    shape = tuple(s[1:])
            if shape is None:
                raise UnsupportedTFOp(
                    f"Placeholder '{n.name}' has no shape; pass input_shapes")
            node = Input(shape)
            sym[n.name] = node
            inputs.append(node)
        elif op in ("Identity", "StopGradient", "CheckNumerics"):
            src = _canon(n.inputs[0])
            if src in sym:
                sym[n.name] = sym[src]
            else:
                c = const_of(src)
                if c is not None:
                    consts[n.name] = c
        elif op == "MatMul":
            w = const_of(_canon(n.inputs[1]))
            if w is None:
                raise UnsupportedTFOp(f"MatMul '{n.name}': non-const weights")
            if "transpose_a" in n.attrs and n.attrs["transpose_a"].b:
                raise UnsupportedTFOp("MatMul transpose_a")
            if "transpose_b" in n.attrs and n.attrs["transpose_b"].b:
                w = w.T
            fold = bias_fold_target(n)
            layer = nn.Linear(w.shape[0], w.shape[1],
                              with_bias=fold is not None, name=_pyname(n.name))
            params = {"weight": w}
            out = n.name
            if fold is not None:
                ba, bias = fold
                params["bias"] = bias
                folded.add(ba.name)
                out = ba.name
            add_layer(layer, params, {}, [sym_in(n)], out)
        elif op == "Conv2D":
            w = const_of(_canon(n.inputs[1]))
            if w is None:
                raise UnsupportedTFOp(f"Conv2D '{n.name}': non-const weights")
            if "data_format" in n.attrs and n.attrs["data_format"].s not in (
                    b"", b"NHWC"):
                raise UnsupportedTFOp("Conv2D: only NHWC data_format")
            strides = n.attrs["strides"].ints if "strides" in n.attrs else [1] * 4
            pad = n.attrs["padding"].s.decode() if "padding" in n.attrs else "VALID"
            dil = n.attrs["dilations"].ints if "dilations" in n.attrs else [1] * 4
            fold = bias_fold_target(n)
            kh, kw, cin, cout = w.shape
            layer = nn.Conv2D(cin, cout, (kh, kw), stride=tuple(strides[1:3]),
                              padding=pad, dilation=tuple(dil[1:3]),
                              with_bias=fold is not None, name=_pyname(n.name))
            params = {"weight": w}
            out = n.name
            if fold is not None:
                ba, bias = fold
                params["bias"] = bias
                folded.add(ba.name)
                out = ba.name
            add_layer(layer, params, {}, [sym_in(n)], out)
        elif op == "BiasAdd":
            b = const_of(_canon(n.inputs[1]))
            if b is None:
                raise UnsupportedTFOp(f"BiasAdd '{n.name}': non-const bias")
            layer = nn.CAdd(b.shape, name=_pyname(n.name))
            add_layer(layer, {"bias": b}, {}, [sym_in(n)], n.name)
        elif op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
            scale = const_of(_canon(n.inputs[1]))
            offset = const_of(_canon(n.inputs[2]))
            mean = const_of(_canon(n.inputs[3]))
            var = const_of(_canon(n.inputs[4]))
            if any(v is None for v in (scale, offset, mean, var)):
                raise UnsupportedTFOp(f"{op} '{n.name}': non-const stats")
            eps = n.attrs["epsilon"].fval if "epsilon" in n.attrs else 1e-3
            layer = nn.BatchNorm(scale.shape[0], eps=eps, name=_pyname(n.name))
            add_layer(layer, {"weight": scale, "bias": offset},
                      {"running_mean": mean, "running_var": var},
                      [sym_in(n)], n.name)
        elif op in ("Add", "AddV2", "Sub", "Mul", "Maximum", "Minimum",
                    "RealDiv"):
            a, b = _canon(n.inputs[0]), _canon(n.inputs[1])
            table = {"Add": nn.CAddTable, "AddV2": nn.CAddTable,
                     "Sub": nn.CSubTable, "Mul": nn.CMulTable,
                     "Maximum": nn.CMaxTable, "Minimum": nn.CMinTable,
                     "RealDiv": nn.CDivTable}
            if a in sym and b in sym:
                add_layer(table[op](name=_pyname(n.name)), {}, {},
                          [sym[a], sym[b]], n.name)
                continue
            x_name, c_name = (a, b) if a in sym else (b, a)
            if x_name not in sym:
                raise UnsupportedTFOp(f"{op} '{n.name}': no tensor input")
            c = const_of(c_name)
            if c is None:
                raise UnsupportedTFOp(f"{op} '{n.name}': non-const operand")
            if c.ndim == 0:
                if op in ("Add", "AddV2"):
                    layer = nn.AddConstant(float(c), name=_pyname(n.name))
                elif op == "Sub" and x_name == a:
                    layer = nn.AddConstant(-float(c), name=_pyname(n.name))
                elif op == "Mul":
                    layer = nn.MulConstant(float(c), name=_pyname(n.name))
                elif op == "RealDiv" and x_name == a:
                    layer = nn.MulConstant(1.0 / float(c), name=_pyname(n.name))
                else:
                    raise UnsupportedTFOp(f"{op}(const, x) not supported")
                add_layer(layer, {}, {}, [sym[x_name]], n.name)
            elif op in ("Add", "AddV2"):
                layer = nn.CAdd(c.shape, name=_pyname(n.name))
                add_layer(layer, {"bias": c}, {}, [sym[x_name]], n.name)
            elif op == "Mul":
                layer = nn.CMul(c.shape, name=_pyname(n.name))
                add_layer(layer, {"weight": c}, {}, [sym[x_name]], n.name)
            else:
                raise UnsupportedTFOp(f"{op} with non-scalar const")
        elif op == "LeakyRelu":
            alpha = n.attrs["alpha"].fval if "alpha" in n.attrs else 0.2
            add_layer(nn.LeakyReLU(alpha, name=_pyname(n.name)), {}, {},
                      [sym_in(n)], n.name)
        elif op in acts:
            add_layer(acts[op](name=_pyname(n.name)), {}, {}, [sym_in(n)],
                      n.name)
        elif op in ("MaxPool", "AvgPool"):
            ks = n.attrs["ksize"].ints
            st = n.attrs["strides"].ints
            pad = n.attrs["padding"].s.decode()
            cls = nn.MaxPool2D if op == "MaxPool" else nn.AvgPool2D
            layer = cls(tuple(ks[1:3]), stride=tuple(st[1:3]), padding=pad,
                        name=_pyname(n.name))
            add_layer(layer, {}, {}, [sym_in(n)], n.name)
        elif op == "Reshape":
            shape = const_of(_canon(n.inputs[1]))
            if shape is None:
                raise UnsupportedTFOp(f"Reshape '{n.name}': non-const shape")
            shape = [int(d) for d in shape]
            if shape and shape[0] == -1:
                layer = nn.Reshape(shape[1:], batch_mode=True,
                                   name=_pyname(n.name))
            else:
                layer = nn.Reshape(shape, batch_mode=False,
                                   name=_pyname(n.name))
            add_layer(layer, {}, {}, [sym_in(n)], n.name)
        elif op == "Squeeze":
            dims = n.attrs["squeeze_dims"].ints if "squeeze_dims" in n.attrs \
                else None
            layer = nn.Squeeze(tuple(dims) if dims else None,
                               name=_pyname(n.name))
            add_layer(layer, {}, {}, [sym_in(n)], n.name)
        elif op == "Mean":
            idx = const_of(_canon(n.inputs[1]))
            if idx is None:
                raise UnsupportedTFOp(f"Mean '{n.name}': non-const indices")
            keep = n.attrs["keep_dims"].b if "keep_dims" in n.attrs else False
            layer = nn.Mean(tuple(int(i) for i in np.atleast_1d(idx)),
                            keepdims=keep, name=_pyname(n.name))
            add_layer(layer, {}, {}, [sym_in(n)], n.name)
        elif op == "Pad":
            pads = const_of(_canon(n.inputs[1]))
            if pads is None:
                raise UnsupportedTFOp(f"Pad '{n.name}': non-const paddings")
            layer = nn.PadOp([[int(a) for a in row] for row in pads],
                             name=_pyname(n.name))
            add_layer(layer, {}, {}, [sym_in(n)], n.name)
        elif op in ("ConcatV2", "Concat"):
            if op == "ConcatV2":
                axis = const_of(_canon(n.inputs[-1]))
                data = n.inputs[:-1]
            else:
                axis = const_of(_canon(n.inputs[0]))
                data = n.inputs[1:]
            if axis is None:
                raise UnsupportedTFOp(f"{op} '{n.name}': non-const axis")
            parents = [sym[_canon(i)] for i in data]
            add_layer(nn.JoinTable(int(axis), name=_pyname(n.name)), {}, {},
                      parents, n.name)
        else:
            raise UnsupportedTFOp(
                f"unsupported TF op '{op}' (node '{n.name}')")

    if not inputs:
        raise UnsupportedTFOp("graph has no Placeholder inputs")
    if outputs:
        out_nodes = [sym[o] for o in outputs]
    else:
        out_nodes, seen = [], set()
        for n in nodes:
            nd = sym.get(n.name)
            if (nd is not None and not consumers.get(n.name)
                    and nd not in inputs and nd.id not in seen):
                seen.add(nd.id)
                out_nodes.append(nd)
    model = Model(inputs, out_nodes, name="TFImported")

    params: Dict[str, Dict] = {}
    state: Dict[str, Dict] = {}
    by_layer = {id(l): (p, s) for l, p, s in imported}
    for node in model.order:
        if node.layer is not None and id(node.layer) in by_layer:
            p, s = by_layer[id(node.layer)]
            if p:
                params[node.name] = {k: np.asarray(v) for k, v in p.items()}
            if s:
                state[node.name] = {k: np.asarray(v) for k, v in s.items()}
    return model, {"params": params, "state": state}


# ---------------------------------------------------------------------------
# Export: model → GraphDef
# ---------------------------------------------------------------------------


def save_tf_graph(model, variables: Dict[str, Any],
                  sample=None, path: Optional[str] = None,
                  input_names: Optional[Sequence[str]] = None) -> bytes:
    """Export a Sequential or functional Model as a frozen GraphDef.

    ``sample`` (a sample input array, or list of arrays for multi-input
    models) drives shape inference — needed to emit Placeholder shapes and
    to resolve ``Flatten`` into a concrete TF ``Reshape``.  Covers the layer
    set the reference's ``TensorflowSaver`` handles: Linear, Conv2D (SAME /
    int padding), BatchNorm (inference form), pooling, activations,
    Reshape/Flatten/Squeeze, Dropout (→ Identity), CAddTable, JoinTable,
    GlobalAvgPool2D, ZeroPadding2D, CAdd/CMul, Pad.
    """
    from bigdl_tpu.keras.engine import Model as KModel
    from bigdl_tpu.nn.module import Sequential

    b = GraphDefBuilder()
    uid = [0]

    def fresh(base: str) -> str:
        uid[0] += 1
        return f"{base}_{uid[0]}"

    params = variables.get("params", {})
    state = variables.get("state", {})

    if isinstance(model, KModel):
        samples = None
        if sample is not None:
            samples = sample if isinstance(sample, (list, tuple)) else [sample]
        name_of: Dict[int, str] = {}
        val_of: Dict[int, Any] = {}
        for i, inp in enumerate(model.inputs):
            nm = (input_names[i] if input_names and i < len(input_names)
                  else f"input_{i}")
            if samples is not None:
                shape = (-1,) + tuple(np.shape(samples[i])[1:])
                val_of[inp.id] = np.asarray(samples[i])
            elif inp.shape is not None:
                shape = (-1,) + tuple(inp.shape)
            else:
                shape = (-1,)
            b.node(nm, "Placeholder", dtype=_attr_type(DT_FLOAT),
                   shape=_attr_shape(shape))
            name_of[inp.id] = nm
        for node in model.order:
            if node.layer is None:
                continue
            ins = [name_of[p.id] for p in node.parents]
            in_shapes = [np.shape(val_of[p.id]) for p in node.parents] \
                if samples is not None else None
            p = params.get(node.name, {})
            s = state.get(node.name, {})
            out = _emit_layer(b, fresh, node.layer, p, s, ins, in_shapes)
            name_of[node.id] = out
            if samples is not None:
                xs = [val_of[pn.id] for pn in node.parents]
                y, _ = node.layer.apply({"params": p, "state": s}, *xs,
                                        training=False)
                val_of[node.id] = np.asarray(y)
    elif isinstance(model, Sequential):
        shape = ((-1,) + tuple(np.shape(sample)[1:])) if sample is not None \
            else (-1,)
        b.node("input_0", "Placeholder", dtype=_attr_type(DT_FLOAT),
               shape=_attr_shape(shape))
        cur, val = "input_0", (np.asarray(sample) if sample is not None
                               else None)
        for i, layer in enumerate(model.layers):
            k = model._key(i)
            p, s = params.get(k, {}), state.get(k, {})
            in_shapes = [np.shape(val)] if val is not None else None
            cur = _emit_layer(b, fresh, layer, p, s, [cur], in_shapes)
            if val is not None:
                val, _ = layer.apply({"params": p, "state": s}, val,
                                     training=False)
                val = np.asarray(val)
    else:
        raise UnsupportedTFOp(f"cannot export {type(model).__name__}")

    data = b.bytes()
    if path:
        with open(path, "wb") as fh:
            fh.write(data)
    return data


def _np(v) -> np.ndarray:
    return np.asarray(v)


def _emit_layer(b: GraphDefBuilder, fresh, layer, params: Dict, state: Dict,
                ins: List[str], in_shapes: Optional[List[Tuple]] = None) -> str:
    """Emit GraphDef node(s) for one catalog layer; returns output node name."""
    from bigdl_tpu import nn
    from bigdl_tpu.nn.module import Sequential

    t = type(layer).__name__
    x = ins[0] if ins else None

    if isinstance(layer, Sequential):
        cur = x
        shapes = in_shapes
        for i, sub in enumerate(layer.layers):
            k = layer._key(i)
            cur = _emit_layer(b, fresh, sub, params.get(k, {}),
                              state.get(k, {}), [cur], shapes)
            shapes = None  # inner shape tracking only at the top level
        return cur

    if isinstance(layer, nn.Linear):
        w = b.const(fresh("weight"), _np(params["weight"]).astype(np.float32))
        out = b.node(fresh("MatMul"), "MatMul", [x, w],
                     transpose_a=_attr_b(False), transpose_b=_attr_b(False))
        if layer.with_bias:
            bias = b.const(fresh("bias"), _np(params["bias"]).astype(np.float32))
            out = b.node(fresh("BiasAdd"), "BiasAdd", [out, bias])
        return out

    if isinstance(layer, nn.Conv2D) and t in ("Conv2D", "SpatialConvolution"):
        if layer.groups != 1:
            raise UnsupportedTFOp("grouped Conv2D export")
        w = b.const(fresh("kernel"), _np(params["weight"]).astype(np.float32))
        pad = layer.padding
        src = x
        if isinstance(pad, str):
            tf_pad = pad.upper()
        else:
            ph, pw = (pad, pad) if isinstance(pad, int) else tuple(pad)
            if (ph, pw) == (-1, -1):
                tf_pad = "SAME"
            elif (ph, pw) == (0, 0):
                tf_pad = "VALID"
            else:
                pads = b.const(fresh("pads"), np.asarray(
                    [[0, 0], [ph, ph], [pw, pw], [0, 0]], np.int32))
                src = b.node(fresh("Pad"), "Pad", [x, pads])
                tf_pad = "VALID"
        sh, sw = layer.stride
        dh, dw = layer.dilation
        out = b.node(fresh("Conv2D"), "Conv2D", [src, w],
                     strides=_attr_int_list([1, sh, sw, 1]),
                     dilations=_attr_int_list([1, dh, dw, 1]),
                     padding=_attr_s(tf_pad.encode()),
                     data_format=_attr_s(b"NHWC"))
        if layer.with_bias:
            bias = b.const(fresh("bias"), _np(params["bias"]).astype(np.float32))
            out = b.node(fresh("BiasAdd"), "BiasAdd", [out, bias])
        return out

    if isinstance(layer, nn.BatchNorm):
        c = _np(state["running_mean"]).shape[0]
        scale = _np(params["weight"]) if layer.affine else np.ones(c, np.float32)
        offset = _np(params["bias"]) if layer.affine else np.zeros(c, np.float32)
        sc = b.const(fresh("gamma"), scale.astype(np.float32))
        of = b.const(fresh("beta"), offset.astype(np.float32))
        mu = b.const(fresh("mean"), _np(state["running_mean"]).astype(np.float32))
        var = b.const(fresh("variance"),
                      _np(state["running_var"]).astype(np.float32))
        return b.node(fresh("FusedBatchNormV3"), "FusedBatchNormV3",
                      [x, sc, of, mu, var], epsilon=_attr_f(layer.eps),
                      is_training=_attr_b(False))

    if isinstance(layer, (nn.MaxPool2D, nn.AvgPool2D)):
        op = "MaxPool" if isinstance(layer, nn.MaxPool2D) else "AvgPool"
        pad = layer.padding
        if isinstance(pad, str):
            tf_pad = pad.upper()
        else:
            ph, pw = (pad, pad) if isinstance(pad, int) else tuple(pad)
            if (ph, pw) != (0, 0):
                raise UnsupportedTFOp(f"int-padded {op} export")
            tf_pad = "VALID"
        kh, kw = layer.kernel_size
        sh, sw = layer.stride
        return b.node(fresh(op), op, [x],
                      ksize=_attr_int_list([1, kh, kw, 1]),
                      strides=_attr_int_list([1, sh, sw, 1]),
                      padding=_attr_s(tf_pad.encode()))

    if isinstance(layer, nn.GlobalAvgPool2D):
        idx = b.const(fresh("axes"), np.asarray([1, 2], np.int32))
        return b.node(fresh("Mean"), "Mean", [x, idx], keep_dims=_attr_b(False))

    if isinstance(layer, nn.Flatten):
        if not in_shapes:
            raise UnsupportedTFOp(
                "Flatten export needs `sample` for shape inference")
        flat = int(np.prod(in_shapes[0][1:]))
        shape = b.const(fresh("shape"), np.asarray([-1, flat], np.int32))
        return b.node(fresh("Reshape"), "Reshape", [x, shape])

    if isinstance(layer, nn.Reshape):
        if layer.batch_mode:
            tgt = [-1] + [int(d) for d in layer.shape]
        else:
            tgt = [int(d) for d in layer.shape]
        shape = b.const(fresh("shape"), np.asarray(tgt, np.int32))
        return b.node(fresh("Reshape"), "Reshape", [x, shape])

    if isinstance(layer, nn.Squeeze):
        dims = layer.dim
        attrs = {}
        if dims is not None:
            attrs["squeeze_dims"] = _attr_int_list(
                [int(d) for d in np.atleast_1d(dims)])
        return b.node(fresh("Squeeze"), "Squeeze", [x], **attrs)

    if isinstance(layer, (nn.Dropout, nn.Identity)):
        return b.node(fresh("Identity"), "Identity", [x])

    if isinstance(layer, nn.CAdd):
        bias = b.const(fresh("bias"), _np(params["bias"]).astype(np.float32))
        return b.node(fresh("AddV2"), "AddV2", [x, bias])

    if isinstance(layer, nn.CMul):
        w = b.const(fresh("weight"), _np(params["weight"]).astype(np.float32))
        return b.node(fresh("Mul"), "Mul", [x, w])

    if isinstance(layer, nn.CAddTable):
        out = ins[0]
        for other in ins[1:]:
            out = b.node(fresh("AddV2"), "AddV2", [out, other])
        return out

    if isinstance(layer, nn.JoinTable):
        axis = b.const(fresh("axis"), np.asarray(layer.dim, np.int32))
        return b.node(fresh("ConcatV2"), "ConcatV2", list(ins) + [axis],
                      N=_attr_i(len(ins)))

    if isinstance(layer, nn.ZeroPadding2D):
        ph, pw = layer.padding
        pads = b.const(fresh("pads"), np.asarray(
            [[0, 0], [ph, ph], [pw, pw], [0, 0]], np.int32))
        return b.node(fresh("Pad"), "Pad", [x, pads])

    if isinstance(layer, nn.PadOp):
        pads = b.const(fresh("pads"), np.asarray(layer.paddings, np.int32))
        return b.node(fresh("Pad"), "Pad", [x, pads])

    if isinstance(layer, nn.LeakyReLU):
        return b.node(fresh("LeakyRelu"), "LeakyRelu", [x],
                      alpha=_attr_f(layer.negval))

    act = _ACT_EXPORT.get(t)
    if act is not None:
        return b.node(fresh(act), act, [x])

    raise UnsupportedTFOp(f"cannot export layer {t}")


_ACT_EXPORT = {
    "ReLU": "Relu", "ReLU6": "Relu6", "ELU": "Elu", "Sigmoid": "Sigmoid",
    "Tanh": "Tanh", "SoftMax": "Softmax", "LogSoftMax": "LogSoftmax",
    "SoftPlus": "Softplus", "SoftSign": "Softsign", "Exp": "Exp",
    "Log": "Log", "Sqrt": "Sqrt", "Square": "Square", "Abs": "Abs",
    "Negative": "Neg", "Floor": "Floor", "Ceil": "Ceil", "Sign": "Sign",
    "Sin": "Sin", "Cos": "Cos",
}


def from_tf_function(fn, input_signature=None):
    """Live-trace a ``tf.function`` (or a callable taking tf tensors, e.g. a
    keras model's call) into the GraphDef importer: concrete function →
    variables frozen to constants → serialized GraphDef → ``load_tf_graph``.

    Reference analog: TFNet loading frozen TF graphs for inference
    (``scala/orca/.../net/TFNet`` ⚠, SURVEY.md §3.2).  The structural keras
    converter (``utils/keras_convert.from_tf_keras``) is the TRAINING path;
    this one covers arbitrary traced TF computations for inference.

    ``input_signature``: list of ``tf.TensorSpec`` (batch dim may be
    concrete) — required unless ``fn`` is already a concrete function.
    Returns ``(model, variables)``.
    """
    import tensorflow as tf
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    if not isinstance(fn, tf.types.experimental.ConcreteFunction):
        wrapped = fn if isinstance(fn, tf.types.experimental.PolymorphicFunction) \
            else tf.function(fn)
        if input_signature is None:
            raise ValueError("from_tf_function needs input_signature "
                             "(list of tf.TensorSpec)")
        fn = wrapped.get_concrete_function(*input_signature)
    frozen = convert_variables_to_constants_v2(fn)
    gdef = frozen.graph.as_graph_def()
    shapes = {}
    for t in frozen.inputs:
        name = t.name.split(":")[0]
        if t.shape.rank is not None:
            shapes[name] = [d if d is not None else 1 for d in t.shape]
    return load_tf_graph(gdef.SerializeToString(), input_shapes=shapes)
