"""Stock ``tf.keras`` model conversion — the Orca TF2 Estimator path.

Reference analog (unverified — mount empty): ``python/orca/src/bigdl/orca/
learn/tf2/estimator.py`` — ``Estimator.from_keras(model_creator)`` trains a
STOCK ``tf.keras`` model data-parallel (workers run
``MultiWorkerMirroredStrategy``).  TPU-native: the keras model is converted
ONCE to a native keras-engine :class:`Model` (weights carried over), trained
with the ZeRO-1 sharded step on the mesh, and trained weights export BACK
into the original keras model — TF never runs on the hot path, mirroring
what ``utils/torch_convert.py`` does for torch fx graphs.

Works against Keras 3 (the Keras bundled with TF 2.x in this image) via the
public layer/config/weights surface: the functional graph is walked through
each layer's inbound node (``input_tensors → output_tensors``), so
Sequential, functional (residual/multi-input) and nested Bidirectional
models all convert.
"""

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["from_tf_keras", "export_tf_keras_weights",
           "convert_keras_optimizer", "convert_keras_loss"]


class UnsupportedKerasLayer(NotImplementedError):
    pass


def _cfg(layer) -> Dict[str, Any]:
    return layer.get_config()


def _act_layer(name: Optional[str]):
    """keras activation string -> catalog layer instance (None = linear)."""
    from bigdl_tpu import nn as N

    if name is None or name == "linear":
        return None
    table = {
        "relu": N.ReLU, "relu6": N.ReLU6, "sigmoid": N.Sigmoid,
        "tanh": N.Tanh, "softmax": N.SoftMax, "gelu": N.GELU,
        "elu": N.ELU, "silu": N.SiLU, "swish": N.Swish,
        "softplus": N.SoftPlus, "softsign": N.SoftSign,
        "hard_sigmoid": N.HardSigmoid, "leaky_relu": N.LeakyReLU,
        "hard_silu": N.HardSwish, "hard_swish": N.HardSwish,
        "log_softmax": N.LogSoftMax, "mish": N.Mish,
        "exponential": N.Exp,
    }
    if name not in table:
        raise UnsupportedKerasLayer(f"activation {name!r}")
    return table[name]()


def _pad(cfg) -> str:
    p = cfg.get("padding", "valid")
    if p not in ("same", "valid"):
        raise UnsupportedKerasLayer(f"padding {p!r}")
    return p


def _require_channels_last(cfg, lname):
    if cfg.get("data_format", "channels_last") != "channels_last":
        raise UnsupportedKerasLayer(
            f"{lname}: channels_first (TPU-native layout is NHWC; rebuild "
            "the keras model channels_last)")


# ---------------------------------------------------------------------------
# per-layer converters: klayer -> list of (our_layer, params, state, kind)
# ``kind`` keys the weight-export transform (None = no weights)
# ---------------------------------------------------------------------------

def _conv_dense_like(klayer, cfg, our_layer, kind):
    w = klayer.get_weights()
    params = {"weight": w[0]}
    if cfg.get("use_bias", True):
        params["bias"] = w[1]
    steps = [(our_layer, params, {}, kind)]
    act = _act_layer(cfg.get("activation"))
    if act is not None:
        steps.append((act, {}, {}, None))
    return steps


def _convert_dense(klayer, cfg):
    from bigdl_tpu import nn as N

    k = klayer.get_weights()[0]
    layer = N.Linear(k.shape[0], k.shape[1],
                     with_bias=cfg.get("use_bias", True))
    return _conv_dense_like(klayer, cfg, layer, "dense")


def _convert_conv2d(klayer, cfg):
    from bigdl_tpu import nn as N

    _require_channels_last(cfg, "Conv2D")
    k = klayer.get_weights()[0]  # HWIO — same layout as ours
    groups = cfg.get("groups", 1)
    layer = N.Conv2D(k.shape[2] * groups, k.shape[3],
                     kernel_size=tuple(cfg["kernel_size"]),
                     stride=tuple(cfg["strides"]), padding=_pad(cfg),
                     dilation=tuple(cfg.get("dilation_rate", (1, 1))),
                     groups=groups, with_bias=cfg.get("use_bias", True))
    return _conv_dense_like(klayer, cfg, layer, "conv")


def _convert_conv1d(klayer, cfg):
    from bigdl_tpu import nn as N

    k = klayer.get_weights()[0]  # (k, in, out) — same as ours
    layer = N.Conv1D(k.shape[1], k.shape[2], kernel_size=k.shape[0],
                     stride=cfg["strides"][0],
                     padding="valid" if cfg["padding"] == "causal"
                     else _pad(cfg),
                     dilation=cfg.get("dilation_rate", (1,))[0],
                     causal=cfg["padding"] == "causal",
                     with_bias=cfg.get("use_bias", True))
    return _conv_dense_like(klayer, cfg, layer, "conv")


def _convert_depthwise(klayer, cfg):
    from bigdl_tpu import nn as N

    _require_channels_last(cfg, "DepthwiseConv2D")
    w = klayer.get_weights()
    kh, kw, cin, mult = w[0].shape
    layer = N.DepthwiseConv2D(cin, kernel_size=(kh, kw),
                              stride=tuple(cfg["strides"]),
                              padding=_pad(cfg), depth_multiplier=mult,
                              with_bias=cfg.get("use_bias", True))
    # keras (h,w,cin,mult) -> ours (h,w,1,cin*mult): C-order flatten keeps
    # output channel g*mult+m = keras [:, :, g, m]
    params = {"weight": w[0].reshape(kh, kw, 1, cin * mult)}
    if cfg.get("use_bias", True):
        params["bias"] = w[1]
    steps = [(layer, params, {}, "depthwise")]
    act = _act_layer(cfg.get("activation"))
    if act is not None:
        steps.append((act, {}, {}, None))
    return steps


def _convert_conv2d_transpose(klayer, cfg):
    from bigdl_tpu import nn as N

    _require_channels_last(cfg, "Conv2DTranspose")
    if tuple(cfg.get("dilation_rate", (1, 1))) != (1, 1):
        raise UnsupportedKerasLayer("Conv2DTranspose with dilation")
    if cfg.get("output_padding") not in (None, (0, 0)):
        raise UnsupportedKerasLayer("Conv2DTranspose output_padding")
    k = klayer.get_weights()[0]   # (kh, kw, out, in) — our storage exactly
    layer = N.Conv2DTranspose(k.shape[3], k.shape[2],
                              kernel_size=tuple(cfg["kernel_size"]),
                              stride=tuple(cfg["strides"]),
                              padding=_pad(cfg),
                              with_bias=cfg.get("use_bias", True))
    return _conv_dense_like(klayer, cfg, layer, "conv_transpose")


def _convert_separable(klayer, cfg):
    from bigdl_tpu import nn as N

    _require_channels_last(cfg, "SeparableConv2D")
    w = klayer.get_weights()
    dk, pk = w[0], w[1]           # (kh,kw,cin,mult), (1,1,cin*mult,out)
    kh, kw, cin, mult = dk.shape
    layer = N.SeparableConv2D(cin, pk.shape[3],
                              kernel_size=(kh, kw),
                              stride=tuple(cfg["strides"]),
                              padding=_pad(cfg), depth_multiplier=mult,
                              with_bias=cfg.get("use_bias", True))
    params = {"depthwise": {"weight": dk.reshape(kh, kw, 1, cin * mult)},
              "pointwise": {"weight": pk}}
    if cfg.get("use_bias", True):
        params["pointwise"]["bias"] = w[2]
    steps = [(layer, params, {}, "separable")]
    act = _act_layer(cfg.get("activation"))
    if act is not None:
        steps.append((act, {}, {}, None))
    return steps


def _convert_time_distributed(klayer, cfg):
    inner = klayer.layer
    if type(inner).__name__ != "Dense":
        raise UnsupportedKerasLayer(
            f"TimeDistributed({type(inner).__name__}) — only Dense (which "
            "the native Linear already applies per timestep)")
    return _convert_dense(inner, inner.get_config())


def _convert_mha(klayer, cfg):
    """keras-3 MultiHeadAttention: einsum-shaped per-head kernels
    (d, heads, head_dim) reshape onto the native fused projections
    (d, heads*head_dim)."""
    from bigdl_tpu import nn as N

    heads, kd = cfg["num_heads"], cfg["key_dim"]
    if cfg.get("value_dim") not in (None, kd):
        raise UnsupportedKerasLayer("MultiHeadAttention value_dim != key_dim")
    if cfg.get("output_shape") is not None:
        raise UnsupportedKerasLayer("MultiHeadAttention output_shape")
    if tuple(cfg.get("attention_axes") or (1,)) != (1,):
        raise UnsupportedKerasLayer("MultiHeadAttention attention_axes")
    if not cfg.get("use_bias", True):
        raise UnsupportedKerasLayer("MultiHeadAttention use_bias=False")
    w = klayer.get_weights()
    qk, qb, kk, kb, vk, vb, ok, ob = w
    d_model = qk.shape[0]
    h = heads * kd
    layer = N.MultiHeadAttention(h, heads, attn_dropout=cfg.get("dropout", 0))
    if h != d_model:
        # our wo is (h, d_model) already — shapes line up either way
        pass
    params = {
        "wq": qk.reshape(d_model, h), "bq": qb.reshape(h),
        "wk": kk.reshape(kk.shape[0], h), "bk": kb.reshape(h),
        "wv": vk.reshape(vk.shape[0], h), "bv": vb.reshape(h),
        "wo": ok.reshape(h, ok.shape[-1]), "bo": ob,
    }
    return [(layer, params, {}, "mha")]


def _convert_batchnorm(klayer, cfg):
    from bigdl_tpu import nn as N

    axis = cfg.get("axis", -1)
    if isinstance(axis, (list, tuple)):
        axis = axis[0]
    nd = len(klayer.input.shape)
    if axis not in (-1, nd - 1):
        raise UnsupportedKerasLayer(
            f"BatchNormalization over axis {axis} (only last-axis/NHWC)")
    scale = cfg.get("scale", True)
    center = cfg.get("center", True)
    if not (scale and center):
        raise UnsupportedKerasLayer("BatchNormalization without scale/center")
    gamma, beta, mean, var = klayer.get_weights()
    layer = N.BatchNorm(len(gamma), eps=cfg.get("epsilon", 1e-3),
                        momentum=1.0 - cfg.get("momentum", 0.99))
    return [(layer, {"weight": gamma, "bias": beta},
             {"running_mean": mean, "running_var": var}, "bn")]


def _convert_layernorm(klayer, cfg):
    from bigdl_tpu import nn as N

    axis = cfg.get("axis", -1)
    if isinstance(axis, (list, tuple)):
        axis = axis[-1] if len(axis) == 1 else axis
    nd = len(klayer.input.shape)
    if axis not in (-1, nd - 1):
        raise UnsupportedKerasLayer(f"LayerNormalization over axis {axis}")
    gamma, beta = klayer.get_weights()
    layer = N.LayerNorm(len(gamma), eps=cfg.get("epsilon", 1e-3))
    return [(layer, {"weight": gamma, "bias": beta}, {}, "ln")]


def _convert_embedding(klayer, cfg):
    from bigdl_tpu import nn as N

    if cfg.get("mask_zero"):
        raise UnsupportedKerasLayer(
            "Embedding(mask_zero=True): keras propagates an implicit mask "
            "into downstream RNNs; the converted graph would silently drop "
            "it — pad-bucket the data or pass masks explicitly instead")
    w = klayer.get_weights()[0]
    layer = N.Embedding(w.shape[0], w.shape[1])
    return [(layer, {"weight": w}, {}, "embedding")]


def _rnn_common_checks(cfg, lname):
    if cfg.get("activation", "tanh") != "tanh" or \
            cfg.get("recurrent_activation", "sigmoid") != "sigmoid":
        raise UnsupportedKerasLayer(f"{lname}: non-default activations")
    for flag in ("return_state", "stateful", "unroll"):
        if cfg.get(flag):
            raise UnsupportedKerasLayer(f"{lname}: {flag}=True")
    if cfg.get("dropout", 0.0) or cfg.get("recurrent_dropout", 0.0):
        raise UnsupportedKerasLayer(f"{lname}: recurrent dropout")


def _lstm_parts(klayer, cfg):
    """(our LSTM layer, params) — keras gate order i,f,c,o == ours i,f,g,o."""
    from bigdl_tpu import nn as N

    _rnn_common_checks(cfg, "LSTM")
    w = klayer.get_weights()
    kernel, rec = w[0], w[1]
    layer = N.LSTM(kernel.shape[0], rec.shape[0],
                   return_sequences=cfg.get("return_sequences", False),
                   go_backwards=cfg.get("go_backwards", False))
    params = {"w_in": kernel, "w_rec": rec,
              "bias": w[2] if cfg.get("use_bias", True)
              else np.zeros((kernel.shape[1],), np.float32)}
    return layer, params


def _gru_parts(klayer, cfg):
    """keras GRU (gate order z,r,h; reset_after=True) -> ours (r,z,n with
    recurrent bias)."""
    from bigdl_tpu import nn as N

    _rnn_common_checks(cfg, "GRU")
    if not cfg.get("reset_after", True):
        raise UnsupportedKerasLayer(
            "GRU reset_after=False (the pre-matmul reset form; the native "
            "GRU implements the keras-default reset_after=True recurrence)")
    w = klayer.get_weights()
    kernel, rec = w[0], w[1]
    h = rec.shape[0]

    def permute(m):  # columns [z,r,h] -> [r,z,n]
        z, r, n = np.split(m, 3, axis=-1)
        return np.concatenate([r, z, n], axis=-1)

    layer = N.GRU(kernel.shape[0], h,
                  return_sequences=cfg.get("return_sequences", False),
                  go_backwards=cfg.get("go_backwards", False))
    params = {"w_in": permute(kernel), "w_rec": permute(rec)}
    if cfg.get("use_bias", True):
        bias = w[2]
        if bias.ndim == 2:  # (2, 3h): input bias + recurrent bias
            params["bias"] = permute(bias[0])
            params["bias_rec"] = permute(bias[1])
        else:
            params["bias"] = permute(bias)
    else:
        params["bias"] = np.zeros((3 * h,), np.float32)
    return layer, params


def _simplernn_parts(klayer, cfg):
    from bigdl_tpu import nn as N

    if cfg.get("activation", "tanh") != "tanh":
        raise UnsupportedKerasLayer("SimpleRNN: non-tanh activation")
    for flag in ("return_state", "stateful", "unroll"):
        if cfg.get(flag):
            raise UnsupportedKerasLayer(f"SimpleRNN: {flag}=True")
    if cfg.get("dropout", 0.0) or cfg.get("recurrent_dropout", 0.0):
        raise UnsupportedKerasLayer("SimpleRNN: recurrent dropout")
    w = klayer.get_weights()
    layer = N.SimpleRNN(w[0].shape[0], w[1].shape[0],
                        return_sequences=cfg.get("return_sequences", False),
                        go_backwards=cfg.get("go_backwards", False))
    params = {"w_in": w[0], "w_rec": w[1],
              "bias": (w[2] if cfg.get("use_bias", True)
                       else np.zeros((w[0].shape[1],), np.float32))}
    return layer, params


def _convert_simplernn(klayer, cfg):
    layer, params = _simplernn_parts(klayer, cfg)
    return [(layer, params, {}, "lstm")]   # same 3-blob export layout


def _convert_lstm(klayer, cfg):
    layer, params = _lstm_parts(klayer, cfg)
    return [(layer, params, {}, "lstm")]


def _convert_gru(klayer, cfg):
    layer, params = _gru_parts(klayer, cfg)
    return [(layer, params, {}, "gru")]


def _convert_convlstm2d(klayer, cfg):
    """keras ConvLSTM2D: separate input/recurrent kernels (kh,kw,cin,4f) /
    (kh,kw,f,4f), gate order i,f,c,o — concatenated along the input-channel
    axis they ARE the native fused [x;h] kernel."""
    from bigdl_tpu import nn as N

    _require_channels_last(cfg, "ConvLSTM2D")
    if cfg.get("activation", "tanh") != "tanh" or \
            cfg.get("recurrent_activation", "sigmoid") != "sigmoid":
        raise UnsupportedKerasLayer("ConvLSTM2D: non-default activations")
    if tuple(cfg.get("strides", (1, 1))) != (1, 1) or \
            cfg.get("padding") != "same":
        raise UnsupportedKerasLayer(
            "ConvLSTM2D: needs strides=1, padding='same' (the native "
            "recurrence keeps the spatial shape)")
    if cfg.get("dropout", 0.0) or cfg.get("recurrent_dropout", 0.0):
        raise UnsupportedKerasLayer("ConvLSTM2D: recurrent dropout")
    w = klayer.get_weights()
    kernel, rec = w[0], w[1]
    kh, kw, cin, four_f = kernel.shape
    f = four_f // 4
    layer = N.ConvLSTM2D(cin, f, (kh, kw), peephole=False,
                         return_sequences=cfg.get("return_sequences", False))
    params = {"weight": np.concatenate([kernel, rec], axis=2),
              "bias": (w[2] if cfg.get("use_bias", True)
                       else np.zeros((four_f,), np.float32))}
    return [(layer, params, {}, "convlstm")]


def _convert_bidirectional(klayer, cfg):
    from bigdl_tpu import nn as N

    mode = cfg.get("merge_mode", "concat")
    if mode not in ("concat", "sum"):
        raise UnsupportedKerasLayer(f"Bidirectional merge_mode {mode!r}")
    fwd_k, bwd_k = klayer.forward_layer, klayer.backward_layer
    inner = type(fwd_k).__name__
    if inner == "LSTM":
        parts, kind = _lstm_parts, "bilstm"
    elif inner == "GRU":
        parts, kind = _gru_parts, "bigru"
    elif inner == "SimpleRNN":
        parts, kind = _simplernn_parts, "bilstm"  # same 3-blob export
    else:
        raise UnsupportedKerasLayer(f"Bidirectional({inner})")
    f_layer, f_params = parts(fwd_k, fwd_k.get_config())
    b_layer, b_params = parts(bwd_k, bwd_k.get_config())
    b_layer.go_backwards = True
    layer = N.BiRecurrent(f_layer, b_layer, merge=mode)
    return [(layer, {"fwd": f_params, "bwd": b_params}, {}, kind)]


def _convert_prelu(klayer, cfg):
    from bigdl_tpu import nn as N

    alpha = klayer.get_weights()[0]
    if alpha.ndim != 1:
        alpha = alpha.reshape(-1)
    return [(N.PReLU(len(alpha)), {"alpha": alpha}, {}, "prelu")]


def _no_weight(builder):
    def convert(klayer, cfg):
        layer = builder(klayer, cfg)
        return [(layer, {}, {}, None)] if layer is not None else []
    return convert


def _merge(our_name):
    def build(klayer, cfg):
        from bigdl_tpu import nn as N

        return getattr(N, our_name)()
    return _no_weight(build)


def _build_pool2d(cls_name):
    def build(klayer, cfg):
        from bigdl_tpu import nn as N

        _require_channels_last(cfg, cls_name)
        return getattr(N, cls_name)(
            kernel_size=tuple(cfg["pool_size"]),
            stride=tuple(cfg["strides"] or cfg["pool_size"]),
            padding=_pad(cfg))
    return _no_weight(build)


def _build_pool1d(cls_name):
    def build(klayer, cfg):
        from bigdl_tpu import nn as N

        ps = cfg["pool_size"]
        ps = ps[0] if isinstance(ps, (list, tuple)) else ps
        st = cfg["strides"] or ps
        st = st[0] if isinstance(st, (list, tuple)) else st
        return getattr(N, cls_name)(kernel_size=ps, stride=st,
                                    padding=_pad(cfg))
    return _no_weight(build)


def _build_global_pool(cls_name):
    def build(klayer, cfg):
        from bigdl_tpu import nn as N

        if cfg.get("keepdims"):
            raise UnsupportedKerasLayer(f"{cls_name} keepdims=True")
        return getattr(N, cls_name)()
    return _no_weight(build)


def _build_activation(klayer, cfg):
    act = cfg["activation"]
    if not isinstance(act, str):
        raise UnsupportedKerasLayer(f"Activation({act!r})")
    layer = _act_layer(act)
    if layer is None:
        return []
    return [(layer, {}, {}, None)]


def _build_relu(klayer, cfg):
    from bigdl_tpu import nn as N

    max_value = cfg.get("max_value")
    slope = cfg.get("negative_slope", 0.0)
    if max_value not in (None, 6.0) or cfg.get("threshold", 0.0) \
            or (max_value is not None and slope):
        raise UnsupportedKerasLayer(
            "ReLU with max_value/threshold/negative_slope combination")
    if slope:
        layer = N.LeakyReLU(slope)
    elif max_value == 6.0:
        layer = N.ReLU6()
    else:
        layer = N.ReLU()
    return [(layer, {}, {}, None)]


_CONVERTERS = {
    "Dense": _convert_dense,
    "Conv2D": _convert_conv2d,
    "Conv1D": _convert_conv1d,
    "DepthwiseConv2D": _convert_depthwise,
    "Conv2DTranspose": _convert_conv2d_transpose,
    "SeparableConv2D": _convert_separable,
    "TimeDistributed": _convert_time_distributed,
    "MultiHeadAttention": _convert_mha,
    "BatchNormalization": _convert_batchnorm,
    "LayerNormalization": _convert_layernorm,
    "Embedding": _convert_embedding,
    "LSTM": _convert_lstm,
    "SimpleRNN": _convert_simplernn,
    "GRU": _convert_gru,
    "Bidirectional": _convert_bidirectional,
    "ConvLSTM2D": _convert_convlstm2d,
    "PReLU": _convert_prelu,
    "Activation": _build_activation,
    "ReLU": _build_relu,
    "MaxPooling2D": _build_pool2d("MaxPool2D"),
    "AveragePooling2D": _build_pool2d("AvgPool2D"),
    "MaxPooling1D": _build_pool1d("MaxPool1D"),
    "AveragePooling1D": _build_pool1d("AvgPool1D"),
    "GlobalAveragePooling2D": _build_global_pool("GlobalAvgPool2D"),
    "GlobalMaxPooling2D": _build_global_pool("GlobalMaxPool2D"),
    "GlobalAveragePooling1D": _build_global_pool("GlobalAvgPool1D"),
    "GlobalMaxPooling1D": _build_global_pool("GlobalMaxPool1D"),
    "Add": _merge("CAddTable"),
    "Multiply": _merge("CMulTable"),
    "Subtract": _merge("CSubTable"),
    "Average": _merge("CAveTable"),
    "Maximum": _merge("CMaxTable"),
    "Minimum": _merge("CMinTable"),
    "Softmax": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["SoftMax"]).SoftMax()),
    "Flatten": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["Flatten"]).Flatten()),
    "Dropout": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["Dropout"]).Dropout(cfg["rate"])),
    "SpatialDropout2D": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["SpatialDropout2D"]).SpatialDropout2D(
            cfg["rate"])),
    "Reshape": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["Reshape"]).Reshape(
            tuple(cfg["target_shape"]))),
    "Permute": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["Permute"]).Permute(
            tuple(cfg["dims"]))),
    "ZeroPadding2D": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["ZeroPadding2D"]).ZeroPadding2D(
            tuple(tuple(p) for p in cfg["padding"]))),
    "UpSampling2D": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["UpSampling2D"]).UpSampling2D(
            tuple(cfg["size"]))),
    "Identity": _no_weight(lambda kl, cfg: None),
    "Cropping2D": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["Cropping2D"]).Cropping2D(
            tuple(tuple(c) for c in cfg["cropping"]))),
    "Cropping1D": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["Cropping1D"]).Cropping1D(
            tuple(cfg["cropping"]))),
    "ZeroPadding1D": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["ZeroPadding1D"]).ZeroPadding1D(
            tuple(cfg["padding"]) if isinstance(cfg["padding"], (list, tuple))
            else cfg["padding"])),
    "UpSampling1D": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["UpSampling1D"]).UpSampling1D(
            int(cfg["size"]))),
    "GaussianNoise": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["GaussianNoise"]).GaussianNoise(
            cfg["stddev"])),
    "GaussianDropout": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["GaussianDropout"]).GaussianDropout(
            cfg["rate"])),
    "RepeatVector": _no_weight(lambda kl, cfg: __import__(
        "bigdl_tpu.nn", fromlist=["RepeatVector"]).RepeatVector(
            int(cfg["n"]))),
}


def _convert_concat(klayer, cfg, nd_hint=None):
    from bigdl_tpu import nn as N

    axis = cfg.get("axis", -1)
    return [(N.JoinTable(axis), {}, {}, None)]


def from_tf_keras(kmodel):
    """Convert a stock (built) tf.keras model → ``(Model, variables)``.

    Walks each layer's inbound node via the public Keras-3 node API; weights
    carry over in our conventions (Dense (in,out), Conv HWIO, LSTM fused
    i,f,g,o gates).  The returned model carries ``_tfkeras_export_map`` so
    :func:`export_tf_keras_weights` can write trained weights back."""
    from bigdl_tpu.keras.engine import Input, Model

    if not getattr(kmodel, "built", True) or not getattr(
            kmodel, "inputs", None):
        raise ValueError(
            "from_tf_keras: model must be built with known input shapes "
            "(use an Input layer or call build())")

    sym: Dict[int, Any] = {}      # id(KerasTensor) -> engine Node
    inputs = []
    for kt in kmodel.inputs:
        if any(d is None for d in kt.shape[1:]):
            raise UnsupportedKerasLayer(
                f"input {kt.name!r} has dynamic non-batch dims "
                f"{tuple(kt.shape)} — XLA needs static shapes; rebuild the "
                "keras model with concrete sequence/spatial dims (pad or "
                "bucket variable-length data)")
        shape = tuple(int(d) for d in kt.shape[1:])
        node = Input(shape)
        sym[id(kt)] = node
        inputs.append(node)

    params: Dict[str, Dict] = {}
    state: Dict[str, Dict] = {}
    export_map: List[Tuple[str, str, str]] = []  # (keras name, kind, node)

    pending = [l for l in kmodel.layers
               if type(l).__name__ != "InputLayer"]
    progress = True
    while pending and progress:
        progress = False
        for klayer in list(pending):
            nodes = [n for n in getattr(klayer, "_inbound_nodes", [])
                     if all(id(t) in sym for t in n.input_tensors)]
            if not nodes:
                continue
            if len(nodes) > 1:
                raise UnsupportedKerasLayer(
                    f"layer {klayer.name!r} is used more than once (shared "
                    "weights are not representable in the converted graph)")
            knode = nodes[0]
            lname = type(klayer).__name__
            cfg = _cfg(klayer)
            if lname == "Concatenate":
                steps = _convert_concat(klayer, cfg)
            elif lname in _CONVERTERS:
                steps = _CONVERTERS[lname](klayer, cfg)
            else:
                raise UnsupportedKerasLayer(
                    f"no conversion for keras layer {lname} "
                    f"({klayer.name!r})")

            parents = [sym[id(t)] for t in knode.input_tensors]
            if lname == "MultiHeadAttention":
                # call(query, value, key=value): our layer consumes
                # (x, context) with k and v both from context
                if len(parents) == 3:
                    if knode.input_tensors[1] is not knode.input_tensors[2]:
                        raise UnsupportedKerasLayer(
                            "MultiHeadAttention with key is not value")
                    parents = parents[:2]
                if len(parents) == 2 and parents[0] is parents[1]:
                    parents = parents[:1]          # plain self-attention
            if not steps:  # identity-like
                out = parents[0]
            else:
                out = None
                for i, (layer, p, s, kind) in enumerate(steps):
                    src = parents if (i == 0 and len(parents) > 1) \
                        else (out if out is not None else parents[0])
                    out = layer(src)
                    if p:
                        params[out.name] = p
                    if s:
                        state[out.name] = s
                    if kind is not None:
                        export_map.append((klayer.name, kind, out.name))
            for t in knode.output_tensors:
                sym[id(t)] = out
            pending.remove(klayer)
            progress = True
    if pending:
        raise UnsupportedKerasLayer(
            f"could not resolve graph inputs for layers "
            f"{[l.name for l in pending]}")

    outputs = [sym[id(t)] for t in kmodel.outputs]
    model = Model(inputs, outputs, name="KerasConverted")
    model._tfkeras_export_map = export_map

    def _np(tree):
        if isinstance(tree, dict):
            return {k: _np(v) for k, v in tree.items()}
        return np.asarray(tree, np.float32)

    return model, {"params": _np(params), "state": _np(state)}


# ---------------------------------------------------------------------------
# export back into the live keras model
# ---------------------------------------------------------------------------

def _unpermute_gru(m):  # ours [r,z,n] -> keras [z,r,h]
    r, z, n = np.split(np.asarray(m), 3, axis=-1)
    return np.concatenate([z, r, n], axis=-1)


def _rnn_weights(kind, p, klayer_cfg_use_bias=True):
    if kind == "lstm":
        out = [np.asarray(p["w_in"]), np.asarray(p["w_rec"])]
        if klayer_cfg_use_bias:
            out.append(np.asarray(p["bias"]))
        return out
    # gru
    out = [_unpermute_gru(p["w_in"]), _unpermute_gru(p["w_rec"])]
    if klayer_cfg_use_bias:
        if "bias_rec" in p:
            out.append(np.stack([_unpermute_gru(p["bias"]),
                                 _unpermute_gru(p["bias_rec"])]))
        else:
            out.append(_unpermute_gru(p["bias"]))
    return out


def export_tf_keras_weights(model, variables, kmodel) -> None:
    """Write trained ``variables`` back into the ORIGINAL keras model
    in-place (``set_weights``), completing the round trip."""
    params = variables.get("params", variables)
    state = variables.get("state", {})
    by_name = {l.name: l for l in kmodel.layers}
    for kname, kind, node_name in getattr(model, "_tfkeras_export_map", []):
        klayer = by_name[kname]
        p = params.get(node_name, {})
        s = state.get(node_name, {})
        use_bias = klayer.get_config().get("use_bias", True)
        if kind in ("dense", "conv", "conv_transpose"):
            w = [np.asarray(p["weight"])]
            if "bias" in p:
                w.append(np.asarray(p["bias"]))
        elif kind == "separable":
            dw = np.asarray(p["depthwise"]["weight"])
            kh, kw, _one, cm = dw.shape
            mult = klayer.get_config().get("depth_multiplier", 1)
            w = [dw.reshape(kh, kw, cm // mult, mult),
                 np.asarray(p["pointwise"]["weight"])]
            if "bias" in p["pointwise"]:
                w.append(np.asarray(p["pointwise"]["bias"]))
        elif kind == "depthwise":
            kh, kw, _one, cout = np.asarray(p["weight"]).shape
            mult = klayer.get_config().get("depth_multiplier", 1)
            w = [np.asarray(p["weight"]).reshape(kh, kw, cout // mult, mult)]
            if use_bias:
                w.append(np.asarray(p["bias"]))
        elif kind == "bn":
            w = [np.asarray(p["weight"]), np.asarray(p["bias"]),
                 np.asarray(s["running_mean"]), np.asarray(s["running_var"])]
        elif kind == "ln":
            w = [np.asarray(p["weight"]), np.asarray(p["bias"])]
        elif kind == "embedding":
            w = [np.asarray(p["weight"])]
        elif kind == "convlstm":
            fused = np.asarray(p["weight"])
            kcfg = klayer.get_config()
            cin = fused.shape[2] - fused.shape[3] // 4
            w = [fused[:, :, :cin], fused[:, :, cin:]]
            if kcfg.get("use_bias", True):
                w.append(np.asarray(p["bias"]))
        elif kind in ("lstm", "gru"):
            w = _rnn_weights(kind, p, use_bias)
        elif kind in ("bilstm", "bigru"):
            inner = kind[2:]
            w = (_rnn_weights(inner, p["fwd"], use_bias)
                 + _rnn_weights(inner, p["bwd"], use_bias))
        elif kind == "mha":
            kcfg = klayer.get_config()
            heads, kd = kcfg["num_heads"], kcfg["key_dim"]
            w = [np.asarray(p["wq"]).reshape(-1, heads, kd),
                 np.asarray(p["bq"]).reshape(heads, kd),
                 np.asarray(p["wk"]).reshape(-1, heads, kd),
                 np.asarray(p["bk"]).reshape(heads, kd),
                 np.asarray(p["wv"]).reshape(-1, heads, kd),
                 np.asarray(p["bv"]).reshape(heads, kd),
                 np.asarray(p["wo"]).reshape(heads, kd, -1),
                 np.asarray(p["bo"])]
        elif kind == "prelu":
            cur = klayer.get_weights()[0]
            w = [np.asarray(p["alpha"]).reshape(cur.shape)]
        else:  # pragma: no cover
            raise ValueError(f"unknown export kind {kind}")
        klayer.set_weights(w)


# ---------------------------------------------------------------------------
# optimizer / loss mapping (keras compile() objects -> native)
# ---------------------------------------------------------------------------

def convert_keras_optimizer(kopt):
    """keras.optimizers.* -> native OptimMethod."""
    from bigdl_tpu.optim import optim_method as OM

    name = type(kopt).__name__
    lr = float(np.asarray(kopt.learning_rate))
    wd = float(kopt.weight_decay or 0.0) if hasattr(kopt, "weight_decay") \
        else 0.0
    if name == "SGD":
        return OM.SGD(learning_rate=lr,
                      momentum=float(np.asarray(
                          getattr(kopt, "momentum", 0.0))),
                      weight_decay=wd, nesterov=bool(
                          getattr(kopt, "nesterov", False)))
    if name == "AdamW" or (name == "Adam" and wd):
        return OM.AdamWeightDecay(
            learning_rate=lr, beta1=float(kopt.beta_1),
            beta2=float(kopt.beta_2), epsilon=float(kopt.epsilon),
            weight_decay=wd)
    if name == "Adam":
        return OM.Adam(learning_rate=lr, beta1=float(kopt.beta_1),
                       beta2=float(kopt.beta_2), epsilon=float(kopt.epsilon))
    if name == "RMSprop":
        return OM.RMSprop(learning_rate=lr, decay_rate=float(kopt.rho),
                          epsilon=float(kopt.epsilon))
    if name == "Adagrad":
        return OM.Adagrad(learning_rate=lr)
    if name == "Adadelta":
        return OM.Adadelta(learning_rate=lr, decay_rate=float(kopt.rho),
                           epsilon=float(kopt.epsilon))
    raise NotImplementedError(f"no mapping for keras optimizer {name}")


class _OneHotLogitsCE:
    """Categorical cross-entropy over LOGITS with one-hot targets
    (keras CategoricalCrossentropy(from_logits=True))."""

    def forward(self, output, target):
        import jax
        import jax.numpy as jnp

        logp = jax.nn.log_softmax(output.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.sum(target * logp, axis=-1))

    def __call__(self, output, target):
        return self.forward(output, target)


class _ProbNLL:
    """NLL over PROBABILITIES (keras from_logits=False models end in
    softmax) — log + ClassNLL, matching sparse_categorical_crossentropy."""

    def __init__(self):
        from bigdl_tpu.nn.criterion import ClassNLLCriterion

        self._nll = ClassNLLCriterion()

    def forward(self, output, target):
        import jax.numpy as jnp

        return self._nll.forward(jnp.log(jnp.maximum(output, 1e-12)), target)

    def __call__(self, output, target):
        return self.forward(output, target)


def convert_keras_loss(kloss):
    """keras loss (string or object) -> native criterion."""
    from bigdl_tpu.nn import criterion as C
    from bigdl_tpu.nn import criterion_extra as CE

    if isinstance(kloss, str):
        name = kloss
        from_logits = False
    else:
        name = type(kloss).__name__
        from_logits = bool(getattr(kloss, "from_logits", False))
        # keras serializes config on the instance for the functional form
        if hasattr(kloss, "get_config"):
            try:
                from_logits = bool(
                    kloss.get_config().get("from_logits", from_logits))
            except Exception:
                pass
    key = name.lower()
    if key in ("sparsecategoricalcrossentropy",
               "sparse_categorical_crossentropy"):
        return C.CrossEntropyCriterion() if from_logits else _ProbNLL()
    if key in ("categoricalcrossentropy", "categorical_crossentropy"):
        return _OneHotLogitsCE() if from_logits \
            else CE.CategoricalCrossEntropy()
    if key in ("meansquarederror", "mse", "mean_squared_error"):
        return C.MSECriterion()
    if key in ("meanabsoluteerror", "mae", "mean_absolute_error"):
        return C.AbsCriterion()
    if key in ("binarycrossentropy", "binary_crossentropy"):
        return C.BCEWithLogitsCriterion() if from_logits else C.BCECriterion()
    if key in ("huber",):
        return C.SmoothL1Criterion()
    if key in ("kldivergence", "kl_divergence"):
        return CE.KullbackLeiblerDivergenceCriterion()
    if key in ("poisson",):
        return CE.PoissonCriterion()
    raise NotImplementedError(f"no mapping for keras loss {name}")
