"""IR graph + execution-engine retargeting — ``utils/intermediate`` analog.

Reference analog (unverified — mount empty):
``utils/intermediate/{IRGraph,IRToBlas,IRToDnn}.scala`` — a built graph is
lifted to an engine-neutral IR and re-emitted for either the ``mklblas``
engine or the ``mkldnn`` engine, where ``nn/mkldnn/Fusion.scala`` applies
inference rewrites (conv+bn fold, conv+relu fusion) before lowering to
oneDNN primitives.

TPU-native re-design: the two engines become

- ``"xla"``   — plain catalog modules; XLA's own fuser does the elementwise
  stitching (the mklblas analog, and the identity rebuild).
- ``"fused"`` — inference-oriented rewrites before compilation (the mkldnn
  ``Phase.INFERENCE`` analog):
    * ``Conv2D → BatchNorm``  folded into the conv weights/bias
      (``Fusion.scala`` fuseConvBn)
    * ``Linear → BatchNorm``  folded likewise
    * ``LayerNorm``           re-emitted as the single-pass Pallas kernel
      (``ops.fused.fused_layernorm``)
    * ``Dropout`` / ``Identity`` dropped (no-ops in inference)

Usage::

    ir = IRGraph.from_model(model, variables)      # Sequential or keras Model
    fast, fast_vars = ir.to_model(engine="fused")  # inference-ready twin
    same, same_vars = ir.to_model(engine="xla")    # identity rebuild

The returned pair is a keras-engine functional ``Model`` + variables; the
original model is untouched (functional discipline, like ``nn.quantized``).
"""

import copy
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import EMPTY, Module, Sequential


class PallasLayerNorm(Module):
    """LayerNorm twin backed by the single-pass Pallas kernel (params are
    interchangeable with ``nn.LayerNorm``)."""

    def __init__(self, num_features: Optional[int] = None, eps: float = 1e-6,
                 name=None):
        super().__init__(name)
        self.num_features = num_features
        self.eps = eps

    def build(self, rng, x):
        c = self.num_features or x.shape[-1]
        return {"weight": jnp.ones((c,)), "bias": jnp.zeros((c,))}, EMPTY

    def forward(self, params, state, x, training=False, rng=None):
        from bigdl_tpu.ops.fused import fused_layernorm

        shape = x.shape
        x2 = x.reshape((-1, shape[-1]))
        y = fused_layernorm(x2, params["weight"], params["bias"],
                            eps=self.eps)
        return y.reshape(shape), EMPTY


class IRNode:
    """One op in the engine-neutral graph."""

    __slots__ = ("layer", "params", "state", "parents", "is_input", "uid")
    _counter = [0]

    def __init__(self, layer=None, params=None, state=None, parents=(),
                 is_input=False):
        IRNode._counter[0] += 1
        self.uid = IRNode._counter[0]
        self.layer = layer
        self.params = dict(params or {})
        self.state = dict(state or {})
        self.parents: List[IRNode] = list(parents)
        self.is_input = is_input

    def __repr__(self):
        t = "Input" if self.is_input else type(self.layer).__name__
        return f"IRNode({t}#{self.uid})"


class IRGraph:
    """Engine-neutral graph of IRNodes (reference ``IRGraph.scala``)."""

    def __init__(self, inputs: List[IRNode], outputs: List[IRNode],
                 order: List[IRNode]):
        self.inputs = inputs
        self.outputs = outputs
        self.order = order  # topological, inputs included

    # ------------------------------------------------------------------ build
    @staticmethod
    def from_model(model, variables: Dict[str, Any]) -> "IRGraph":
        from bigdl_tpu.keras.engine import Model as KModel

        params = variables.get("params", EMPTY) or {}
        state = variables.get("state", EMPTY) or {}
        if isinstance(model, KModel):
            by_id: Dict[int, IRNode] = {}
            order: List[IRNode] = []
            inputs: List[IRNode] = []
            for node in model.order:
                if node.layer is None:
                    ir = IRNode(is_input=True)
                    inputs.append(ir)
                else:
                    ir = IRNode(node.layer, params.get(node.name, {}),
                                state.get(node.name, {}),
                                [by_id[p.id] for p in node.parents])
                by_id[node.id] = ir
                order.append(ir)
            outputs = [by_id[o.id] for o in model.outputs]
            return IRGraph(inputs, outputs, order)
        if isinstance(model, Sequential):
            inp = IRNode(is_input=True)
            order = [inp]
            cur = inp
            cur = IRGraph._chain_sequential(model, params, state, cur, order)
            return IRGraph([inp], [cur], order)
        raise TypeError(f"cannot lift {type(model).__name__} to IR")

    @staticmethod
    def _chain_sequential(seq: Sequential, params, state, cur, order):
        for i, child in enumerate(seq.layers):
            k = seq._key(i)
            cp = params.get(k, EMPTY) if params else EMPTY
            cs = state.get(k, EMPTY) if state else EMPTY
            if isinstance(child, Sequential):
                cur = IRGraph._chain_sequential(child, cp, cs, cur, order)
            else:
                node = IRNode(child, cp, cs, [cur])
                order.append(node)
                cur = node
        return cur

    # ------------------------------------------------------------ retargeting
    def to_model(self, engine: str = "xla"):
        """Emit a (keras Model, variables) pair for the given engine."""
        if engine not in ("xla", "fused"):
            raise ValueError(f"unknown engine {engine!r}: 'xla' or 'fused'")
        nodes = list(self.order)
        outputs = list(self.outputs)
        if engine == "fused":
            nodes, outputs = _fuse_pass(nodes, outputs)
        return _emit(self.inputs, nodes, outputs)


# ---------------------------------------------------------------------------
# fusion pass (reference nn/mkldnn/Fusion.scala, inference phase)
# ---------------------------------------------------------------------------


def _consumer_counts(nodes: List[IRNode]) -> Dict[int, int]:
    c: Dict[int, int] = {}
    for n in nodes:
        for p in n.parents:
            c[p.uid] = c.get(p.uid, 0) + 1
    return c


def _copy_graph(nodes: List[IRNode], outputs: List[IRNode]):
    """Uid-preserving deep copy of the node list (parents remapped into the
    copies).  The fuse pass rewires parents in place; operating on copies
    keeps the IRGraph itself immutable so a later ``to_model("xla")`` on the
    same graph still emits the original wiring."""
    by_uid: Dict[int, IRNode] = {}
    copies = []
    for n in nodes:
        c = copy.copy(n)          # keeps uid (slot-for-slot copy)
        c.params = dict(n.params)
        c.state = dict(n.state)
        c.parents = [by_uid[p.uid] for p in n.parents]
        by_uid[c.uid] = c
        copies.append(c)
    return copies, [by_uid[o.uid] for o in outputs]


def _fuse_pass(nodes: List[IRNode], outputs: List[IRNode]):
    from bigdl_tpu.nn import layers as L
    from bigdl_tpu.nn.module import Identity

    nodes, outputs = _copy_graph(nodes, outputs)
    out_ids = {o.uid for o in outputs}

    # 1. drop inference no-ops (Dropout, Identity) by rewiring consumers
    drop = {}
    for n in nodes:
        if n.layer is not None and isinstance(n.layer,
                                              (L.Dropout, Identity)) \
                and len(n.parents) == 1:
            drop[n.uid] = n.parents[0]
    if drop:
        def resolve(p: IRNode) -> IRNode:
            while p.uid in drop:
                p = drop[p.uid]
            return p
        for n in nodes:
            n.parents = [resolve(p) for p in n.parents]
        outputs = [resolve(o) for o in outputs]
        out_ids = {o.uid for o in outputs}
        nodes = [n for n in nodes if n.uid not in drop]

    # 2. fold BatchNorm into a preceding single-consumer Conv2D/Linear
    counts = _consumer_counts(nodes)
    folded: Dict[int, IRNode] = {}
    for n in nodes:
        if n.uid in folded:
            continue
        lay = n.layer
        if lay is None or not isinstance(lay, L.BatchNorm):
            continue
        if len(n.parents) != 1:
            continue
        prod = n.parents[0]
        if prod.uid in folded or prod.layer is None:
            continue
        if not isinstance(prod.layer, (L.Conv2D, L.Linear)):
            continue
        if type(prod.layer) not in (L.Conv2D, L.Linear):
            continue  # exact types only: subclasses may scale differently
        if counts.get(prod.uid, 0) != 1 or prod.uid in out_ids:
            continue
        if not n.state:
            continue
        mean = np.asarray(n.state["running_mean"], np.float64)
        var = np.asarray(n.state["running_var"], np.float64)
        eps = lay.eps
        if lay.affine:
            gamma = np.asarray(n.params["weight"], np.float64)
            beta = np.asarray(n.params["bias"], np.float64)
        else:
            gamma = np.ones_like(mean)
            beta = np.zeros_like(mean)
        scale = gamma / np.sqrt(var + eps)  # per-out-channel

        new = copy.copy(prod)
        new.params = dict(prod.params)
        w = np.asarray(prod.params["weight"], np.float64)
        # Conv2D weight (kh,kw,cin,cout), Linear weight (in,out): the out
        # channel is the LAST axis for both
        new.params["weight"] = (w * scale).astype(np.float32)
        old_bias = (np.asarray(prod.params["bias"], np.float64)
                    if prod.layer.with_bias else 0.0)
        new_bias = ((old_bias - mean) * scale + beta).astype(np.float32)
        if not prod.layer.with_bias:
            new.layer = copy.copy(prod.layer)
            new.layer.with_bias = True
        new.params["bias"] = new_bias
        folded[prod.uid] = new
        folded[n.uid] = new  # BN node itself resolves to the fused conv

    if folded:
        def resolve2(p: IRNode) -> IRNode:
            seen = set()
            while p.uid in folded and p.uid not in seen:
                seen.add(p.uid)
                p = folded[p.uid]
            return p
        new_nodes = []
        emitted = set()
        for n in nodes:
            r = resolve2(n)
            if r.uid in emitted:
                continue
            if n.uid in folded and folded[n.uid] is not r:
                continue
            r.parents = [resolve2(p) for p in r.parents]
            new_nodes.append(r)
            emitted.add(r.uid)
        # BN nodes resolve to their fused producer; drop originals
        nodes = [n for n in new_nodes
                 if not (n.uid in folded and folded[n.uid] is not n)]
        outputs = [resolve2(o) for o in outputs]

    # 3. LayerNorm -> Pallas kernel twin
    for n in nodes:
        if n.layer is not None and type(n.layer).__name__ == "LayerNorm":
            ln = n.layer
            n.layer = PallasLayerNorm(ln.num_features, eps=ln.eps,
                                      name=ln.name)

    return nodes, outputs


# ---------------------------------------------------------------------------
# emission
# ---------------------------------------------------------------------------


def _emit(ir_inputs: List[IRNode], nodes: List[IRNode],
          ir_outputs: List[IRNode]):
    from bigdl_tpu.keras.engine import Input, Model

    sym: Dict[int, Any] = {}
    k_inputs = []
    for ir in ir_inputs:
        node = Input(None)
        sym[ir.uid] = node
        k_inputs.append(node)

    params: Dict[str, Dict] = {}
    state: Dict[str, Dict] = {}
    for ir in nodes:
        if ir.is_input:
            continue
        # fresh layer copy so the emitted model shares nothing mutable
        layer = copy.copy(ir.layer)
        parents = [sym[p.uid] for p in ir.parents]
        node = layer(parents[0] if len(parents) == 1 else parents)
        sym[ir.uid] = node
        if ir.params:
            params[node.name] = {k: jnp.asarray(v)
                                 for k, v in ir.params.items()}
        if ir.state:
            state[node.name] = {k: jnp.asarray(v)
                                for k, v in ir.state.items()}
    outputs = [sym[o.uid] for o in ir_outputs]
    model = Model(k_inputs, outputs, name="IRModel")
    return model, {"params": params, "state": state}
