"""Profiling — TensorBoard trace capture around training iterations.

Reference analog (unverified — mount empty): ``dllib/optim/Metrics.scala``'s
per-iteration timing breakdown + mkldnn perf-dump flags (SURVEY.md §6.1).
TPU mapping per the survey: ``jax.profiler`` traces (XLA op-level timeline,
viewable in TensorBoard's trace viewer / xprof) replace the hand-rolled
counters for device-side visibility; the host-side ``Metrics`` timers stay
for the input-pipeline/dispatch split.
"""

import contextlib
from typing import Optional

from bigdl_tpu.utils.log import get_logger

log = get_logger(__name__)


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax.profiler trace for the enclosed block."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info("profiler trace written to %s", log_dir)


class IterationProfiler:
    """Trace a window of training iterations — the pattern the reference's
    per-iteration Metrics dump serves: profile steps [start, stop) once the
    pipeline is warm (never step 0: that would capture compile, not
    steady state)."""

    def __init__(self, log_dir: str, start_iter: int = 10,
                 num_iters: int = 5):
        self.log_dir = log_dir
        self.start_iter = max(1, start_iter)
        self.stop_iter = self.start_iter + num_iters
        self._active = False
        self.done = False

    def step(self, iteration: int) -> None:
        """Call once per training iteration (before the step dispatch)."""
        import jax

        if self.done:
            return
        if not self._active and iteration >= self.start_iter:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif self._active and iteration >= self.stop_iter:
            jax.profiler.stop_trace()
            self._active = False
            self.done = True
            log.info("profiler trace (iters %d-%d) written to %s",
                     self.start_iter, self.stop_iter - 1, self.log_dir)

    def close(self) -> None:
        """Stop a trace the window left open (training ended inside it);
        idempotent — the driver's finally and an explicit close may both
        run."""
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self.done = True
            log.info("profiler trace (window truncated by end of training) "
                     "written to %s", self.log_dir)

    def __enter__(self) -> "IterationProfiler":
        return self

    def __exit__(self, *a) -> bool:
        self.close()
        return False


def annotate(name: str):
    """Named region for the trace viewer (jax.profiler.TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
