from bigdl_tpu.utils.log import get_logger
from bigdl_tpu.utils.table import T, Table

__all__ = ["get_logger", "T", "Table"]
