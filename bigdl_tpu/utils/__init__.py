from bigdl_tpu.utils.log import get_logger
from bigdl_tpu.utils.table import T, Table
from bigdl_tpu.utils.interop import from_torch, to_torch

__all__ = ["get_logger", "T", "Table", "from_torch", "to_torch"]
