"""Minimal protobuf wire-format codec (zero-dependency).

Reference analog (unverified — mount empty): the reference links the real
protobuf runtime for its model formats (``utils/tf/TensorflowLoader.scala``
reads TF ``GraphDef``; ``utils/caffe/CaffeLoader.scala`` reads Caffe
``NetParameter``; ``utils/serializer`` writes ``bigdl.proto``).  Here we
implement just the wire format — varint, fixed32/64, length-delimited —
so the TF/Caffe interop modules can parse and emit those protobufs without
a protobuf (or tensorflow/caffe) dependency in the image.

A parsed message is ``{field_number: [(wire_type, raw)]}`` where ``raw`` is
an ``int`` for varints, ``bytes`` for length-delimited fields, and 4/8-byte
``bytes`` for fixed32/64.  Interpretation (string vs sub-message vs packed
array) is the caller's job, exactly as in the wire spec.
"""

import struct
from typing import Any, Dict, List, Optional, Tuple

WIRE_VARINT = 0
WIRE_I64 = 1
WIRE_LEN = 2
WIRE_I32 = 5


# ---------------------------------------------------------------------------
# decoding
# ---------------------------------------------------------------------------


def read_varint(data: bytes, i: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    n = len(data)
    while True:
        if i >= n:
            raise ValueError("truncated varint")
        b = data[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, i
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def parse(data: bytes) -> Dict[int, List[Tuple[int, Any]]]:
    """Parse one message into {field: [(wire_type, raw), ...]} in order."""
    fields: Dict[int, List[Tuple[int, Any]]] = {}
    i, n = 0, len(data)
    while i < n:
        tag, i = read_varint(data, i)
        field, wire = tag >> 3, tag & 0x7
        if wire == WIRE_VARINT:
            v, i = read_varint(data, i)
        elif wire == WIRE_LEN:
            ln, i = read_varint(data, i)
            v = data[i:i + ln]
            if len(v) != ln:
                raise ValueError("truncated length-delimited field")
            i += ln
        elif wire == WIRE_I32:
            v = data[i:i + 4]
            if len(v) != 4:
                raise ValueError("truncated fixed32 field")
            i += 4
        elif wire == WIRE_I64:
            v = data[i:i + 8]
            if len(v) != 8:
                raise ValueError("truncated fixed64 field")
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append((wire, v))
    return fields


def _signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def get_int(fields, num: int, default: int = 0) -> int:
    vals = fields.get(num)
    return _signed64(vals[-1][1]) if vals else default


def get_bool(fields, num: int, default: bool = False) -> bool:
    return bool(get_int(fields, num, int(default)))


def get_bytes(fields, num: int, default: bytes = b"") -> bytes:
    vals = fields.get(num)
    return vals[-1][1] if vals else default


def get_str(fields, num: int, default: str = "") -> str:
    return get_bytes(fields, num, default.encode()).decode("utf-8")


def get_f32(fields, num: int, default: float = 0.0) -> float:
    vals = fields.get(num)
    if not vals:
        return default
    wire, raw = vals[-1]
    if wire == WIRE_I32:
        return struct.unpack("<f", raw)[0]
    raise ValueError("field is not fixed32")


def get_f64(fields, num: int, default: float = 0.0) -> float:
    vals = fields.get(num)
    if not vals:
        return default
    return struct.unpack("<d", vals[-1][1])[0]


def repeated(fields, num: int) -> List[Any]:
    """Raw values of a repeated field (caller interprets)."""
    return [raw for _, raw in fields.get(num, [])]


def repeated_ints(fields, num: int) -> List[int]:
    """Repeated varint field, accepting both packed and unpacked encoding."""
    out: List[int] = []
    for wire, raw in fields.get(num, []):
        if wire == WIRE_VARINT:
            out.append(_signed64(raw))
        elif wire == WIRE_LEN:  # packed
            i = 0
            while i < len(raw):
                v, i = read_varint(raw, i)
                out.append(_signed64(v))
        else:
            raise ValueError("not a varint field")
    return out


def repeated_f32(fields, num: int) -> List[float]:
    out: List[float] = []
    for wire, raw in fields.get(num, []):
        if wire == WIRE_I32:
            out.append(struct.unpack("<f", raw)[0])
        elif wire == WIRE_LEN:  # packed
            out.extend(struct.unpack(f"<{len(raw) // 4}f", raw))
    return out


def repeated_f64(fields, num: int) -> List[float]:
    out: List[float] = []
    for wire, raw in fields.get(num, []):
        if wire == WIRE_I64:
            out.append(struct.unpack("<d", raw)[0])
        elif wire == WIRE_LEN:
            out.extend(struct.unpack(f"<{len(raw) // 8}d", raw))
    return out


# ---------------------------------------------------------------------------
# encoding
# ---------------------------------------------------------------------------


def _varint_bytes(v: int) -> bytes:
    if v < 0:
        v += 1 << 64  # two's-complement 64-bit, per spec
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class Msg:
    """Append-only protobuf message builder."""

    def __init__(self):
        self.buf = bytearray()

    def _tag(self, field: int, wire: int):
        self.buf += _varint_bytes((field << 3) | wire)

    def varint(self, field: int, v: int) -> "Msg":
        self._tag(field, WIRE_VARINT)
        self.buf += _varint_bytes(int(v))
        return self

    def boolean(self, field: int, v: bool) -> "Msg":
        return self.varint(field, 1 if v else 0)

    def f32(self, field: int, v: float) -> "Msg":
        self._tag(field, WIRE_I32)
        self.buf += struct.pack("<f", float(v))
        return self

    def f64(self, field: int, v: float) -> "Msg":
        self._tag(field, WIRE_I64)
        self.buf += struct.pack("<d", float(v))
        return self

    def blob(self, field: int, data: bytes) -> "Msg":
        self._tag(field, WIRE_LEN)
        self.buf += _varint_bytes(len(data))
        self.buf += bytes(data)
        return self

    def string(self, field: int, s: str) -> "Msg":
        return self.blob(field, s.encode("utf-8"))

    def msg(self, field: int, sub: "Msg") -> "Msg":
        return self.blob(field, bytes(sub.buf))

    def packed_ints(self, field: int, vals) -> "Msg":
        body = b"".join(_varint_bytes(int(v)) for v in vals)
        return self.blob(field, body)

    def packed_f32(self, field: int, vals) -> "Msg":
        return self.blob(field, struct.pack(f"<{len(vals)}f", *map(float, vals)))

    def bytes(self) -> bytes:
        return bytes(self.buf)
