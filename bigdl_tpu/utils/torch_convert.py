"""Stock-torch-module conversion: architecture AND weights → bigdl_tpu.

Reference analog (unverified — mount empty): Orca's headline capability is
training *stock* torch models (``orca/learn/pytorch/estimator.py``,
SURVEY.md §4.3) — the reference pickles the torch module into JVM workers
and runs torch itself.  TPU-native re-design: torch never runs on the hot
path.  The module's ``torch.fx`` graph is traced once on host, each node is
re-emitted as a catalog layer in a keras-engine functional ``Model`` (NHWC
layouts, XLA-compilable), and the torch weights are converted into the
variables pytree — training then runs the normal ZeRO-1 sharded step.

Conventions/limits (raise with a clear message otherwise):
- 4-D tensors are assumed NCHW on the torch side; the emitted model is
  NHWC (inputs must be fed channels-last).  Linear layers consuming a
  flattened conv map get their weight columns permuted accordingly.
- supported leaves: Conv1d/2d, ConvTranspose2d, Linear, BatchNorm1d/2d,
  GroupNorm, LayerNorm, Embedding, PReLU, activations, pooling
  (Max/Avg/AdaptiveAvg(1)), Flatten, Dropout, MultiheadAttention
  (batch_first), LSTM/GRU (batch_first; any num_layers, bidirectional,
  inter-layer dropout — converted as a chain of scan layers),
  TransformerEncoder/TransformerEncoderLayer (structural leaf, both norm
  orders; their forwards break under symbolic trace), Upsample.
- supported graph ops: +, -, *, / (tensor and scalar), cat,
  flatten/view(b,-1) incl. dynamic x.size(0)/x.shape[0] forms, mean over
  spatial dims, y[:, i] timestep select, F.interpolate (scale_factor),
  functional activations (relu/gelu/sigmoid/tanh/softmax/silu/leaky_relu/
  elu/log_softmax/hardswish/softplus), getitem(0) on MHA/LSTM outputs.
"""

import operator
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from bigdl_tpu import nn as N
from bigdl_tpu.nn.module import EMPTY


def _np(t):
    return t.detach().cpu().numpy()


# ---------------------------------------------------------------------------
# leaf-module converters: torch module -> (our layer, params, state)
# ---------------------------------------------------------------------------


def _conv2d(tm):
    pad = tm.padding if isinstance(tm.padding, str) else tuple(tm.padding)
    if pad == (0, 0):
        pad = 0
    layer = N.Conv2D(tm.in_channels, tm.out_channels,
                     tuple(tm.kernel_size), stride=tuple(tm.stride),
                     padding=("SAME" if pad == "same" else pad),
                     dilation=tuple(tm.dilation), groups=tm.groups,
                     with_bias=tm.bias is not None)
    p = {"weight": jnp.asarray(_np(tm.weight).transpose(2, 3, 1, 0))}
    if tm.bias is not None:
        p["bias"] = jnp.asarray(_np(tm.bias))
    return layer, p, {}


def _conv1d(tm):
    pad = tm.padding if isinstance(tm.padding, str) else tm.padding[0]
    layer = N.Conv1D(tm.in_channels, tm.out_channels, tm.kernel_size[0],
                     stride=tm.stride[0],
                     padding=("SAME" if pad == "same" else pad),
                     dilation=tm.dilation[0], groups=tm.groups,
                     with_bias=tm.bias is not None)
    p = {"weight": jnp.asarray(_np(tm.weight).transpose(2, 1, 0))}
    if tm.bias is not None:
        p["bias"] = jnp.asarray(_np(tm.bias))
    return layer, p, {}


def _convtranspose2d(tm):
    if (tuple(tm.output_padding) != (0, 0) or tuple(tm.dilation) != (1, 1)
            or tm.groups != 1):
        raise NotImplementedError(
            "ConvTranspose2d conversion supports output_padding=0, "
            "dilation=1, groups=1")
    layer = N.Conv2DTranspose(tm.in_channels, tm.out_channels,
                              tuple(tm.kernel_size), stride=tuple(tm.stride),
                              padding=tuple(tm.padding),
                              with_bias=tm.bias is not None)
    p = {"weight": jnp.asarray(_np(tm.weight).transpose(2, 3, 1, 0))}
    if tm.bias is not None:
        p["bias"] = jnp.asarray(_np(tm.bias))
    return layer, p, {}


def _linear(tm, permute_from: Optional[Tuple[int, int, int]] = None):
    layer = N.Linear(tm.in_features, tm.out_features,
                     with_bias=tm.bias is not None)
    w = _np(tm.weight)                                  # (out, in)
    if permute_from is not None:
        c, h, wd = permute_from                         # torch flatten = CHW
        w = (w.reshape(-1, c, h, wd).transpose(0, 2, 3, 1)
             .reshape(w.shape[0], -1))                  # ours = HWC
    p = {"weight": jnp.asarray(w.T)}
    if tm.bias is not None:
        p["bias"] = jnp.asarray(_np(tm.bias))
    return layer, p, {}


def _batchnorm(tm):
    if tm.momentum is None:
        raise NotImplementedError(
            "BatchNorm momentum=None (cumulative averaging) has no "
            "equivalent; set a numeric momentum")
    layer = N.BatchNorm(tm.num_features, eps=tm.eps,
                        momentum=tm.momentum,
                        affine=tm.affine)
    p = {}
    if tm.affine:
        p = {"weight": jnp.asarray(_np(tm.weight)),
             "bias": jnp.asarray(_np(tm.bias))}
    s = {"running_mean": jnp.asarray(_np(tm.running_mean)),
         "running_var": jnp.asarray(_np(tm.running_var))}
    return layer, p, s


def _layernorm(tm):
    if len(tm.normalized_shape) != 1:
        raise NotImplementedError("LayerNorm over >1 trailing dim")
    layer = N.LayerNorm(tm.normalized_shape[0], eps=tm.eps)
    return layer, {"weight": jnp.asarray(_np(tm.weight)),
                   "bias": jnp.asarray(_np(tm.bias))}, {}


def _groupnorm(tm):
    layer = N.GroupNorm(tm.num_groups, tm.num_channels, eps=tm.eps,
                        affine=tm.affine)
    p = {}
    if tm.affine:
        p = {"weight": jnp.asarray(_np(tm.weight)),
             "bias": jnp.asarray(_np(tm.bias))}
    return layer, p, {}


def _embedding(tm):
    layer = N.Embedding(tm.num_embeddings, tm.embedding_dim)
    return layer, {"weight": jnp.asarray(_np(tm.weight))}, {}


def _mha(tm):
    if not tm.batch_first:
        raise NotImplementedError("MultiheadAttention needs batch_first=True")
    if tm.in_proj_weight is None or tm.in_proj_bias is None:
        raise NotImplementedError(
            "MultiheadAttention conversion needs the packed in-projection "
            "with bias (bias=False and kdim/vdim variants unsupported)")
    e = tm.embed_dim
    layer = N.MultiHeadAttention(e, tm.num_heads)
    w = _np(tm.in_proj_weight)
    b = _np(tm.in_proj_bias)
    p = {"wq": jnp.asarray(w[:e].T), "wk": jnp.asarray(w[e:2 * e].T),
         "wv": jnp.asarray(w[2 * e:].T),
         "bq": jnp.asarray(b[:e]), "bk": jnp.asarray(b[e:2 * e]),
         "bv": jnp.asarray(b[2 * e:]),
         "wo": jnp.asarray(_np(tm.out_proj.weight).T),
         "bo": jnp.asarray(_np(tm.out_proj.bias))}
    return layer, p, {}


def _rnn_dir_params(tm, kind, k, suffix):
    """Params of torch layer k, one direction.  Gate orders match ours
    exactly (torch LSTM i,f,g,o; torch GRU r,z,n), and torch's GRU
    candidate form ``tanh(x_n + b_in + r*(W_hn h + b_hn))`` is precisely
    our reset-after recurrence with the ``bias_rec`` recurrent bias."""
    w_ih = _np(getattr(tm, f"weight_ih_l{k}{suffix}"))
    w_hh = _np(getattr(tm, f"weight_hh_l{k}{suffix}"))
    p = {"w_in": jnp.asarray(w_ih.T), "w_rec": jnp.asarray(w_hh.T)}
    if tm.bias:
        b_ih = _np(getattr(tm, f"bias_ih_l{k}{suffix}"))
        b_hh = _np(getattr(tm, f"bias_hh_l{k}{suffix}"))
        if kind == "GRU":
            p["bias"] = jnp.asarray(b_ih)
            p["bias_rec"] = jnp.asarray(b_hh)
        else:  # LSTM: both biases are additive outside every gate
            p["bias"] = jnp.asarray(b_ih + b_hh)
    else:
        p["bias"] = jnp.zeros((w_ih.shape[0],))
        if kind == "GRU":
            p["bias_rec"] = jnp.zeros((w_ih.shape[0],))
    return p


def _rnn_chain(tm, kind):
    """torch nn.LSTM/nn.GRU (any num_layers, optionally bidirectional) →
    list of (our_layer, params, tag) chained in sequence.  ``tag`` keys the
    export back to the torch ``weight_*_l{k}[_reverse]`` names."""
    if not tm.batch_first:
        raise NotImplementedError(f"{kind} conversion needs batch_first=True")
    cls = N.LSTM if kind == "LSTM" else N.GRU
    steps = []
    for k in range(tm.num_layers):
        d_in = tm.input_size if k == 0 else \
            tm.hidden_size * (2 if tm.bidirectional else 1)
        if tm.dropout and k > 0:
            # torch applies dropout to the OUTPUT of every layer but the
            # last, i.e. before each subsequent layer's input
            steps.append((N.Dropout(tm.dropout), {}, None))
        if tm.bidirectional:
            fwd = cls(d_in, tm.hidden_size, return_sequences=True)
            bwd = cls(d_in, tm.hidden_size, return_sequences=True,
                      go_backwards=True)
            layer = N.BiRecurrent(fwd, bwd, merge="concat")
            p = {"fwd": _rnn_dir_params(tm, kind, k, ""),
                 "bwd": _rnn_dir_params(tm, kind, k, "_reverse")}
            steps.append((layer, p, f"Bi{kind}@l{k}"))
        else:
            layer = cls(d_in, tm.hidden_size, return_sequences=True)
            steps.append((layer, _rnn_dir_params(tm, kind, k, ""),
                          f"{kind}@l{k}"))
    return steps


def _prelu(tm):
    return N.PReLU(), {"alpha": jnp.asarray(_np(tm.weight))}, {}


def _upsample(tm):
    if tm.scale_factor is None:
        raise NotImplementedError("Upsample with target size (use "
                                  "scale_factor)")
    sf = tm.scale_factor
    sf = tuple(int(s) for s in sf) if isinstance(sf, (tuple, list)) \
        else (int(sf), int(sf))
    if any(float(s) != int(s) for s in (
            tm.scale_factor if isinstance(tm.scale_factor, (tuple, list))
            else [tm.scale_factor])):
        raise NotImplementedError("non-integer Upsample scale_factor")
    mode = tm.mode
    if mode == "nearest":
        return N.UpSampling2D(sf, mode="nearest"), {}, {}
    if mode == "bilinear":
        if tm.align_corners:
            raise NotImplementedError("Upsample align_corners=True "
                                      "(half-pixel centers only)")
        return N.UpSampling2D(sf, mode="bilinear"), {}, {}
    raise NotImplementedError(f"Upsample mode {mode!r}")


def _pool2d(tm, cls):
    k = tm.kernel_size if isinstance(tm.kernel_size, tuple) else \
        (tm.kernel_size, tm.kernel_size)
    s = tm.stride if isinstance(tm.stride, tuple) else \
        (tm.stride, tm.stride) if tm.stride else k
    pad = tm.padding if isinstance(tm.padding, tuple) else \
        (tm.padding, tm.padding)
    if pad == (0, 0):
        pad = 0
    return cls(k, s, padding=pad,
               ceil_mode=getattr(tm, "ceil_mode", False)), {}, {}


_SIMPLE = {
    "ReLU": lambda tm: (N.ReLU(), {}, {}),
    "ReLU6": lambda tm: (N.ReLU6(), {}, {}),
    "GELU": lambda tm: (N.GELU(), {}, {}),
    "SiLU": lambda tm: (N.SiLU(), {}, {}),
    "Sigmoid": lambda tm: (N.Sigmoid(), {}, {}),
    "Tanh": lambda tm: (N.Tanh(), {}, {}),
    "ELU": lambda tm: (N.ELU(tm.alpha), {}, {}),
    "LeakyReLU": lambda tm: (N.LeakyReLU(tm.negative_slope), {}, {}),
    "Softmax": lambda tm: (N.SoftMax(), {}, {}),
    "LogSoftmax": lambda tm: (N.LogSoftMax(), {}, {}),
    "Mish": lambda tm: (N.Mish(), {}, {}),
    "Softplus": lambda tm: (N.SoftPlus(), {}, {}),
    "Softsign": lambda tm: (N.SoftSign(), {}, {}),
    "Tanhshrink": lambda tm: (N.TanhShrink(), {}, {}),
    "Softshrink": lambda tm: (N.SoftShrink(tm.lambd), {}, {}),
    "Hardshrink": lambda tm: (N.HardShrink(tm.lambd), {}, {}),
    "LogSigmoid": lambda tm: (N.LogSigmoid(), {}, {}),
    "Hardswish": lambda tm: (N.HardSwish(), {}, {}),
    "Hardsigmoid": lambda tm: (N.HardSigmoid(), {}, {}),
    "Hardtanh": lambda tm: (N.HardTanh(tm.min_val, tm.max_val), {}, {}),
    "Identity": lambda tm: (N.Identity(), {}, {}),
    "Dropout": lambda tm: (N.Dropout(tm.p), {}, {}),
    "Flatten": lambda tm: (N.Flatten(), {}, {}),
    "Linear": _linear,
    "Conv2d": _conv2d,
    "Conv1d": _conv1d,
    "ConvTranspose2d": _convtranspose2d,
    "BatchNorm1d": _batchnorm,
    "BatchNorm2d": _batchnorm,
    "GroupNorm": _groupnorm,
    "LayerNorm": _layernorm,
    "Embedding": _embedding,
    "PReLU": _prelu,
    "MultiheadAttention": _mha,
    "Upsample": _upsample,
    "UpsamplingNearest2d": _upsample,
    "UpsamplingBilinear2d": _upsample,
    "MaxPool2d": lambda tm: _pool2d(tm, N.MaxPool2D),
    "AvgPool2d": lambda tm: _pool2d(tm, N.AvgPool2D),
}


class _ConvertTracer:
    """fx tracer whose leaves are exactly the convertible torch modules —
    containers and custom modules are traced through."""

    def build(self, tmodule):
        import torch.fx as fx

        leaf_names = set(_SIMPLE) | {"AdaptiveAvgPool2d", "LSTM", "GRU",
                             "TransformerEncoder",
                             "TransformerEncoderLayer"}

        class T(fx.Tracer):
            def is_leaf_module(self, m, qualname):
                return type(m).__name__ in leaf_names

        tracer = T()
        graph = tracer.trace(tmodule)
        gm = fx.GraphModule(tracer.root, graph)
        # `a, _ = mha(...)`-style unpacks leave dead getitem nodes behind
        gm.graph.eliminate_dead_code()
        gm.recompile()
        return gm


def _meta_shape(node):
    tm = node.meta.get("tensor_meta")
    return tuple(tm.shape) if tm is not None and hasattr(tm, "shape") else None


def from_torch_module(tmodule, example_input=None):
    """torch.nn.Module → (keras-engine Model, variables) with weights.

    ``example_input``: numpy array (or tuple of arrays for multi-input
    modules) in TORCH layout (e.g. NCHW), used for shape propagation —
    required when the graph flattens conv maps into Linear layers (the
    weight-permutation fixup needs shapes) or concatenates on mapped
    axes."""
    import torch

    tmodule = tmodule.eval()
    # fx always traces the ROOT's forward, so a module that must convert
    # as a leaf (RNNs, TransformerEncoder[Layer], MHA — their forwards
    # break under symbolic trace) gets a trivial wrapper root; export
    # quals drop the wrapper prefix again below
    _LEAF_ROOTS = {"LSTM", "GRU", "TransformerEncoder",
                   "TransformerEncoderLayer", "MultiheadAttention"}
    wrapped = type(tmodule).__name__ in _LEAF_ROOTS
    if wrapped:
        class _Root(torch.nn.Module):
            def __init__(self, m):
                super().__init__()
                self.mod = m

            def forward(self, x):
                return self.mod(x)

        tmodule = _Root(tmodule)
    gm = _ConvertTracer().build(tmodule)
    if example_input is not None:
        from torch.fx.passes.shape_prop import ShapeProp

        ex = (example_input if isinstance(example_input, (tuple, list))
              else (example_input,))
        ShapeProp(gm).propagate(
            *(torch.tensor(np.asarray(e)) for e in ex))

    from bigdl_tpu.keras.engine import Input, Model

    sym: Dict[Any, Any] = {}        # fx node -> keras node
    params: Dict[str, Dict] = {}
    state: Dict[str, Dict] = {}
    pre_flatten: Dict[Any, Tuple[int, int, int]] = {}  # flatten out -> CHW
    flat_already: set = set()       # nodes whose output is already (b, c)
    inputs = []
    outputs = []
    # (keras node name, torch qualname, torch type, linear permute_from) —
    # consumed by export_state_dict for the round trip back to torch
    export_map = []

    def emit(fx_node, layer, parents, p=None, s=None):
        kn = layer(parents[0] if len(parents) == 1 else list(parents))
        sym[fx_node] = kn
        if p:
            params[kn.name] = p
        if s:
            state[kn.name] = s
        return kn

    def to_nhwc_shape(shape):
        if shape is None:
            return None
        if len(shape) == 4:
            return (shape[2], shape[3], shape[1])
        return tuple(shape[1:])

    def conv_axis(fx_node, dim):
        """torch dim on an NCHW tensor -> our NHWC axis."""
        shape = _meta_shape(fx_node)
        if shape is None:
            raise ValueError(
                "axis-mapped op on an unknown-shape tensor: pass "
                "example_input so shapes can be propagated (a torch dim on "
                "a 4-D NCHW tensor maps to a different NHWC axis)")
        if len(shape) == 4:
            table = {0: 0, 1: -1, 2: 1, 3: 2, -1: 2, -2: 1, -3: -1, -4: 0}
            if dim not in table:
                raise NotImplementedError(f"axis {dim} on a 4-D tensor")
            return table[dim]
        return dim

    def is_flatten_to_vec(node):
        """view/reshape/flatten collapsing everything AFTER the batch dim
        (start_dim=1, end_dim=-1).  Other start/end dims fall through to
        the generic unsupported-node error — a partial flatten is not a
        batch-preserving vectorization."""
        if ((node.op == "call_function" and node.target is torch.flatten)
                or (node.op == "call_method"
                    and node.target == "flatten")):
            start = (node.args[1] if len(node.args) > 1
                     else node.kwargs.get("start_dim", 0))
            end = (node.args[2] if len(node.args) > 2
                   else node.kwargs.get("end_dim", -1))
            return start == 1 and end == -1
        if node.op == "call_method" and node.target in ("view", "reshape"):
            if len(node.args) != 3 or node.args[2] != -1:
                return False
            # x.view(n, -1) is only a batch-preserving flatten when n IS the
            # batch size; x.view(6, -1) on a (2,3,4,5) tensor would otherwise
            # convert to Flatten() and be silently wrong
            first = node.args[1]
            if isinstance(first, torch.fx.Node):
                # dynamic batch: y.view(x.size(0), -1) traces args[1] as a
                # size(0)-of-some-node (or shape[0] getitem); accept when
                # that node's batch dim provably equals the view source's
                src = node.args[0]
                import operator

                size_src = None
                if (first.op == "call_method" and first.target == "size"
                        and len(first.args) == 2 and first.args[1] == 0):
                    size_src = first.args[0]
                elif (first.op == "call_function"
                        and first.target is operator.getitem
                        and len(first.args) == 2 and first.args[1] == 0
                        and isinstance(first.args[0], torch.fx.Node)
                        and first.args[0].op == "call_function"
                        and first.args[0].target is getattr
                        and first.args[0].args[1:] == ("shape",)):
                    size_src = first.args[0].args[0]
                if size_src is None:
                    return False
                if size_src is src:
                    return True
                ss, vs = _meta_shape(size_src), _meta_shape(src)
                return ss is not None and vs is not None and ss[0] == vs[0]
            src_shape = _meta_shape(node.args[0])
            # without shape metadata the batch-dim check cannot run — fall
            # through to the unsupported-node error (pass example_input)
            return src_shape is not None and first == src_shape[0]
        return False

    def _consumed_by_flatten(node):
        """Scalar side nodes (size/shape/getitem) are skippable when every
        consumer is an accepted batch-preserving flatten (possibly through
        another scalar side node, e.g. getattr-shape → getitem → view)."""
        users = list(node.users)
        return bool(users) and all(
            is_flatten_to_vec(u) or _consumed_by_flatten(u) for u in users)

    def handle_flatten(node, src):
        if src in flat_already:     # AdaptiveAvgPool2d(1) already emitted (b,c)
            sym[node] = sym[src]
            return
        shape = _meta_shape(src)
        if shape is not None and len(shape) == 4:
            pre = (shape[1], shape[2], shape[3])
            kn = emit(node, N.Flatten(), [sym[src]])
            pre_flatten[node] = pre
        elif shape is None:
            raise ValueError(
                "flatten of an unknown-shape tensor: pass example_input so "
                "shapes can be propagated (needed for the NCHW->NHWC Linear "
                "weight fixup)")
        else:
            emit(node, N.Flatten(), [sym[src]])

    for node in gm.graph.nodes:
        if node.op == "placeholder":
            shape = _meta_shape(node)
            kn = Input(to_nhwc_shape(shape))
            sym[node] = kn
            inputs.append(kn)

        elif node.op == "call_module":
            tm = gm.get_submodule(node.target)
            tname = type(tm).__name__
            src_nodes = [a for a in node.args
                         if isinstance(a, torch.fx.Node)]
            if tname == "AdaptiveAvgPool2d":
                out = tm.output_size
                out = out if isinstance(out, tuple) else (out, out)
                if out not in ((1, 1), (1,)):
                    raise NotImplementedError(
                        "AdaptiveAvgPool2d only supported with output 1")
                emit(node, N.GlobalAvgPool2D(), [sym[src_nodes[0]]])
                flat_already.add(node)
                continue
            if tname in ("LSTM", "GRU"):
                kn = sym[src_nodes[0]]
                for layer, p, tag in _rnn_chain(tm, tname):
                    kn = layer(kn)
                    if p:
                        params[kn.name] = p
                        export_map.append((kn.name, node.target, tag, None))
                sym[node] = kn
                continue
            if tname in ("TransformerEncoder", "TransformerEncoderLayer"):
                # torch's forward has mask-canonicalization that breaks fx
                # tracing, so the layer is a LEAF converted structurally:
                # its anatomy (self_attn/linear1/linear2/norm1/norm2,
                # norm_first) is fixed by torch
                def put(kn2, layer, p, qual2, sub_tname):
                    kn2 = layer(kn2)
                    if p:
                        params[kn2.name] = p
                        export_map.append((kn2.name, qual2, sub_tname, None))
                    return kn2

                def one_block(tl, kn_in, qual2):
                    if tl.self_attn.batch_first is False:
                        raise NotImplementedError(
                            "TransformerEncoderLayer needs batch_first=True")
                    act = {torch.nn.functional.relu: N.ReLU,
                           torch.nn.functional.gelu: N.GELU}.get(
                        tl.activation)
                    if act is None:
                        raise NotImplementedError(
                            f"encoder activation {tl.activation}")
                    mha_l, mha_p, _ = _mha(tl.self_attn)

                    def attn_part(kn_x):
                        a = put(kn_x, mha_l, mha_p,
                                f"{qual2}.self_attn", "MultiheadAttention")
                        if tl.dropout1.p:
                            a = N.Dropout(tl.dropout1.p)(a)
                        return a

                    def ff_part(kn_x):
                        l1, p1, _ = _linear(tl.linear1)
                        h = put(kn_x, l1, p1, f"{qual2}.linear1", "Linear")
                        h = act()(h)
                        if tl.dropout.p:
                            h = N.Dropout(tl.dropout.p)(h)
                        l2, p2, _ = _linear(tl.linear2)
                        h = put(h, l2, p2, f"{qual2}.linear2", "Linear")
                        if tl.dropout2.p:
                            h = N.Dropout(tl.dropout2.p)(h)
                        return h

                    def norm(kn_x, tn, name):
                        nl, np_, _ = _layernorm(tn)
                        return put(kn_x, nl, np_, f"{qual2}.{name}",
                                   "LayerNorm")

                    if tl.norm_first:
                        a = attn_part(norm(kn_in, tl.norm1, "norm1"))
                        x1 = N.CAddTable()([kn_in, a])
                        f = ff_part(norm(x1, tl.norm2, "norm2"))
                        return N.CAddTable()([x1, f])
                    a = attn_part(kn_in)
                    x1 = norm(N.CAddTable()([kn_in, a]), tl.norm1, "norm1")
                    f = ff_part(x1)
                    return norm(N.CAddTable()([x1, f]), tl.norm2, "norm2")

                kn = sym[src_nodes[0]]
                if tname == "TransformerEncoder":
                    for li, tl in enumerate(tm.layers):
                        kn = one_block(tl, kn,
                                       f"{node.target}.layers.{li}")
                    if tm.norm is not None:
                        nl, np_, _ = _layernorm(tm.norm)
                        kn = put(kn, nl, np_, f"{node.target}.norm",
                                 "LayerNorm")
                else:
                    kn = one_block(tm, kn, node.target)
                sym[node] = kn
                continue
            if tname not in _SIMPLE:
                raise NotImplementedError(
                    f"no conversion for torch module {tname} "
                    f"(at graph node {node.name})")
            conv = _SIMPLE[tname]
            # elementwise layers preserve the flattened HWC element order,
            # so a pending Linear weight-permutation marker flows through
            # (classifier heads commonly interleave Dropout/ReLU between
            # flatten and fc)
            _PASSTHROUGH = ("Dropout", "ReLU", "ReLU6", "GELU", "SiLU",
                            "Sigmoid", "Tanh", "ELU", "LeakyReLU",
                            "Hardtanh", "Identity", "PReLU")
            if tname in _PASSTHROUGH and src_nodes \
                    and src_nodes[0] in pre_flatten:
                pre_flatten[node] = pre_flatten[src_nodes[0]]
            permute_from = None
            if tname == "Linear":
                src = src_nodes[0]
                permute_from = pre_flatten.get(src)
                layer, p, s = conv(tm, permute_from)
            elif tname == "MultiheadAttention":
                q, k, v = node.args[0], node.args[1], node.args[2]
                layer, p, s = conv(tm)
                if q is k and k is v:
                    parents = [sym[q]]
                elif k is v:
                    parents = [sym[q], sym[k]]
                else:
                    raise NotImplementedError(
                        "MultiheadAttention with distinct k and v")
                kn = emit(node, layer, parents, p, s)
                export_map.append((kn.name, node.target, tname, None))
                continue
            else:
                layer, p, s = conv(tm)
            kn = emit(node, layer, [sym[src_nodes[0]]], p, s)
            if p or s:
                export_map.append((kn.name, node.target, tname, permute_from))

        elif node.op == "call_function":
            fn = node.target
            if (fn is getattr or fn is operator.getitem) \
                    and _consumed_by_flatten(node):
                pass  # x.shape[0] chain feeding an accepted flatten
            elif fn in (operator.add, torch.add, operator.sub, torch.sub,
                      operator.mul, torch.mul, operator.truediv,
                      torch.div):
                a, b = node.args[0], node.args[1]
                a_t = isinstance(a, torch.fx.Node)
                b_t = isinstance(b, torch.fx.Node)
                sub = fn in (operator.sub, torch.sub)
                div = fn in (operator.truediv, torch.div)
                mul = fn in (operator.mul, torch.mul)
                if a_t and b_t:
                    from bigdl_tpu.keras.layers import Merge

                    if sub:
                        emit(node, N.CSubTable(), [sym[a], sym[b]])
                    elif div:
                        emit(node, N.CDivTable(), [sym[a], sym[b]])
                    else:
                        emit(node, Merge("mul" if mul else "sum"),
                             [sym[a], sym[b]])
                elif a_t and isinstance(b, (int, float)):
                    # scalar arithmetic (x/255.0-style normalization)
                    if mul:
                        lay = N.MulConstant(float(b))
                    elif div:
                        lay = N.MulConstant(1.0 / float(b))
                    else:
                        lay = N.AddConstant(float(-b if sub else b))
                    if a in pre_flatten:   # elementwise: marker flows on
                        pre_flatten[node] = pre_flatten[a]
                    emit(node, lay, [sym[a]])
                else:
                    raise NotImplementedError(
                        f"{fn} with operands ({type(a).__name__}, "
                        f"{type(b).__name__}) at node {node.name}")
            elif fn is torch.cat:
                tensors = node.args[0]
                dim = node.args[1] if len(node.args) > 1 else \
                    node.kwargs.get("dim", 0)
                axis = conv_axis(tensors[0], dim)
                from bigdl_tpu.keras.layers import Merge

                emit(node, Merge("concat", concat_axis=axis),
                     [sym[t] for t in tensors])
            elif fn is operator.getitem:
                src = node.args[0]
                tm_name = (type(gm.get_submodule(src.target)).__name__
                           if src.op == "call_module" else "")
                idx = node.args[1]
                if idx == 0 and tm_name in ("LSTM", "GRU",
                                            "MultiheadAttention"):
                    sym[node] = sym[src]   # our layer returns the seq output
                elif (isinstance(idx, tuple) and len(idx) == 2
                        and idx[0] == slice(None)
                        and isinstance(idx[1], int)):
                    # y[:, i] — timestep select (e.g. last RNN output)
                    emit(node, N.Select(1, idx[1]), [sym[src]])
                else:
                    raise NotImplementedError(
                        f"getitem[{idx}] on {src}")
            elif is_flatten_to_vec(node):
                handle_flatten(node, node.args[0])
            elif fn in (torch.relu, torch.nn.functional.relu):
                if node.args[0] in pre_flatten:
                    pre_flatten[node] = pre_flatten[node.args[0]]
                emit(node, N.ReLU(), [sym[node.args[0]]])
            elif fn is torch.nn.functional.interpolate:
                sf = node.kwargs.get("scale_factor") or (
                    node.args[2] if len(node.args) > 2 else None)
                mode = node.kwargs.get("mode", "nearest")
                if sf is None:
                    raise NotImplementedError(
                        "F.interpolate with target size (use scale_factor)")
                sfp = tuple(int(s) for s in sf) if isinstance(
                    sf, (tuple, list)) else (int(sf), int(sf))
                if mode not in ("nearest", "bilinear") or (
                        mode == "bilinear"
                        and node.kwargs.get("align_corners")):
                    raise NotImplementedError(
                        f"F.interpolate mode {mode!r}/align_corners")
                emit(node, N.UpSampling2D(sfp, mode=mode),
                     [sym[node.args[0]]])
            elif fn in (torch.nn.functional.silu,):
                emit(node, N.SiLU(), [sym[node.args[0]]])
            elif fn is torch.nn.functional.leaky_relu:
                slope = (node.args[1] if len(node.args) > 1
                         else node.kwargs.get("negative_slope", 0.01))
                emit(node, N.LeakyReLU(float(slope)), [sym[node.args[0]]])
            elif fn in (torch.nn.functional.elu,):
                alpha = (node.args[1] if len(node.args) > 1
                         else node.kwargs.get("alpha", 1.0))
                emit(node, N.ELU(float(alpha)), [sym[node.args[0]]])
            elif fn is torch.nn.functional.log_softmax:
                emit(node, N.LogSoftMax(), [sym[node.args[0]]])
            elif fn in (torch.nn.functional.hardswish,):
                emit(node, N.HardSwish(), [sym[node.args[0]]])
            elif fn in (torch.nn.functional.softplus,):
                emit(node, N.SoftPlus(), [sym[node.args[0]]])
            elif fn is torch.nn.functional.gelu:
                emit(node, N.GELU(), [sym[node.args[0]]])
            elif fn in (torch.sigmoid, torch.nn.functional.sigmoid):
                emit(node, N.Sigmoid(), [sym[node.args[0]]])
            elif fn in (torch.tanh, torch.nn.functional.tanh):
                emit(node, N.Tanh(), [sym[node.args[0]]])
            elif fn is torch.nn.functional.softmax:
                emit(node, N.SoftMax(), [sym[node.args[0]]])
            elif fn is torch.nn.functional.dropout:
                if node.args[0] in pre_flatten:
                    pre_flatten[node] = pre_flatten[node.args[0]]
                p = node.args[1] if len(node.args) > 1 else \
                    node.kwargs.get("p", 0.5)
                emit(node, N.Dropout(p), [sym[node.args[0]]])
            else:
                raise NotImplementedError(
                    f"no conversion for function {fn} "
                    f"(at graph node {node.name})")

        elif node.op == "call_method":
            if is_flatten_to_vec(node):
                handle_flatten(node, node.args[0])
            elif node.target == "size" and _consumed_by_flatten(node):
                # x.size(0) consumed only by accepted batch-preserving
                # flattens (the x.view(x.size(0), -1) idiom) — scalar side
                # value, nothing to emit
                pass
            elif node.target == "contiguous":
                sym[node] = sym[node.args[0]]
            elif node.target == "mean":
                src = node.args[0]
                dims = node.args[1] if len(node.args) > 1 else \
                    node.kwargs.get("dim")
                shape = _meta_shape(src)
                dim_list = ([dims] if isinstance(dims, int)
                            else list(dims or ()))
                if shape and len(shape) == 4 and tuple(sorted(
                        d % 4 for d in dim_list)) == (2, 3):
                    emit(node, N.GlobalAvgPool2D(), [sym[src]])
                    flat_already.add(node)
                elif shape and len(shape) == 3 and len(dim_list) == 1:
                    # sequence pooling (b, t, d): same axis both layouts
                    emit(node, N.Mean(dim=dim_list[0] % 3), [sym[src]])
                else:
                    raise NotImplementedError(
                        f"mean over dims {dims} (spatial NCHW mean or one "
                        "axis of a 3-D tensor)")
            else:
                raise NotImplementedError(
                    f"no conversion for method .{node.target}() "
                    f"(at graph node {node.name})")

        elif node.op == "output":
            args = node.args[0]
            outs = args if isinstance(args, (tuple, list)) else [args]
            outputs = [sym[o] for o in outs]

        elif node.op == "get_attr":
            raise NotImplementedError(
                f"free tensor attribute {node.target} in the graph")

    if wrapped:  # strip the wrapper prefix from export quals
        def _strip(q):
            return q[4:] if q.startswith("mod.") else ("" if q == "mod" else q)

        export_map = [(n, _strip(q), t, pf) for n, q, t, pf in export_map]
    model = Model(inputs, outputs, name="TorchConverted")
    model._torch_export_map = export_map
    return model, {"params": params, "state": state}


def export_state_dict(model, variables) -> Dict[str, Any]:
    """Inverse of the conversion: trained variables → a torch
    ``state_dict``-shaped dict of torch tensors keyed by the ORIGINAL
    module's parameter names (``<qualname>.weight`` etc.), ready for
    ``tmodule.load_state_dict``.  RNN recurrent biases come back fused
    into ``bias_ih_l0`` (``bias_hh_l0`` zeros) — mathematically the same
    cell."""
    import torch

    emap = getattr(model, "_torch_export_map", None)
    if emap is None:
        raise ValueError("model was not produced by from_torch_module")
    params = variables.get("params", {})
    state = variables.get("state", {})
    out: Dict[str, Any] = {}

    def t(a):
        return torch.tensor(np.asarray(a))

    for kname, qual, tname, permute_from in emap:
        p = params.get(kname, {})
        s = state.get(kname, {})
        if tname == "Linear":
            w = np.asarray(p["weight"]).T          # (out, in_hwc)
            if permute_from is not None:
                c, h, wd = permute_from
                w = (w.reshape(-1, h, wd, c).transpose(0, 3, 1, 2)
                     .reshape(w.shape[0], -1))
            out[f"{qual}.weight"] = t(w)
            if "bias" in p:
                out[f"{qual}.bias"] = t(p["bias"])
        elif tname == "Conv2d":
            out[f"{qual}.weight"] = t(
                np.asarray(p["weight"]).transpose(3, 2, 0, 1))
            if "bias" in p:
                out[f"{qual}.bias"] = t(p["bias"])
        elif tname == "Conv1d":
            out[f"{qual}.weight"] = t(
                np.asarray(p["weight"]).transpose(2, 1, 0))
            if "bias" in p:
                out[f"{qual}.bias"] = t(p["bias"])
        elif tname == "ConvTranspose2d":
            out[f"{qual}.weight"] = t(
                np.asarray(p["weight"]).transpose(3, 2, 0, 1))
            if "bias" in p:
                out[f"{qual}.bias"] = t(p["bias"])
        elif tname in ("BatchNorm1d", "BatchNorm2d"):
            if "weight" in p:
                out[f"{qual}.weight"] = t(p["weight"])
                out[f"{qual}.bias"] = t(p["bias"])
            out[f"{qual}.running_mean"] = t(s["running_mean"])
            out[f"{qual}.running_var"] = t(s["running_var"])
        elif tname in ("LayerNorm", "GroupNorm"):
            if "weight" in p:
                out[f"{qual}.weight"] = t(p["weight"])
                out[f"{qual}.bias"] = t(p["bias"])
        elif tname == "Embedding":
            out[f"{qual}.weight"] = t(p["weight"])
        elif tname == "PReLU":
            out[f"{qual}.weight"] = t(p["alpha"])
        elif tname == "MultiheadAttention":
            w = np.concatenate([np.asarray(p["wq"]).T, np.asarray(p["wk"]).T,
                                np.asarray(p["wv"]).T], 0)
            b = np.concatenate([np.asarray(p["bq"]), np.asarray(p["bk"]),
                                np.asarray(p["bv"])], 0)
            out[f"{qual}.in_proj_weight"] = t(w)
            out[f"{qual}.in_proj_bias"] = t(b)
            out[f"{qual}.out_proj.weight"] = t(np.asarray(p["wo"]).T)
            out[f"{qual}.out_proj.bias"] = t(p["bo"])
        elif "@l" in tname and tname.split("@")[0].lstrip("Bi") in (
                "LSTM", "GRU"):
            base, lk = tname.split("@l")
            kind = base.lstrip("Bi")

            def put(dp, suffix, lk=lk, kind=kind):
                out[f"{qual}.weight_ih_l{lk}{suffix}"] = t(
                    np.asarray(dp["w_in"]).T)
                out[f"{qual}.weight_hh_l{lk}{suffix}"] = t(
                    np.asarray(dp["w_rec"]).T)
                if kind == "GRU":
                    out[f"{qual}.bias_ih_l{lk}{suffix}"] = t(dp["bias"])
                    out[f"{qual}.bias_hh_l{lk}{suffix}"] = t(dp["bias_rec"])
                else:
                    out[f"{qual}.bias_ih_l{lk}{suffix}"] = t(dp["bias"])
                    out[f"{qual}.bias_hh_l{lk}{suffix}"] = \
                        torch.zeros_like(t(dp["bias"]))

            if base.startswith("Bi"):
                put(p["fwd"], "")
                put(p["bwd"], "_reverse")
            else:
                put(p, "")
        else:  # pragma: no cover — emitters above cover every param leaf
            raise NotImplementedError(f"export for {tname}")
    return out


# ---------------------------------------------------------------------------
# loss / optimizer mapping
# ---------------------------------------------------------------------------


def convert_torch_loss(tloss):
    """Map a torch loss instance to the equivalent criterion."""
    from bigdl_tpu.nn.criterion import Criterion

    if isinstance(tloss, Criterion):
        return tloss
    mapping = {
        "CrossEntropyLoss": N.CrossEntropyCriterion,
        "MSELoss": N.MSECriterion,
        "L1Loss": N.AbsCriterion,
        "NLLLoss": N.ClassNLLCriterion,
        "BCELoss": N.BCECriterion,
        "BCEWithLogitsLoss": N.BCEWithLogitsCriterion,
        "SmoothL1Loss": N.SmoothL1Criterion,
    }
    tname = type(tloss).__name__
    if tname not in mapping:
        raise NotImplementedError(f"no criterion mapping for torch {tname}")
    return mapping[tname]()


def convert_torch_optimizer(topt):
    """Map a torch.optim.Optimizer instance (its hyperparameters — the
    state is per-parameter torch tensors and starts fresh) to an
    OptimMethod."""
    from bigdl_tpu.optim.optim_method import (SGD, Adam, AdamWeightDecay,
                                              OptimMethod, RMSprop)

    if isinstance(topt, OptimMethod):
        return topt
    if len(topt.param_groups) > 1:
        raise NotImplementedError(
            "multi-param-group torch optimizers (per-group lr/wd) have no "
            "flat-parameter OptimMethod mapping — pass a native OptimMethod "
            "instead")
    g = topt.param_groups[0]
    tname = type(topt).__name__
    if tname == "SGD":
        return SGD(learning_rate=g["lr"], momentum=g.get("momentum", 0.0),
                   weight_decay=g.get("weight_decay", 0.0),
                   nesterov=g.get("nesterov", False))
    if tname == "Adam":
        b1, b2 = g.get("betas", (0.9, 0.999))
        return Adam(learning_rate=g["lr"], beta1=b1, beta2=b2,
                    epsilon=g.get("eps", 1e-8))
    if tname == "AdamW":
        b1, b2 = g.get("betas", (0.9, 0.999))
        return AdamWeightDecay(learning_rate=g["lr"], beta1=b1, beta2=b2,
                               weight_decay=g.get("weight_decay", 1e-2))
    if tname == "RMSprop":
        return RMSprop(learning_rate=g["lr"],
                       decay_rate=g.get("alpha", 0.99),
                       epsilon=g.get("eps", 1e-8))
    raise NotImplementedError(f"no OptimMethod mapping for torch {tname}")
