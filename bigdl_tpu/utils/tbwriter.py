"""TensorBoard event-file writer (no tensorboard/tensorflow dependency).

Reference analog (unverified — mount empty): ``dllib/utils/visualization/``
+ the bundled ``FileWriter`` that serialises TensorBoard ``Event`` protobufs
(SURVEY.md §6.1) so training curves open in stock TensorBoard.

The event-file format is a TFRecord stream:
    [uint64 length][uint32 masked-crc32c(length)][payload][uint32 masked-crc32c(payload)]
where payload is an ``Event`` protobuf.  The tiny subset of proto fields
needed (Event.wall_time=1 double, Event.step=2 int64, Event.file_version=3
string, Event.summary=5 message; Summary.value=1 repeated message;
Summary.Value.tag=1 string, .simple_value=2 float) is hand-encoded below —
pulling in protobuf codegen for five fields would be the tail wagging the
dog.
"""

import os
import struct
import time
from typing import Optional

# ---------------------------------------------------------------------------
# crc32c (Castagnoli), table-driven — required by the TFRecord framing.
# ---------------------------------------------------------------------------

_CRC_TABLE = []


def _crc_table():
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def _crc32c(data: bytes) -> int:
    tbl = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = tbl[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# minimal protobuf wire encoding
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _key(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _pb_double(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", v)


def _pb_float(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


def _pb_int64(field: int, v: int) -> bytes:
    return _key(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _pb_bytes(field: int, v: bytes) -> bytes:
    return _key(field, 2) + _varint(len(v)) + v


def _pb_str(field: int, v: str) -> bytes:
    return _pb_bytes(field, v.encode())


def _event(wall: float, step: Optional[int] = None,
           file_version: Optional[str] = None,
           summary: Optional[bytes] = None) -> bytes:
    out = _pb_double(1, wall)
    if step is not None:
        out += _pb_int64(2, step)
    if file_version is not None:
        out += _pb_str(3, file_version)
    if summary is not None:
        out += _pb_bytes(5, summary)
    return out


def _scalar_summary(tag: str, value: float) -> bytes:
    val = _pb_str(1, tag) + _pb_float(2, value)
    return _pb_bytes(1, val)


def _packed_doubles(field: int, vals) -> bytes:
    body = struct.pack(f"<{len(vals)}d", *map(float, vals))
    return _key(field, 2) + _varint(len(body)) + body


def _histogram_summary(tag: str, values, bins: int = 30) -> bytes:
    """Summary.Value with a HistogramProto (tensorflow/core/framework/
    summary.proto: min=1, max=2, num=3, sum=4, sum_squares=5,
    bucket_limit=6, bucket=7) — the parameter-histogram stream the
    reference's TrainSummary emits when 'Parameters' is enabled."""
    import numpy as np

    v = np.asarray(values, np.float64).reshape(-1)
    v = v[np.isfinite(v)]  # diverged params must not kill the monitoring
    if v.size == 0:
        v = np.zeros((1,))
    counts, edges = np.histogram(v, bins=bins)
    histo = (_pb_double(1, float(v.min())) + _pb_double(2, float(v.max()))
             + _pb_double(3, float(v.size)) + _pb_double(4, float(v.sum()))
             + _pb_double(5, float((v * v).sum()))
             + _packed_doubles(6, edges[1:]) + _packed_doubles(7, counts))
    val = _pb_str(1, tag) + _pb_bytes(5, histo)
    return _pb_bytes(1, val)


class TensorBoardWriter:
    """Write ``events.out.tfevents.*`` scalar streams stock TensorBoard can
    read.  API mirrors the reference FileWriter surface used by
    TrainSummary/ValidationSummary."""

    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.{os.getpid()}"
        self.path = os.path.join(log_dir, fname)
        self._f = open(self.path, "ab")
        self._record(_event(time.time(), file_version="brain.Event:2"))

    def _record(self, payload: bytes):
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))
        self._f.flush()

    def add_scalar(self, tag: str, value: float, step: int):
        self._record(_event(time.time(), step=step,
                            summary=_scalar_summary(tag, float(value))))

    def add_histogram(self, tag: str, values, step: int, bins: int = 30):
        self._record(_event(time.time(), step=step,
                            summary=_histogram_summary(tag, values, bins)))

    def close(self):
        if not self._f.closed:
            self._f.flush()
            self._f.close()


def read_scalars(path: str):
    """Parse an event file written by TensorBoardWriter back into
    (step, tag, value) tuples — used by tests and by ``TrainSummary.
    read_scalar`` (reference API)."""
    out = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos + 12 <= len(data):
        (length,) = struct.unpack_from("<Q", data, pos)
        payload = data[pos + 12: pos + 12 + length]
        pos += 12 + length + 4
        step, tag, value = 0, None, None
        # walk top-level Event fields
        p = 0
        while p < len(payload):
            key = payload[p]
            field, wire = key >> 3, key & 7
            p += 1
            if wire == 1:
                p += 8
            elif wire == 5:
                p += 4
            elif wire == 0:
                v = 0
                shift = 0
                while True:
                    b = payload[p]
                    p += 1
                    v |= (b & 0x7F) << shift
                    shift += 7
                    if not b & 0x80:
                        break
                if field == 2:
                    step = v
            elif wire == 2:
                ln = 0
                shift = 0
                while True:
                    b = payload[p]
                    p += 1
                    ln |= (b & 0x7F) << shift
                    shift += 7
                    if not b & 0x80:
                        break
                sub = payload[p:p + ln]
                p += ln
                if field == 5:  # summary -> value submessage
                    sp = 1
                    sln = 0
                    shift = 0
                    while sp < len(sub):
                        b = sub[sp]
                        sp += 1
                        sln |= (b & 0x7F) << shift
                        shift += 7
                        if not b & 0x80:
                            break
                    vmsg = sub[sp:sp + sln]
                    vp = 0
                    while vp < len(vmsg):
                        k = vmsg[vp]
                        f_, w_ = k >> 3, k & 7
                        vp += 1
                        if w_ == 2:
                            l2 = 0
                            shift2 = 0
                            while True:  # length is a varint (tags >= 128 B)
                                b2 = vmsg[vp]
                                vp += 1
                                l2 |= (b2 & 0x7F) << shift2
                                shift2 += 7
                                if not b2 & 0x80:
                                    break
                            if f_ == 1:
                                tag = vmsg[vp:vp + l2].decode()
                            vp += l2
                        elif w_ == 5:
                            if f_ == 2:
                                (value,) = struct.unpack_from("<f", vmsg, vp)
                            vp += 4
                        elif w_ == 0:
                            while vmsg[vp] & 0x80:
                                vp += 1
                            vp += 1
                        elif w_ == 1:
                            vp += 8
        if tag is not None:
            out.append((step, tag, value))
    return out
