"""Model interop: import/export weights from/to torch modules.

Reference analog (unverified — mount empty): the reference ships model
*import-export* beyond its own format — ``utils/caffe/CaffeLoader.scala``,
``utils/tf/TensorflowLoader.scala`` (SURVEY.md §3.1) — so reference users can
bring externally-trained weights.  Caffe/TF1 graphs are legacy; the living
ecosystem interchange today is torch modules, so the TPU-native equivalent
imports/exports torch ``state_dict`` weights.

Mapping is **structural**: the ordered list of parameterized torch leaf
modules must match the ordered list of parameterized bigdl_tpu leaf modules
(containers are walked in order).  Layout conversions applied per type:

==================  =======================  ==========================
torch               bigdl_tpu                transform
------------------  -----------------------  --------------------------
Linear (out,in)     Linear (in,out)          transpose
Conv2d OIHW         Conv2D HWIO              permute(2,3,1,0)
ConvTranspose2d     Conv2DTranspose          permute(2,3,1,0)  (I,O,H,W →
  (in,out,kh,kw)      (kh,kw,out,in)          H,W,O,I)
Conv1d OIW          Conv1D WIO               permute(2,1,0)
BatchNorm*d         BatchNorm                weight/bias + running stats
Embedding           Embedding                copy
LayerNorm           LayerNorm                copy
PReLU               PReLU                    copy (per-channel)
==================  =======================  ==========================

NCHW→NHWC is a *model-structure* concern (our models are NHWC); the caller
feeds NHWC inputs and this module only converts the kernels.
"""

from typing import Any, Dict, List, Tuple

import numpy as np

import jax.numpy as jnp

from bigdl_tpu.nn.module import Container, Module


def _our_leaves(module: Module, variables: Dict[str, Any]
                ) -> List[Tuple[Module, Dict, Dict]]:
    """Ordered (module, params, state) triples for parameterized leaves."""
    out = []
    params = variables.get("params", {})
    state = variables.get("state", {})
    if isinstance(module, Container):
        for i, child in enumerate(module.layers):
            k = module._key(i)
            out += _our_leaves(child, {"params": params.get(k, {}),
                                       "state": state.get(k, {})})
    elif params or state:
        out.append((module, params, state))
    return out


def _torch_leaves(tmodule) -> List[Any]:
    """Ordered torch leaf modules that own parameters or buffers directly."""
    out = []
    for m in tmodule.modules():
        has_own = any(True for _ in m.parameters(recurse=False)) or any(
            True for _ in m.buffers(recurse=False))
        if has_own:
            out.append(m)
    return out


def _convert(tm, our: Module, params: Dict, state: Dict
             ) -> Tuple[Dict, Dict]:
    """Produce new (params, state) for ``our`` from torch module ``tm``."""
    import torch

    def np_(t):
        return t.detach().cpu().numpy()

    tname = type(tm).__name__
    new_p = dict(params)
    new_s = dict(state)

    def set_bias():
        if tm.bias is not None and "bias" not in params:
            raise ValueError(
                f"torch {tname} has a bias but {type(our).__name__} was "
                "built with with_bias=False — silent drop refused")
        if tm.bias is not None:
            new_p["bias"] = jnp.asarray(np_(tm.bias))

    if tname == "Linear":
        new_p["weight"] = jnp.asarray(np_(tm.weight).T)
        set_bias()
    elif tname == "Conv2d":
        new_p["weight"] = jnp.asarray(np_(tm.weight).transpose(2, 3, 1, 0))
        set_bias()
    elif tname == "ConvTranspose2d":
        # torch (in, out, kh, kw) → ours (kh, kw, out, in)
        new_p["weight"] = jnp.asarray(np_(tm.weight).transpose(2, 3, 1, 0))
        set_bias()
    elif tname == "Conv1d":
        new_p["weight"] = jnp.asarray(np_(tm.weight).transpose(2, 1, 0))
        set_bias()
    elif tname in ("BatchNorm1d", "BatchNorm2d", "BatchNorm3d"):
        if tm.weight is not None and "weight" not in params:
            raise ValueError(
                f"torch {tname} is affine but {type(our).__name__} was "
                "built with affine=False — silent drop refused")
        if tm.weight is not None:
            new_p["weight"] = jnp.asarray(np_(tm.weight))
            new_p["bias"] = jnp.asarray(np_(tm.bias))
        new_s["running_mean"] = jnp.asarray(np_(tm.running_mean))
        new_s["running_var"] = jnp.asarray(np_(tm.running_var))
    elif tname == "Embedding":
        new_p["weight"] = jnp.asarray(np_(tm.weight))
    elif tname == "LayerNorm":
        new_p["weight"] = jnp.asarray(np_(tm.weight))
        new_p["bias"] = jnp.asarray(np_(tm.bias))
    elif tname == "PReLU":
        new_p["alpha"] = jnp.asarray(np_(tm.weight))
    else:
        raise NotImplementedError(
            f"no torch→bigdl_tpu conversion for {tname} → "
            f"{type(our).__name__}")
    # shape sanity + template-dtype restore, params AND state
    for tree, tmpl in ((new_p, params), (new_s, state)):
        for k, v in tree.items():
            if k in tmpl:
                want = tuple(np.shape(tmpl[k]))
                if want != tuple(v.shape):
                    raise ValueError(
                        f"{type(our).__name__}.{k}: torch shape "
                        f"{tuple(v.shape)} != model shape {want}")
                tree[k] = v.astype(np.asarray(tmpl[k]).dtype)
    return new_p, new_s


def from_torch(tmodule, model: Module, variables: Dict[str, Any]
               ) -> Dict[str, Any]:
    """Copy weights from a torch module into a structurally-matching
    bigdl_tpu ``variables`` tree (returns a NEW tree; input untouched)."""
    ours = _our_leaves(model, variables)
    theirs = _torch_leaves(tmodule)
    if len(ours) != len(theirs):
        raise ValueError(
            f"structure mismatch: bigdl_tpu model has {len(ours)} "
            f"parameterized leaves, torch module has {len(theirs)}: "
            f"{[type(m).__name__ for m, _, _ in ours]} vs "
            f"{[type(m).__name__ for m in theirs]}")

    converted = [_convert(tm, om, p, s)
                 for tm, (om, p, s) in zip(theirs, ours)]

    # rebuild the nested variables dict by walking the same paths again
    idx = [0]

    def rebuild(module, params, state):
        if isinstance(module, Container):
            np_, ns_ = dict(params), dict(state)
            for i, child in enumerate(module.layers):
                k = module._key(i)
                cp, cs = rebuild(child, params.get(k, {}), state.get(k, {}))
                if cp:
                    np_[k] = cp
                if cs:
                    ns_[k] = cs
            return np_, ns_
        if params or state:
            p, s = converted[idx[0]]
            idx[0] += 1
            return p, s
        return params, state

    p, s = rebuild(model, variables.get("params", {}),
                   variables.get("state", {}))
    return {"params": p, "state": s}


def to_torch(model: Module, variables: Dict[str, Any], tmodule):
    """Reverse direction: write bigdl_tpu weights into a torch module."""
    import torch

    ours = _our_leaves(model, variables)
    theirs = _torch_leaves(tmodule)
    if len(ours) != len(theirs):
        raise ValueError("structure mismatch between models")
    with torch.no_grad():
        for tm, (om, p, s) in zip(theirs, ours):
            tname = type(tm).__name__
            if tname == "Linear":
                tm.weight.copy_(torch.tensor(np.asarray(p["weight"]).T))
                if tm.bias is not None and "bias" in p:
                    tm.bias.copy_(torch.tensor(np.asarray(p["bias"])))
            elif tname == "Conv2d":
                tm.weight.copy_(torch.tensor(
                    np.asarray(p["weight"]).transpose(3, 2, 0, 1)))
                if tm.bias is not None and "bias" in p:
                    tm.bias.copy_(torch.tensor(np.asarray(p["bias"])))
            elif tname in ("BatchNorm1d", "BatchNorm2d", "BatchNorm3d"):
                if "weight" in p:
                    tm.weight.copy_(torch.tensor(np.asarray(p["weight"])))
                    tm.bias.copy_(torch.tensor(np.asarray(p["bias"])))
                tm.running_mean.copy_(
                    torch.tensor(np.asarray(s["running_mean"])))
                tm.running_var.copy_(
                    torch.tensor(np.asarray(s["running_var"])))
            elif tname == "ConvTranspose2d":
                # ours (kh, kw, out, in) → torch (in, out, kh, kw)
                tm.weight.copy_(torch.tensor(
                    np.asarray(p["weight"]).transpose(3, 2, 0, 1)))
                if tm.bias is not None and "bias" in p:
                    tm.bias.copy_(torch.tensor(np.asarray(p["bias"])))
            elif tname == "Conv1d":
                tm.weight.copy_(torch.tensor(
                    np.asarray(p["weight"]).transpose(2, 1, 0)))
                if tm.bias is not None and "bias" in p:
                    tm.bias.copy_(torch.tensor(np.asarray(p["bias"])))
            elif tname == "PReLU":
                tm.weight.copy_(torch.tensor(np.asarray(p["alpha"])))
            elif tname == "Embedding":
                tm.weight.copy_(torch.tensor(np.asarray(p["weight"])))
            elif tname == "LayerNorm":
                tm.weight.copy_(torch.tensor(np.asarray(p["weight"])))
                tm.bias.copy_(torch.tensor(np.asarray(p["bias"])))
            else:
                raise NotImplementedError(
                    f"no bigdl_tpu→torch conversion for {tname}")
    return tmodule
