"""Durable model format — the ``ModuleSerializer``/protobuf analog.

Reference (unverified — mount empty): ``dllib/utils/serializer/
ModuleSerializer.scala`` + ``bigdl.proto`` — a versioned protobuf with
per-layer converters and weights as tensor blobs (SURVEY.md §6.4).

TPU-native format: a directory with
- ``manifest.json``: format version, model class/repr, tree structure with
  dtypes/shapes (the proto-schema role, human-readable)
- ``weights.npz``: flat path->array map (the tensor-blob role; zero-copy
  into jnp on load)

Multi-host discipline: only process 0 writes; every process can read.

``path`` may be local or a remote URI (``gs://…`` — the reference's
``Module.saveModule`` takes an HDFS path the same way, ``File.scala``);
remote writes order the manifest LAST so a partial upload is never
mistaken for a saved model.
"""

from typing import Any, Dict

import numpy as np

import jax

from bigdl_tpu.utils import storage

FORMAT_VERSION = 1


def _flatten(tree) -> Dict[str, np.ndarray]:
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        out[jax.tree_util.keystr(path)] = np.asarray(leaf)
    return out


def _unflatten_like(template, flat: Dict[str, np.ndarray]):
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths:
        key = jax.tree_util.keystr(path)
        if key not in flat:
            raise KeyError(f"checkpoint missing tensor {key}")
        arr = flat[key]
        want = tuple(np.shape(leaf))
        if tuple(arr.shape) != want:
            raise ValueError(
                f"shape mismatch for {key}: saved {arr.shape}, model {want}")
        # restore the template leaf's dtype (e.g. bf16 params aggregated /
        # stored as f32 must come back bf16)
        leaves.append(arr.astype(np.asarray(leaf).dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_model(path: str, model, variables: Dict[str, Any],
               overwrite: bool = True) -> None:
    """``Module.saveModule(path, overWrite)`` analog."""
    if storage.exists(storage.join(path, "manifest.json")) and not overwrite:
        raise FileExistsError(f"{path} exists and overwrite=False")
    if jax.process_index() != 0:
        return
    storage.makedirs(path)
    flat = _flatten(variables)
    manifest = {
        "format_version": FORMAT_VERSION,
        "model_class": type(model).__name__ if model is not None else None,
        "model_repr": repr(model) if model is not None else None,
        "tensors": {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                    for k, v in flat.items()},
    }
    # weights first, manifest last: remote stores have no atomic rename,
    # so the manifest's presence is the completeness marker.  When
    # overwriting, the OLD manifest goes first — it must not certify
    # half-rewritten weights if this write crashes.
    manifest_path = storage.join(path, "manifest.json")
    if storage.is_remote(path) and storage.exists(manifest_path):
        storage.remove_tree(manifest_path, ignore_errors=False)
    with storage.open_file(storage.join(path, "weights.npz"), "wb") as f:
        np.savez(f, **{k: v for k, v in flat.items()})
    storage.write_json(manifest_path, manifest, indent=1)


def load_model(path: str, model=None,
               template: Dict[str, Any] = None) -> Dict[str, Any]:
    """Load variables saved by ``save_model``.  If ``template`` (a variables
    pytree, e.g. from ``model.init``) is given, the result keeps its exact
    structure and shapes are validated; otherwise a nested-dict pytree is
    rebuilt from the flat paths."""
    manifest = storage.read_json(storage.join(path, "manifest.json"))
    if manifest["format_version"] > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format v{manifest['format_version']} is newer than "
            f"this library (v{FORMAT_VERSION})")
    flat = storage.load_npz(storage.join(path, "weights.npz"))
    if template is not None:
        return _unflatten_like(template, flat)
    # rebuild nested dicts from keystr paths like "['params']['block_0']['w']"
    root: Dict[str, Any] = {}
    for key, arr in flat.items():
        parts = [p.strip("[]'\"") for p in key.split("][")]
        parts = [p.replace("['", "").replace("']", "") for p in parts]
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root
