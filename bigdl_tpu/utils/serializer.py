"""Durable model format — the ``ModuleSerializer``/protobuf analog.

Reference (unverified — mount empty): ``dllib/utils/serializer/
ModuleSerializer.scala`` + ``bigdl.proto`` — a versioned protobuf with
per-layer converters and weights as tensor blobs (SURVEY.md §6.4).

TPU-native format: a directory with
- ``manifest.json``: format version, model class/repr, tree structure with
  dtypes/shapes (the proto-schema role, human-readable)
- ``weights.npz``: flat path->array map (the tensor-blob role; zero-copy
  into jnp on load)

Multi-host discipline: only process 0 writes; every process can read.
"""

import json
import os
from typing import Any, Dict

import numpy as np

import jax

FORMAT_VERSION = 1


def _flatten(tree) -> Dict[str, np.ndarray]:
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        out[jax.tree_util.keystr(path)] = np.asarray(leaf)
    return out


def _unflatten_like(template, flat: Dict[str, np.ndarray]):
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths:
        key = jax.tree_util.keystr(path)
        if key not in flat:
            raise KeyError(f"checkpoint missing tensor {key}")
        arr = flat[key]
        want = tuple(np.shape(leaf))
        if tuple(arr.shape) != want:
            raise ValueError(
                f"shape mismatch for {key}: saved {arr.shape}, model {want}")
        # restore the template leaf's dtype (e.g. bf16 params aggregated /
        # stored as f32 must come back bf16)
        leaves.append(arr.astype(np.asarray(leaf).dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_model(path: str, model, variables: Dict[str, Any],
               overwrite: bool = True) -> None:
    """``Module.saveModule(path, overWrite)`` analog."""
    if os.path.exists(os.path.join(path, "manifest.json")) and not overwrite:
        raise FileExistsError(f"{path} exists and overwrite=False")
    if jax.process_index() != 0:
        return
    os.makedirs(path, exist_ok=True)
    flat = _flatten(variables)
    manifest = {
        "format_version": FORMAT_VERSION,
        "model_class": type(model).__name__ if model is not None else None,
        "model_repr": repr(model) if model is not None else None,
        "tensors": {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                    for k, v in flat.items()},
    }
    with open(os.path.join(path, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)
    np.savez(os.path.join(path, "weights.npz"),
             **{k: v for k, v in flat.items()})


def load_model(path: str, model=None,
               template: Dict[str, Any] = None) -> Dict[str, Any]:
    """Load variables saved by ``save_model``.  If ``template`` (a variables
    pytree, e.g. from ``model.init``) is given, the result keeps its exact
    structure and shapes are validated; otherwise a nested-dict pytree is
    rebuilt from the flat paths."""
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    if manifest["format_version"] > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format v{manifest['format_version']} is newer than "
            f"this library (v{FORMAT_VERSION})")
    with np.load(os.path.join(path, "weights.npz")) as z:
        flat = {k: z[k] for k in z.files}
    if template is not None:
        return _unflatten_like(template, flat)
    # rebuild nested dicts from keystr paths like "['params']['block_0']['w']"
    root: Dict[str, Any] = {}
    for key, arr in flat.items():
        parts = [p.strip("[]'\"") for p in key.split("][")]
        parts = [p.replace("['", "").replace("']", "") for p in parts]
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root
