"""Caffe model import/export — the ``utils/caffe`` analog.

Reference analog (unverified — mount empty):
``utils/caffe/CaffeLoader.scala`` converts a Caffe ``NetParameter``
(binary ``.caffemodel``) into a BigDL graph + weights;
``utils/caffe/CaffePersister.scala`` writes one back.  Same role here,
with the wire format read/written via ``utils/proto`` (no caffe/protobuf
dependency), producing a keras-engine functional ``Model``.

Layout note: Caffe is NCHW, this framework is NHWC.  On import conv/BN
weights are transposed to HWIO, channel-wise ``Concat axis=1`` becomes
``JoinTable(3)``, and an ``InnerProduct`` consuming a 4-D blob gets a
``Transpose(0,3,1,2)+Flatten`` prefix so numerics match Caffe's NCHW
flatten exactly.  Imported models therefore take NHWC inputs like every
other model in the framework.

Import:  ``model, variables = load_caffe(path_or_bytes)``
Export:  ``blob = save_caffe(model, variables, sample, path=...)``
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.utils import proto
from bigdl_tpu.utils.proto import Msg


class UnsupportedCaffeLayer(ValueError):
    pass


# ---------------------------------------------------------------------------
# caffe.proto subset codec
# ---------------------------------------------------------------------------
# Field numbers from BVLC caffe.proto:
#   NetParameter: name=1, input=3, input_dim=4, layer=100 (LayerParameter)
#   LayerParameter: name=1, type=2, bottom=3, top=4, blobs=7,
#     concat_param=104, convolution_param=106, dropout_param=108,
#     eltwise_param=110, inner_product_param=117, lrn_param=118,
#     pooling_param=121, batch_norm_param=139, scale_param=142,
#     input_param=143
#   BlobProto: data=5 (packed float), shape=7 (BlobShape: dim=1)


def _decode_blob(data: bytes) -> np.ndarray:
    f = proto.parse(data)
    vals = np.asarray(proto.repeated_f32(f, 5), np.float32)
    shape_raw = proto.get_bytes(f, 7)
    if shape_raw:
        dims = proto.repeated_ints(proto.parse(shape_raw), 1)
    else:  # legacy num/channels/height/width fields 1-4
        dims = [proto.get_int(f, i, 1) for i in (1, 2, 3, 4)]
        while len(dims) > 1 and dims[0] == 1:
            dims = dims[1:]
    return vals.reshape(tuple(dims))


def _encode_blob(arr: np.ndarray) -> Msg:
    arr = np.asarray(arr, np.float32)
    shape = Msg()
    for d in arr.shape:
        shape.varint(1, int(d))
    # packed float wire format == little-endian IEEE754 concatenation
    return Msg().msg(7, shape).blob(
        5, np.ascontiguousarray(arr, np.float32).tobytes())


class CaffeLayer:
    def __init__(self, name: str, type_: str, bottoms: List[str],
                 tops: List[str], blobs: List[np.ndarray],
                 params: Dict[str, Dict]):
        self.name, self.type = name, type_
        self.bottoms, self.tops, self.blobs = bottoms, tops, blobs
        self.params = params  # param-message name -> parsed fields

    def __repr__(self):
        return f"CaffeLayer({self.type}:{self.name})"


_PARAM_FIELDS = {
    104: "concat", 106: "convolution", 108: "dropout", 110: "eltwise",
    117: "inner_product", 118: "lrn", 121: "pooling", 139: "batch_norm",
    142: "scale", 143: "input", 125: "softmax", 133: "reshape",
}


def parse_caffe_net(data: bytes) -> Tuple[str, List[CaffeLayer]]:
    f = proto.parse(data)
    net_name = proto.get_str(f, 1)
    layers = []
    for raw in proto.repeated(f, 100):
        lf = proto.parse(raw)
        params = {}
        for num, pname in _PARAM_FIELDS.items():
            b = proto.get_bytes(lf, num)
            if b:
                params[pname] = proto.parse(b)
        layers.append(CaffeLayer(
            proto.get_str(lf, 1), proto.get_str(lf, 2),
            [b.decode() for b in proto.repeated(lf, 3)],
            [b.decode() for b in proto.repeated(lf, 4)],
            [_decode_blob(b) for b in proto.repeated(lf, 7)],
            params))
    return net_name, layers


# ---------------------------------------------------------------------------
# import
# ---------------------------------------------------------------------------


def _conv_geom(p, field_pair, repeated_field, default):
    """Caffe allows kernel_size (repeated) or kernel_h/kernel_w; same for
    stride/pad."""
    h_field, w_field = field_pair
    h = proto.get_int(p, h_field, 0)
    w = proto.get_int(p, w_field, 0)
    if h or w:
        return (h or default, w or default)
    rep = proto.repeated_ints(p, repeated_field)
    if not rep:
        return (default, default)
    if len(rep) == 1:
        return (rep[0], rep[0])
    return (rep[0], rep[1])


def load_caffe(source, input_shapes: Optional[Dict[str, Sequence[int]]] = None):
    """Import a Caffe NetParameter (deploy-style, with Input layer or
    ``input_shapes`` giving NHWC shapes).  Returns ``(model, variables)``."""
    from bigdl_tpu import nn
    from bigdl_tpu.keras.engine import Input, Model, Node

    if isinstance(source, str):
        with open(source, "rb") as fh:
            source = fh.read()
    _, layers = parse_caffe_net(source)

    sym: Dict[str, Node] = {}
    shape: Dict[str, Tuple[int, ...]] = {}  # NHWC shapes incl. batch
    inputs: List[Node] = []
    imported: List[Tuple[Any, Dict, Dict]] = []
    pending_bn: Dict[str, Tuple[Any, Dict, Dict]] = {}  # top -> BN awaiting Scale

    def add_layer(layer, p, s, parents, top, out_shape):
        node = layer(parents[0] if len(parents) == 1 else parents)
        imported.append((layer, p, s))
        sym[top] = node
        shape[top] = out_shape

    for lay in layers:
        t = lay.type
        if t in ("Input", "Data", "DummyData"):
            for ti, top in enumerate(lay.tops):
                dims = None
                if "input" in lay.params:
                    shapes_raw = proto.repeated(lay.params["input"], 1)
                    if ti < len(shapes_raw):
                        dims = proto.repeated_ints(
                            proto.parse(shapes_raw[ti]), 1)
                if input_shapes and top in input_shapes:
                    nhwc = tuple(input_shapes[top])
                elif dims and len(dims) == 4:
                    n, c, h, w = dims
                    nhwc = (n, h, w, c)
                elif dims:
                    nhwc = tuple(dims)
                else:
                    raise UnsupportedCaffeLayer(
                        f"Input '{top}' has no shape; pass input_shapes (NHWC)")
                node = Input(nhwc[1:])
                sym[top] = node
                shape[top] = nhwc
                inputs.append(node)
            continue

        bottom = lay.bottoms[0] if lay.bottoms else None
        top = lay.tops[0] if lay.tops else lay.name
        x = sym.get(bottom)
        if x is None:
            raise UnsupportedCaffeLayer(
                f"{t} '{lay.name}': unknown bottom '{bottom}'")
        in_shape = shape[bottom]

        if t == "Convolution":
            p = lay.params.get("convolution", {})
            cout = proto.get_int(p, 1)
            bias_term = proto.get_bool(p, 2, True)
            kh, kw = _conv_geom(p, (11, 12), 4, 1)
            sh, sw = _conv_geom(p, (13, 14), 6, 1)
            ph, pw = _conv_geom(p, (9, 10), 3, 0)
            group = proto.get_int(p, 5, 1)
            dil = proto.repeated_ints(p, 18)
            d = dil[0] if dil else 1
            w = lay.blobs[0]  # (cout, cin/g, kh, kw)
            w = np.transpose(w, (2, 3, 1, 0))  # HWIO
            layer = nn.Conv2D(in_shape[3], cout, (kh, kw), stride=(sh, sw),
                              padding=(ph, pw), dilation=d, groups=group,
                              with_bias=bias_term, name=_pyname(lay.name))
            params = {"weight": w}
            if bias_term:
                params["bias"] = lay.blobs[1]
            oh = (in_shape[1] + 2 * ph - ((kh - 1) * d + 1)) // sh + 1
            ow = (in_shape[2] + 2 * pw - ((kw - 1) * d + 1)) // sw + 1
            add_layer(layer, params, {}, [x], top, (in_shape[0], oh, ow, cout))
        elif t == "InnerProduct":
            p = lay.params.get("inner_product", {})
            cout = proto.get_int(p, 1)
            bias_term = proto.get_bool(p, 2, True)
            w = lay.blobs[0]  # (cout, cin) — cin over NCHW-flattened input
            parents = [x]
            if len(in_shape) == 4:
                tr = nn.Transpose((0, 3, 1, 2), name=_pyname(lay.name) + "_nchw")
                fl = nn.Flatten(name=_pyname(lay.name) + "_flat")
                node = tr(parents[0])
                imported.append((tr, {}, {}))
                node = fl(node)
                imported.append((fl, {}, {}))
                parents = [node]
            layer = nn.Linear(w.shape[1], cout, with_bias=bias_term,
                              name=_pyname(lay.name))
            params = {"weight": w.T}
            if bias_term:
                params["bias"] = lay.blobs[1]
            add_layer(layer, params, {}, parents, top, (in_shape[0], cout))
        elif t == "Pooling":
            p = lay.params.get("pooling", {})
            pool = proto.get_int(p, 1, 0)  # 0=MAX 1=AVE
            if proto.get_bool(p, 12, False):  # global_pooling
                layer = (nn.GlobalMaxPool2D(name=_pyname(lay.name)) if pool == 0
                         else nn.GlobalAvgPool2D(name=_pyname(lay.name)))
                add_layer(layer, {}, {}, [x], top,
                          (in_shape[0], in_shape[3]))
                continue
            kh, kw = _conv_geom(p, (5, 6), 2, 1)
            sh, sw = _conv_geom(p, (7, 8), 3, 1)
            ph, pw = _conv_geom(p, (9, 10), 4, 0)
            cls = nn.MaxPool2D if pool == 0 else nn.AvgPool2D
            # caffe pooling rounds output size UP (ceil mode)
            layer = cls((kh, kw), stride=(sh, sw), padding=(ph, pw),
                        ceil_mode=True, name=_pyname(lay.name))
            oh = -(-(in_shape[1] + 2 * ph - kh) // sh) + 1
            ow = -(-(in_shape[2] + 2 * pw - kw) // sw) + 1
            add_layer(layer, {}, {}, [x], top,
                      (in_shape[0], oh, ow, in_shape[3]))
        elif t == "ReLU":
            add_layer(nn.ReLU(name=_pyname(lay.name)), {}, {}, [x], top,
                      in_shape)
        elif t == "Sigmoid":
            add_layer(nn.Sigmoid(name=_pyname(lay.name)), {}, {}, [x], top,
                      in_shape)
        elif t == "TanH":
            add_layer(nn.Tanh(name=_pyname(lay.name)), {}, {}, [x], top,
                      in_shape)
        elif t in ("Softmax", "SoftmaxWithLoss"):
            add_layer(nn.SoftMax(name=_pyname(lay.name)), {}, {}, [x], top,
                      in_shape)
        elif t == "Dropout":
            p = lay.params.get("dropout", {})
            ratio = proto.get_f32(p, 1, 0.5)
            add_layer(nn.Dropout(ratio, name=_pyname(lay.name)), {}, {}, [x],
                      top, in_shape)
        elif t == "LRN":
            p = lay.params.get("lrn", {})
            size = proto.get_int(p, 1, 5)
            alpha = proto.get_f32(p, 2, 1.0)
            beta = proto.get_f32(p, 3, 0.75)
            k = proto.get_f32(p, 5, 1.0)
            add_layer(nn.LRN(size, alpha, beta, k, name=_pyname(lay.name)),
                      {}, {}, [x], top, in_shape)
        elif t == "BatchNorm":
            p = lay.params.get("batch_norm", {})
            eps = proto.get_f32(p, 3, 1e-5)
            mean, var = lay.blobs[0], lay.blobs[1]
            sf = float(lay.blobs[2].reshape(-1)[0]) if len(lay.blobs) > 2 else 1.0
            sf = 1.0 / sf if sf != 0 else 1.0
            bn = nn.BatchNorm(mean.shape[0], eps=eps, affine=True,
                              name=_pyname(lay.name))
            params = {"weight": np.ones_like(mean), "bias": np.zeros_like(mean)}
            state = {"running_mean": mean * sf, "running_var": var * sf}
            # a DIRECTLY-following Scale layer folds its gamma/beta into this
            # dict; the fold checks sym[top] is still this BN's node so any
            # intervening layer (even in-place) invalidates it
            add_layer(bn, params, state, [x], top, in_shape)
            pending_bn[top] = (sym[top], params, state)
        elif t == "Scale":
            prev = pending_bn.pop(bottom, None)
            if prev is not None and sym.get(bottom) is not prev[0]:
                prev = None  # another layer ran in between; don't fold
            p = lay.params.get("scale", {})
            bias_term = proto.get_bool(p, 4, False)
            gamma = lay.blobs[0]
            beta = lay.blobs[1] if bias_term and len(lay.blobs) > 1 else \
                np.zeros_like(gamma)
            if prev is not None:
                _, bn_params, _ = prev
                bn_params["weight"] = gamma
                bn_params["bias"] = beta
                sym[top] = sym[bottom]
                shape[top] = in_shape
            else:
                layer = nn.CMul(gamma.shape, name=_pyname(lay.name))
                add_layer(layer, {"weight": gamma}, {}, [x], top, in_shape)
                if bias_term:
                    bl = nn.CAdd(beta.shape, name=_pyname(lay.name) + "_b")
                    add_layer(bl, {"bias": beta}, {}, [sym[top]], top, in_shape)
        elif t == "Eltwise":
            p = lay.params.get("eltwise", {})
            op = proto.get_int(p, 1, 1)  # default SUM
            coeff = proto.repeated_f32(p, 2)
            parents = [sym[b] for b in lay.bottoms]
            if coeff and op == 1 and list(coeff) == [1.0, -1.0]:
                cls = nn.CSubTable
            elif coeff and any(c != 1.0 for c in coeff):
                raise UnsupportedCaffeLayer(
                    f"Eltwise '{lay.name}': coeff {coeff} not supported")
            else:
                cls = {0: nn.CMulTable, 1: nn.CAddTable, 2: nn.CMaxTable}[op]
            add_layer(cls(name=_pyname(lay.name)), {}, {}, parents, top,
                      in_shape)
        elif t == "Concat":
            p = lay.params.get("concat", {})
            axis = proto.get_int(p, 2, 1)
            if len(in_shape) == 4:
                dim = {0: 0, 1: 3, 2: 1, 3: 2}[axis]  # NCHW -> NHWC
            else:
                dim = axis
            parents = [sym[b] for b in lay.bottoms]
            out = list(in_shape)
            out[dim] = sum(shape[b][dim] for b in lay.bottoms)
            add_layer(nn.JoinTable(dim, name=_pyname(lay.name)), {}, {},
                      parents, top, tuple(out))
        elif t == "Reshape":
            p = lay.params.get("reshape", {})
            dims = proto.repeated_ints(proto.parse(proto.get_bytes(p, 1)), 1) \
                if proto.get_bytes(p, 1) else []
            if len(in_shape) == 4 and dims[:1] in ([0], [-1]) and \
                    list(dims[1:]) == [-1]:
                # NCHW flatten == our Flatten behind a transpose
                tr = nn.Transpose((0, 3, 1, 2), name=_pyname(lay.name) + "_n")
                add_layer(tr, {}, {}, [x], top + "__pre", in_shape)
                fl = nn.Flatten(name=_pyname(lay.name))
                add_layer(fl, {}, {}, [sym[top + "__pre"]], top,
                          (in_shape[0], int(np.prod(in_shape[1:]))))
            elif len(in_shape) != 4 and dims and dims[0] in (0, -1):
                tgt = [int(d) for d in dims[1:]]
                add_layer(nn.Reshape(tgt, batch_mode=True,
                                     name=_pyname(lay.name)), {}, {}, [x],
                          top, (in_shape[0],) + tuple(
                              np.abs(tgt) if -1 not in tgt else
                              [int(np.prod(in_shape[1:]))]))
            else:
                raise UnsupportedCaffeLayer(
                    f"Reshape '{lay.name}' dims {dims} on rank-"
                    f"{len(in_shape)} blob")
        elif t == "Flatten":
            add_layer(nn.Transpose((0, 3, 1, 2), name=_pyname(lay.name) + "_n")
                      if len(in_shape) == 4 else nn.Identity(), {}, {}, [x],
                      top + "__pre", in_shape)
            fl = nn.Flatten(name=_pyname(lay.name))
            add_layer(fl, {}, {}, [sym[top + "__pre"]], top,
                      (in_shape[0], int(np.prod(in_shape[1:]))))
        else:
            raise UnsupportedCaffeLayer(
                f"unsupported Caffe layer type '{t}' ('{lay.name}')")

    if not inputs:
        raise UnsupportedCaffeLayer("net has no Input layer")
    consumed = set()
    for lay in layers:
        for b in lay.bottoms:
            if not (lay.tops and lay.tops[0] == b):  # in-place doesn't consume
                consumed.add(b)
    out_nodes, seen = [], set()
    for top_name, nd in sym.items():
        if top_name.endswith("__pre"):
            continue
        if top_name not in consumed and nd not in inputs and nd.id not in seen:
            seen.add(nd.id)
            out_nodes.append(nd)
    from bigdl_tpu.keras.engine import Model
    model = Model(inputs, out_nodes, name="CaffeImported")

    params: Dict[str, Dict] = {}
    state: Dict[str, Dict] = {}
    by_layer = {id(l): (p, s) for l, p, s in imported}
    for node in model.order:
        if node.layer is not None and id(node.layer) in by_layer:
            p, s = by_layer[id(node.layer)]
            if p:
                params[node.name] = {k: np.asarray(v, np.float32)
                                     for k, v in p.items()}
            if s:
                state[node.name] = {k: np.asarray(v, np.float32)
                                    for k, v in s.items()}
    return model, {"params": params, "state": state}


def _pyname(nm: str) -> str:
    return nm.replace("/", "_").replace(":", "_")


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------


def save_caffe(model, variables: Dict[str, Any], sample=None,
               path: Optional[str] = None) -> bytes:
    """Export a Sequential or functional Model as a binary Caffe
    NetParameter (deploy-style: Input layer + weights in blobs).

    The exported net is NCHW per Caffe convention; conv weights are
    transposed from HWIO, Linear weights reordered when they follow a
    spatial blob (requires ``sample`` for shape tracking, like the TF
    exporter).
    """
    from bigdl_tpu.keras.engine import Model as KModel
    from bigdl_tpu.nn.module import Sequential

    net = Msg().string(1, getattr(model, "name", "net"))
    uid = [0]

    def fresh(base):
        uid[0] += 1
        return f"{base}_{uid[0]}"

    def emit(name: str, type_: str, bottoms: List[str], top: str,
             blobs: Sequence[np.ndarray] = (), **param_msgs: Msg):
        m = Msg().string(1, name).string(2, type_)
        for b in bottoms:
            m.string(3, b)
        m.string(4, top)
        for blob in blobs:
            m.msg(7, _encode_blob(blob))
        field_of = {v: k for k, v in _PARAM_FIELDS.items()}
        for pname, pmsg in param_msgs.items():
            m.msg(field_of[pname], pmsg)
        net.msg(100, m)
        return top

    params = variables.get("params", {})
    state = variables.get("state", {})
    ctx: Dict[str, Any] = {"flat": {}}  # flatten-top -> pre-flatten (H, W, C)

    if isinstance(model, Sequential):
        if sample is None:
            raise UnsupportedCaffeLayer("save_caffe needs `sample`")
        x = np.asarray(sample)
        nchw = ((x.shape[0], x.shape[3], x.shape[1], x.shape[2])
                if x.ndim == 4 else x.shape)
        ip = Msg()
        bs = Msg()
        for d in nchw:
            bs.varint(1, int(d))
        ip.msg(1, bs)
        emit("data", "Input", [], "data", input=ip)
        cur, val = "data", x
        for i, layer in enumerate(model.layers):
            k = model._key(i)
            p, s = params.get(k, {}), state.get(k, {})
            cur = _emit_caffe_layer(emit, fresh, layer, p, s, [cur],
                                    [np.shape(val)], ctx)
            val2, _ = layer.apply({"params": p, "state": s}, val,
                                  training=False)
            val = np.asarray(val2)
    elif isinstance(model, KModel):
        if sample is None:
            raise UnsupportedCaffeLayer("save_caffe needs `sample`")
        samples = sample if isinstance(sample, (list, tuple)) else [sample]
        name_of: Dict[int, str] = {}
        val_of: Dict[int, np.ndarray] = {}
        for i, inp in enumerate(model.inputs):
            x = np.asarray(samples[i])
            nchw = ((x.shape[0], x.shape[3], x.shape[1], x.shape[2])
                    if x.ndim == 4 else x.shape)
            ip = Msg()
            bs = Msg()
            for d in nchw:
                bs.varint(1, int(d))
            ip.msg(1, bs)
            top = f"data_{i}"
            emit(top, "Input", [], top, input=ip)
            name_of[inp.id] = top
            val_of[inp.id] = x
        for node in model.order:
            if node.layer is None:
                continue
            ins = [name_of[p.id] for p in node.parents]
            shapes = [np.shape(val_of[p.id]) for p in node.parents]
            p = params.get(node.name, {})
            s = state.get(node.name, {})
            name_of[node.id] = _emit_caffe_layer(emit, fresh, node.layer, p, s,
                                                 ins, shapes, ctx)
            xs = [val_of[pn.id] for pn in node.parents]
            y, _ = node.layer.apply({"params": p, "state": s}, *xs,
                                    training=False)
            val_of[node.id] = np.asarray(y)
    else:
        raise UnsupportedCaffeLayer(f"cannot export {type(model).__name__}")

    data = net.bytes()
    if path:
        with open(path, "wb") as fh:
            fh.write(data)
    return data


def _emit_caffe_layer(emit, fresh, layer, params, state, ins: List[str],
                      in_shapes: List[Tuple], ctx: Dict) -> str:
    from bigdl_tpu import nn
    from bigdl_tpu.nn.module import Sequential

    t = type(layer).__name__
    x = ins[0] if ins else None

    if isinstance(layer, Sequential):
        cur = x
        shapes = in_shapes
        for i, sub in enumerate(layer.layers):
            k = layer._key(i)
            cur = _emit_caffe_layer(emit, fresh, sub, params.get(k, {}),
                                    state.get(k, {}), [cur], shapes, ctx)
            shapes = None
        return cur

    if isinstance(layer, nn.Conv2D) and t in ("Conv2D", "SpatialConvolution"):
        w = np.asarray(params["weight"])  # HWIO
        w_nchw = np.transpose(w, (3, 2, 0, 1))
        pad = layer.padding
        if isinstance(pad, str):
            if pad.upper() != "SAME":
                raise UnsupportedCaffeLayer(f"padding '{pad}'")
            kh, kw = layer.kernel_size
            ph, pw = (kh - 1) // 2, (kw - 1) // 2  # odd-kernel SAME
        else:
            ph, pw = (pad, pad) if isinstance(pad, int) else tuple(pad)
        p = (Msg().varint(1, layer.out_channels)
             .varint(2, 1 if layer.with_bias else 0)
             .varint(11, layer.kernel_size[0]).varint(12, layer.kernel_size[1])
             .varint(13, layer.stride[0]).varint(14, layer.stride[1])
             .varint(9, ph).varint(10, pw).varint(5, layer.groups))
        if layer.dilation != (1, 1):
            p.varint(18, layer.dilation[0])
        blobs = [w_nchw] + ([np.asarray(params["bias"])] if layer.with_bias
                            else [])
        return emit(fresh("conv"), "Convolution", [x], fresh("conv_top"),
                    blobs, convolution=p)

    if isinstance(layer, nn.Linear):
        w = np.asarray(params["weight"])  # (in, out), NHWC-flat rows
        if in_shapes and len(in_shapes[0]) == 4:
            raise UnsupportedCaffeLayer(
                "export Linear on 4-D blob: insert Flatten first")
        if x in ctx["flat"]:
            # caffe enumerates flattened features NCHW; reorder the NHWC-flat
            # weight rows to match (position k of the caffe weight = NHWC row
            # nchw_from_nhwc[k])
            h, wd, c = ctx["flat"][x]
            nchw_from_nhwc = np.transpose(
                np.arange(h * wd * c).reshape(h, wd, c), (2, 0, 1)).reshape(-1)
            w = w[nchw_from_nhwc, :]
        p = Msg().varint(1, w.shape[1]).varint(2, 1 if layer.with_bias else 0)
        blobs = [w.T] + ([np.asarray(params["bias"])] if layer.with_bias
                         else [])
        return emit(fresh("fc"), "InnerProduct", [x], fresh("fc_top"), blobs,
                    inner_product=p)

    if isinstance(layer, nn.BatchNorm):
        mean = np.asarray(state["running_mean"])
        var = np.asarray(state["running_var"])
        bn_p = Msg().f32(3, layer.eps)
        top = emit(fresh("bn"), "BatchNorm", [x], fresh("bn_top"),
                   [mean, var, np.asarray([1.0], np.float32)],
                   batch_norm=bn_p)
        if layer.affine:
            sc_p = Msg().boolean(4, True)
            top = emit(fresh("scale"), "Scale", [top], fresh("scale_top"),
                       [np.asarray(params["weight"]),
                        np.asarray(params["bias"])], scale=sc_p)
        return top

    if isinstance(layer, (nn.MaxPool2D, nn.AvgPool2D)):
        if not layer.ceil_mode:
            # caffe always ceil-rounds the output size; a floor-mode pool is
            # only representable when floor == ceil (window tiles exactly)
            ok = False
            if in_shapes and len(in_shapes[0]) == 4:
                pad = layer.padding
                ph, pw = ((0, 0) if isinstance(pad, str)
                          else ((pad, pad) if isinstance(pad, int)
                                else tuple(pad)))
                ok = ((in_shapes[0][1] + 2 * ph - layer.kernel_size[0])
                      % layer.stride[0] == 0 and
                      (in_shapes[0][2] + 2 * pw - layer.kernel_size[1])
                      % layer.stride[1] == 0)
            if not ok:
                raise UnsupportedCaffeLayer(
                    "floor-mode pooling does not tile the input exactly; "
                    "caffe Pooling is ceil-mode only")
        pad = layer.padding
        ph, pw = ((0, 0) if isinstance(pad, str)
                  else ((pad, pad) if isinstance(pad, int) else tuple(pad)))
        if isinstance(pad, str) and pad.upper() != "VALID":
            raise UnsupportedCaffeLayer("SAME pooling export")
        p = (Msg().varint(1, 0 if isinstance(layer, nn.MaxPool2D) else 1)
             .varint(5, layer.kernel_size[0]).varint(6, layer.kernel_size[1])
             .varint(7, layer.stride[0]).varint(8, layer.stride[1])
             .varint(9, ph).varint(10, pw))
        return emit(fresh("pool"), "Pooling", [x], fresh("pool_top"),
                    pooling=p)

    if isinstance(layer, (nn.GlobalAvgPool2D, nn.GlobalMaxPool2D)):
        p = (Msg().varint(1, 1 if isinstance(layer, nn.GlobalAvgPool2D) else 0)
             .boolean(12, True))
        return emit(fresh("gpool"), "Pooling", [x], fresh("gpool_top"),
                    pooling=p)

    if isinstance(layer, nn.LRN):
        p = (Msg().varint(1, layer.size).f32(2, layer.alpha)
             .f32(3, layer.beta).f32(5, layer.k))
        return emit(fresh("lrn"), "LRN", [x], fresh("lrn_top"), lrn=p)

    if isinstance(layer, nn.Dropout):
        p = Msg().f32(1, getattr(layer, "p", 0.5))
        return emit(fresh("drop"), "Dropout", [x], fresh("drop_top"),
                    dropout=p)

    if isinstance(layer, nn.CAdd):
        bias = np.asarray(params["bias"]).reshape(-1)
        return emit(fresh("bias"), "Scale", [x], fresh("bias_top"),
                    [np.ones_like(bias), bias], scale=Msg().boolean(4, True))

    if isinstance(layer, nn.CMul):
        w = np.asarray(params["weight"]).reshape(-1)
        return emit(fresh("scale"), "Scale", [x], fresh("scale_top"), [w],
                    scale=Msg().boolean(4, False))

    if isinstance(layer, nn.CAddTable):
        p = Msg().varint(1, 1)
        return emit(fresh("elt"), "Eltwise", list(ins), fresh("elt_top"),
                    eltwise=p)

    if isinstance(layer, nn.CMulTable):
        p = Msg().varint(1, 0)
        return emit(fresh("elt"), "Eltwise", list(ins), fresh("elt_top"),
                    eltwise=p)

    if isinstance(layer, nn.CMaxTable):
        p = Msg().varint(1, 2)
        return emit(fresh("elt"), "Eltwise", list(ins), fresh("elt_top"),
                    eltwise=p)

    if isinstance(layer, nn.JoinTable):
        dim = layer.dim
        rank = len(in_shapes[0]) if in_shapes else 2
        if rank == 4:
            axis = {3: 1, 1: 2, 2: 3, -1: 1}.get(dim)
        else:
            axis = 1 if dim in (1, -1) else dim
        if axis is None:
            raise UnsupportedCaffeLayer(f"JoinTable dim {dim}")
        p = Msg().varint(2, axis)
        return emit(fresh("concat"), "Concat", list(ins), fresh("concat_top"),
                    concat=p)

    if isinstance(layer, nn.Flatten) or (
            isinstance(layer, nn.Reshape) and layer.batch_mode
            and len(layer.shape) == 1 and in_shapes
            and len(in_shapes[0]) == 4):
        # Caffe's Flatten is over NCHW; the importer re-inserts the NHWC
        # transpose, and the geometry recorded here lets a following
        # InnerProduct reorder its weight rows to match.  A batch-mode
        # Reshape to one dim over a 4-D blob IS a flatten (the form the TF
        # round-trip produces).
        top = emit(fresh("flat"), "Flatten", [x], fresh("flat_top"))
        if in_shapes and len(in_shapes[0]) == 4:
            ctx["flat"][top] = tuple(in_shapes[0][1:4])
        return top

    if isinstance(layer, nn.Reshape):
        if in_shapes and len(in_shapes[0]) == 4:
            raise UnsupportedCaffeLayer(
                "general Reshape on 4-D blob (NCHW/NHWC ambiguous)")
        bs = Msg().varint(1, 0)  # dim 0 = keep batch
        for d in layer.shape:
            bs.varint(1, int(d))
        return emit(fresh("reshape"), "Reshape", [x], fresh("reshape_top"),
                    reshape=Msg().msg(1, bs))

    if t in ("ReLU",):
        return emit(fresh("relu"), "ReLU", [x], fresh("relu_top"))
    if t == "Sigmoid":
        return emit(fresh("sig"), "Sigmoid", [x], fresh("sig_top"))
    if t == "Tanh":
        return emit(fresh("tanh"), "TanH", [x], fresh("tanh_top"))
    if t == "SoftMax":
        return emit(fresh("prob"), "Softmax", [x], fresh("prob_top"))
    if t == "Identity":
        return x

    raise UnsupportedCaffeLayer(f"cannot export layer {t}")
