"""Local-or-remote filesystem seam for checkpoints, models, and records.

Reference analog (unverified — mount empty): ``dllib/utils/File.scala``,
whose ``save``/``load`` accept a local path OR an HDFS URI, so
``Optimizer.setCheckpoint`` works on cluster storage.  The TPU-native
equivalent of HDFS is object storage (``gs://`` on a TPU VM, ``s3://``
elsewhere): a preemption-safe checkpoint written only to the VM's local
disk is a checkpoint you lose with the VM.

Design: every path-taking function here dispatches on the URI scheme —
plain paths (and ``file://``) use ``os``/``open`` directly with zero new
dependencies; any other scheme routes through ``fsspec`` when installed
(``gs://`` additionally needs ``gcsfs``, ``s3://`` needs ``s3fs``) and
raises one actionable error when not.  ``memory://`` gives tests a real
remote-semantics filesystem with no network.

Remote "directories" follow object-store semantics: they exist only as
key prefixes, creation is a no-op, and rename is copy+delete (object
stores have no atomic rename — the checkpoint writer handles atomicity
with a manifest-last write order instead; the manifest is written only
after every blob it references, and readers treat a prefix without a
manifest as not-a-checkpoint).
"""

import json
import os
import posixpath
import shutil
from typing import IO, List, Optional

from bigdl_tpu.resilience import faults
from bigdl_tpu.utils.log import get_logger

log = get_logger("bigdl_tpu.storage")

__all__ = [
    "is_remote", "join", "basename", "open_file", "exists", "isdir",
    "isfile", "listdir", "list_files", "makedirs", "remove_tree",
    "read_json", "write_json", "load_npz", "glob", "fingerprint",
    "mirror_tree",
]


def is_remote(path: str) -> bool:
    """True for scheme'd URIs (``gs://…``, ``s3://…``, ``memory://…``)
    other than ``file://``."""
    if "://" not in path:
        return False
    return path.split("://", 1)[0] != "file"


def _strip_file_scheme(path: str) -> str:
    return path[len("file://"):] if path.startswith("file://") else path


def _fs(path: str):
    """The fsspec filesystem for a remote URI, with an actionable error
    when the optional dependency is missing."""
    try:
        import fsspec
    except ImportError as e:
        raise ImportError(
            f"path {path!r} needs the optional 'fsspec' dependency for "
            "remote filesystems (pip install fsspec; plus gcsfs for gs:// "
            "or s3fs for s3://). Local paths work without it.") from e
    try:
        fs, _ = fsspec.core.url_to_fs(path)
    except (ImportError, ValueError) as e:
        scheme = path.split("://", 1)[0]
        extra = {"gs": "gcsfs", "gcs": "gcsfs", "s3": "s3fs"}.get(
            scheme, f"an fsspec backend for {scheme}://")
        raise ImportError(
            f"fsspec has no handler for {path!r}; install {extra}") from e
    return fs


def _fs_path(path: str):
    """(fs, path-without-scheme) — fsspec methods want the stripped form
    for some backends but accept the full URI for most; use strip_protocol
    which is backend-correct."""
    fs = _fs(path)
    return fs, fs._strip_protocol(path)


def join(path: str, *parts: str) -> str:
    if is_remote(path):
        return posixpath.join(path, *parts)
    return os.path.join(_strip_file_scheme(path), *parts)


def basename(path: str) -> str:
    if is_remote(path):
        return posixpath.basename(path.rstrip("/"))
    return os.path.basename(_strip_file_scheme(path))


def open_file(path: str, mode: str = "rb") -> IO:
    faults.fire("storage_io_fail")  # the one seam every byte crosses
    if is_remote(path):
        fs, p = _fs_path(path)
        return fs.open(p, mode)
    return open(_strip_file_scheme(path), mode)


def exists(path: str) -> bool:
    if is_remote(path):
        fs, p = _fs_path(path)
        return fs.exists(p)
    return os.path.exists(_strip_file_scheme(path))


def isdir(path: str) -> bool:
    if is_remote(path):
        fs, p = _fs_path(path)
        return fs.isdir(p)
    return os.path.isdir(_strip_file_scheme(path))


def isfile(path: str) -> bool:
    if is_remote(path):
        fs, p = _fs_path(path)
        return fs.isfile(p)
    return os.path.isfile(_strip_file_scheme(path))


def glob(pattern: str) -> List[str]:
    """Sorted matches; remote results keep the full URI scheme
    (``fs.unstrip_protocol`` — a bare ``startswith(scheme)`` check would
    misfire on buckets named like the scheme, e.g. ``gs-data``)."""
    if not is_remote(pattern):
        import glob as _glob
        return sorted(_glob.glob(_strip_file_scheme(pattern)))
    fs, p = _fs_path(pattern)
    return [fs.unstrip_protocol(str(m)) for m in sorted(fs.glob(p))]


def list_files(path: str) -> List[str]:
    """Child FILE names of a directory, from ONE listing call — no
    per-child stat round-trips (a 1000-object GCS dir must not cost 1000
    sequential isfile calls)."""
    if is_remote(path):
        fs, p = _fs_path(path)
        try:
            infos = fs.ls(p, detail=True)
        except FileNotFoundError:
            return []
        base = p.rstrip("/")
        out = []
        for info in infos:
            full = str(info.get("name", "")).rstrip("/")
            name = posixpath.basename(full)
            if name and full != base and info.get("type") == "file":
                out.append(name)
        return sorted(out)
    path = _strip_file_scheme(path)
    return sorted(n for n in os.listdir(path)
                  if os.path.isfile(os.path.join(path, n)))


def listdir(path: str) -> List[str]:
    """Child NAMES (not full paths); [] for a missing remote prefix (an
    object-store 'directory' that holds nothing does not exist)."""
    if is_remote(path):
        fs, p = _fs_path(path)
        try:
            infos = fs.ls(p, detail=False)
        except FileNotFoundError:
            return []
        base = p.rstrip("/")
        out = []
        for child in infos:
            name = posixpath.basename(str(child).rstrip("/"))
            if name and str(child).rstrip("/") != base:
                out.append(name)
        return out
    return os.listdir(_strip_file_scheme(path))


def makedirs(path: str) -> None:
    """No-op on object stores (prefixes need no creation)."""
    if is_remote(path):
        return
    os.makedirs(_strip_file_scheme(path), exist_ok=True)


def remove_tree(path: str, ignore_errors: bool = True) -> None:
    if is_remote(path):
        fs, p = _fs_path(path)
        try:
            fs.rm(p, recursive=True)
        except FileNotFoundError:
            if not ignore_errors:
                raise
        except Exception as e:
            if not ignore_errors:
                raise
            # swallowed by contract (GC must not kill training), but NOT
            # silently: a sustained auth/permission failure here means
            # checkpoint GC is a no-op and storage grows unboundedly
            log.warning("remote remove_tree(%s) failed (%s: %s); "
                        "continuing, but storage is NOT being reclaimed",
                        path, type(e).__name__, e)
        return
    path = _strip_file_scheme(path)
    if os.path.isdir(path):
        shutil.rmtree(path, ignore_errors=ignore_errors)
    elif os.path.exists(path):
        try:
            os.remove(path)
        except OSError:
            if not ignore_errors:
                raise
    elif not ignore_errors:
        raise FileNotFoundError(path)


def load_npz(path: str) -> dict:
    """Load an npz into a plain dict, materializing every array BEFORE the
    file closes — ``np.load`` over an fsspec file is lazy, and a leaked
    lazy handle reads from a closed stream."""
    import numpy as np

    with open_file(path, "rb") as f:
        with np.load(f) as z:
            return {k: z[k] for k in z.files}


def fingerprint(path: str) -> Optional[dict]:
    """Change-detection identity of a file: whichever of size/etag/mtime/
    checksum the backend exposes (stringified — etags and mtimes differ in
    type across backends).  None when the file is missing or the backend
    cannot stat it; callers treat None as "cannot verify" (stale-allowed),
    not as a failure."""
    try:
        if is_remote(path):
            fs, p = _fs_path(path)
            info = fs.info(p)
            out = {k: str(info[k])
                   for k in ("size", "etag", "ETag", "mtime", "checksum",
                             "md5Hash", "LastModified")
                   if info.get(k) is not None}
            return out or None
        st = os.stat(_strip_file_scheme(path))
        return {"size": str(st.st_size), "mtime": str(st.st_mtime)}
    except (OSError, ImportError, KeyError):
        return None


def mirror_tree(src: str, dst: str, policy=None, metrics=None,
                sleep=None) -> int:
    """Copy every file under ``src`` (recursively) to ``dst`` — the remote
    checkpoint mirror: the off-cluster copy that survives the whole pod
    (and its shared filesystem) being reclaimed.  Returns bytes copied.

    Each file upload runs under a BOUNDED retry-with-backoff (default: 3
    retries, 0.2s exponential base) instead of a single attempt — object
    stores blip, and a mirror that silently lost one blob is worse than
    none.  Every retry is accounted under the standard
    ``retries_by_cause.transient_storage`` counter so mirror flakiness
    shows up in /metrics next to every other storage retry.  Exhausted
    retries raise: the CALLER decides whether a missing mirror is fatal
    (the checkpoint writer logs and keeps the intact primary).

    Any ``manifest.json`` is copied LAST within the whole tree, preserving
    the checkpoint writer's manifest-last ordering — a crash mid-mirror
    leaves a prefix readers treat as not-a-checkpoint."""
    import time as _time

    if policy is None:
        from bigdl_tpu.resilience.retry import RetryPolicy

        policy = RetryPolicy(max_retries=3, base_s=0.2, max_s=5.0)
    if metrics is None:
        from bigdl_tpu.optim.metrics import global_metrics

        metrics = global_metrics()
    sleep = sleep or _time.sleep

    def walk(rel: str):
        base = join(src, rel) if rel else src
        for name in listdir(base):
            p = f"{rel}/{name}" if rel else name
            if isdir(join(src, p)):
                yield from walk(p)
            else:
                yield p

    files = sorted(walk(""),
                   key=lambda p: (p.split("/")[-1] == "manifest.json", p))
    makedirs(dst)
    total = 0
    for p in files:
        target = join(dst, p)
        d = target.rsplit("/", 1)[0] if "/" in p else dst
        makedirs(d)
        attempt = 0
        while True:
            try:
                with open_file(join(src, p), "rb") as f:
                    data = f.read()
                with open_file(target, "wb") as g:
                    g.write(data)
                total += len(data)
                break
            except Exception as e:
                attempt += 1
                if attempt > policy.max_retries:
                    raise
                metrics.inc("retries_by_cause.transient_storage")
                delay = policy.backoff(attempt)
                log.warning(
                    "mirror %s -> %s failed (%s: %s); retry %d/%d in %.2fs",
                    p, dst, type(e).__name__, e, attempt,
                    policy.max_retries, delay)
                sleep(delay)
    return total


def read_json(path: str):
    with open_file(path, "r") as f:
        return json.load(f)


def write_json(path: str, obj, indent: Optional[int] = None) -> None:
    with open_file(path, "w") as f:
        json.dump(obj, f, indent=indent)
