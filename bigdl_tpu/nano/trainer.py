"""Nano Trainer — reference ``nano.pytorch.Trainer`` (a patched Lightning
trainer: single-node acceleration, multi-process DDP, bf16).

TPU-native re-design: the "acceleration" knobs map onto what actually
matters on this hardware — the jitted sharded train step already IS the
fast path, bf16 is the compute-policy toggle, and "num_processes" is the
mesh (one process per host; in-process devices come for free).  The class
is a thin Lightning-shaped front over ``optim.Optimizer`` so nano-style
user code ports verbatim:

    trainer = Trainer(max_epochs=5, precision="bf16")
    trainer.fit(model, criterion, optimizer, train_data=(x, y),
                val_data=(vx, vy))
    trainer.validate(...); trainer.predict(...)
"""

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.optim.optimizer import Optimizer, TrainedModel
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import Loss, ValidationMethod


class Trainer:
    """Lightning-shaped fit/validate/predict over the sharded step."""

    def __init__(self, max_epochs: int = 10, batch_size: int = 32,
                 precision: str = "fp32",
                 checkpoint_path: Optional[str] = None,
                 log_every: int = 50):
        if precision not in ("fp32", "bf16"):
            raise ValueError("precision: fp32 | bf16")
        self.max_epochs = max_epochs
        self.batch_size = batch_size
        self.precision = precision
        self.checkpoint_path = checkpoint_path
        self.log_every = log_every
        self._trained: Optional[TrainedModel] = None

    def _dataset(self, data):
        from bigdl_tpu.data.dataset import ArrayDataSet, DataSet

        if isinstance(data, DataSet):
            return data
        x, y = data
        return ArrayDataSet(np.asarray(x), np.asarray(y))

    def fit(self, model, criterion, optim_method, train_data,
            val_data=None,
            val_methods: Sequence[ValidationMethod] = ()) -> TrainedModel:
        from bigdl_tpu.tensor.policy import compute_dtype

        opt = Optimizer(model, self._dataset(train_data), criterion,
                        batch_size=self.batch_size)
        opt.set_optim_method(optim_method)
        opt.set_end_when(Trigger.max_epoch(self.max_epochs))
        opt.log_every = self.log_every
        if val_data is not None:
            methods = list(val_methods) or [Loss(criterion)]
            opt.set_validation(Trigger.every_epoch(),
                               self._dataset(val_data), methods)
        if self.checkpoint_path:
            opt.set_checkpoint(self.checkpoint_path, Trigger.every_epoch())
        if self.precision == "bf16":
            import jax.numpy as jnp

            with compute_dtype(jnp.bfloat16):
                self._trained = opt.optimize()
        else:
            self._trained = opt.optimize()
        return self._trained

    def validate(self, data, methods: Sequence[ValidationMethod]
                 ) -> Dict[str, float]:
        self._require_fit()
        res = self._trained.evaluate(self._dataset(data), list(methods),
                                     self.batch_size)
        return {r.name: r.result for r in res}

    def predict(self, x, batch_size: int = 0):
        self._require_fit()
        return self._trained.predict(np.asarray(x), batch_size)

    @property
    def model(self) -> TrainedModel:
        self._require_fit()
        return self._trained

    def _require_fit(self):
        if self._trained is None:
            raise RuntimeError("call fit() first")
