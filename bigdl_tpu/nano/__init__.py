"""Nano-equivalent: single-device inference acceleration.

Reference analog (unverified — mount empty): ``python/nano/src/bigdl/
nano/`` (SURVEY.md §2 L12): ``InferenceOptimizer.quantize/trace`` compiles
a trained model onto faster runtimes (ONNXRuntime / OpenVINO / INC int8)
and ``.optimize()`` benchmarks every variant and picks the winner;
``nano.pytorch.Trainer`` accelerates single-node training.

TPU-native redesign: the "runtimes" are XLA execution modes of the SAME
model — fp32 jit, bf16-compute jit, int8 Pallas-kernel quantization
(``bigdl_tpu.nn.quantized``) — so ``trace``/``quantize``/``optimize``
keep the reference surface without foreign-runtime exports.  Training
acceleration is native to the core stack (the Optimizer already jits,
shards, and runs bf16); ``nano.Trainer`` is the Lightning-SHAPED front
over it so reference nano user code ports verbatim — precision="bf16"
toggles the compute policy, the mesh replaces num_processes.
"""

from bigdl_tpu.nano.inference import InferenceOptimizer, TracedModel
from bigdl_tpu.nano.trainer import Trainer

__all__ = ["InferenceOptimizer", "TracedModel", "Trainer"]
